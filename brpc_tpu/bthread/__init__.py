"""brpc_tpu.bthread — concurrency layer (SURVEY.md section 2.2).

Work-stealing task scheduler with pluggable idle hooks, butex wait/wake,
timer thread, MPSC execution queues, and versioned lockable correlation ids
— the concurrency substrate under the RPC layer, mirroring
/root/reference/src/bthread/. Synchronization built on butex exactly as the
reference builds mutex/cond/countdown on it.
"""
from __future__ import annotations

import threading
import time
from typing import Optional

from brpc_tpu.bthread import id as bthread_id  # noqa: F401
from brpc_tpu.bthread.butex import (  # noqa: F401
    Butex,
    butex_create,
    butex_wait,
    butex_wake,
    butex_wake_all,
)
from brpc_tpu.bthread.execution_queue import (  # noqa: F401
    ExecutionQueue,
    TaskIterator,
    execution_queue_start,
)
from brpc_tpu.bthread.parking_lot import ParkingLot  # noqa: F401
from brpc_tpu.bthread.task_control import (  # noqa: F401
    TaskControl,
    TaskGroup,
    bthread_join,
    get_task_control,
    start_background,
    start_urgent,
)
from brpc_tpu.bthread.timer_thread import (  # noqa: F401
    TimerThread,
    get_global_timer_thread,
    timer_add,
    timer_del,
)
from brpc_tpu.bthread.work_stealing_queue import WorkStealingQueue  # noqa: F401


def usleep(us: float):
    """bthread_usleep — parks the calling (worker) thread."""
    time.sleep(us / 1e6)


def fd_wait(fd: int, events: str = "r", timeout_s: Optional[float] = None) -> bool:
    """bthread_fd_wait (bthread/fd.cpp:119-170): block the calling task
    until fd is readable ('r') / writable ('w'). True if ready, False on
    timeout."""
    import select

    r = [fd] if "r" in events else []
    w = [fd] if "w" in events else []
    rr, ww, _ = select.select(r, w, [], timeout_s)
    return bool(rr or ww)


def connect(address, timeout_s: float = 1.0):
    """bthread_connect (fd.cpp): blocking-in-task TCP connect returning a
    connected non-blocking socket, or raising OSError."""
    import socket as pysocket

    s = pysocket.create_connection(address, timeout=timeout_s)
    s.setblocking(False)
    return s


class Mutex:
    """bthread_mutex built on butex (bthread/mutex.cpp shape): the lock word
    is the butex value (0 free, 1 locked no waiters, 2 contended)."""

    def __init__(self):
        self._butex = Butex(0)
        self._guard = threading.Lock()

    def lock(self):
        while True:
            with self._guard:
                if self._butex.value == 0:
                    self._butex.value = 1
                    return
                self._butex.value = 2
            self._butex.wait(2, timeout=0.05)

    def unlock(self):
        with self._guard:
            contended = self._butex.value == 2
            self._butex.value = 0
        if contended:
            self._butex.wake(1)

    def __enter__(self):
        self.lock()
        return self

    def __exit__(self, *exc):
        self.unlock()


class Cond:
    """bthread_cond: seq-count butex; broadcast requeues to the mutex
    (bthread/condition_variable.cpp shape)."""

    def __init__(self):
        self._butex = Butex(0)

    def wait(self, mutex: Mutex, timeout: Optional[float] = None) -> bool:
        expected = self._butex.value
        mutex.unlock()
        woke = self._butex.wait(expected, timeout)
        mutex.lock()
        return woke

    def signal(self):
        self._butex.value += 1
        self._butex.wake(1)

    def broadcast(self):
        self._butex.value += 1
        self._butex.wake_all()


class CountdownEvent:
    """bthread::CountdownEvent (countdown_event.h)."""

    def __init__(self, initial_count: int = 1):
        self._butex = Butex(initial_count)
        self._lock = threading.Lock()

    def signal(self, sig: int = 1):
        with self._lock:
            self._butex.value -= sig
            done = self._butex.value <= 0
        if done:
            self._butex.wake_all()

    def add_count(self, v: int = 1):
        with self._lock:
            self._butex.value += v

    def wait(self, timeout: Optional[float] = None) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                current = self._butex.value
            if current <= 0:
                return True
            remain = None if deadline is None else deadline - time.monotonic()
            if remain is not None and remain <= 0:
                return False
            self._butex.wait(current, remain)


_key_registry: dict = {}
_key_lock = threading.Lock()
_next_key = [1]
_tls = threading.local()


def key_create(destructor=None) -> int:
    """bthread_key_create (bthread/key.cpp)."""
    with _key_lock:
        key = _next_key[0]
        _next_key[0] += 1
        _key_registry[key] = destructor
        return key


def setspecific(key: int, value):
    store = getattr(_tls, "store", None)
    if store is None:
        store = {}
        _tls.store = store
    store[key] = value


def getspecific(key: int):
    store = getattr(_tls, "store", None)
    return None if store is None else store.get(key)
