"""bthread_id — versioned, lockable 64-bit correlation ids.

Counterpart of bthread/id.{h,cpp} (/root/reference/src/bthread/id.h:38-60):
an id names one in-flight operation; lock() serializes all touches to its
data; error() delivers a failure to the owner's on_error under the lock
(queued if the lock is held); destroy() invalidates every outstanding copy
of the id (ABA-proof via version); ranged creation lets id+n address the
same slot — brpc's CallId+nretry trick (controller.h:655-664) that gives
every retry attempt its own addressable version.

This is the completion backbone of the RPC layer here, as in the reference:
the response/timeout/cancel paths race by design and the id lock arbitrates.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Dict, Optional, Tuple

INVALID_BTHREAD_ID = 0

# on_error(id_value, data, error_code, error_text) -> None
# MUST finish by calling unlock(id) or unlock_and_destroy(id).
OnError = Callable[[int, object, int, str], None]


class _IdSlot:
    __slots__ = (
        "first_version", "range", "locked", "destroyed", "data", "on_error",
        "pending_errors", "cond", "joined",
    )

    def __init__(self):
        self.first_version = 1
        self.range = 1
        self.locked = False
        self.destroyed = True
        self.data = None
        self.on_error: Optional[OnError] = None
        self.pending_errors: deque = deque()
        self.cond = threading.Condition()
        self.joined = threading.Event()


_slots: Dict[int, _IdSlot] = {}
_free_indexes: deque = deque()
_next_index = 1
_registry_lock = threading.Lock()


def _default_on_error(id_value: int, data, error_code: int, error_text: str):
    unlock_and_destroy(id_value)


def create(data=None, on_error: Optional[OnError] = None) -> int:
    return create_ranged(data, on_error, 1)


def create_ranged(data=None, on_error: Optional[OnError] = None,
                  range_: int = 1) -> int:
    """Versions [v, v+range_) all address this slot (id.h:55-60)."""
    global _next_index
    with _registry_lock:
        if _free_indexes:
            index = _free_indexes.popleft()
            slot = _slots[index]
        else:
            index = _next_index
            _next_index += 1
            slot = _IdSlot()
            _slots[index] = slot
    with slot.cond:
        slot.range = max(1, range_)
        slot.locked = False
        slot.destroyed = False
        slot.data = data
        slot.on_error = on_error or _default_on_error
        slot.pending_errors.clear()
        slot.joined = threading.Event()
        return (index << 32) | slot.first_version


def _resolve(id_value: int) -> Tuple[Optional[_IdSlot], int]:
    index = id_value >> 32
    version = id_value & 0xFFFFFFFF
    with _registry_lock:
        slot = _slots.get(index)
    return slot, version


def _valid(slot: _IdSlot, version: int) -> bool:
    return (not slot.destroyed
            and slot.first_version <= version < slot.first_version + slot.range)


def lock(id_value: int, timeout: Optional[float] = None):
    """Lock the id; returns its data. Raises KeyError if the id is
    destroyed/stale (EINVAL in the reference)."""
    slot, version = _resolve(id_value)
    if slot is None:
        raise KeyError(f"invalid bthread_id {id_value:#x}")
    with slot.cond:
        while True:
            if not _valid(slot, version):
                raise KeyError(f"destroyed bthread_id {id_value:#x}")
            if not slot.locked:
                slot.locked = True
                return slot.data
            if not slot.cond.wait(timeout):
                raise TimeoutError(f"lock timeout on {id_value:#x}")


def trylock(id_value: int):
    slot, version = _resolve(id_value)
    if slot is None:
        raise KeyError(f"invalid bthread_id {id_value:#x}")
    with slot.cond:
        if not _valid(slot, version) or slot.locked:
            return None
        slot.locked = True
        return slot.data


def unlock(id_value: int):
    """Release the lock — but first deliver one queued error, if any, to
    on_error while still holding the lock (id.cpp error-queue semantics)."""
    slot, version = _resolve(id_value)
    if slot is None:
        raise KeyError(f"invalid bthread_id {id_value:#x}")
    pending = None
    with slot.cond:
        if not _valid(slot, version):
            # A stale id (destroyed, possibly with the slot reused by a
            # newer id) must NOT release the current holder's lock.
            raise KeyError(f"destroyed bthread_id {id_value:#x}")
        if not slot.locked:
            raise RuntimeError(f"unlock of unlocked id {id_value:#x}")
        if slot.pending_errors:
            pending = slot.pending_errors.popleft()
        else:
            slot.locked = False
            slot.cond.notify()
    if pending is not None:
        code, text = pending
        slot.on_error(id_value, slot.data, code, text)


def unlock_and_destroy(id_value: int):
    """Invalidate all copies of the id; wake joiners and lock-waiters."""
    slot, version = _resolve(id_value)
    if slot is None:
        raise KeyError(f"invalid bthread_id {id_value:#x}")
    index = id_value >> 32
    with slot.cond:
        slot.first_version += slot.range  # all outstanding versions now stale
        slot.destroyed = True
        slot.locked = False
        slot.pending_errors.clear()
        slot.cond.notify_all()
        slot.joined.set()
    with _registry_lock:
        _free_indexes.append(index)


def join(id_value: int, timeout: Optional[float] = None) -> bool:
    """Block until the id is destroyed. Returns immediately for stale ids."""
    slot, version = _resolve(id_value)
    if slot is None:
        return True
    with slot.cond:
        if not _valid(slot, version):
            return True
        joined = slot.joined
    return joined.wait(timeout)


def error(id_value: int, error_code: int, error_text: str = "") -> bool:
    """Deliver an error: runs on_error under the id lock, or queues it if
    the lock is held (bthread_id_error2). Returns False for stale ids."""
    slot, version = _resolve(id_value)
    if slot is None:
        return False
    with slot.cond:
        if not _valid(slot, version):
            return False
        if slot.locked:
            slot.pending_errors.append((error_code, error_text))
            return True
        slot.locked = True
    slot.on_error(id_value, slot.data, error_code, error_text)
    return True


def is_destroyed(id_value: int) -> bool:
    slot, version = _resolve(id_value)
    return slot is None or not _valid(slot, version)
