"""TaskControl / TaskGroup — the M:N scheduler's worker fleet.

Counterparts of bthread::TaskControl and bthread::TaskGroup
(/root/reference/src/bthread/task_control.h:55-126, task_group.h/cpp):
TaskControl owns N worker threads, each running a TaskGroup loop over a
local work-stealing deque `_rq`, a `_remote_rq` fed by non-workers, and a
fork-style `_bound_rq` of group-pinned tasks that thieves may not touch
(task_group.h:327-330). The idle loop reproduces the monographdb fork's
pluggable shape (task_group.cpp:139-232): registered idle hooks run before
parking — the seam where that fork polls io_uring / an external transaction
processor, and where the TPU build polls libtpu transfer completions
(SURVEY.md section 2.10).

CPython cannot cheaply switch user-space stacks, so a "bthread" here is a
callable executed to completion on a worker (the reference's own
pthread-compatible mode); blocking primitives park the worker thread. The
native C++ core (brpc_tpu/native) provides the real stack-switching M:N
scheduler.
"""
from __future__ import annotations

import random
import threading
from collections import deque
from typing import Callable, Dict, List, Optional

from brpc_tpu import bvar
from brpc_tpu.bthread.parking_lot import ParkingLot
from brpc_tpu.bthread.work_stealing_queue import WorkStealingQueue
from brpc_tpu.butil import flags

# The monographdb fork's idle-loop tuning knobs (task_group.cpp:54-78):
flags.define_int("worker_polling_time_us", 0,
                 "busy-poll this long before parking an idle worker")
flags.define_int("steal_task_rnd", 1,
                 "steal every N idle rounds (1 = every round)")


class TaskMeta:
    __slots__ = ("fn", "args", "tid", "joined", "about_to_quit")

    def __init__(self, fn: Callable, args, tid: int):
        self.fn = fn
        self.args = args
        self.tid = tid
        self.joined = threading.Event()
        self.about_to_quit = False


class TaskGroup:
    def __init__(self, control: "TaskControl", group_id: int):
        self.control = control
        self.group_id = group_id
        self._rq = WorkStealingQueue()
        self._remote_rq: deque = deque()
        self._remote_lock = threading.Lock()
        self._bound_rq: deque = deque()  # group-pinned, never stolen
        self._bound_lock = threading.Lock()
        # fork: one parking lot per worker for precise wakeup
        self.parking_lot = ParkingLot()
        self.nswitch = 0

    # -- producers ---------------------------------------------------------
    def push_local(self, meta: TaskMeta):
        if not self._rq.push(meta):
            self.push_remote(meta)

    def push_remote(self, meta: TaskMeta):
        with self._remote_lock:
            self._remote_rq.append(meta)
        self.parking_lot.signal(1)

    def push_bound(self, meta: TaskMeta):
        """ready_to_run_bound (fork): pin a task to this group."""
        with self._bound_lock:
            self._bound_rq.append(meta)
        self.parking_lot.signal(1)

    # -- consumer ----------------------------------------------------------
    def _next_task(self, steal: bool = True) -> Optional[TaskMeta]:
        with self._bound_lock:
            if self._bound_rq:
                return self._bound_rq.popleft()
        meta = self._rq.pop()
        if meta is not None:
            return meta
        with self._remote_lock:
            if self._remote_rq:
                return self._remote_rq.popleft()
        if not steal:
            return None
        return self.control.steal_task(self.group_id)

    def run_main_task(self):
        """Worker main loop (task_group.cpp:238-270 + wait_task 139-232,
        including the fork's busy-poll window and steal frequency)."""
        import time as _time

        control = self.control
        idle_rounds = 0
        while not control._stopping:
            steal_rnd = max(1, flags.get_flag("steal_task_rnd"))
            meta = self._next_task(
                steal=(idle_rounds % steal_rnd == 0))
            if meta is None:
                idle_rounds += 1
                # Idle: run registered hooks (libtpu poll / ext-processor
                # slot), busy-poll if configured, then park on this lot.
                did_work = False
                for hook in control.idle_hooks:
                    try:
                        did_work |= bool(hook())
                    except Exception:
                        pass
                if did_work:
                    continue
                poll_us = flags.get_flag("worker_polling_time_us")
                if poll_us > 0:
                    deadline = _time.monotonic() + poll_us / 1e6
                    polled = None
                    while _time.monotonic() < deadline:
                        polled = self._next_task()
                        if polled is not None:
                            break
                    if polled is not None:
                        meta = polled
                    else:
                        continue
                if meta is None:
                    expected = self.parking_lot.get_state()
                    if (self._rq.empty() and not self._remote_rq
                            and not self._bound_rq):
                        self.parking_lot.wait(expected, timeout=0.1)
                    continue
            idle_rounds = 0
            self.nswitch += 1
            control._nswitch_var.update(1)
            try:
                meta.fn(*meta.args)
            except Exception:
                import logging

                logging.getLogger(__name__).exception("bthread raised")
            finally:
                meta.joined.set()
                # Detached-by-default reap (bthread_start_background tasks
                # are detached unless joined): tids are never reused, so a
                # later join() of a reaped tid correctly reports finished.
                control._metas.pop(meta.tid, None)
                control._finished_var.update(1)


class TaskControl:
    def __init__(self, concurrency: int = 4):
        self.concurrency = max(1, concurrency)
        self.groups: List[TaskGroup] = []
        self._threads: List[threading.Thread] = []
        self._stopping = False
        self._init_lock = threading.Lock()
        self._started = False
        self._next_tid = 1
        self._tid_lock = threading.Lock()
        self.idle_hooks: List[Callable[[], bool]] = []
        self._metas: Dict[int, TaskMeta] = {}
        # bvar instrumentation mirroring task_control.h:111-121
        self._nswitch_var = bvar.Adder("bthread_switch_count")
        self._finished_var = bvar.Adder("bthread_count_finished")
        bvar.PassiveStatus(lambda: len(self._threads), "bthread_worker_count")
        bvar.PassiveStatus(self._queued_count, "bthread_queued_count")

    def _queued_count(self) -> int:
        return sum(
            len(g._rq) + len(g._remote_rq) + len(g._bound_rq)
            for g in self.groups
        )

    def init(self):
        with self._init_lock:
            if self._started:
                return
            for i in range(self.concurrency):
                g = TaskGroup(self, i)
                self.groups.append(g)
            for g in self.groups:
                t = threading.Thread(
                    target=g.run_main_task, name=f"bthread_worker_{g.group_id}",
                    daemon=True,
                )
                t.start()
                self._threads.append(t)
            self._started = True

    def add_workers(self, n: int):
        """Grow the fleet at runtime (task_control.h:78)."""
        with self._init_lock:
            base = len(self.groups)
            for i in range(n):
                g = TaskGroup(self, base + i)
                self.groups.append(g)
                t = threading.Thread(
                    target=g.run_main_task, name=f"bthread_worker_{g.group_id}",
                    daemon=True,
                )
                t.start()
                self._threads.append(t)
            self.concurrency += n

    def add_idle_hook(self, hook: Callable[[], bool]):
        """Register work for the idle loop (the fork's ext-processor seam,
        task_group.h:223-228). hook() returns True if it did work."""
        self.idle_hooks.append(hook)

    # -- spawn -------------------------------------------------------------
    def start_background(self, fn: Callable, *args) -> int:
        """bthread_start_background: queue to a group, signal its lot."""
        self.init()
        with self._tid_lock:
            tid = self._next_tid
            self._next_tid += 1
        meta = TaskMeta(fn, args, tid)
        self._metas[tid] = meta
        group = self.groups[tid % len(self.groups)]
        group.push_remote(meta)
        return tid

    def start_urgent(self, fn: Callable, *args) -> int:
        """bthread_start_urgent: jumps ahead via the bound lane."""
        self.init()
        with self._tid_lock:
            tid = self._next_tid
            self._next_tid += 1
        meta = TaskMeta(fn, args, tid)
        self._metas[tid] = meta
        group = self.groups[tid % len(self.groups)]
        group.push_bound(meta)
        return tid

    def join(self, tid: int, timeout: Optional[float] = None) -> bool:
        meta = self._metas.get(tid)
        if meta is None:
            return True
        ok = meta.joined.wait(timeout)
        if ok:
            self._metas.pop(tid, None)
        return ok

    def steal_task(self, thief_group_id: int) -> Optional[TaskMeta]:
        """Steal from a random victim's local queue (task_control.h:55);
        bound queues are exempt by construction."""
        n = len(self.groups)
        if n <= 1:
            return None
        start = random.randrange(n)
        for i in range(n):
            victim = self.groups[(start + i) % n]
            if victim.group_id == thief_group_id:
                continue
            meta = victim._rq.steal()
            if meta is not None:
                return meta
            # Remote queues are stealable too (task_control.cpp steal_task
            # covers _remote_rq) — otherwise tasks assigned to a worker
            # blocked in user code would starve.
            with victim._remote_lock:
                if victim._remote_rq:
                    return victim._remote_rq.popleft()
        return None

    def stop_and_join(self):
        self._stopping = True
        for g in self.groups:
            g.parking_lot.stop()
        for t in self._threads:
            t.join(timeout=2.0)


_control: Optional[TaskControl] = None
_control_lock = threading.Lock()


def get_task_control(concurrency: Optional[int] = None) -> TaskControl:
    global _control
    if _control is None:
        with _control_lock:
            if _control is None:
                import os

                # Workers here block in user code (pthread-mode bthreads),
                # so size generously — IO/sleep-bound, not CPU-bound.
                default = max(16, (os.cpu_count() or 1) + 3)
                _control = TaskControl(concurrency or default)
    return _control


def start_background(fn: Callable, *args) -> int:
    return get_task_control().start_background(fn, *args)


def start_urgent(fn: Callable, *args) -> int:
    return get_task_control().start_urgent(fn, *args)


def bthread_join(tid: int, timeout: Optional[float] = None) -> bool:
    return get_task_control().join(tid, timeout)
