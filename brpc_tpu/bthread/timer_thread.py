"""TimerThread — the one dedicated timing thread behind all timeouts.

Counterpart of bthread::TimerThread
(/root/reference/src/bthread/timer_thread.h:32-90): schedule() inserts into
one of 13 hashed buckets to spread producer contention, a single thread
drains buckets into a global min-heap and runs due tasks. RPC timeouts and
backup-request timers ride this (controller.cpp:605,1256).

unschedule() is best-effort exactly as in the reference: it can race the
run; callers needing certainty use the returned Timer's `cancelled` flag
which run() rechecks under the bucket lock.
"""
from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Callable, Dict, Optional

NUM_BUCKETS = 13


class _Task:
    __slots__ = ("run_time", "fn", "args", "seq", "cancelled", "done")

    def __init__(self, run_time: float, fn: Callable, args, seq: int):
        self.run_time = run_time
        self.fn = fn
        self.args = args
        self.seq = seq
        self.cancelled = False
        self.done = False

    def __lt__(self, other: "_Task") -> bool:
        return (self.run_time, self.seq) < (other.run_time, other.seq)


TimerId = int


class TimerThread:
    def __init__(self):
        self._buckets = [[] for _ in range(NUM_BUCKETS)]
        self._bucket_locks = [threading.Lock() for _ in range(NUM_BUCKETS)]
        self._tasks: Dict[TimerId, _Task] = {}
        self._tasks_lock = threading.Lock()
        self._heap: list = []
        self._seq = itertools.count(1)
        self._cond = threading.Condition()
        self._nearest = float("inf")
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._started_lock = threading.Lock()

    def _ensure_started(self):
        if self._thread is None:
            with self._started_lock:
                if self._thread is None:
                    t = threading.Thread(
                        target=self._run, name="bthread_timer", daemon=True
                    )
                    t.start()
                    self._thread = t

    def schedule(self, fn: Callable, delay_s: float, *args) -> TimerId:
        """Run fn(*args) delay_s seconds from now; returns an id for
        unschedule()."""
        self._ensure_started()
        seq = next(self._seq)
        task = _Task(time.monotonic() + max(0.0, delay_s), fn, args, seq)
        bucket = seq % NUM_BUCKETS
        with self._bucket_locks[bucket]:
            self._buckets[bucket].append(task)
        with self._tasks_lock:
            self._tasks[seq] = task
        # Wake the run loop if this beats the nearest deadline.
        with self._cond:
            if task.run_time < self._nearest:
                self._cond.notify()
        return seq

    def unschedule(self, timer_id: TimerId) -> int:
        """0 = cancelled, 1 = already ran/running, -1 = unknown id
        (timer_thread.h unschedule semantics)."""
        with self._tasks_lock:
            task = self._tasks.get(timer_id)
        if task is None:
            return -1
        if task.done:
            return 1
        task.cancelled = True
        return 0

    def _collect(self):
        for i in range(NUM_BUCKETS):
            with self._bucket_locks[i]:
                pending, self._buckets[i] = self._buckets[i], []
            for t in pending:
                heapq.heappush(self._heap, t)

    def _run(self):
        while not self._stop:
            self._collect()
            now = time.monotonic()
            while self._heap and self._heap[0].run_time <= now:
                task = heapq.heappop(self._heap)
                task.done = True
                with self._tasks_lock:
                    self._tasks.pop(task.seq, None)
                if not task.cancelled:
                    try:
                        task.fn(*task.args)
                    except Exception:
                        import logging

                        logging.getLogger(__name__).exception(
                            "timer task raised"
                        )
            next_deadline = self._heap[0].run_time if self._heap else now + 1.0
            with self._cond:
                self._nearest = next_deadline
                wait = max(0.0, min(next_deadline - time.monotonic(), 1.0))
                if wait > 0:
                    self._cond.wait(wait)
                self._nearest = float("inf")

    def stop_and_join(self):
        self._stop = True
        with self._cond:
            self._cond.notify()
        if self._thread:
            self._thread.join(timeout=2.0)


_global_timer: Optional[TimerThread] = None
_global_timer_lock = threading.Lock()


def get_global_timer_thread() -> TimerThread:
    global _global_timer
    if _global_timer is None:
        with _global_timer_lock:
            if _global_timer is None:
                _global_timer = TimerThread()
    return _global_timer


def timer_add(delay_s: float, fn: Callable, *args) -> TimerId:
    """bthread_timer_add equivalent."""
    return get_global_timer_thread().schedule(fn, delay_s, *args)


def timer_del(timer_id: TimerId) -> int:
    return get_global_timer_thread().unschedule(timer_id)
