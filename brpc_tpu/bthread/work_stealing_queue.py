"""WorkStealingQueue — per-worker deque: owner pushes/pops one end, thieves
steal the other.

Counterpart of bthread::WorkStealingQueue
(/root/reference/src/bthread/work_stealing_queue.h:31-157), the Chase-Lev
single-producer ring. CPython can't do the lock-free version (no atomics on
plain ints), so this preserves the *shape* — owner-end LIFO for cache warmth,
thief-end FIFO for fairness — behind one short lock; the native C++ core
(brpc_tpu/native) carries the lock-free implementation.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Optional, TypeVar

T = TypeVar("T")


class WorkStealingQueue:
    def __init__(self, capacity: int = 4096):
        self._q: deque = deque()
        self._capacity = capacity
        self._lock = threading.Lock()

    def push(self, item) -> bool:
        """Owner-only push (bottom)."""
        with self._lock:
            if len(self._q) >= self._capacity:
                return False
            self._q.append(item)
            return True

    def pop(self) -> Optional[object]:
        """Owner-only pop (bottom, LIFO — newest first for locality)."""
        with self._lock:
            return self._q.pop() if self._q else None

    def steal(self) -> Optional[object]:
        """Thief pop (top, FIFO — oldest first)."""
        with self._lock:
            return self._q.popleft() if self._q else None

    def __len__(self) -> int:
        return len(self._q)

    def empty(self) -> bool:
        return not self._q
