"""ExecutionQueue — MPSC serialized executor with batching.

Counterpart of bthread::ExecutionQueue
(/root/reference/src/bthread/execution_queue.h:78-203): many producers
execute() tasks; at most one consumer runs at a time, draining a batch
through a TaskIterator; a high-priority lane jumps the queue. Used by
streaming RPC's ordered delivery and the locality-aware LB here exactly as
in the reference.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Deque, Iterator, Optional


class TaskIterator:
    """Batch iterator handed to the consumer fn (execution_queue.h:94-136)."""

    def __init__(self, tasks, stopped: bool):
        self._tasks = tasks
        self._stopped = stopped

    def __iter__(self) -> Iterator:
        return iter(self._tasks)

    def is_queue_stopped(self) -> bool:
        return self._stopped


class ExecutionQueue:
    def __init__(self, execute_fn: Callable[[TaskIterator], int],
                 scheduler=None, batch_size: int = 256):
        """execute_fn(iterator) -> int; negative return stops the queue.
        scheduler: callable(fn) running fn asynchronously; defaults to the
        global bthread pool."""
        self._execute_fn = execute_fn
        self._tasks: Deque = deque()
        self._high_tasks: Deque = deque()
        self._lock = threading.Lock()
        self._running = False  # one consumer at a time
        self._stopped = False
        self._joined = threading.Event()
        self._batch_size = batch_size
        if scheduler is None:
            from brpc_tpu.bthread.task_control import start_background

            scheduler = start_background
        self._schedule = scheduler

    def execute(self, task, high_priority: bool = False) -> bool:
        with self._lock:
            if self._stopped:
                return False
            (self._high_tasks if high_priority else self._tasks).append(task)
            if self._running:
                return True
            self._running = True
        self._schedule(self._consume)
        return True

    def _consume(self):
        while True:
            with self._lock:
                batch = []
                while self._high_tasks and len(batch) < self._batch_size:
                    batch.append(self._high_tasks.popleft())
                while self._tasks and len(batch) < self._batch_size:
                    batch.append(self._tasks.popleft())
                stopped = self._stopped
                if not batch and not stopped:
                    self._running = False
                    return
            # Tasks accepted before stop() are still drained; only the final,
            # empty iteration reports is_queue_stopped (execution_queue.h
            # stop semantics).
            rc = 0
            try:
                rc = self._execute_fn(
                    TaskIterator(batch, stopped and not batch)
                ) or 0
            except Exception:
                import logging

                logging.getLogger(__name__).exception(
                    "execution queue consumer raised"
                )
            if (stopped and not batch) or rc < 0:
                with self._lock:
                    self._stopped = True
                    self._running = False
                self._joined.set()
                return

    def stop(self):
        """No new tasks; consumer gets one final stopped-iterator run."""
        with self._lock:
            self._stopped = True
            if self._running:
                return
            self._running = True
        self._schedule(self._consume)

    def join(self, timeout: Optional[float] = None) -> bool:
        return self._joined.wait(timeout)


def execution_queue_start(execute_fn, **kw) -> ExecutionQueue:
    return ExecutionQueue(execute_fn, **kw)
