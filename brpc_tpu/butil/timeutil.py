"""Time helpers — counterpart of butil/time.h.

cpuwide_time_ns maps to the fastest monotonic source available; the native
core uses rdtsc-calibrated time the way the reference does.
"""
from __future__ import annotations

import time


def cpuwide_time_ns() -> int:
    return time.monotonic_ns()


def cpuwide_time_us() -> int:
    return time.monotonic_ns() // 1000


def gettimeofday_us() -> int:
    return time.time_ns() // 1000


def monotonic_time_ns() -> int:
    return time.monotonic_ns()


class Timer:
    """Scoped stopwatch (butil::Timer)."""

    __slots__ = ("_start", "_stop")

    def __init__(self):
        self._start = 0
        self._stop = 0

    def start(self):
        self._start = time.monotonic_ns()
        self._stop = self._start

    def stop(self):
        self._stop = time.monotonic_ns()

    def n_elapsed(self) -> int:
        return self._stop - self._start

    def u_elapsed(self) -> int:
        return self.n_elapsed() // 1000

    def m_elapsed(self) -> int:
        return self.n_elapsed() // 1_000_000
