"""recordio — length-prefixed record stream file format.

Counterpart of butil::recordio (/root/reference/src/butil/recordio.h), the
format rpc_dump writes and rpc_replay consumes. Record = magic "RIO1" +
u32 meta_len + u32 payload_len + crc32(meta+payload) + meta + payload.
Meta is a small JSON header (service/method/log_id); payload is the
serialized request.
"""
from __future__ import annotations

import json
import struct
import zlib
from typing import Iterator, Optional, Tuple

MAGIC = b"RIO1"
_HEADER = struct.Struct(">4sIII")


class RecordWriter:
    def __init__(self, path: str):
        self._f = open(path, "ab")

    def write(self, meta: dict, payload: bytes) -> None:
        meta_bytes = json.dumps(meta).encode()
        crc = zlib.crc32(meta_bytes + payload) & 0xFFFFFFFF
        self._f.write(_HEADER.pack(MAGIC, len(meta_bytes), len(payload), crc))
        self._f.write(meta_bytes)
        self._f.write(payload)

    def flush(self):
        self._f.flush()

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RecordReader:
    def __init__(self, path: str):
        self._f = open(path, "rb")

    def read(self) -> Optional[Tuple[dict, bytes]]:
        header = self._f.read(_HEADER.size)
        if len(header) < _HEADER.size:
            return None
        magic, meta_len, payload_len, crc = _HEADER.unpack(header)
        if magic != MAGIC:
            raise ValueError("corrupt recordio stream: bad magic")
        meta_bytes = self._f.read(meta_len)
        payload = self._f.read(payload_len)
        if len(meta_bytes) < meta_len or len(payload) < payload_len:
            return None  # truncated tail
        if zlib.crc32(meta_bytes + payload) & 0xFFFFFFFF != crc:
            raise ValueError("corrupt recordio record: crc mismatch")
        return json.loads(meta_bytes), payload

    def __iter__(self) -> Iterator[Tuple[dict, bytes]]:
        while True:
            rec = self.read()
            if rec is None:
                return
            yield rec

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
