"""flags — gflags-style runtime configuration registry.

The reference configures everything through gflags with live reloading
(reloadable_flags.h:38-42) surfaced at /flags (builtin/flags_service) and
mirrored into bvars (bvar/gflag.h). This module is the same capability:
define typed flags, validate on set, edit live (the builtin console's /flags
endpoint writes through set_flag).
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional


class Flag:
    __slots__ = ("name", "value", "default", "help", "type", "validator", "reloadable")

    def __init__(self, name, value, help_, type_, validator, reloadable):
        self.name = name
        self.value = value
        self.default = value
        self.help = help_
        self.type = type_
        self.validator = validator
        self.reloadable = reloadable


_registry: Dict[str, Flag] = {}
_lock = threading.Lock()


def _define(name: str, default: Any, help_: str, type_: type,
            validator: Optional[Callable[[Any], bool]] = None,
            reloadable: bool = True) -> Flag:
    with _lock:
        if name in _registry:
            raise ValueError(f"flag {name!r} already defined")
        # Environment override: BRPC_TPU_<NAME>. Invalid or
        # validator-rejected values fall back to the default — an env var
        # must not be able to violate a flag's invariants.
        env = os.environ.get("BRPC_TPU_" + name.upper())
        value = default
        if env is not None:
            try:
                parsed = _parse(env, type_)
            except ValueError:
                import logging

                logging.getLogger(__name__).warning(
                    "ignoring unparsable env override for flag %s: %r", name, env
                )
            else:
                if validator is None or validator(parsed):
                    value = parsed
        f = Flag(name, value, help_, type_, validator, reloadable)
        _registry[name] = f
        return f


def _parse(text: str, type_: type) -> Any:
    if type_ is bool:
        return text.lower() in ("1", "true", "yes", "on")
    return type_(text)


def define_int(name: str, default: int, help_: str = "", **kw) -> Flag:
    return _define(name, int(default), help_, int, **kw)


def define_float(name: str, default: float, help_: str = "", **kw) -> Flag:
    return _define(name, float(default), help_, float, **kw)


def define_bool(name: str, default: bool, help_: str = "", **kw) -> Flag:
    return _define(name, bool(default), help_, bool, **kw)


def define_string(name: str, default: str, help_: str = "", **kw) -> Flag:
    return _define(name, str(default), help_, str, **kw)


def get_flag(name: str) -> Any:
    return _registry[name].value


def flag(name: str) -> Flag:
    return _registry[name]


def set_flag(name: str, value: Any) -> bool:
    """Live update (the /flags web editor path). Returns False if the flag is
    unknown, not reloadable, or fails validation."""
    with _lock:
        f = _registry.get(name)
        if f is None or not f.reloadable:
            return False
        if isinstance(value, str):
            try:
                value = _parse(value, f.type)
            except ValueError:
                return False
        if f.validator is not None and not f.validator(value):
            return False
        f.value = value
        return True


def all_flags() -> Dict[str, Flag]:
    with _lock:
        return dict(_registry)
