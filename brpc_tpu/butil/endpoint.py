"""EndPoint — addressable location of a peer.

Counterpart of butil::EndPoint (/root/reference/src/butil/endpoint.h) — an
(ip, port) value type — extended TPU-first with optional device coordinates
(pod, slice, chip, core), so one address type names both DCN peers (host
TCP) and ICI peers (chips inside a pod slice), the way the survey's build
plan calls for (SURVEY.md section 7 stage 1).
"""
from __future__ import annotations

import re
import socket
from dataclasses import dataclass, field
from typing import Optional, Tuple


@dataclass(frozen=True, order=True)
class DeviceCoord:
    """Position of a TPU chip: (pod, slice, chip, core)."""

    pod: int = 0
    slice: int = 0
    chip: int = 0
    core: int = 0

    def __str__(self) -> str:
        return f"tpu:{self.pod}.{self.slice}.{self.chip}.{self.core}"


_ENDPOINT_RE = re.compile(
    r"^(?P<host>[^:]+|\[[0-9a-fA-F:]+\]):(?P<port>\d+)"
    r"(?:/tpu:(?P<pod>\d+)\.(?P<slc>\d+)\.(?P<chip>\d+)\.(?P<core>\d+))?$"
)


@dataclass(frozen=True, order=True)
class EndPoint:
    ip: str = "0.0.0.0"
    port: int = 0
    device: Optional[DeviceCoord] = field(default=None, compare=False)

    @classmethod
    def parse(cls, text: str) -> "EndPoint":
        """Parse 'ip:port' or 'ip:port/tpu:p.s.c.r' forms.

        Mirrors str2endpoint (/root/reference/src/butil/endpoint.h) with the
        device-coordinate extension.
        """
        m = _ENDPOINT_RE.match(text.strip())
        if not m:
            raise ValueError(f"invalid endpoint: {text!r}")
        host = m.group("host").strip("[]")
        port = int(m.group("port"))
        if not 0 <= port <= 65535:
            raise ValueError(f"port out of range: {port}")
        dev = None
        if m.group("pod") is not None:
            dev = DeviceCoord(
                int(m.group("pod")),
                int(m.group("slc")),
                int(m.group("chip")),
                int(m.group("core")),
            )
        return cls(host, port, dev)

    @classmethod
    def of_device(cls, coord: DeviceCoord, port: int = 0) -> "EndPoint":
        """An ICI-only endpoint (no routable host ip)."""
        return cls("0.0.0.0", port, coord)

    def with_device(self, coord: DeviceCoord) -> "EndPoint":
        return EndPoint(self.ip, self.port, coord)

    def resolve(self) -> "EndPoint":
        """Resolve a hostname to an IPv4 address (hostname2endpoint)."""
        try:
            socket.inet_aton(self.ip)
            return self
        except OSError:
            ip = socket.gethostbyname(self.ip)
            return EndPoint(ip, self.port, self.device)

    def as_sockaddr(self) -> Tuple[str, int]:
        return (self.ip, self.port)

    def is_ici(self) -> bool:
        return self.device is not None

    def __str__(self) -> str:
        host = f"[{self.ip}]" if ":" in self.ip else self.ip
        base = f"{host}:{self.port}"
        if self.device is not None:
            return f"{base}/{self.device}"
        return base
