"""ObjectPool / ResourcePool — typed slab pools with versioned-id addressing.

Counterparts of butil::ObjectPool (/root/reference/src/butil/object_pool.h:27)
and butil::ResourcePool (resource_pool.h). ResourcePool hands out dense ids
enabling the id<->pointer trick behind SocketId / bthread_t / CallId: an id
can outlive the object because Address() checks a version stamped into the id
(the use-after-free-proofing pattern of socket_inl.h:28-78).

Ids are 64-bit: (version << 32) | slot_index. A slot's version bumps by 2 on
each recycle (even=free parity kept), so a stale id never addresses a new
occupant.
"""
from __future__ import annotations

import threading
from typing import Callable, Generic, List, Optional, TypeVar

T = TypeVar("T")

INVALID_RESOURCE_ID = 0xFFFFFFFFFFFFFFFF


class ObjectPool(Generic[T]):
    """Freelist pool: get/return objects, constructing on miss."""

    def __init__(self, factory: Callable[[], T], max_free: int = 4096):
        self._factory = factory
        self._free: List[T] = []
        self._max_free = max_free
        self._lock = threading.Lock()
        self._created = 0

    def get(self) -> T:
        with self._lock:
            if self._free:
                return self._free.pop()
            self._created += 1
        return self._factory()

    def put(self, obj: T):
        with self._lock:
            if len(self._free) < self._max_free:
                self._free.append(obj)

    def free_count(self) -> int:
        return len(self._free)

    def created_count(self) -> int:
        return self._created


class _Slot(Generic[T]):
    __slots__ = ("obj", "version")

    def __init__(self):
        self.obj: Optional[T] = None
        self.version = 0  # even = free, odd = in use


class ResourcePool(Generic[T]):
    """Slot pool addressed by versioned 64-bit ids."""

    def __init__(self, factory: Callable[[], T]):
        self._factory = factory
        self._slots: List[_Slot[T]] = []
        self._free_slots: List[int] = []
        self._lock = threading.Lock()

    def get_resource(self) -> "tuple[int, T]":
        """Returns (resource_id, object)."""
        with self._lock:
            if self._free_slots:
                idx = self._free_slots.pop()
                slot = self._slots[idx]
            else:
                idx = len(self._slots)
                slot = _Slot()
                self._slots.append(slot)
            slot.version += 1  # even -> odd: now in use
            if slot.obj is None:
                slot.obj = self._factory()
            rid = (slot.version << 32) | idx
            return rid, slot.obj

    def address(self, rid: int) -> Optional[T]:
        """Validated id->object lookup: None if the id is stale
        (socket_inl.h:28-185 Address())."""
        if rid == INVALID_RESOURCE_ID:
            return None
        idx = rid & 0xFFFFFFFF
        version = rid >> 32
        if idx >= len(self._slots):
            return None
        slot = self._slots[idx]
        if slot.version != version or (version & 1) == 0:
            return None
        return slot.obj

    def return_resource(self, rid: int) -> bool:
        idx = rid & 0xFFFFFFFF
        version = rid >> 32
        with self._lock:
            if idx >= len(self._slots):
                return False
            slot = self._slots[idx]
            if slot.version != version or (version & 1) == 0:
                return False
            slot.version += 1  # odd -> even: free; stale ids now fail
            slot.obj = None
            self._free_slots.append(idx)
            return True

    def size(self) -> int:
        return len(self._slots)
