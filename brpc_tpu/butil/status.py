"""Status — error code + message value type.

Counterpart of butil::Status (/root/reference/src/butil/status.h): a cheap
(code, text) pair where code 0 means OK, used as the return type of fallible
framework calls instead of exceptions on hot paths.
"""
from __future__ import annotations


class Status:
    __slots__ = ("code", "text")

    OK_CODE = 0

    def __init__(self, code: int = 0, text: str = ""):
        self.code = code
        self.text = text

    @classmethod
    def ok(cls) -> "Status":
        return cls(0, "")

    @classmethod
    def error(cls, code: int, text: str) -> "Status":
        if code == 0:
            raise ValueError("error status must have nonzero code")
        return cls(code, text)

    def is_ok(self) -> bool:
        return self.code == 0

    def __bool__(self) -> bool:
        return self.is_ok()

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Status)
            and self.code == other.code
            and self.text == other.text
        )

    def __repr__(self) -> str:
        if self.is_ok():
            return "Status.OK"
        return f"Status({self.code}, {self.text!r})"


# Canonical framework error codes, mirroring brpc's errno extensions
# (/root/reference/src/brpc/errno.proto): negative codes are framework-level.
ENOSERVICE = 1001  # service not found
ENOMETHOD = 1002  # method not found
EREQUEST = 1003  # bad request
ERPCAUTH = 1004  # authentication failed
ETOOMANYFAILS = 1005  # too many sub-channel failures (ParallelChannel)
EBACKUPREQUEST = 1007  # backup request fired
ERPCTIMEDOUT = 1008  # RPC deadline exceeded
EFAILEDSOCKET = 1009  # connection broken during RPC
EHTTP = 1010  # HTTP-level error
EOVERCROWDED = 1011  # too many buffered writes
ERTMPPUBLISHABLE = 1012
ERTMPCREATESTREAM = 1013
EEOF = 1014  # stream EOF
EUNUSED = 1015
ESSL = 1016
EINTERNAL = 2001  # framework internal error
ERESPONSE = 2002  # bad response
ELOGOFF = 2003  # server is logging off (graceful stop)
ELIMIT = 2004  # concurrency limit reached
ECLOSE = 2005  # close socket initiatively
EITP = 2006
ECANCELED = 2007  # RPC canceled by caller
