"""butil misc containers + utilities.

Counterparts of the remaining §2.1 base pieces
(/root/reference/src/butil/): FlatMap (containers/flat_map.h +
flat_map_inl.h), fast_rand (fast_rand.cpp), crc32c (crc32c.cc),
RawPacker/RawUnpacker (raw_pack.h), ThreadLocal (thread_local.h).
"""
from __future__ import annotations

import random
import struct
import threading
from typing import Callable, Generic, Iterator, Optional, Tuple, TypeVar

K = TypeVar("K")
V = TypeVar("V")


class _Node:
    __slots__ = ("key", "value", "next")

    def __init__(self, key, value, next_=None):
        self.key = key
        self.value = value
        self.next = next_


class FlatMap(Generic[K, V]):
    """The reference's "one-level hashing" map (flat_map_inl.h:342-530): a
    bucket array whose slots EMBED the first entry, with collisions
    chained off the embedded node; a resize doubles buckets whenever
    size*100 >= nbucket*load_factor (flat_map.h:279-281). Most lookups hit
    the embedded slot directly — the cache-friendliness the reference
    builds the structure for."""

    def __init__(self, nbucket: int = 32, load_factor: int = 80):
        self._nbucket = max(1, nbucket)
        self._load_factor = load_factor
        self._buckets: list = [None] * self._nbucket
        self._size = 0

    def init(self, nbucket: int, load_factor: int = 80) -> bool:
        if self._size:
            return False  # init only before use, as the reference
        self._nbucket = max(1, nbucket)
        self._load_factor = load_factor
        self._buckets = [None] * self._nbucket
        return True

    def _index(self, key) -> int:
        return hash(key) % self._nbucket  # flatmap_mod (flat_map_inl.h:72)

    def _maybe_resize(self):
        if (self._size + 1) * 100 >= self._nbucket * self._load_factor:
            self.resize(self._nbucket * 2)

    def resize(self, nbucket: int) -> bool:
        old = self._buckets
        self._nbucket = max(1, nbucket)
        self._buckets = [None] * self._nbucket
        for node in old:
            while node is not None:
                nxt = node.next
                idx = self._index(node.key)
                node.next = self._buckets[idx]
                self._buckets[idx] = node
                node = nxt
        return True

    def _find_node(self, key) -> Optional[_Node]:
        node = self._buckets[self._index(key)]
        while node is not None:
            if node.key == key:
                return node
            node = node.next
        return None

    def insert(self, key: K, value: V) -> V:
        node = self._find_node(key)
        if node is not None:
            node.value = value
            return value
        self._maybe_resize()
        idx = self._index(key)
        self._buckets[idx] = _Node(key, value, self._buckets[idx])
        self._size += 1
        return value

    def seek(self, key: K) -> Optional[V]:
        node = self._find_node(key)
        return node.value if node is not None else None

    def __getitem__(self, key: K) -> V:
        """operator[]: inserts default None if missing (flat_map semantic
        is default-construct; here: None)."""
        node = self._find_node(key)
        if node is not None:
            return node.value
        self._maybe_resize()
        idx = self._index(key)
        self._buckets[idx] = _Node(key, None, self._buckets[idx])
        self._size += 1
        return None

    def __setitem__(self, key: K, value: V):
        self.insert(key, value)

    def erase(self, key: K) -> int:
        idx = self._index(key)
        node = self._buckets[idx]
        prev = None
        while node is not None:
            if node.key == key:
                if prev is None:
                    self._buckets[idx] = node.next
                else:
                    prev.next = node.next
                self._size -= 1
                return 1
            prev, node = node, node.next
        return 0

    def __len__(self) -> int:
        return self._size

    def __contains__(self, key: K) -> bool:
        return self._find_node(key) is not None

    def empty(self) -> bool:
        return self._size == 0

    def clear(self):
        self._buckets = [None] * self._nbucket
        self._size = 0

    @property
    def nbucket(self) -> int:
        return self._nbucket

    @property
    def load_factor(self) -> int:
        return self._load_factor

    def __iter__(self) -> Iterator[Tuple[K, V]]:
        for node in self._buckets:
            while node is not None:
                yield node.key, node.value
                node = node.next


_MISSING = object()


# -- fast_rand (fast_rand.cpp) ----------------------------------------------

_tls_rand = threading.local()


def _rng() -> random.Random:
    r = getattr(_tls_rand, "r", None)
    if r is None:
        r = random.Random()
        _tls_rand.r = r
    return r


def fast_rand() -> int:
    """64-bit thread-local PRNG draw."""
    return _rng().getrandbits(64)


def fast_rand_less_than(bound: int) -> int:
    return _rng().randrange(bound) if bound > 0 else 0


def fast_rand_in(lo: int, hi: int) -> int:
    return _rng().randint(lo, hi)


def fast_rand_double() -> float:
    return _rng().random()


# -- crc32c (crc32c.cc, Castagnoli polynomial) -------------------------------

_CRC32C_POLY = 0x82F63B78
_crc32c_table = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ _CRC32C_POLY if _c & 1 else _c >> 1
    _crc32c_table.append(_c)


def crc32c(data: bytes, init: int = 0) -> int:
    crc = init ^ 0xFFFFFFFF
    table = _crc32c_table
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# -- RawPacker / RawUnpacker (raw_pack.h) ------------------------------------

class RawPacker:
    """Sequential big-endian scalar packing."""

    def __init__(self):
        self._parts = []

    def pack32(self, v: int) -> "RawPacker":
        self._parts.append(struct.pack(">I", v & 0xFFFFFFFF))
        return self

    def pack64(self, v: int) -> "RawPacker":
        self._parts.append(struct.pack(">Q", v & 0xFFFFFFFFFFFFFFFF))
        return self

    def bytes(self) -> bytes:
        return b"".join(self._parts)


class RawUnpacker:
    def __init__(self, data: bytes):
        self._data = data
        self._pos = 0

    def unpack32(self) -> int:
        (v,) = struct.unpack_from(">I", self._data, self._pos)
        self._pos += 4
        return v

    def unpack64(self) -> int:
        (v,) = struct.unpack_from(">Q", self._data, self._pos)
        self._pos += 8
        return v


# -- ThreadLocal (thread_local.h) --------------------------------------------

class ThreadLocal(Generic[V]):
    """Per-thread lazily-constructed object."""

    def __init__(self, factory: Callable[[], V]):
        self._factory = factory
        self._tls = threading.local()

    def get(self) -> V:
        v = getattr(self._tls, "v", _MISSING)
        if v is _MISSING:
            v = self._factory()
            self._tls.v = v
        return v

    def reset(self, value: V):
        self._tls.v = value
