"""butil misc containers + utilities.

Counterparts of the remaining §2.1 base pieces
(/root/reference/src/butil/): FlatMap (containers/flat_map.h:110-132),
fast_rand (fast_rand.cpp), crc32c (crc32c.cc), RawPacker/RawUnpacker
(raw_pack.h), ThreadLocal (thread_local.h). CPython's dict is already an
open-addressing hash table, so FlatMap keeps the reference's API
(seek/insert/erase/init) over it rather than re-probing by hand —
idiomatic, same capability.
"""
from __future__ import annotations

import random
import struct
import threading
from typing import Callable, Generic, Iterator, Optional, Tuple, TypeVar

K = TypeVar("K")
V = TypeVar("V")


class FlatMap(Generic[K, V]):
    """flat_map.h API surface over a native hash map."""

    def __init__(self, nbucket: int = 32):
        self._map: dict = {}
        self._nbucket = nbucket  # kept for API parity; dict self-sizes

    def init(self, nbucket: int) -> bool:
        self._nbucket = nbucket
        return True

    def insert(self, key: K, value: V) -> V:
        self._map[key] = value
        return value

    def seek(self, key: K) -> Optional[V]:
        return self._map.get(key)

    def __getitem__(self, key: K) -> V:
        """operator[]: inserts default None if missing (flat_map semantic is
        default-construct; here: None)."""
        return self._map.setdefault(key, None)

    def __setitem__(self, key: K, value: V):
        self._map[key] = value

    def erase(self, key: K) -> int:
        return 1 if self._map.pop(key, _MISSING) is not _MISSING else 0

    def __len__(self) -> int:
        return len(self._map)

    def __contains__(self, key: K) -> bool:
        return key in self._map

    def empty(self) -> bool:
        return not self._map

    def clear(self):
        self._map.clear()

    def __iter__(self) -> Iterator[Tuple[K, V]]:
        return iter(self._map.items())


_MISSING = object()


# -- fast_rand (fast_rand.cpp) ----------------------------------------------

_tls_rand = threading.local()


def _rng() -> random.Random:
    r = getattr(_tls_rand, "r", None)
    if r is None:
        r = random.Random()
        _tls_rand.r = r
    return r


def fast_rand() -> int:
    """64-bit thread-local PRNG draw."""
    return _rng().getrandbits(64)


def fast_rand_less_than(bound: int) -> int:
    return _rng().randrange(bound) if bound > 0 else 0


def fast_rand_in(lo: int, hi: int) -> int:
    return _rng().randint(lo, hi)


def fast_rand_double() -> float:
    return _rng().random()


# -- crc32c (crc32c.cc, Castagnoli polynomial) -------------------------------

_CRC32C_POLY = 0x82F63B78
_crc32c_table = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ _CRC32C_POLY if _c & 1 else _c >> 1
    _crc32c_table.append(_c)


def crc32c(data: bytes, init: int = 0) -> int:
    crc = init ^ 0xFFFFFFFF
    table = _crc32c_table
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# -- RawPacker / RawUnpacker (raw_pack.h) ------------------------------------

class RawPacker:
    """Sequential big-endian scalar packing."""

    def __init__(self):
        self._parts = []

    def pack32(self, v: int) -> "RawPacker":
        self._parts.append(struct.pack(">I", v & 0xFFFFFFFF))
        return self

    def pack64(self, v: int) -> "RawPacker":
        self._parts.append(struct.pack(">Q", v & 0xFFFFFFFFFFFFFFFF))
        return self

    def bytes(self) -> bytes:
        return b"".join(self._parts)


class RawUnpacker:
    def __init__(self, data: bytes):
        self._data = data
        self._pos = 0

    def unpack32(self) -> int:
        (v,) = struct.unpack_from(">I", self._data, self._pos)
        self._pos += 4
        return v

    def unpack64(self) -> int:
        (v,) = struct.unpack_from(">Q", self._data, self._pos)
        self._pos += 8
        return v


# -- ThreadLocal (thread_local.h) --------------------------------------------

class ThreadLocal(Generic[V]):
    """Per-thread lazily-constructed object."""

    def __init__(self, factory: Callable[[], V]):
        self._factory = factory
        self._tls = threading.local()

    def get(self) -> V:
        v = getattr(self._tls, "v", _MISSING)
        if v is _MISSING:
            v = self._factory()
            self._tls.v = v
        return v

    def reset(self, value: V):
        self._tls.v = value
