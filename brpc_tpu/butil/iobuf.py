"""IOBuf — non-contiguous, zero-copy buffer.

Counterpart of butil::IOBuf (/root/reference/src/butil/iobuf.h:64): a chain
of refcounted blocks viewed through (block, offset, length) refs
(iobuf.h:77-104). cut/append move refs, never bytes (iobuf.h:141-214).

TPU-first redesign rather than a port:

* Blocks come from a pluggable arena (iobuf.cpp:163-168 blockmem_allocate is
  the seam brpc's RDMA pool uses). Here the arena abstraction has three
  kinds: host bytearray blocks, user-memory blocks wrapping arbitrary
  buffers with a deleter + 64-bit meta (iobuf.h:257-266 — the meta carried
  the RDMA lkey; here it carries a device-buffer handle), and DEVICE blocks
  that wrap a jax.Array living in TPU HBM. Device payloads ride the chain
  untouched; only a wire boundary (TCP serialization) materializes them,
  while the ICI transport hands the device buffer straight to XLA.
* A per-thread block cache mirrors share_tls_block (iobuf.cpp:323-445).
"""
from __future__ import annotations

import os
import socket
import threading
from collections import deque
from typing import Callable, Iterable, List, Optional, Tuple, Union

DEFAULT_BLOCK_SIZE = 8192  # iobuf.h:70 — 8KB default payload per block
errno_EAGAIN = 11

_tls = threading.local()


class Block:
    """A refcounted contiguous chunk. `data` is writable (bytearray)."""

    __slots__ = ("data", "size", "capacity", "kind", "deleter", "meta",
                 "device_array", "__weakref__")

    HOST = 0
    USER = 1  # wraps caller-owned memory, freed via deleter
    DEVICE = 2  # wraps a jax.Array in HBM

    def __init__(self, capacity: int = DEFAULT_BLOCK_SIZE):
        self.data = bytearray(capacity)
        self.size = 0  # filled prefix
        self.capacity = capacity
        self.kind = Block.HOST
        self.deleter: Optional[Callable] = None
        self.meta = 0
        self.device_array = None

    @classmethod
    def user_block(cls, mem, deleter: Optional[Callable] = None, meta: int = 0) -> "Block":
        b = cls.__new__(cls)
        b.data = mem
        b.size = len(mem)
        b.capacity = len(mem)
        b.kind = Block.USER
        b.deleter = deleter
        b.meta = meta
        b.device_array = None
        return b

    @classmethod
    def device_block(cls, array, meta: int = 0) -> "Block":
        """Wrap a jax.Array (HBM-resident). Zero-copy until a host wire
        boundary forces materialization."""
        b = cls.__new__(cls)
        b.data = None
        b.size = int(array.nbytes)
        b.capacity = b.size
        b.kind = Block.DEVICE
        b.deleter = None
        b.meta = meta
        b.device_array = array
        return b

    def left_space(self) -> int:
        return self.capacity - self.size

    def materialize(self) -> Union[bytes, bytearray, memoryview]:
        """Host view of the block's bytes (device blocks: one device→host
        copy, cached)."""
        if self.kind == Block.DEVICE:
            if self.data is None:
                import numpy as np

                self.data = np.asarray(self.device_array).tobytes()
            return self.data
        return self.data

    def release(self):
        if self.deleter is not None:
            self.deleter(self.data)
            self.deleter = None


def _tls_block_cache() -> List[Block]:
    cache = getattr(_tls, "blocks", None)
    if cache is None:
        cache = []
        _tls.blocks = cache
    return cache


# The blockmem_allocate seam (iobuf.cpp:163-168): a pluggable factory for
# fresh blocks. brpc's RDMA pool points this at ibv_reg_mr'd arenas so all
# IOBuf memory is transfer-registered; here the device transport points it
# at a shared pinned-host arena (HostArena) so payload bytes are staged in
# memory a cross-process peer can map directly. Returns None to fall back
# to plain host blocks (arena exhausted).
_block_allocator: Optional[Callable[[], Optional[Block]]] = None
_alloc_gen = 0  # bumped on every allocator switch; stamps TLS caches


def set_block_allocator(alloc: Optional[Callable[[], Optional[Block]]]):
    global _block_allocator, _alloc_gen
    _block_allocator = alloc
    # Generation bump invalidates EVERY thread's cached pre-switch blocks
    # (each thread checks its stamp on next use), not just this thread's.
    _alloc_gen += 1


def _new_block() -> Block:
    if _block_allocator is not None:
        b = _block_allocator()
        if b is not None:
            return b
    return Block()


def share_tls_block() -> Block:
    """Grab a thread-cached block with free space (iobuf.cpp:323-445)."""
    if getattr(_tls, "alloc_gen", None) != _alloc_gen:
        _tls.blocks = []
        _tls.alloc_gen = _alloc_gen
    cache = _tls_block_cache()
    while cache:
        b = cache[-1]
        if b.left_space() > 0:
            return b
        cache.pop()
    b = _new_block()
    cache.append(b)
    return b


def release_tls_blocks():
    _tls_block_cache().clear()


class BlockRef:
    """View of [offset, offset+length) inside one Block (iobuf.h:77-104)."""

    __slots__ = ("block", "offset", "length")

    def __init__(self, block: Block, offset: int, length: int):
        self.block = block
        self.offset = offset
        self.length = length

    def view(self) -> memoryview:
        data = self.block.materialize()
        return memoryview(data)[self.offset : self.offset + self.length]


_Appendable = Union[bytes, bytearray, memoryview, str, "IOBuf"]


class IOBuf:
    """Chain of BlockRefs. All structural ops are O(#refs), zero-copy."""

    __slots__ = ("_refs", "_length")

    def __init__(self, data: Optional[_Appendable] = None):
        self._refs: "deque[BlockRef]" = deque()
        self._length = 0
        if data is not None:
            self.append(data)

    # -- size / state ------------------------------------------------------
    def __len__(self) -> int:
        return self._length

    @property
    def length(self) -> int:
        return self._length

    def empty(self) -> bool:
        return self._length == 0

    def backing_block_count(self) -> int:
        return len(self._refs)

    def clear(self):
        self._refs.clear()
        self._length = 0

    # -- append ------------------------------------------------------------
    def append(self, data: _Appendable):
        if isinstance(data, IOBuf):
            # Zero-copy: share the refs (blocks are shared, not copied),
            # mirroring IOBuf::append(const IOBuf&) (iobuf.h:141).
            self._refs.extend(
                BlockRef(r.block, r.offset, r.length) for r in data._refs
            )
            self._length += data._length
            return
        if isinstance(data, str):
            data = data.encode()
        n = len(data)
        if n == 0:
            return
        mv = memoryview(data)
        pos = 0
        while pos < n:
            b = share_tls_block()
            take = min(n - pos, b.left_space())
            b.data[b.size : b.size + take] = mv[pos : pos + take]
            ref = BlockRef(b, b.size, take)
            b.size += take
            self._append_ref(ref)
            pos += take

    def append_user_data(
        self, mem, deleter: Optional[Callable] = None, meta: int = 0
    ):
        """Zero-copy append of caller-owned memory (iobuf.h:257-266). `meta`
        travels with the block — the slot where brpc's RDMA path rode the
        lkey; here it can carry a device buffer handle."""
        b = Block.user_block(mem, deleter, meta)
        self._append_ref(BlockRef(b, 0, b.size))

    def append_device_array(self, array, meta: int = 0):
        """Zero-copy append of a jax.Array living in HBM."""
        b = Block.device_block(array, meta)
        self._append_ref(BlockRef(b, 0, b.size))

    def _append_ref(self, ref: BlockRef):
        if ref.length == 0:
            return
        # Merge with tail if it is the contiguous continuation in the same
        # block (keeps ref count low for appender-style writes).
        if self._refs:
            tail = self._refs[-1]
            if (
                tail.block is ref.block
                and tail.offset + tail.length == ref.offset
            ):
                tail.length += ref.length
                self._length += ref.length
                return
        self._refs.append(ref)
        self._length += ref.length

    # -- cut (zero-copy pop from front) ------------------------------------
    def cut(self, n: int) -> "IOBuf":
        """Move first n bytes into a new IOBuf without copying
        (iobuf.h:141-214 cutn)."""
        out = IOBuf()
        self.cut_into(out, n)
        return out

    def cut_into(self, out: "IOBuf", n: int) -> int:
        n = min(n, self._length)
        remain = n
        while remain > 0:
            r = self._refs[0]
            if r.length <= remain:
                out._append_ref(BlockRef(r.block, r.offset, r.length))
                self._refs.popleft()
                self._length -= r.length
                remain -= r.length
            else:
                out._append_ref(BlockRef(r.block, r.offset, remain))
                r.offset += remain
                r.length -= remain
                self._length -= remain
                remain = 0
        return n

    def cutn_bytes(self, n: int) -> bytes:
        """Copy out and remove the first n bytes."""
        return self.cut(n).to_bytes()

    def pop_front(self, n: int) -> int:
        n = min(n, self._length)
        remain = n
        while remain > 0:
            r = self._refs[0]
            if r.length <= remain:
                self._refs.popleft()
                remain -= r.length
                self._length -= r.length
            else:
                r.offset += remain
                r.length -= remain
                self._length -= remain
                remain = 0
        return n

    def pop_back(self, n: int) -> int:
        n = min(n, self._length)
        remain = n
        while remain > 0:
            r = self._refs[-1]
            if r.length <= remain:
                self._refs.pop()
                remain -= r.length
                self._length -= r.length
            else:
                r.length -= remain
                self._length -= remain
                remain = 0
        return n

    # -- read (copy out, non-destructive) ----------------------------------
    def copy_to_bytes(self, n: Optional[int] = None, pos: int = 0) -> bytes:
        if n is None:
            n = self._length - pos
        n = max(0, min(n, self._length - pos))
        out = bytearray(n)
        wrote = 0
        skip = pos
        for r in self._refs:
            if wrote >= n:
                break
            if skip >= r.length:
                skip -= r.length
                continue
            take = min(r.length - skip, n - wrote)
            v = r.view()
            out[wrote : wrote + take] = v[skip : skip + take]
            wrote += take
            skip = 0
        return bytes(out)

    def to_bytes(self) -> bytes:
        if len(self._refs) == 1:
            return bytes(self._refs[0].view())
        return self.copy_to_bytes()

    def device_arrays(self) -> List:
        """The HBM-resident payloads riding this chain, in order."""
        return [
            r.block.device_array
            for r in self._refs
            if r.block.kind == Block.DEVICE
        ]

    def iter_views(self) -> Iterable[memoryview]:
        for r in self._refs:
            yield r.view()

    # -- fd I/O ------------------------------------------------------------
    def cut_into_file_descriptor(self, fd: int, max_bytes: Optional[int] = None) -> int:
        """Scatter-gather write of the front of the chain (iobuf.h:159-208)."""
        limit = self._length if max_bytes is None else min(max_bytes, self._length)
        views, got = [], 0
        for r in self._refs:
            if got >= limit or len(views) >= 64:  # IOV_MAX-ish
                break
            take = min(r.length, limit - got)
            v = r.view()
            views.append(v[:take] if take < r.length else v)
            got += take
        if not views:
            return 0
        nw = os.writev(fd, views)
        if nw > 0:
            self.pop_front(nw)
        return nw

    def cut_into_socket(self, sock: socket.socket, max_bytes: Optional[int] = None) -> int:
        import ssl as _ssl

        if isinstance(sock, _ssl.SSLSocket):
            # TLS records can't scatter-gather raw fds; send one view at a
            # time through the SSL layer (iobuf.h:159-208 SSL write path).
            if self._length == 0:
                return 0
            view = self._refs[0].view()
            if max_bytes is not None:
                view = view[:max_bytes]
            try:
                n = sock.send(view)
            except _ssl.SSLWantWriteError:
                raise BlockingIOError(errno_EAGAIN, "ssl want write")
            if n > 0:
                self.pop_front(n)
            return n
        return self.cut_into_file_descriptor(sock.fileno(), max_bytes)

    def __eq__(self, other) -> bool:
        if isinstance(other, IOBuf):
            return self._length == other._length and self.to_bytes() == other.to_bytes()
        if isinstance(other, (bytes, bytearray)):
            return self._length == len(other) and self.to_bytes() == bytes(other)
        return NotImplemented

    def __repr__(self) -> str:
        head = self.copy_to_bytes(min(16, self._length))
        return f"IOBuf(len={self._length}, refs={len(self._refs)}, head={head!r})"


class IOPortal(IOBuf):
    """IOBuf that reads from fds, keeping partially-filled tail blocks
    (iobuf.h:455-497)."""

    __slots__ = ()

    def append_from_file_descriptor(self, fd: int, max_bytes: int = 65536) -> int:
        got = 0
        while got < max_bytes:
            b = share_tls_block()
            want = min(b.left_space(), max_bytes - got)
            try:
                data = os.read(fd, want)
            except BlockingIOError:
                if got == 0:
                    raise  # no data at all: would-block, NOT EOF
                break
            if not data:
                if got == 0:
                    return 0  # EOF
                break
            n = len(data)
            b.data[b.size : b.size + n] = data
            self._append_ref(BlockRef(b, b.size, n))
            b.size += n
            got += n
            if n < want:
                break
        return got

    def append_from_socket(self, sock: socket.socket, max_bytes: int = 65536) -> int:
        import ssl as _ssl

        if isinstance(sock, _ssl.SSLSocket):
            got = 0
            while got < max_bytes:
                b = share_tls_block()
                want = min(b.left_space(), max_bytes - got)
                try:
                    data = sock.recv(want)
                except _ssl.SSLWantReadError:
                    if got == 0:
                        raise BlockingIOError(errno_EAGAIN, "ssl want read")
                    break
                if not data:
                    if got == 0:
                        return 0  # EOF
                    break
                n = len(data)
                b.data[b.size : b.size + n] = data
                self._append_ref(BlockRef(b, b.size, n))
                b.size += n
                got += n
                if n < want:
                    break
            return got
        return self.append_from_file_descriptor(sock.fileno(), max_bytes)


class IOBufAppender:
    """Fast sequential writer holding the current tail block
    (iobuf.h:678)."""

    __slots__ = ("buf",)

    def __init__(self):
        self.buf = IOBuf()

    def append(self, data: _Appendable):
        self.buf.append(data)

    def push_back(self, byte: int):
        self.buf.append(bytes([byte]))

    def take(self) -> IOBuf:
        out = self.buf
        self.buf = IOBuf()
        return out


class IOBufCutter:
    """Fast front-parser (iobuf.h:503): sequential cutn/peek over an IOBuf."""

    __slots__ = ("_buf",)

    def __init__(self, buf: IOBuf):
        self._buf = buf

    def remaining(self) -> int:
        return len(self._buf)

    def peek_bytes(self, n: int) -> bytes:
        return self._buf.copy_to_bytes(n)

    def cutn(self, n: int) -> bytes:
        if len(self._buf) < n:
            raise EOFError(f"need {n} bytes, have {len(self._buf)}")
        return self._buf.cutn_bytes(n)

    def cut_uint32_be(self) -> int:
        return int.from_bytes(self.cutn(4), "big")

    def cut_uint64_be(self) -> int:
        return int.from_bytes(self.cutn(8), "big")
