"""butil — base library (Python surface of the native core).

Python counterparts of /root/reference/src/butil: IOBuf (iobuf.h:64),
ObjectPool/ResourcePool (object_pool.h:27, resource_pool.h),
DoublyBufferedData (containers/doubly_buffered_data.h:38), EndPoint
(endpoint.h), Status (status.h), flags (gflags usage throughout).

The C++ native core (native/src/butil_*) is the performance path; these
Python classes are the veneer used by the pure-Python RPC surface and by
tests, with identical semantics.
"""

from brpc_tpu.butil.status import Status  # noqa: F401
from brpc_tpu.butil.endpoint import EndPoint  # noqa: F401
from brpc_tpu.butil.iobuf import IOBuf, IOBufAppender, IOPortal  # noqa: F401
from brpc_tpu.butil.pools import ObjectPool, ResourcePool, INVALID_RESOURCE_ID  # noqa: F401
from brpc_tpu.butil.dbd import DoublyBufferedData  # noqa: F401
