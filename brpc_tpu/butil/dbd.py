"""DoublyBufferedData — read-mostly data with near-lock-free reads.

Counterpart of butil::DoublyBufferedData
(/root/reference/src/butil/containers/doubly_buffered_data.h:38-67): readers
grab a per-thread mutex (uncontended in steady state) and read the foreground
copy; Modify() applies the mutation to the background copy, flips fg/bg, then
serially acquires every reader mutex to make sure no reader still sees the
old foreground, and applies the mutation again. Backbone of load-balancer
server lists (load_balancer.h:72).
"""
from __future__ import annotations

import threading
from typing import Callable, Generic, List, TypeVar

T = TypeVar("T")


class _ReaderTls:
    __slots__ = ("lock",)

    def __init__(self):
        self.lock = threading.Lock()


class DoublyBufferedData(Generic[T]):
    def __init__(self, factory: Callable[[], T]):
        self._data: List[T] = [factory(), factory()]
        self._fg_index = 0
        self._modify_lock = threading.Lock()
        self._readers_lock = threading.Lock()
        self._readers: List[_ReaderTls] = []
        self._tls = threading.local()

    def _reader(self) -> _ReaderTls:
        r = getattr(self._tls, "r", None)
        if r is None:
            r = _ReaderTls()
            self._tls.r = r
            with self._readers_lock:
                self._readers.append(r)
        return r

    class _ScopedPtr(Generic[T]):
        __slots__ = ("data", "_lock")

        def __init__(self, data: T, lock: threading.Lock):
            self.data = data
            self._lock = lock

        def __enter__(self) -> T:
            return self.data

        def __exit__(self, *exc):
            self._lock.release()
            return False

    def read(self) -> "DoublyBufferedData._ScopedPtr[T]":
        """Usage: `with dbd.read() as value: ...` — holds only this thread's
        own mutex, so concurrent readers never contend with each other."""
        r = self._reader()
        r.lock.acquire()
        return self._ScopedPtr(self._data[self._fg_index], r.lock)

    def modify(self, fn: Callable[[T], object]):
        """Apply fn to both copies with a fg/bg flip in between. fn must be
        deterministic w.r.t. the copy it receives."""
        with self._modify_lock:
            bg = 1 - self._fg_index
            fn(self._data[bg])
            self._fg_index = bg  # new readers now see the modified copy
            # Wait out readers of the old foreground: acquiring each reader
            # mutex once proves no reader holds a reference to it.
            with self._readers_lock:
                readers = list(self._readers)
            for r in readers:
                r.lock.acquire()
                r.lock.release()
            fn(self._data[1 - bg])
