"""Ring attention — sequence-parallel attention via ICI neighbor exchange.

Long-context attention with the sequence sharded over a mesh axis: K/V
shards rotate around the ring with lax.ppermute while each device
accumulates its queries' attention online (flash-attention style
log-sum-exp rescaling), so peak memory is O(T_local) and all communication
is neighbor-to-neighbor over ICI.

This is the tensor-stream analog of the reference's streaming RPC + combo
channels (SURVEY.md section 5 "long-context" row): the ring is a
PartitionChannel over the sequence dimension whose transport is XLA
ppermute instead of sockets.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _block_attend(q, k, v, m, l, o, mask):
    """One online-softmax accumulation step.

    q: [B, Tq, H, Dh]; k, v: [B, Tk, H, Dh]
    m, l: [B, H, Tq] running max / normalizer; o: [B, Tq, H, Dh]
    mask: [Tq, Tk] additive mask (0 or -inf) or None.
    """
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale  # [B,H,Tq,Tk]
    if mask is not None:
        s = s + mask[None, None, :, :]
    m_new = jnp.maximum(m, s.max(axis=-1))
    # exp of fully-masked rows: m stays at _NEG_INF, guard the subtraction.
    p = jnp.exp(s - m_new[..., None])
    correction = jnp.exp(m - m_new)
    l_new = l * correction + p.sum(axis=-1)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p, v
    )
    return m_new, l_new, o_new


def ring_attention(q, k, v, axis_name: str, causal: bool = True):
    """Attention over a sequence sharded along `axis_name`.

    q, k, v: [B, T_local, H, Dh] — this device's sequence shard.
    Device i holds tokens [i*T_local, (i+1)*T_local). Must run inside
    shard_map with `axis_name` in scope. Differentiable (ppermute has a
    transpose rule), so the same code path serves fwd+bwd.
    """
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, T, H, Dh = q.shape

    m0 = jnp.full((B, H, T), _NEG_INF, dtype=q.dtype)
    l0 = jnp.zeros((B, H, T), dtype=q.dtype)
    o0 = jnp.zeros_like(q)

    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    iota = lax.broadcasted_iota(jnp.int32, (T, T), 0)
    iota_t = lax.broadcasted_iota(jnp.int32, (T, T), 1)

    def step(carry, i):
        m, l, o, k_cur, v_cur = carry
        src_idx = (my_idx - i) % axis_size  # origin of the held K/V shard
        if causal:
            # src block fully in the past -> no mask; same block -> lower
            # triangular; future block -> fully masked.
            tri = jnp.where(iota >= iota_t, 0.0, _NEG_INF).astype(q.dtype)
            full = jnp.zeros((T, T), q.dtype)
            none = jnp.full((T, T), _NEG_INF, q.dtype)
            mask = jnp.where(
                src_idx < my_idx, full, jnp.where(src_idx == my_idx, tri, none)
            )
        else:
            mask = None
        m, l, o = _block_attend(q, k_cur, v_cur, m, l, o, mask)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (m, l, o, k_nxt, v_nxt), None

    (m, l, o, _, _), _ = lax.scan(
        step, (m0, l0, o0, k, v), jnp.arange(axis_size)
    )
    # Fully-masked rows have l == 0; emit zeros there.
    l = jnp.where(l == 0.0, 1.0, l)
    return o / l.transpose(0, 2, 1)[..., None]


def local_attention(q, k, v, causal: bool = True):
    """Single-device reference path (ring of size 1) used by forward_local
    and by tests as the ground truth for ring_attention."""
    B, T, H, Dh = q.shape
    m = jnp.full((B, H, T), _NEG_INF, dtype=q.dtype)
    l = jnp.zeros((B, H, T), dtype=q.dtype)
    o = jnp.zeros_like(q)
    mask = None
    if causal:
        iota = lax.broadcasted_iota(jnp.int32, (T, T), 0)
        iota_t = lax.broadcasted_iota(jnp.int32, (T, T), 1)
        mask = jnp.where(iota >= iota_t, 0.0, _NEG_INF).astype(q.dtype)
    m, l, o = _block_attend(q, k, v, m, l, o, mask)
    l = jnp.where(l == 0.0, 1.0, l)
    return o / l.transpose(0, 2, 1)[..., None]
