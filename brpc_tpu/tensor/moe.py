"""Mixture-of-Experts with expert parallelism over an `ep` mesh axis.

Top-1 (switch) routing with static capacity, dense one-hot dispatch/combine
einsums (MXU-friendly — no gathers/scatters with dynamic shapes), and an
all_to_all shuffle along the `ep` axis so each device runs only its local
expert shard. This is the TPU-native DynamicPartitionChannel
(/root/reference/src/brpc/partition_channel.h:136-142): requests (tokens)
are routed to partitions (experts) whose capacity differs, over a collective
transport instead of per-partition sockets.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class MoEParams(NamedTuple):
    router: jax.Array  # [D, E]
    w_in: jax.Array  # [E, D, F]
    w_out: jax.Array  # [E, F, D]


def init_moe(key, d_model: int, d_ff: int, n_experts: int, dtype) -> MoEParams:
    k1, k2, k3 = jax.random.split(key, 3)
    scale_in = 1.0 / jnp.sqrt(d_model)
    scale_out = 1.0 / jnp.sqrt(d_ff)
    return MoEParams(
        router=(jax.random.normal(k1, (d_model, n_experts)) * scale_in).astype(dtype),
        w_in=(jax.random.normal(k2, (n_experts, d_model, d_ff)) * scale_in).astype(dtype),
        w_out=(jax.random.normal(k3, (n_experts, d_ff, d_model)) * scale_out).astype(dtype),
    )


def _route(x, router, n_experts: int, capacity: int):
    """Top-1 routing -> (dispatch [N,E,C] one-hot, combine [N,E,C] weighted)."""
    logits = jnp.einsum("nd,de->ne", x, router)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)  # [N]
    gate = jnp.max(probs, axis=-1)  # [N]
    onehot = jax.nn.one_hot(expert, n_experts, dtype=jnp.float32)  # [N,E]
    # Position of each token within its expert's capacity buffer.
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0  # [N,E], -1 if unrouted
    in_cap = (pos >= 0) & (pos < capacity)
    pos_cap = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
    cap_onehot = jax.nn.one_hot(pos_cap, capacity, dtype=jnp.float32)  # [N,E,C]
    dispatch = cap_onehot * in_cap[..., None]  # [N,E,C]
    combine = dispatch * gate[:, None, None]
    return dispatch, combine


def moe_layer(
    params: MoEParams,
    x,  # [N, D] local tokens (flattened batch*seq shard)
    *,
    n_experts: int,
    capacity_factor: float = 2.0,
    ep_axis: str | None = None,
):
    """Run the MoE. With ep_axis inside shard_map, params.w_in/w_out hold
    only the local expert shard [E/ep, D, F] and tokens shuttle via
    all_to_all; without ep_axis all experts are local (single-chip path).
    """
    N, D = x.shape
    dtype = x.dtype
    capacity = max(1, int(capacity_factor * N / n_experts))
    dispatch, combine = _route(x, params.router, n_experts, capacity)
    dispatch = dispatch.astype(dtype)
    combine = combine.astype(dtype)
    # Dense dispatch: [E, C, D] expert input buffers.
    buf = jnp.einsum("nec,nd->ecd", dispatch, x)

    if ep_axis is not None:
        ep = lax.psum(1, ep_axis)
        e_local = n_experts // ep
        assert e_local * ep == n_experts, "n_experts must divide by ep size"
        # [E, C, D] -> [ep, E_local, C, D]; all_to_all swaps the ep dim with
        # the (implicit) device dim: afterwards device j holds, for each of
        # its local experts, the C-slots contributed by every peer.
        buf = buf.reshape(ep, e_local, capacity, D)
        buf = lax.all_to_all(buf, ep_axis, split_axis=0, concat_axis=0, tiled=False)
        # buf: [ep, E_local, C, D] -- first dim now indexes source peer.
        buf = buf.transpose(1, 0, 2, 3).reshape(e_local, ep * capacity, D)
        y = jnp.einsum("ecd,edf->ecf", buf, params.w_in)
        y = jax.nn.gelu(y)
        y = jnp.einsum("ecf,efd->ecd", y, params.w_out)
        y = y.reshape(e_local, ep, capacity, D).transpose(1, 0, 2, 3)
        y = lax.all_to_all(y, ep_axis, split_axis=0, concat_axis=0, tiled=False)
        # y: [ep, E_local, C, D] -- dim0 = expert shard; global expert id is
        # shard * E_local + local, matching the dispatch layout.
        y = y.reshape(n_experts, capacity, D)
    else:
        y = jnp.einsum("ecd,edf->ecf", buf, params.w_in)
        y = jax.nn.gelu(y)
        y = jnp.einsum("ecf,efd->ecd", y, params.w_out)

    # Combine back to token order: [N, D].
    return jnp.einsum("nec,ecd->nd", combine, y)
