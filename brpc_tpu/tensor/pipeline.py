"""SPMD pipeline parallelism over a `pp` mesh axis.

GPipe-style schedule expressed as one SPMD program: every pipeline stage
runs the same lax.scan; microbatch activations hop stage-to-stage with
lax.ppermute (neighbor ICI transfers). This is the TPU-native cascade /
streaming-stage pattern of the reference (SURVEY.md section 2.12 "Pipelining
(PP-like)": cascade_echo + streaming RPC + async calls).

All control flow is static (scan over M + S - 1 ticks with where-guards), so
XLA sees a fixed communication schedule it can overlap with compute.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def spmd_pipeline(
    stage_fn: Callable,
    stage_params,
    microbatches,  # [M, ...mb_shape] — replicated along the pp axis
    axis_name: str,
):
    """Run `stage_fn(stage_params, x_mb)` as a pipeline over `axis_name`.

    Each device holds its own stage's params (stage_params is pp-sharded by
    the caller's shard_map in_specs). Returns the last stage's outputs
    [M, ...mb_shape], broadcast to every stage via a masked psum so callers
    on any stage can compute the loss. Differentiable end-to-end (ppermute
    and the where-guards have transpose rules).
    """
    n_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    m = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    n_ticks = m + n_stages - 1
    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

    outputs0 = jnp.zeros((m,) + mb_shape, microbatches.dtype)
    recv0 = jnp.zeros(mb_shape, microbatches.dtype)

    def tick(carry, t):
        recv, outputs = carry
        # Stage 0 feeds from the microbatch queue; later stages consume what
        # the previous stage sent last tick.
        feed_idx = jnp.clip(t, 0, m - 1)
        feed = lax.dynamic_index_in_dim(microbatches, feed_idx, 0, keepdims=False)
        x_in = jnp.where(stage == 0, feed, recv)
        y = stage_fn(stage_params, x_in)
        # Last stage commits microbatch (t - (S-1)) when it is in range.
        out_idx = t - (n_stages - 1)
        valid = jnp.logical_and(stage == n_stages - 1,
                                jnp.logical_and(out_idx >= 0, out_idx < m))
        committed = lax.dynamic_update_index_in_dim(
            outputs, y, jnp.clip(out_idx, 0, m - 1), 0
        )
        outputs = jnp.where(valid, committed, outputs)
        recv = lax.ppermute(y, axis_name, perm)
        return (recv, outputs), None

    (_, outputs), _ = lax.scan(tick, (recv0, outputs0), jnp.arange(n_ticks))
    # Only the last stage holds real outputs; zero-mask + psum broadcasts
    # them to every stage (the reference's "response returns to caller").
    outputs = jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs))
    return lax.psum(outputs, axis_name)
