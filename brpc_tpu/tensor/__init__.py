"""tensor — the tensor-transport compute layer.

The reference is an RPC framework, so its "models" are its transport users
(SURVEY.md section 2.12 maps ParallelChannel/PartitionChannel/streaming onto
collective patterns). This package is the TPU-native realization of that
mapping: ring neighbor-exchange attention for sequence/context parallelism
(the streaming-RPC analog, stream.cpp:458-586), expert-parallel MoE via
all_to_all (DynamicPartitionChannel, partition_channel.h:136), and an SPMD
pipeline via ppermute (the cascade_echo staging pattern), composed into a
flagship transformer used by __graft_entry__ and bench.
"""

from brpc_tpu.tensor.config import ModelConfig  # noqa: F401
from brpc_tpu.tensor.model import (  # noqa: F401
    init_params,
    forward_local,
    make_spmd_forward,
    make_spmd_train_step,
)
