"""Model / mesh configuration for the flagship tensor-transport model."""
from __future__ import annotations

import dataclasses
from typing import Tuple


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 4
    d_head: int = 32
    d_ff: int = 256
    n_layers: int = 2  # layers PER pipeline stage
    n_experts: int = 4
    expert_capacity_factor: float = 2.0
    dtype: str = "bfloat16"

    @property
    def d_qkv(self) -> int:
        return self.n_heads * self.d_head


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical mesh axes. Sizes multiply to the device count.

    dp: data (batch) replication of params / sharding of batch
    pp: pipeline stages
    tp: tensor (megatron) sharding of heads / ffn
    sp: sequence (context) sharding — ring attention axis
    ep: expert sharding — MoE all_to_all axis
    """

    dp: int = 1
    pp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1

    AXIS_NAMES: Tuple[str, ...] = ("dp", "pp", "tp", "sp", "ep")

    @property
    def shape(self):
        return {"dp": self.dp, "pp": self.pp, "tp": self.tp, "sp": self.sp, "ep": self.ep}

    @property
    def n_devices(self) -> int:
        return self.dp * self.pp * self.tp * self.sp * self.ep

    @classmethod
    def factorize(cls, n: int) -> "MeshSpec":
        """Spread n devices over axes, preferring tp, pp, dp, then sp, ep —
        all five axes exist (size>=1) so every collective path executes."""
        sizes = {"dp": 1, "pp": 1, "tp": 1, "sp": 1, "ep": 1}
        order = ["tp", "pp", "dp", "sp", "ep"]
        i = 0
        while n % 2 == 0 and n > 1:
            sizes[order[i % len(order)]] *= 2
            n //= 2
            i += 1
        sizes["dp"] *= n  # odd remainder rides dp
        return cls(**sizes)
