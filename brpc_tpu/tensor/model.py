"""Flagship model: a 5D-parallel transformer LM built on the tensor layer.

Composes the transport primitives into the model used by __graft_entry__ and
the TPU benches:

  dp — batch sharding, gradient merge by psum (the ParallelChannel +
       ResponseMerger mapping, SURVEY.md section 2.12)
  pp — spmd_pipeline over stages (cascade/streaming)
  tp — megatron head/ffn sharding with identity-fwd/psum-bwd boundaries
  sp — ring attention over the sequence (long-context first-class)
  ep — expert-parallel MoE via all_to_all

Everything is pure JAX under jit: static shapes, lax.scan for layer loops,
collectives only via named mesh axes inside shard_map.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from brpc_tpu.jaxcompat import shard_map as compat_shard_map
from brpc_tpu.tensor.config import MeshSpec, ModelConfig
from brpc_tpu.tensor.moe import MoEParams, init_moe, moe_layer
from brpc_tpu.tensor.pipeline import spmd_pipeline
from brpc_tpu.tensor.ring_attention import local_attention, ring_attention


class LayerParams(NamedTuple):
    ln1: jax.Array  # [L, D]
    wq: jax.Array  # [L, D, H*Dh]
    wk: jax.Array  # [L, D, H*Dh]
    wv: jax.Array  # [L, D, H*Dh]
    wo: jax.Array  # [L, H*Dh, D]
    ln2: jax.Array  # [L, D]
    moe: MoEParams  # router [L,D,E], w_in [L,E,D,F], w_out [L,E,F,D]


class Params(NamedTuple):
    embed: jax.Array  # [V, D] (tied unembedding)
    layers: LayerParams  # stacked over ALL layers (n_layers * pp)
    final_norm: jax.Array  # [D]


def _dtype(cfg: ModelConfig):
    return jnp.dtype(cfg.dtype)


def init_params(cfg: ModelConfig, key, pp_stages: int = 1) -> Params:
    dt = _dtype(cfg)
    n_total = cfg.n_layers * pp_stages
    keys = jax.random.split(key, 6)
    d, dq = cfg.d_model, cfg.d_qkv

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape) / np.sqrt(fan_in)).astype(dt)

    moe_keys = jax.random.split(keys[5], n_total)
    moes = [init_moe(mk, d, cfg.d_ff, cfg.n_experts, dt) for mk in moe_keys]
    moe = MoEParams(*(jnp.stack(t) for t in zip(*moes)))
    return Params(
        embed=dense(keys[0], (cfg.vocab, d), d),
        layers=LayerParams(
            ln1=jnp.ones((n_total, d), dt),
            wq=dense(keys[1], (n_total, d, dq), d),
            wk=dense(keys[2], (n_total, d, dq), d),
            wv=dense(keys[3], (n_total, d, dq), d),
            wo=dense(keys[4], (n_total, dq, d), dq),
            ln2=jnp.ones((n_total, d), dt),
            moe=moe,
        ),
        final_norm=jnp.ones((d,), dt),
    )


def params_pspecs(cfg: ModelConfig) -> Params:
    """PartitionSpecs: pp shards the stacked layer dim, tp the head dims, ep
    the expert dim; embed/final_norm replicated."""
    return Params(
        embed=P(None, None),
        layers=LayerParams(
            ln1=P("pp", None),
            wq=P("pp", None, "tp"),
            wk=P("pp", None, "tp"),
            wv=P("pp", None, "tp"),
            wo=P("pp", "tp", None),
            ln2=P("pp", None),
            moe=MoEParams(
                router=P("pp", None, None),
                w_in=P("pp", "ep", None, None),
                w_out=P("pp", "ep", None, None),
            ),
        ),
        final_norm=P(None),
    )


def _rmsnorm(x, scale):
    xf = x.astype(jnp.float32)
    rms = jnp.sqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + 1e-6)
    return ((xf / rms) * scale.astype(jnp.float32)).astype(x.dtype)


def _identity_fwd_psum_bwd(axis_name):
    """Megatron 'f': activations replicated fwd; cotangent psum'd bwd so
    replicated-weight grads stay identical across the tp group."""

    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, g):
        return (lax.psum(g, axis_name),)

    f.defvjp(fwd, bwd)
    return f


def _psum_fwd_identity_bwd(axis_name):
    """Megatron 'g': partial outputs summed fwd; cotangent passes through."""

    @jax.custom_vjp
    def g(x):
        return lax.psum(x, axis_name)

    def fwd(x):
        return lax.psum(x, axis_name), None

    def bwd(_, gr):
        return (gr,)

    g.defvjp(fwd, bwd)
    return g


def _layer(
    x,  # [B, T, D] local activation shard
    lp,  # one layer's params (local shards)
    cfg: ModelConfig,
    tp_axis: Optional[str],
    sp_axis: Optional[str],
    ep_axis: Optional[str],
    n_heads_local: int,
):
    B, T, D = x.shape
    h = _rmsnorm(x, lp.ln1)
    if tp_axis is not None:
        h = _identity_fwd_psum_bwd(tp_axis)(h)
    q = (h @ lp.wq).reshape(B, T, n_heads_local, cfg.d_head)
    k = (h @ lp.wk).reshape(B, T, n_heads_local, cfg.d_head)
    v = (h @ lp.wv).reshape(B, T, n_heads_local, cfg.d_head)
    if sp_axis is not None:
        attn = ring_attention(q, k, v, sp_axis, causal=True)
    else:
        attn = local_attention(q, k, v, causal=True)
    y = attn.reshape(B, T, n_heads_local * cfg.d_head) @ lp.wo
    if tp_axis is not None:
        y = _psum_fwd_identity_bwd(tp_axis)(y)
    x = x + y

    h2 = _rmsnorm(x, lp.ln2)
    m = moe_layer(
        lp.moe,
        h2.reshape(B * T, D),
        n_experts=cfg.n_experts,
        capacity_factor=cfg.expert_capacity_factor,
        ep_axis=ep_axis,
    )
    return x + m.reshape(B, T, D)


def _stack_scan(layers: LayerParams, x, layer_fn):
    """Run the stacked layers with lax.scan (static unrolled graph size 1)."""

    def body(carry, lp):
        return layer_fn(carry, lp), None

    out, _ = lax.scan(body, x, layers)
    return out


def forward_local(params: Params, tokens, cfg: ModelConfig):
    """Single-device forward (the jittable entry() path): identical math to
    the SPMD path with every mesh axis of size 1."""
    x = jnp.take(params.embed, tokens, axis=0)
    layer_fn = functools.partial(
        _layer,
        cfg=cfg,
        tp_axis=None,
        sp_axis=None,
        ep_axis=None,
        n_heads_local=cfg.n_heads,
    )
    x = _stack_scan(params.layers, x, lambda c, lp: layer_fn(c, lp))
    x = _rmsnorm(x, params.final_norm)
    return (x @ params.embed.T).astype(jnp.float32)


def _loss_from_logits(logits, labels):
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return nll.sum()


def make_mesh(spec: MeshSpec) -> Mesh:
    devs = np.array(jax.devices()[: spec.n_devices]).reshape(
        spec.dp, spec.pp, spec.tp, spec.sp, spec.ep
    )
    return Mesh(devs, MeshSpec.AXIS_NAMES)


def _shard_map(fn, mesh, in_specs, out_specs):
    """shard_map with replication checking off: masked psum broadcasts and
    all_to_all-replicated values are mathematically replicated but opaque to
    the checker."""
    return compat_shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check=False
    )


def make_spmd_forward(cfg: ModelConfig, spec: MeshSpec, n_microbatches: int = 1):
    """Forward over the full 5-axis mesh; returns (mesh, jitted fn)."""
    mesh = make_mesh(spec)
    fwd = _make_spmd_fwd_inner(cfg, spec, n_microbatches)
    mapped = _shard_map(
        fwd,
        mesh,
        in_specs=(params_pspecs(cfg), P("dp", "sp")),
        out_specs=P("dp", "sp", None),
    )
    return mesh, jax.jit(mapped)


def _make_spmd_fwd_inner(cfg: ModelConfig, spec: MeshSpec, n_microbatches: int):
    tp_axis = "tp" if spec.tp > 1 else None
    sp_axis = "sp"  # always ring over sp (size-1 ring degenerates correctly)
    ep_axis = "ep" if spec.ep > 1 else None
    n_heads_local = cfg.n_heads // spec.tp
    assert n_heads_local * spec.tp == cfg.n_heads, "n_heads must divide tp"
    if ep_axis is not None:
        assert cfg.n_experts % spec.ep == 0, "n_experts must divide ep"

    layer_fn = functools.partial(
        _layer,
        cfg=cfg,
        tp_axis=tp_axis,
        sp_axis=sp_axis,
        ep_axis=ep_axis,
        n_heads_local=n_heads_local,
    )

    def stage_fn(stage_layers, x_mb):
        return _stack_scan(stage_layers, x_mb, lambda c, lp: layer_fn(c, lp))

    def fwd(params: Params, tokens):
        B, T = tokens.shape  # local shard: [B/dp, T/sp]
        x = jnp.take(params.embed, tokens, axis=0)
        assert B % n_microbatches == 0, "local batch must divide microbatches"
        mb = B // n_microbatches
        x = x.reshape(n_microbatches, mb, T, cfg.d_model)
        if spec.pp > 1:
            out = spmd_pipeline(stage_fn, params.layers, x, "pp")
        else:
            out = jax.vmap(lambda m: stage_fn(params.layers, m))(x)
        x = out.reshape(B, T, cfg.d_model)
        x = _rmsnorm(x, params.final_norm)
        return (x @ params.embed.T).astype(jnp.float32)

    return fwd


def make_spmd_train_step(
    cfg: ModelConfig,
    spec: MeshSpec,
    n_microbatches: int = 1,
    lr: float = 1e-2,
):
    """Full training step over the 5-axis mesh: fwd, bwd, gradient merge
    (psum over dp+sp; pp for shared leaves), SGD update. Returns
    (mesh, jitted (params, tokens, labels) -> (loss, new_params))."""
    mesh = make_mesh(spec)
    fwd = _make_spmd_fwd_inner(cfg, spec, n_microbatches)
    pspecs = params_pspecs(cfg)

    n_global_tokens_factor = spec.dp * spec.sp  # local count * this = global

    def step(params: Params, tokens, labels):
        def loss_fn(p):
            logits = fwd(p, tokens)
            local = _loss_from_logits(logits, labels)
            total = lax.psum(local, ("dp", "sp"))
            n = tokens.size * n_global_tokens_factor
            return total / n

        loss, grads = jax.value_and_grad(loss_fn)(params)

        def sync(g, spec_leaf):
            g = lax.psum(g, ("dp", "sp"))
            # Leaves not stacked over pp (embed, final_norm) get partial
            # contributions per stage -> reduce over pp too.
            if not (len(spec_leaf) > 0 and spec_leaf[0] == "pp"):
                g = lax.psum(g, "pp")
            return g

        grads = jax.tree.map(
            sync, grads, pspecs, is_leaf=lambda x: isinstance(x, P)
        )
        new_params = jax.tree.map(
            lambda p, g: (p - lr * g.astype(jnp.float32).astype(p.dtype)),
            params,
            grads,
        )
        return loss, new_params

    mapped = _shard_map(
        step,
        mesh,
        in_specs=(pspecs, P("dp", "sp"), P("dp", "sp")),
        out_specs=(P(), pspecs),
    )
    return mesh, jax.jit(mapped)
