"""EventDispatcher — the epoll loop(s) feeding the RPC stack.

Counterpart of brpc::EventDispatcher
(/root/reference/src/brpc/event_dispatcher.{h,cpp},
event_dispatcher_epoll.cpp:249-262): N dedicated loops; readable fds hand
off to their consumer (Socket input event) which runs user work in scheduler
tasks, never on the loop thread; EPOLLOUT waiters register one-shot wakeups
(AddEpollOut) used by connects and KeepWrite.

Registration calls arrive from any thread, so they queue through a self-pipe
(the loop's selector is only touched by the loop thread).
"""
from __future__ import annotations

import os
import selectors
import threading
from typing import Callable, Dict, List, Optional

from brpc_tpu.butil import flags

flags.define_int("event_dispatcher_num", 1,
                 "number of event dispatcher loops (event_dispatcher.cpp:30)")


class EventDispatcher:
    def __init__(self):
        self._selector = selectors.DefaultSelector()
        self._wakeup_r, self._wakeup_w = os.pipe()
        os.set_blocking(self._wakeup_r, False)
        self._selector.register(self._wakeup_r, selectors.EVENT_READ, None)
        self._pending: List = []
        self._pending_lock = threading.Lock()
        self._read_consumers: Dict[int, Callable] = {}
        self._write_consumers: Dict[int, Callable] = {}
        self._suspended: set = set()
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._started_lock = threading.Lock()

    # -- public (thread-safe) ---------------------------------------------
    def add_consumer(self, fd: int, on_readable: Callable[[], None]):
        """Register fd for read events (AddConsumer, event_dispatcher.h:61).
        on_readable() is invoked on the loop thread and must only schedule."""
        self._enqueue(("add_read", fd, on_readable))

    def add_epollout(self, fd: int, on_writable: Callable[[], None]):
        """One-shot writable wakeup (AddEpollOut, event_dispatcher.h:80)."""
        self._enqueue(("add_write", fd, on_writable))

    def remove_consumer(self, fd: int):
        self._enqueue(("remove", fd, None))

    def remove_and_close(self, fd: int, fileobj):
        """Unregister fd and close `fileobj` ON THE LOOP THREAD, in that
        order. Closing on the caller thread races the loop two ways: the
        selector keeps polling a closed fd until the queued remove
        applies (OSError spin), and — worse — a new connection can reuse
        the fd NUMBER, so the stale queued remove then unregisters the
        new socket's consumer (the accept-vs-teardown race the native
        runtime fixes with its deferred listener close). With the close
        deferred behind the unregister on the one thread that touches
        the selector, neither interleaving exists."""
        if self._stop or self._thread is None or not self._thread.is_alive():
            try:
                fileobj.close()
            except OSError:
                pass
            self._read_consumers.pop(fd, None)
            self._write_consumers.pop(fd, None)
            return
        self._enqueue(("remove_close", fd, fileobj))

    def suspend_read(self, fd: int):
        """Stop delivering read events while a reader drains the fd —
        edge-trigger-and-rearm semantics over a level-triggered selector
        (the consumer is re-armed by resume_read)."""
        self._enqueue(("suspend_read", fd, None))

    def resume_read(self, fd: int):
        self._enqueue(("resume_read", fd, None))

    def start(self):
        with self._started_lock:
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="event_dispatcher", daemon=True
                )
                self._thread.start()

    def stop(self):
        self._stop = True
        self._wake()

    def join(self, timeout: float = 2.0):
        if self._thread:
            self._thread.join(timeout)

    # -- internals ---------------------------------------------------------
    def _enqueue(self, op):
        self.start()
        with self._pending_lock:
            self._pending.append(op)
        self._wake()

    def _wake(self):
        try:
            os.write(self._wakeup_w, b"x")
        except OSError:
            pass

    def _apply_pending(self):
        with self._pending_lock:
            ops, self._pending = self._pending, []
        for kind, fd, cb in ops:
            try:
                if kind == "add_read":
                    self._read_consumers[fd] = cb
                    self._suspended.discard(fd)
                    self._reregister(fd)
                elif kind == "suspend_read":
                    if fd in self._read_consumers:
                        self._suspended.add(fd)
                        self._reregister(fd)
                elif kind == "resume_read":
                    if fd in self._read_consumers:
                        self._suspended.discard(fd)
                        self._reregister(fd)
                elif kind == "add_write":
                    self._write_consumers[fd] = cb
                    self._reregister(fd)
                elif kind in ("remove", "remove_close"):
                    self._read_consumers.pop(fd, None)
                    self._write_consumers.pop(fd, None)
                    self._suspended.discard(fd)
                    try:
                        self._selector.unregister(fd)
                    except (KeyError, ValueError, OSError):
                        pass
                    if kind == "remove_close":
                        try:
                            cb.close()  # cb slot carries the file object
                        except OSError:
                            pass
            except (ValueError, OSError):
                # fd already closed — consumer cleanup races are benign
                self._read_consumers.pop(fd, None)
                self._write_consumers.pop(fd, None)

    def _reregister(self, fd: int):
        events = 0
        if fd in self._read_consumers and fd not in self._suspended:
            events |= selectors.EVENT_READ
        if fd in self._write_consumers:
            events |= selectors.EVENT_WRITE
        if events == 0:
            try:
                self._selector.unregister(fd)
            except (KeyError, ValueError, OSError):
                pass
            return
        try:
            self._selector.modify(fd, events, None)
        except KeyError:
            self._selector.register(fd, events, None)

    def _run(self):
        while not self._stop:
            self._apply_pending()
            try:
                events = self._selector.select(timeout=0.5)
            except OSError:
                continue
            for key, mask in events:
                fd = key.fd
                if fd == self._wakeup_r:
                    try:
                        os.read(self._wakeup_r, 4096)
                    except OSError:
                        pass
                    continue
                if mask & selectors.EVENT_WRITE:
                    cb = self._write_consumers.pop(fd, None)
                    if cb is not None:
                        try:
                            self._reregister(fd)
                        except (KeyError, ValueError, OSError):
                            pass
                        try:
                            cb()
                        except Exception:
                            _log_cb_error()
                if mask & selectors.EVENT_READ:
                    cb = self._read_consumers.get(fd)
                    if cb is not None:
                        try:
                            cb()
                        except Exception:
                            _log_cb_error()


def _log_cb_error():
    import logging

    logging.getLogger(__name__).exception("dispatcher consumer raised")


_dispatchers: List[EventDispatcher] = []
_dispatchers_lock = threading.Lock()


def get_global_dispatcher(fd_hint: int = 0) -> EventDispatcher:
    """fd-hashed pick among -event_dispatcher_num loops
    (GetGlobalEventDispatcher, event_dispatcher.cpp)."""
    with _dispatchers_lock:
        if not _dispatchers:
            for _ in range(max(1, flags.get_flag("event_dispatcher_num"))):
                d = EventDispatcher()
                d.start()
                _dispatchers.append(d)
    return _dispatchers[fd_hint % len(_dispatchers)]
