"""tpu_std — the canonical framed protocol (baidu_std's role).

Counterpart of policy/baidu_rpc_protocol.cpp
(/root/reference/src/brpc/policy/baidu_rpc_protocol.cpp:95-137): a 12-byte
header `"TRPC" + body_size + meta_size`, then an RpcMeta protobuf, the
payload, and an attachment whose size rides in the meta. The attachment is
the tensor lane: device payloads are described by meta.tensors so the
receiver can rebuild jax.Arrays (host path materializes bytes; the device
transport hands buffers to XLA directly).

Server path ProcessRpcRequest (:314) and response path SendRpcResponse
(:139) are process_request / the done closure here; client response path
(:565) is process_response.
"""
from __future__ import annotations

import struct
import time

import numpy as np

from brpc_tpu.bthread import id as bthread_id
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.rpc import compress as compress_mod
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.rpc.protocol import (
    InputMessageBase,
    ParseResult,
    Protocol,
    ProtocolType,
    register_protocol,
)
from brpc_tpu.rpc.proto import rpc_meta_pb2

MAGIC = b"TRPC"
HEADER_LEN = 12
MAX_BODY = 512 * 1024 * 1024


class RpcMessage(InputMessageBase):
    __slots__ = ("meta", "payload", "attachment", "is_request")

    def __init__(self, meta, payload: bytes, attachment: IOBuf):
        super().__init__()
        self.meta = meta
        self.payload = payload
        self.attachment = attachment
        self.is_request = meta.HasField("request")


def pack_frame(meta, payload: bytes, attachment: IOBuf) -> IOBuf:
    meta.attachment_size = len(attachment)
    meta_bytes = meta.SerializeToString()
    body_size = len(meta_bytes) + len(payload) + len(attachment)
    out = IOBuf()
    out.append(MAGIC + struct.pack(">II", body_size, len(meta_bytes)))
    out.append(meta_bytes)
    if payload:
        out.append(payload)
    if len(attachment):
        out.append(attachment)  # zero-copy ref share
    return out


def _meta_shutdown_bit(meta_bytes: bytes) -> bool:
    """Lame-duck SHUTDOWN bit: top-level RpcMeta varint field 8 — our
    native servers' graceful-drain signal. The field is not in
    rpc_meta.proto (proto3 drops it silently), so scan the raw bytes
    with a minimal tag walk."""
    i, n = 0, len(meta_bytes)

    def varint(i):
        v = s = 0
        while i < n:
            b = meta_bytes[i]
            i += 1
            v |= (b & 0x7F) << s
            if not b & 0x80:
                return v, i
            s += 7
        return None, i

    while i < n:
        tag, i = varint(i)
        if tag is None:
            return False
        field, wire = tag >> 3, tag & 7
        if field == 8 and wire == 0:
            v, i = varint(i)
            return bool(v)
        if wire == 0:
            v, i = varint(i)
            if v is None:
                return False
        elif wire == 2:
            ln, i = varint(i)
            if ln is None or i + ln > n:
                return False
            i += ln
        elif wire == 1:
            i += 8
        elif wire == 5:
            i += 4
        else:
            return False
    return False


def parse(portal: IOBuf, sock, read_eof: bool, arg) -> ParseResult:
    """ParseRpcMessage analog (baidu_rpc_protocol.cpp:95-137)."""
    if len(portal) < HEADER_LEN:
        head = portal.copy_to_bytes(min(4, len(portal)))
        if MAGIC.startswith(head):
            return ParseResult.not_enough()
        return ParseResult.try_others()
    header = portal.copy_to_bytes(HEADER_LEN)
    if header[:4] != MAGIC:
        return ParseResult.try_others()
    body_size, meta_size = struct.unpack(">II", header[4:12])
    if body_size > MAX_BODY or meta_size > body_size:
        return ParseResult.error_()
    if len(portal) < HEADER_LEN + body_size:
        return ParseResult.not_enough()
    portal.pop_front(HEADER_LEN)
    meta_bytes = portal.cutn_bytes(meta_size)
    meta = rpc_meta_pb2.RpcMeta()
    try:
        meta.ParseFromString(meta_bytes)
    except Exception:
        return ParseResult.error_()
    # Lame-duck signal (graceful server churn): mark the socket draining
    # — LB selection skips it, in-flight RPCs keep completing, and the
    # eventual close is a planned removal. A correlation_id-0 control
    # frame carries no call; rejection frames proceed normally (their
    # cid completes with ELIMIT, which the retry path re-balances).
    if meta.HasField("response") and _meta_shutdown_bit(meta_bytes):
        try:
            sock.mark_lame_duck()
        except AttributeError:
            pass  # shims without the flag (native raw lane)
    att_size = meta.attachment_size
    payload_size = body_size - meta_size - att_size
    if payload_size < 0:
        return ParseResult.error_()
    payload = portal.cutn_bytes(payload_size)
    attachment = portal.cut(att_size)
    return ParseResult.ok(RpcMessage(meta, payload, attachment))


# -- tensor attachment helpers (TPU-native lane) ---------------------------

def attach_arrays(cntl_attachment: IOBuf, meta, arrays):
    """Describe + append device arrays to an attachment."""
    for arr in arrays:
        t = meta.tensors.add()
        t.dtype = str(arr.dtype)
        t.shape.extend(int(d) for d in arr.shape)
        t.nbytes = int(arr.nbytes)
        cntl_attachment.append_device_array(arr)


def extract_arrays(attachment: IOBuf, meta):
    """Rebuild numpy arrays (host path) from a tensor-bearing attachment.
    The device transport overrides this with direct HBM handoff."""
    out = []
    for t in meta.tensors:
        raw = attachment.cutn_bytes(t.nbytes)
        try:
            import ml_dtypes  # bundled with jax: bfloat16 etc.

            dtype = np.dtype(t.dtype) if t.dtype in np.sctypeDict else np.dtype(
                getattr(ml_dtypes, t.dtype)
            )
        except (TypeError, AttributeError, ImportError):
            dtype = np.dtype(t.dtype)
        out.append(np.frombuffer(raw, dtype=dtype).reshape(tuple(t.shape)))
    return out


# -- client side -----------------------------------------------------------

def serialize_request(request, cntl: Controller):
    if request is None:
        return b""
    if isinstance(request, (bytes, bytearray)):
        return bytes(request)
    return request.SerializeToString()


def pack_request(payload: bytes, cntl: Controller, correlation_id: int) -> IOBuf:
    meta = rpc_meta_pb2.RpcMeta()
    service, _, method = cntl._method_full_name.rpartition(".")
    meta.request.service_name = service
    meta.request.method_name = method
    meta.request.log_id = cntl.log_id
    meta.request.trace_id = cntl.trace_id
    meta.request.span_id = cntl.span_id
    if cntl._deadline is not None:
        remain_ms = max(0, int((cntl._deadline - time.monotonic()) * 1000))
        meta.request.timeout_ms = remain_ms
    auth = (cntl._channel.options.auth
            if cntl._channel is not None else None)
    if auth is not None:
        cred = auth.generate_credential(cntl)
        if cred is None:
            raise ValueError("authenticator refused to generate credential")
        meta.request.auth_data = cred
    meta.correlation_id = correlation_id
    meta.compress_type = cntl.compress_type
    if cntl._request_stream is not None:
        meta.stream_id = cntl._request_stream.stream_id
    if cntl._outbound_tensors:
        # Tensor lane: the socket's DeviceEndpoint (or a per-call fallback)
        # fills meta.tensors + attachment (device_transport.py).
        from brpc_tpu.rpc.device_transport import DeviceEndpoint

        ep = (cntl._current_sock.app_state
              if cntl._current_sock is not None else None)
        if not isinstance(ep, DeviceEndpoint):
            ep = DeviceEndpoint()
        ep.prepare_send(cntl._outbound_tensors, meta,
                        cntl.request_attachment)
    payload = compress_mod.compress(payload, cntl.compress_type)
    return pack_frame(meta, payload, cntl.request_attachment)


def process_response(msg: RpcMessage):
    """Client completion (baidu_rpc_protocol.cpp:565): lock the attempt's
    CallId version and hand the controller the response."""
    cid = msg.meta.correlation_id
    try:
        cntl = bthread_id.lock(cid)
    except (KeyError, TimeoutError):
        return  # late/duplicate response for an already-ended RPC
    if not isinstance(cntl, Controller):
        try:
            bthread_id.unlock(cid)
        except Exception:
            pass
        return
    payload = compress_mod.decompress(msg.payload, msg.meta.compress_type)
    cntl._on_response(msg.meta, payload, msg.attachment, msg.socket)


# -- server side -----------------------------------------------------------

def send_rpc_response(sock, correlation_id: int, cntl: Controller,
                      response, attachment: IOBuf):
    """SendRpcResponse analog (baidu_rpc_protocol.cpp:139)."""
    # Handlers may have pre-filled tensors into the response meta.
    meta = cntl._response_meta or rpc_meta_pb2.RpcMeta()
    meta.correlation_id = correlation_id
    meta.response.error_code = cntl.error_code_value
    if cntl.error_code_value:
        meta.response.error_text = cntl.error_text_value
    if cntl._accepted_stream is not None:
        meta.stream_id = cntl._accepted_stream.stream_id
    payload = b""
    if response is not None and not cntl.failed():
        payload = (bytes(response) if isinstance(response, (bytes, bytearray))
                   else response.SerializeToString())
        payload = compress_mod.compress(payload, cntl.compress_type)
    meta.compress_type = cntl.compress_type
    frame = pack_frame(meta, payload, attachment)
    sock.write(frame)
    if cntl.close_connection_flag:
        sock.set_failed(errors.ECLOSE, "close_connection requested")


def process_request(msg: RpcMessage):
    """Server path (ProcessRpcRequest, baidu_rpc_protocol.cpp:314)."""
    server = msg.arg
    meta = msg.meta
    cid = meta.correlation_id
    sock = msg.socket
    cntl = Controller()
    cntl.server = server
    cntl.remote_side = sock.remote_side
    cntl.service_name = meta.request.service_name
    cntl.method_name = meta.request.method_name
    cntl.log_id = meta.request.log_id
    cntl.trace_id = meta.request.trace_id
    cntl.compress_type = meta.compress_type
    cntl.request_attachment = msg.attachment
    cntl._remote_stream_id = meta.stream_id
    cntl._server_socket = sock
    cntl._rpc_meta = meta
    cntl._response_meta = rpc_meta_pb2.RpcMeta()
    cntl.server_start_time = time.monotonic()
    if meta.request.timeout_ms > 0:
        cntl.timeout_ms = meta.request.timeout_ms

    if server is None:
        cntl.set_failed(errors.EINVAL, "no server bound to connection")
        return send_rpc_response(sock, cid, cntl, None, IOBuf())

    if server.auth is not None:
        ok, ctx = False, None
        try:
            ok, ctx = server.auth.verify_credential(
                meta.request.auth_data, sock.remote_side)
        except Exception:
            ok = False
        if not ok:
            cntl.set_failed(errors.EAUTH, "authentication failed")
            return send_rpc_response(sock, cid, cntl, None, IOBuf())
        cntl.auth_context = ctx

    if server.interceptor is not None:
        try:
            ok, code, text = server.interceptor(cntl)
        except Exception as e:
            ok, code, text = False, errors.EINVAL, f"interceptor raised: {e}"
        if not ok:
            cntl.set_failed(code or errors.EPERM, text or "rejected")
            return send_rpc_response(sock, cid, cntl, None, IOBuf())

    entry = server.find_method(cntl.service_name, cntl.method_name)
    if entry is None:
        missing_service = server.find_service(cntl.service_name) is None
        cntl.set_failed(
            errors.ENOSERVICE if missing_service else errors.ENOMETHOD,
            f"unknown {cntl.service_name}.{cntl.method_name}",
        )
        return send_rpc_response(sock, cid, cntl, None, IOBuf())
    service_obj, method_info, method_status = entry

    if not method_status.on_requested():
        cntl.set_failed(errors.ELIMIT, "reached max_concurrency")
        return send_rpc_response(sock, cid, cntl, None, IOBuf())

    request = method_info.request_class()
    try:
        payload = compress_mod.decompress(msg.payload, meta.compress_type)
        if payload:
            request.ParseFromString(payload)
    except Exception as e:
        method_status.on_response(errors.EREQUEST, cntl.server_start_time)
        cntl.set_failed(errors.EREQUEST, f"fail to parse request: {e}")
        return send_rpc_response(sock, cid, cntl, None, IOBuf())

    response = method_info.response_class()
    responded = [False]

    from brpc_tpu import rpcz

    span = rpcz.start_server_span(
        f"{cntl.service_name}.{cntl.method_name}", meta, sock.remote_side)
    cntl.span = span
    if span is not None:
        span.request_size = len(msg.payload)

    if server.session_pool is not None:
        cntl.session_local_data = server.session_pool.borrow()

    def done():
        if responded[0]:
            return
        responded[0] = True
        method_status.on_response(cntl.error_code_value,
                                  cntl.server_start_time)
        if span is not None:
            span.end(cntl.error_code_value)
        if server.session_pool is not None:
            server.session_pool.return_(cntl.session_local_data)
            cntl.session_local_data = None
        send_rpc_response(sock, cid, cntl, response,
                          cntl.response_attachment)

    # The handler owns `done` (may call it asynchronously later); we only
    # respond for it if it raises before responding. Nested client calls
    # made by the handler parent under this span (tls_bls parenting).
    try:
        with rpcz.parent_scope(span):
            method_info.handler(service_obj, cntl, request, response, done)
    except Exception as e:
        if not responded[0]:
            cntl.set_failed(errors.EINVAL, f"method raised: {e}")
            done()


register_protocol(Protocol(
    name="tpu_std",
    type=ProtocolType.TPU_STD,
    parse=parse,
    serialize_request=serialize_request,
    pack_request=pack_request,
    process_request=process_request,
    process_response=process_response,
))
