"""Server — multi-protocol RPC server.

Counterpart of brpc::Server (/root/reference/src/brpc/server.{h,cpp}):
AddService builds the (service, method) map with a MethodStatus per method
(server.cpp:705-719); Start listens, builds one InputMessenger carrying a
handler per enabled protocol (multi-protocol port, server.cpp:576), starts
the Acceptor (StartInternal, server.cpp:750+), registers builtin services
unless disabled (server.cpp:468-563,949), and exposes default process
variables; Stop/Join is graceful (server.h:426-441).
"""
from __future__ import annotations

import socket as pysocket
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple

from brpc_tpu import bvar
from brpc_tpu.bthread import get_task_control
from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.rpc.acceptor import Acceptor
from brpc_tpu.rpc.input_messenger import InputMessenger
from brpc_tpu.rpc.method_status import MethodStatus
from brpc_tpu.rpc.protocol import globally_initialize, list_server_protocols
from brpc_tpu.rpc.service import MethodInfo, Service


@dataclass
class ServerOptions:
    """Mirror of brpc::ServerOptions (server.h:59-285), trimmed to the
    implemented surface."""

    num_threads: int = 8
    max_concurrency: int = 0  # 0 = unlimited; else per-server limiter
    method_max_concurrency: Dict[str, int] = field(default_factory=dict)
    idle_timeout_s: float = -1
    has_builtin_services: bool = True
    auth: Optional[object] = None  # Authenticator
    interceptor: Optional[Callable] = None  # (cntl)->(ok, code, text)
    server_info_name: str = ""
    session_local_data_factory: Optional[Callable] = None
    enabled_protocols: Tuple[str, ...] = ()  # empty = all registered
    # restful.cpp role: "/v1/echo => EchoService.Echo, /v1/x => S.M"
    restful_mappings: str = ""
    # server speaks redis when set (ServerOptions::redis_service role)
    redis_service: Optional[object] = None
    # server speaks memcache binary protocol when set
    memcache_service: Optional[object] = None
    # server speaks framed thrift when set (ThriftService role)
    thrift_service: Optional[object] = None
    # server speaks nshead when set (NsheadService adaptor role)
    nshead_service: Optional[object] = None
    # server speaks mongo wire protocol when set (MongoServiceAdaptor role,
    # mongo_service_adaptor.h:27)
    mongo_service_adaptor: Optional[object] = None
    # server speaks RTMP when set (the RtmpService gate; use
    # rpc.rtmp_protocol.RtmpService() for the publish->play relay hub)
    rtmp_service: Optional[object] = None
    # server speaks esp when set (our extension; reference is client-only)
    esp_service: Optional[object] = None
    # TLS (ServerSSLOptions role): PEM paths; empty = plaintext
    ssl_certfile: str = ""
    ssl_keyfile: str = ""
    # Mount the port on the native C++ runtime (nat_rpc.cpp): accept/epoll/
    # framing/writes run on fibers + native IOBuf; Python services execute
    # on the py lane (usercode_backup_pool discipline). tpu_std and HTTP
    # parse natively; other protocols ride the raw fallback lane to the
    # Python protocol stack. At most ONE native-runtime server may be
    # live per process.
    use_native_runtime: bool = False
    # With use_native_runtime: also register the built-in NATIVE echo
    # usercode (tpu_std EchoService.Echo + HTTP POST /echo) — C++ handlers
    # that shadow same-named Python services, the builtin-native-service
    # discipline of server.cpp:468-563. Bench/diagnostic lanes.
    native_builtin_echo: bool = False
    # With use_native_runtime + redis_service: execute the GET/SET
    # command family against a NATIVE in-memory store (DictRedisService
    # semantics in C++); unknown commands still reach the Python
    # handlers. The store's data lives native-side only.
    native_redis_store: bool = False
    # Usercode WORKER PROCESSES (the reference's N-worker usercode
    # concurrency, server.h:59-285 + usercode_backup_pool.h): with
    # use_native_runtime, kind-3/4 (HTTP/gRPC) dispatch fans out over
    # shm rings to this many Python processes, each with its own GIL.
    # py_worker_factory = "module:function" returning the Service list
    # the workers serve (must be importable in a fresh process).
    py_workers: int = 0
    py_worker_factory: str = ""
    # Graceful shutdown (Server::Stop(timeout)/Join + the
    # graceful_quit_on_sigterm flag of server.cpp): stop() quiesces the
    # native runtime first — stop accepting, lame-duck every connection
    # (h2 GOAWAY, HTTP Connection: close, tpu_std SHUTDOWN bit, RESP
    # close-after-reply), drain admitted work (incl. shm workers) under
    # this deadline with ELIMIT/503 rejections for new arrivals, close
    # sockets only once flushed. <= 0 skips the drain (abrupt stop).
    graceful_shutdown_timeout_ms: int = 5000
    # SIGTERM becomes stop()+join()+exit(0): planned restarts (rolling
    # deploys) drain instead of dropping in-flight work.
    graceful_quit_on_sigterm: bool = False


class Server:
    def __init__(self, options: Optional[ServerOptions] = None):
        self.options = options or ServerOptions()
        self._services: Dict[str, Service] = {}
        # full method map: (service, method) -> (svc obj, MethodInfo, MethodStatus)
        self._methods: Dict[Tuple[str, str], Tuple[Service, MethodInfo, MethodStatus]] = {}
        self._listen_fd: Optional[pysocket.socket] = None
        self._acceptor: Optional[Acceptor] = None
        self._native_mount = None  # NativeRuntimeMount when use_native_runtime
        self._messenger: Optional[InputMessenger] = None
        self.listen_endpoint: Optional[EndPoint] = None
        self._started = False
        self._stopped_event = threading.Event()
        self.start_time = 0.0
        self.interceptor = self.options.interceptor
        self.auth = self.options.auth
        self.redis_service = self.options.redis_service
        self.memcache_service = self.options.memcache_service
        self.thrift_service = self.options.thrift_service
        self.nshead_service = self.options.nshead_service
        self.session_pool = None
        if self.options.session_local_data_factory is not None:
            from brpc_tpu.rpc.data_pools import SimpleDataPool

            self.session_pool = SimpleDataPool(
                self.options.session_local_data_factory)
        self._lock = threading.Lock()
        # restful path -> (service_name, method_name)
        self.restful_map: Dict[str, Tuple[str, str]] = {}
        for part in (self.options.restful_mappings or "").split(","):
            part = part.strip()
            if not part:
                continue
            path, _, target = part.partition("=>")
            service, _, method = target.strip().rpartition(".")
            path = "/" + path.strip().strip("/")
            if service and method:
                self.restful_map[path] = (service, method)

    # -- service registry --------------------------------------------------
    def add_service(self, service: Service) -> int:
        name = service.service_name()
        with self._lock:
            if self._started:
                return -1  # services must be added before Start (server.h)
            if name in self._services:
                return -1
            self._services[name] = service
            from brpc_tpu.rpc.concurrency_limiter import (
                create_concurrency_limiter,
            )

            for mname, minfo in service.methods().items():
                full = f"{name}.{mname}"
                spec = self.options.method_max_concurrency.get(full, 0)
                if not spec:
                    spec = self.options.max_concurrency
                limiter = create_concurrency_limiter(spec) if spec else None
                status = MethodStatus(full, limiter)
                self._methods[(name, mname)] = (service, minfo, status)
        return 0

    def remove_service(self, service: Service) -> int:
        name = service.service_name()
        with self._lock:
            if self._started or name not in self._services:
                return -1
            del self._services[name]
            for key in [k for k in self._methods if k[0] == name]:
                del self._methods[key]
        return 0

    def find_service(self, name: str) -> Optional[Service]:
        return self._services.get(name)

    def find_method(self, service_name: str, method_name: str):
        return self._methods.get((service_name, method_name))

    def method_statuses(self) -> Dict[str, MethodStatus]:
        return {f"{k[0]}.{k[1]}": v[2] for k, v in self._methods.items()}

    @property
    def service_count(self) -> int:
        return len(self._services)

    # -- lifecycle ---------------------------------------------------------
    def start(self, address="127.0.0.1:0") -> int:
        """StartInternal analog (server.cpp:750+). address: 'ip:port',
        EndPoint, or bare port int (0 = ephemeral)."""
        globally_initialize()
        if isinstance(address, int):
            ep = EndPoint("127.0.0.1", address)
        elif isinstance(address, EndPoint):
            ep = address
        else:
            ep = EndPoint.parse(address)
        with self._lock:
            if self._started:
                return -1
            get_task_control(self.options.num_threads)
            if self.options.has_builtin_services:
                from brpc_tpu.builtin import register_builtin_services

                register_builtin_services(self)
            if self.options.use_native_runtime:
                from brpc_tpu.rpc.native_runtime import NativeRuntimeMount

                self._native_mount = NativeRuntimeMount(
                    self, self.options.num_threads)
                try:
                    port = self._native_mount.start(
                        ep.ip, ep.port,
                        native_echo=self.options.native_builtin_echo)
                except Exception:
                    # bind conflict, toolchain missing, or a second native
                    # server (the runtime mounts ONE per process)
                    self._native_mount = None
                    return -1
                self.listen_endpoint = EndPoint(ep.ip, port)
                self._started = True
                self.start_time = time.time()
                if self.options.graceful_quit_on_sigterm:
                    self._install_sigterm_handler()
                bvar.expose_default_variables()
                return 0
            lfd = pysocket.socket(pysocket.AF_INET, pysocket.SOCK_STREAM)
            lfd.setsockopt(pysocket.SOL_SOCKET, pysocket.SO_REUSEADDR, 1)
            try:
                lfd.bind((ep.ip, ep.port))
            except OSError:
                lfd.close()
                return -1
            lfd.listen(1024)
            self.listen_endpoint = EndPoint(ep.ip, lfd.getsockname()[1])
            self._listen_fd = lfd
            protocols = list_server_protocols()
            if self.options.enabled_protocols:
                protocols = [p for p in protocols
                             if p.name in self.options.enabled_protocols]
            self._messenger = InputMessenger(protocols, arg=self)
            ssl_ctx = None
            if self.options.ssl_certfile:
                import ssl as _ssl

                ssl_ctx = _ssl.SSLContext(_ssl.PROTOCOL_TLS_SERVER)
                ssl_ctx.load_cert_chain(self.options.ssl_certfile,
                                        self.options.ssl_keyfile or None)
            self._acceptor = Acceptor(self._messenger, ssl_context=ssl_ctx)
            self._acceptor.start_accept(lfd)
            self._started = True
            self.start_time = time.time()
            if self.options.graceful_quit_on_sigterm:
                self._install_sigterm_handler()
        bvar.expose_default_variables()
        return 0

    def _install_sigterm_handler(self):
        """graceful_quit_on_sigterm (server.cpp's flag): a planned
        restart SIGTERM runs the full quiesce/drain lifecycle, then
        exits 0. Only installable from the main thread; elsewhere the
        embedder owns signal routing."""
        import signal
        import sys

        if threading.current_thread() is not threading.main_thread():
            return

        def _on_sigterm(signum, frame):
            self.stop()
            self.join(5.0)
            sys.exit(0)

        try:
            signal.signal(signal.SIGTERM, _on_sigterm)
        except (ValueError, OSError):
            pass

    def stop(self, graceful: bool = True) -> int:
        """Graceful stop (Server::Stop/Join, server.h:426-441): no new
        connections, lame-duck signaling on live ones, existing RPCs
        drain up to options.graceful_shutdown_timeout_ms, new arrivals
        are rejected on the wire (never reset). graceful=False skips the
        drain (the old abrupt behavior)."""
        with self._lock:
            if not self._started:
                return -1
            self._started = False
        timeout_ms = (self.options.graceful_shutdown_timeout_ms
                      if graceful else 0)
        if getattr(self, "_native_mount", None) is not None:
            self._native_mount.stop(quiesce_timeout_ms=timeout_ms)
            self._native_mount = None
        if self._acceptor is not None:
            self._acceptor.stop_accept()
        self._stopped_event.set()
        return 0

    def join(self, timeout: Optional[float] = None) -> int:
        self._stopped_event.wait(timeout)
        return 0

    def run_until_asked_to_quit(self):
        try:
            while self._started:
                time.sleep(0.5)
        except KeyboardInterrupt:
            self.stop()
            self.join()

    @property
    def is_running(self) -> bool:
        return self._started

    def connection_count(self) -> int:
        return self._acceptor.connection_count() if self._acceptor else 0

    def list_connections(self):
        return self._acceptor.list_connections() if self._acceptor else []
