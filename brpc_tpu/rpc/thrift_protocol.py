"""Thrift wire protocol glue — framed TBinary over the Socket stack
(policy/thrift_protocol.cpp role). Client correlation via thrift seqid
(== the attempt cid's low bits, matched through a per-connection map).
"""
from __future__ import annotations

import struct

from brpc_tpu.bthread import id as bthread_id
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.rpc.protocol import (
    InputMessageBase,
    ParseResult,
    Protocol,
    ProtocolType,
    register_protocol,
)
from brpc_tpu.rpc.thrift import (
    MSG_CALL,
    MSG_EXCEPTION,
    MSG_REPLY,
    ThriftMessage,
    pack_message,
    unpack_message,
)

MAX_FRAME = 64 << 20


class ThriftInputMessage(InputMessageBase):
    __slots__ = ("name", "msg_type", "seqid", "body", "is_request")

    def __init__(self, name, msg_type, seqid, body):
        super().__init__()
        self.name = name
        self.msg_type = msg_type
        self.seqid = seqid
        self.body = body
        self.is_request = msg_type in (MSG_CALL, 4)


def parse(portal: IOBuf, sock, read_eof: bool, arg) -> ParseResult:
    if len(portal) < 8:
        head = portal.copy_to_bytes(min(8, len(portal)))
        # framed thrift: 4-byte length then 0x8001 version
        if len(head) >= 6 and head[4] == 0x80 and head[5] == 0x01:
            return ParseResult.not_enough()
        if len(head) < 6:
            return ParseResult.not_enough() if _maybe(head) else ParseResult.try_others()
        return ParseResult.try_others()
    header = portal.copy_to_bytes(8)
    if not (header[4] == 0x80 and header[5] == 0x01):
        return ParseResult.try_others()
    (length,) = struct.unpack(">I", header[:4])
    if length > MAX_FRAME:
        return ParseResult.error_()
    if len(portal) < 4 + length:
        return ParseResult.not_enough()
    portal.pop_front(4)
    payload = portal.cutn_bytes(length)
    try:
        name, msg_type, seqid, body = unpack_message(payload)
    except (ValueError, EOFError):
        return ParseResult.error_()
    return ParseResult.ok(ThriftInputMessage(name, msg_type, seqid, body))


def _maybe(head: bytes) -> bool:
    # can't rule out framed thrift until we see byte 4/5
    return len(head) <= 4


def serialize_request(request, cntl: Controller):
    if isinstance(request, ThriftMessage):
        cntl._thrift_method = request.method_name
        import pickle

        return pickle.dumps(request.body)  # inter-fn carrier, not the wire
    raise TypeError("thrift channel takes a ThriftMessage")


def pack_request(payload: bytes, cntl: Controller, correlation_id: int) -> IOBuf:
    import pickle

    body = pickle.loads(payload)
    seqid = correlation_id & 0x7FFFFFFF
    sock = cntl._current_sock
    m = getattr(sock, "_thrift_cids", None)
    if m is None:
        m = {}
        sock._thrift_cids = m
    m[seqid] = correlation_id
    return IOBuf(pack_message(cntl._thrift_method, MSG_CALL, seqid, body))


def process_response(msg: ThriftInputMessage):
    sock = msg.socket
    m = getattr(sock, "_thrift_cids", None) or {}
    cid = m.pop(msg.seqid, None)
    if cid is None:
        return
    try:
        cntl = bthread_id.lock(cid)
    except (KeyError, TimeoutError):
        return
    if not isinstance(cntl, Controller):
        try:
            bthread_id.unlock(cid)
        except Exception:
            pass
        return
    if msg.msg_type == MSG_EXCEPTION:
        text = msg.body.get(1, (0, b""))[1]
        if isinstance(text, bytes):
            text = text.decode("utf-8", "replace")
        cntl.set_failed(errors.EREQUEST, f"thrift exception: {text}")
    else:
        resp = cntl._response
        if isinstance(resp, ThriftMessage):
            resp.method_name = msg.name
            resp.body = msg.body
    cntl._end_rpc_locked_or_not(locked=True)


def process_request(msg: ThriftInputMessage):
    from brpc_tpu.rpc.thrift import T_STRING

    server = msg.arg
    service = getattr(server, "thrift_service", None) if server else None
    sock = msg.socket
    if service is None:
        out = pack_message(msg.name, MSG_EXCEPTION, msg.seqid,
                           {1: (T_STRING, b"no thrift service")})
        sock.write(IOBuf(out))
        return
    try:
        result = service.dispatch(msg.name, msg.body)
        out = pack_message(msg.name, MSG_REPLY, msg.seqid, result or {})
    except Exception as e:
        out = pack_message(msg.name, MSG_EXCEPTION, msg.seqid,
                           {1: (T_STRING, str(e).encode())})
    sock.write(IOBuf(out))


register_protocol(Protocol(
    name="thrift",
    type=ProtocolType.THRIFT,
    parse=parse,
    serialize_request=serialize_request,
    pack_request=pack_request,
    process_request=process_request,
    process_response=process_response,
))
