"""Redis wire protocol — counterpart of policy/redis_protocol.cpp
(/root/reference/src/brpc/policy/redis_protocol.cpp): client side sends
RESP command batches through Channel (responses matched in order, like the
reference's pipelined redis connection); server side parses commands and
dispatches to the Server's redis_service (ServerOptions.redis_service),
replying in arrival order (handled inline on the reader to preserve it).
"""
from __future__ import annotations

from collections import deque

from brpc_tpu.bthread import id as bthread_id
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.rpc.protocol import (
    InputMessageBase,
    ParseResult,
    Protocol,
    ProtocolType,
    register_protocol,
)
from brpc_tpu.rpc.redis import (
    RedisRequest,
    RedisResponse,
    parse_reply,
)


class RedisMessage(InputMessageBase):
    __slots__ = ("replies", "commands", "is_request")

    def __init__(self, replies=None, commands=None):
        super().__init__()
        self.replies = replies
        self.commands = commands
        self.is_request = commands is not None


def parse(portal: IOBuf, sock, read_eof: bool, arg) -> ParseResult:
    if portal.empty():
        return ParseResult.not_enough()
    head = portal.copy_to_bytes(1)
    server_side = arg is not None and getattr(arg, "redis_service", None)
    if arg is not None and not server_side:
        # Serving port without a RedisService: don't claim bytes that may
        # belong to a weak-magic protocol behind us (the reference's
        # ParseRedisMessage also bails when redis_service is unset).
        return ParseResult.try_others()
    if head not in (b"*", b"+", b"-", b":", b"$"):
        return ParseResult.try_others()
    data = portal.copy_to_bytes()
    # Server side: expect command arrays; client: any RESP values. Parse as
    # many complete values as available into ONE message (a batch).
    values = []
    pos = 0
    try:
        while pos < len(data):
            r = parse_reply(data, pos)
            if r is None:
                break
            value, pos = r
            values.append(value)
    except ValueError:
        return ParseResult.error_()
    if not values:
        return ParseResult.not_enough()
    portal.pop_front(pos)
    if server_side and getattr(sock, "_is_server_conn", True) and any(
            v.kind == "array" for v in values):
        commands = []
        for v in values:
            if v.kind == "array":
                commands.append([item.value for item in v.value])
        return ParseResult.ok(RedisMessage(commands=commands))
    return ParseResult.ok(RedisMessage(replies=values))


def serialize_request(request, cntl: Controller):
    if isinstance(request, RedisRequest):
        cntl._redis_command_count = request.command_count
        return request.serialize()
    if isinstance(request, (bytes, bytearray)):
        return bytes(request)
    raise TypeError("redis channel takes a RedisRequest")


def pack_request(payload: bytes, cntl: Controller, correlation_id: int) -> IOBuf:
    return IOBuf(payload)


def on_packed(sock, cntl: Controller, correlation_id: int):
    q = getattr(sock, "_redis_pipeline", None)
    if q is None:
        q = deque()
        sock._redis_pipeline = q
    q.append((correlation_id, getattr(cntl, "_redis_command_count", 1)))
    sock._is_server_conn = False  # this end is a client


def process_response(msg: RedisMessage):
    sock = msg.socket
    q = getattr(sock, "_redis_pipeline", None)
    pending = getattr(sock, "_redis_pending", None)
    if pending is None:
        pending = []
        sock._redis_pending = pending
    pending.extend(msg.replies or [])
    while q:
        cid, want = q[0]
        if len(pending) < want:
            return
        replies, sock._redis_pending = pending[:want], pending[want:]
        pending = sock._redis_pending
        q.popleft()
        try:
            cntl = bthread_id.lock(cid)
        except (KeyError, TimeoutError):
            continue
        if not isinstance(cntl, Controller):
            try:
                bthread_id.unlock(cid)
            except Exception:
                pass
            continue
        resp = cntl._response
        if isinstance(resp, RedisResponse):
            for r in replies:
                resp.add(r)
        first_err = next((r for r in replies if r.is_error()), None)
        if first_err is not None:
            cntl.set_failed(errors.EREQUEST, str(first_err.value))
        cntl._end_rpc_locked_or_not(locked=True)


def process_request(msg: RedisMessage):
    """Server dispatch (run inline: replies must go out in command order)."""
    server = msg.arg
    service = getattr(server, "redis_service", None) if server else None
    out = IOBuf()
    for args in msg.commands or []:
        if service is None:
            from brpc_tpu.rpc.redis import RedisReply

            out.append(RedisReply.error("ERR no redis service").encode())
        else:
            out.append(service.dispatch(args).encode())
    msg.socket.write(out)


register_protocol(Protocol(
    name="redis",
    type=ProtocolType.REDIS,
    parse=parse,
    serialize_request=serialize_request,
    pack_request=pack_request,
    process_request=process_request,
    process_response=process_response,
    process_inline=True,
    extra={"on_packed": on_packed},
))
