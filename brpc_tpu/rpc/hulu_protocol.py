"""hulu_pbrpc — Baidu's legacy pb-rpc protocol, wire-compatible framing.

Counterpart of /root/reference/src/brpc/policy/hulu_pbrpc_protocol.cpp:
12-byte header `"HULU" + u32le(meta_size+payload_size) + u32le(meta_size)`
(HuluRawPacker stores host order, hulu_pbrpc_protocol.cpp:100-149), then a
HuluRpcRequestMeta / HuluRpcResponseMeta protobuf, then the payload.

Dispatch: stock hulu addresses methods by (unqualified service name,
descriptor method_index) and optionally method_name (hulu_pbrpc_meta.proto
fields 1/2/14). We always send method_name and accept either on the server
(method_index resolves against sorted method-name order — descriptor order
for alphabetically-declared services); calling a stock hulu server that
ignores method_name requires index agreement. Correlation rides in the
meta, so hulu supports pooled connections like tpu_std.
"""
from __future__ import annotations

import struct

from brpc_tpu.bthread import id as bthread_id
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.rpc import compress as compress_mod
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.rpc.pb_dispatch import dispatch_pb_request
from brpc_tpu.rpc.protocol import (
    InputMessageBase,
    ParseResult,
    Protocol,
    ProtocolType,
    register_protocol,
)
from brpc_tpu.rpc.proto import legacy_meta_pb2

MAGIC = b"HULU"
HEADER_LEN = 12
MAX_BODY = 64 << 20

# hulu compress enum (hulu_pbrpc_protocol.cpp:58-96) -> our registry codes
_HULU_NONE, _HULU_SNAPPY, _HULU_GZIP, _HULU_ZLIB = 0, 1, 2, 3
_FROM_HULU = {_HULU_NONE: compress_mod.COMPRESS_NONE,
              _HULU_SNAPPY: compress_mod.COMPRESS_SNAPPY,
              _HULU_GZIP: compress_mod.COMPRESS_GZIP,
              _HULU_ZLIB: compress_mod.COMPRESS_ZLIB}
_TO_HULU = {v: k for k, v in _FROM_HULU.items()}

# method full name -> derived descriptor index (None = underivable);
# computed once — the pool lookup (and its usual KeyError for services
# registered under Python class names) must not run per call
_method_index_cache = {}


def _derive_method_index(service: str, method: str):
    key = service + "." + method
    if key in _method_index_cache:
        return _method_index_cache[key]
    idx = None
    try:
        from google.protobuf import descriptor_pool

        sd = descriptor_pool.Default().FindServiceByName(service)
        idx = sd.FindMethodByName(method).index
    except Exception:
        idx = None
    _method_index_cache[key] = idx
    return idx


class HuluMessage(InputMessageBase):
    __slots__ = ("meta", "payload", "is_request")

    def __init__(self, meta, payload: bytes, is_request: bool):
        super().__init__()
        self.meta = meta
        self.payload = payload
        self.is_request = is_request


def _pack_frame(meta, payload: bytes) -> IOBuf:
    meta_bytes = meta.SerializeToString()
    out = IOBuf()
    out.append(MAGIC + struct.pack("<II", len(meta_bytes) + len(payload),
                                   len(meta_bytes)))
    out.append(meta_bytes)
    if payload:
        out.append(payload)
    return out


def parse(portal: IOBuf, sock, read_eof: bool, arg) -> ParseResult:
    if len(portal) < HEADER_LEN:
        head = portal.copy_to_bytes(min(4, len(portal)))
        if MAGIC.startswith(head):
            return ParseResult.not_enough()
        return ParseResult.try_others()
    header = portal.copy_to_bytes(HEADER_LEN)
    if header[:4] != MAGIC:
        return ParseResult.try_others()
    body_size, meta_size = struct.unpack("<II", header[4:12])
    if body_size > MAX_BODY or meta_size > body_size:
        return ParseResult.error_()
    if len(portal) < HEADER_LEN + body_size:
        return ParseResult.not_enough()
    portal.pop_front(HEADER_LEN)
    meta_bytes = portal.cutn_bytes(meta_size)
    payload = portal.cutn_bytes(body_size - meta_size)
    # Serving connections carry requests, client connections responses
    # (the reference packs different metas per direction).
    is_server_conn = arg is not None
    meta_cls = (legacy_meta_pb2.HuluRpcRequestMeta if is_server_conn
                else legacy_meta_pb2.HuluRpcResponseMeta)
    meta = meta_cls()
    try:
        meta.ParseFromString(meta_bytes)
    except Exception:
        return ParseResult.error_()
    return ParseResult.ok(HuluMessage(meta, payload, is_server_conn))


def serialize_request(request, cntl: Controller):
    if request is None:
        return b""
    if isinstance(request, (bytes, bytearray)):
        return bytes(request)
    return request.SerializeToString()


def pack_request(payload: bytes, cntl: Controller, correlation_id: int) -> IOBuf:
    meta = legacy_meta_pb2.HuluRpcRequestMeta()
    service, _, method = cntl._method_full_name.rpartition(".")
    # Stock hulu uses the UNQUALIFIED service name (service->name(), not
    # full_name — hulu_pbrpc_protocol.cpp:444); ours registers class names.
    meta.service_name = service.rpartition(".")[2]
    # Stock hulu servers dispatch by method_index and IGNORE method_name
    # (FindMethodPropertyByNameAndIndex) — the reference client sends
    # method->index(). Honor an explicit cntl.hulu_method_index (the
    # nova_method_index discipline), else derive the descriptor index
    # from the protobuf pool when the service is a real pb service.
    idx = getattr(cntl, "hulu_method_index", None)
    if idx is None:
        idx = _derive_method_index(service, method)
    meta.method_index = idx if idx is not None else 0
    meta.method_name = method
    meta.correlation_id = correlation_id
    meta.log_id = cntl.log_id
    if cntl.trace_id:
        meta.trace_id = cntl.trace_id
        meta.span_id = cntl.span_id
    auth = cntl._channel.options.auth if cntl._channel is not None else None
    if auth is not None:
        cred = auth.generate_credential(cntl)
        if cred is None:
            raise ValueError("authenticator refused to generate credential")
        meta.credential_data = cred
    if cntl.compress_type:
        meta.compress_type = _TO_HULU.get(cntl.compress_type, _HULU_NONE)
    payload = compress_mod.compress(payload, cntl.compress_type)
    if len(cntl.request_attachment):
        # pb bytes + raw attachment share the payload; user_message_size
        # marks the boundary (hulu_pbrpc_protocol.cpp:354-359)
        meta.user_message_size = len(payload)
        payload = payload + cntl.request_attachment.copy_to_bytes(
            len(cntl.request_attachment))
    return _pack_frame(meta, payload)


def process_response(msg: HuluMessage):
    meta = msg.meta
    cid = meta.correlation_id
    try:
        cntl = bthread_id.lock(cid)
    except (KeyError, TimeoutError):
        return
    if not isinstance(cntl, Controller):
        try:
            bthread_id.unlock(cid)
        except Exception:
            pass
        return
    try:
        if meta.error_code:
            cntl.set_failed(meta.error_code, meta.error_text or "hulu error")
        else:
            payload = msg.payload
            # user_message_size splits (compressed) pb bytes from the raw
            # trailing attachment (hulu_pbrpc_protocol.cpp:354-359); the
            # split must happen BEFORE decompression — the sender appends
            # the attachment after compressing the pb part
            if (meta.HasField("user_message_size")
                    and 0 <= meta.user_message_size <= len(payload)):
                cntl.response_attachment.append(
                    payload[meta.user_message_size:])
                payload = payload[:meta.user_message_size]
            payload = compress_mod.decompress(
                payload, _FROM_HULU.get(meta.compress_type, 0))
            resp = cntl._response
            if resp is not None and payload:
                resp.ParseFromString(payload)
    except Exception as e:
        cntl.set_failed(errors.ERESPONSE, f"fail to parse response: {e}")
    cntl._end_rpc_locked_or_not(locked=True)


def _send_response(sock, cid: int, cntl: Controller, response):
    meta = legacy_meta_pb2.HuluRpcResponseMeta()
    meta.correlation_id = cid
    if cntl.failed():
        meta.error_code = cntl.error_code_value
        meta.error_text = cntl.error_text_value
        payload = b""
    else:
        payload = (response.SerializeToString()
                   if response is not None else b"")
        if cntl.compress_type:
            meta.compress_type = _TO_HULU.get(cntl.compress_type, 0)
            payload = compress_mod.compress(payload, cntl.compress_type)
        if len(cntl.response_attachment):
            meta.user_message_size = len(payload)
            payload = payload + cntl.response_attachment.copy_to_bytes(
                len(cntl.response_attachment))
    sock.write(_pack_frame(meta, payload))
    if cntl.close_connection_flag:
        sock.set_failed(errors.ECLOSE, "close_connection requested")


def process_request(msg: HuluMessage):
    """Server path (ProcessHuluRequest's role)."""
    server = msg.arg
    meta = msg.meta
    cid = meta.correlation_id
    sock = msg.socket
    cntl = Controller()
    cntl.log_id = meta.log_id
    cntl.trace_id = meta.trace_id
    payload = msg.payload
    if (meta.HasField("user_message_size")
            and 0 <= meta.user_message_size <= len(payload)):
        cntl.request_attachment.append(payload[meta.user_message_size:])
        payload = payload[:meta.user_message_size]

    def send_response(c, response):
        _send_response(sock, cid, c, response)

    if server is not None and server.auth is not None:
        ok, ctx = False, None
        try:
            ok, ctx = server.auth.verify_credential(
                meta.credential_data, sock.remote_side)
        except Exception:
            ok = False
        if not ok:
            cntl.set_failed(errors.EAUTH, "authentication failed")
            return send_response(cntl, None)
        cntl.auth_context = ctx

    method_name = meta.method_name
    if server is not None and not method_name:
        service = server.find_service(meta.service_name)
        if service is not None:
            names = sorted(service.methods().keys())
            if 0 <= meta.method_index < len(names):
                method_name = names[meta.method_index]
    dispatch_pb_request(server, sock, meta.service_name, method_name or "",
                        payload, _FROM_HULU.get(meta.compress_type, 0),
                        send_response, cntl)


register_protocol(Protocol(
    name="hulu_pbrpc",
    type=ProtocolType.HULU,
    parse=parse,
    serialize_request=serialize_request,
    pack_request=pack_request,
    process_request=process_request,
    process_response=process_response,
))
