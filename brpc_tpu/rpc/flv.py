"""FLV muxer/demuxer — counterpart of /root/reference/src/brpc/rtmp.h's
FLV helpers (FlvWriter/FlvReader roles): the container RTMP media rides in
when dumped to files or served over HTTP (flv tags ARE rtmp message
payloads with an 11-byte tag header).

Tag types mirror RTMP message types: 8 audio, 9 video, 18 script data.
"""
from __future__ import annotations

import struct
from typing import Iterator, Optional, Tuple

FLV_TAG_AUDIO = 8
FLV_TAG_VIDEO = 9
FLV_TAG_SCRIPT = 18

FLV_HEADER_AUDIO = 0x04
FLV_HEADER_VIDEO = 0x01


def file_header(has_audio: bool = True, has_video: bool = True) -> bytes:
    flags = (FLV_HEADER_AUDIO if has_audio else 0) | (
        FLV_HEADER_VIDEO if has_video else 0)
    #                 signature  ver  flags  header size   PreviousTagSize0
    return b"FLV" + bytes([1, flags]) + struct.pack(">I", 9) + b"\x00" * 4


def encode_tag(tag_type: int, timestamp_ms: int, payload: bytes) -> bytes:
    """One FLV tag + its trailing PreviousTagSize."""
    ts = timestamp_ms & 0xFFFFFFFF
    header = struct.pack(">B", tag_type)
    header += struct.pack(">I", len(payload))[1:]        # DataSize u24
    header += struct.pack(">I", ts & 0xFFFFFF)[1:]       # Timestamp u24
    header += bytes([(ts >> 24) & 0xFF])                 # TimestampExtended
    header += b"\x00\x00\x00"                            # StreamID
    return header + payload + struct.pack(">I", 11 + len(payload))


class FlvWriter:
    """Streams tags into a file-like object (the FlvWriter role)."""

    def __init__(self, fp, has_audio: bool = True, has_video: bool = True):
        self._fp = fp
        self._fp.write(file_header(has_audio, has_video))

    def write_tag(self, tag_type: int, timestamp_ms: int, payload: bytes):
        self._fp.write(encode_tag(tag_type, timestamp_ms, payload))

    def write_audio(self, timestamp_ms: int, payload: bytes):
        self.write_tag(FLV_TAG_AUDIO, timestamp_ms, payload)

    def write_video(self, timestamp_ms: int, payload: bytes):
        self.write_tag(FLV_TAG_VIDEO, timestamp_ms, payload)

    def write_metadata(self, timestamp_ms: int, payload: bytes):
        self.write_tag(FLV_TAG_SCRIPT, timestamp_ms, payload)


def read_tags(data: bytes) -> Iterator[Tuple[int, int, bytes]]:
    """Yields (tag_type, timestamp_ms, payload) from an FLV byte string
    (the FlvReader role)."""
    if data[:3] != b"FLV":
        raise ValueError("not an FLV stream")
    header_size = struct.unpack(">I", data[5:9])[0]
    pos = header_size + 4  # skip PreviousTagSize0
    n = len(data)
    while pos + 11 <= n:
        tag_type = data[pos]
        size = struct.unpack(">I", b"\x00" + data[pos + 1:pos + 4])[0]
        ts = struct.unpack(">I", b"\x00" + data[pos + 4:pos + 7])[0]
        ts |= data[pos + 7] << 24
        body_at = pos + 11
        if body_at + size > n:
            return  # truncated tail
        yield tag_type, ts, data[body_at:body_at + size]
        pos = body_at + size + 4  # skip PreviousTagSize


def probe(data: bytes) -> Optional[dict]:
    """Quick sanity probe: header flags + first-tag info, or None."""
    if len(data) < 13 or data[:3] != b"FLV":
        return None
    return {"version": data[3], "has_audio": bool(data[4] & 4),
            "has_video": bool(data[4] & 1)}
