"""nshead protocol — Baidu's classic 36-byte-header framing.

Counterpart of brpc's nshead support (/root/reference/src/brpc/nshead.h:
NSHEAD_MAGICNUM 0xfb709394; policy/nshead_protocol.cpp +
nshead_pb_service_adaptor.{h,cpp}): header = {u16 id, u16 version,
u32 log_id, char provider[16], u32 magic, u32 reserved, u32 body_len}
(little-endian), then the body. Servers install an NsheadService whose
handler sees (controller, NsheadMessage, done); the pb adaptor maps bodies
to protobuf messages by content — here via the mcpack2pb front-end, the
pairing the nshead_mcpack protocol uses.

The nshead-framed pb-rpc variants (nova_pbrpc, public_pbrpc, ubrpc) build
on this module — see legacy_nshead_family.py; hulu/sofa have their own
framings (hulu_protocol.py, sofa_protocol.py).
"""
from __future__ import annotations

import struct
from typing import Callable, Optional

from brpc_tpu.bthread import id as bthread_id
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.rpc.protocol import (
    InputMessageBase,
    ParseResult,
    Protocol,
    ProtocolType,
    register_protocol,
)

NSHEAD_MAGICNUM = 0xFB709394
_HEAD = struct.Struct("<HHI16sIII")  # 36 bytes
HEAD_SIZE = _HEAD.size


class NsheadMessage:
    """head fields + body bytes (nshead_message.h role)."""

    def __init__(self, body: bytes = b"", id_: int = 0, version: int = 0,
                 log_id: int = 0, provider: bytes = b"brpc_tpu",
                 reserved: int = 0):
        self.id = id_
        self.version = version
        self.log_id = log_id
        self.provider = provider[:16]
        self.reserved = reserved  # nova rides its method index here
        self.body = body

    def serialize(self) -> bytes:
        return _HEAD.pack(self.id, self.version, self.log_id,
                          self.provider.ljust(16, b"\x00"),
                          NSHEAD_MAGICNUM, self.reserved,
                          len(self.body)) + self.body


class NsheadInputMessage(InputMessageBase):
    __slots__ = ("msg", "is_request")

    def __init__(self, msg: NsheadMessage):
        super().__init__()
        self.msg = msg
        self.is_request = True  # role decided by connection side


def parse(portal: IOBuf, sock, read_eof: bool, arg) -> ParseResult:
    if len(portal) < HEAD_SIZE:
        head = portal.copy_to_bytes(min(HEAD_SIZE, len(portal)))
        if len(head) >= 28:
            (magic,) = struct.unpack_from("<I", head, 24)
            if magic != NSHEAD_MAGICNUM:
                return ParseResult.try_others()
            return ParseResult.not_enough()
        # cannot see the magic yet; only claim if it could still match
        return ParseResult.not_enough() if len(head) < 28 else ParseResult.try_others()
    raw = portal.copy_to_bytes(HEAD_SIZE)
    id_, version, log_id, provider, magic, res, body_len = _HEAD.unpack(raw)
    if magic != NSHEAD_MAGICNUM:
        return ParseResult.try_others()
    if body_len > (64 << 20):
        return ParseResult.error_()
    if len(portal) < HEAD_SIZE + body_len:
        return ParseResult.not_enough()
    portal.pop_front(HEAD_SIZE)
    body = portal.cutn_bytes(body_len)
    msg = NsheadMessage(body, id_, version, log_id,
                        provider.rstrip(b"\x00"), reserved=res)
    return ParseResult.ok(NsheadInputMessage(msg))


def serialize_request(request, cntl: Controller):
    if isinstance(request, NsheadMessage):
        return request.body
    if isinstance(request, (bytes, bytearray)):
        return bytes(request)
    raise TypeError("nshead channel takes an NsheadMessage or bytes")


def pack_request(payload: bytes, cntl: Controller, correlation_id: int) -> IOBuf:
    # nshead has no correlation field wide enough; responses arrive in
    # order on the connection (the reference treats nshead as
    # one-request-at-a-time per connection too).
    sock = cntl._current_sock
    from collections import deque

    q = getattr(sock, "_nshead_pipeline", None)
    if q is None:
        q = deque()
        sock._nshead_pipeline = q
    q.append(correlation_id)
    msg = NsheadMessage(payload, log_id=cntl.log_id & 0xFFFFFFFF)
    return IOBuf(msg.serialize())


def process_response(msg: NsheadInputMessage):
    sock = msg.socket
    q = getattr(sock, "_nshead_pipeline", None)
    if not q:
        return
    cid = q.popleft()
    try:
        cntl = bthread_id.lock(cid)
    except (KeyError, TimeoutError):
        return
    if not isinstance(cntl, Controller):
        try:
            bthread_id.unlock(cid)
        except Exception:
            pass
        return
    resp = cntl._response
    if isinstance(resp, NsheadMessage):
        resp.body = msg.msg.body
        resp.id = msg.msg.id
        resp.log_id = msg.msg.log_id
    cntl._end_rpc_locked_or_not(locked=True)


def process_request(msg: NsheadInputMessage):
    server = msg.arg
    service = getattr(server, "nshead_service", None) if server else None
    sock = msg.socket
    if service is None:
        # Not a serving connection: this frame is a RESPONSE to our client
        # (nshead frames carry no request/response marker).
        return process_response(msg)
    cntl = Controller()
    cntl.server = server
    cntl.remote_side = sock.remote_side
    cntl.log_id = msg.msg.log_id
    responded = [False]

    def done(response: Optional[NsheadMessage] = None):
        if responded[0]:
            return
        responded[0] = True
        out = response or NsheadMessage()
        out.log_id = msg.msg.log_id
        sock.write(IOBuf(out.serialize()))

    try:
        service.process_nshead_request(cntl, msg.msg, done)
    except Exception as e:
        if not responded[0]:
            done(NsheadMessage(f"error: {e}".encode()))


class NsheadService:
    """Base for nshead servers (NsheadService role): override
    process_nshead_request(cntl, request_msg, done)."""

    def process_nshead_request(self, cntl, request: NsheadMessage,
                               done: Callable):
        done(NsheadMessage(request.body))  # default: echo


class NsheadPbServiceAdaptor(NsheadService):
    """pb front-end over nshead bodies via mcpack
    (nshead_pb_service_adaptor.h + nshead_mcpack pairing): bodies are
    mcpack-encoded pb messages; handler sees decoded pb."""

    def __init__(self, request_class, response_class, handler):
        self.request_class = request_class
        self.response_class = response_class
        self.handler = handler  # (cntl, request_pb, response_pb) -> None

    def process_nshead_request(self, cntl, request: NsheadMessage, done):
        from brpc_tpu.mcpack2pb import mcpack_to_pb, pb_to_mcpack

        try:
            req_pb = mcpack_to_pb(request.body, self.request_class)
        except (ValueError, IndexError, KeyError) as e:
            done(NsheadMessage(f"bad mcpack body: {e}".encode()))
            return
        resp_pb = self.response_class()
        self.handler(cntl, req_pb, resp_pb)
        done(NsheadMessage(pb_to_mcpack(resp_pb)))


register_protocol(Protocol(
    name="nshead",
    type=ProtocolType.NSHEAD,
    parse=parse,
    serialize_request=serialize_request,
    pack_request=pack_request,
    process_request=process_request,
    process_response=process_response,
    process_inline=True,
))

