"""MethodStatus — per-method concurrency gate + latency stats.

Counterpart of brpc::MethodStatus
(/root/reference/src/brpc/details/method_status.{h,cpp}): every method
tracks in-flight concurrency and a LatencyRecorder; a ConcurrencyLimiter
may reject before user code runs (rejection path of
baidu_rpc_protocol.cpp:456-459).
"""
from __future__ import annotations

import threading
import time
from typing import Optional

from brpc_tpu import bvar


class MethodStatus:
    def __init__(self, full_name: str, limiter: Optional[object] = None):
        self.full_name = full_name
        self._concurrency = 0
        self._lock = threading.Lock()
        self.latency_recorder = bvar.LatencyRecorder(
            full_name.replace(".", "_").replace("/", "_")
        )
        self._rejected = bvar.Adder()
        self.limiter = limiter  # ConcurrencyLimiter or None

    def on_requested(self) -> bool:
        """False = reject with ELIMIT (OnRequested, method_status.h)."""
        with self._lock:
            if self.limiter is not None and not self.limiter.on_requested(
                self._concurrency
            ):
                self._rejected.update(1)
                return False
            self._concurrency += 1
            return True

    def on_response(self, error_code: int, start_time_s: float):
        latency_us = (time.monotonic() - start_time_s) * 1e6
        with self._lock:
            self._concurrency -= 1
        self.latency_recorder.update(latency_us)
        if self.limiter is not None:
            self.limiter.on_response(error_code, latency_us)

    @property
    def concurrency(self) -> int:
        return self._concurrency

    @property
    def rejected_count(self) -> int:
        return self._rejected.get_value()

    def describe(self) -> str:
        return (
            f"{self.full_name}: concurrency={self._concurrency} "
            f"rejected={self.rejected_count} {self.latency_recorder.describe()}"
        )
