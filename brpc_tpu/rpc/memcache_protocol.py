"""Memcache binary wire protocol — counterpart of
policy/memcache_binary_protocol.cpp: client requests batched per call and
matched to in-order responses (the pipelined matching the reference's
memcache connection uses); server side (when ServerOptions.memcache_service
is set) dispatches to MemcacheService.
"""
from __future__ import annotations

from collections import deque

from brpc_tpu.bthread import id as bthread_id
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.rpc.memcache import (
    MAGIC_REQUEST,
    MAGIC_RESPONSE,
    MemcacheRequest,
    MemcacheResponse,
    parse_op,
)
from brpc_tpu.rpc.protocol import (
    InputMessageBase,
    ParseResult,
    Protocol,
    ProtocolType,
    register_protocol,
)


class MemcacheMessage(InputMessageBase):
    __slots__ = ("ops", "is_request")

    def __init__(self, ops, is_request):
        super().__init__()
        self.ops = ops
        self.is_request = is_request


def parse(portal: IOBuf, sock, read_eof: bool, arg) -> ParseResult:
    if portal.empty():
        return ParseResult.not_enough()
    head = portal.copy_to_bytes(1)[0]
    if head not in (MAGIC_REQUEST, MAGIC_RESPONSE):
        return ParseResult.try_others()
    data = portal.copy_to_bytes()
    ops = []
    pos = 0
    while pos < len(data):
        r = parse_op(data, pos)
        if r is None:
            break
        op, pos = r
        ops.append(op)
    if not ops:
        return ParseResult.not_enough()
    portal.pop_front(pos)
    return ParseResult.ok(MemcacheMessage(ops, ops[0]["magic"] == MAGIC_REQUEST))


def serialize_request(request, cntl: Controller):
    if isinstance(request, MemcacheRequest):
        cntl._memcache_op_count = request.op_count
        return request.serialize()
    if isinstance(request, (bytes, bytearray)):
        return bytes(request)
    raise TypeError("memcache channel takes a MemcacheRequest")


def pack_request(payload: bytes, cntl: Controller, correlation_id: int) -> IOBuf:
    return IOBuf(payload)


def on_packed(sock, cntl: Controller, correlation_id: int):
    q = getattr(sock, "_mc_pipeline", None)
    if q is None:
        q = deque()
        sock._mc_pipeline = q
    q.append((correlation_id, getattr(cntl, "_memcache_op_count", 1)))


def process_response(msg: MemcacheMessage):
    sock = msg.socket
    q = getattr(sock, "_mc_pipeline", None)
    pending = getattr(sock, "_mc_pending", None)
    if pending is None:
        pending = []
        sock._mc_pending = pending
    pending.extend(msg.ops)
    while q:
        cid, want = q[0]
        if len(pending) < want:
            return
        ops, sock._mc_pending = pending[:want], pending[want:]
        pending = sock._mc_pending
        q.popleft()
        try:
            cntl = bthread_id.lock(cid)
        except (KeyError, TimeoutError):
            continue
        if not isinstance(cntl, Controller):
            try:
                bthread_id.unlock(cid)
            except Exception:
                pass
            continue
        resp = cntl._response
        if isinstance(resp, MemcacheResponse):
            for op in ops:
                resp.add_result(op)
        cntl._end_rpc_locked_or_not(locked=True)


def process_request(msg: MemcacheMessage):
    server = msg.arg
    service = getattr(server, "memcache_service", None) if server else None
    out = IOBuf()
    for op in msg.ops:
        if service is None:
            from brpc_tpu.rpc.memcache import STATUS_ITEM_NOT_STORED, pack_op

            out.append(pack_op(op["opcode"], magic=MAGIC_RESPONSE,
                               status=STATUS_ITEM_NOT_STORED,
                               opaque=op["opaque"]))
        else:
            out.append(service.handle(op))
    msg.socket.write(out)


register_protocol(Protocol(
    name="memcache",
    type=ProtocolType.MEMCACHE,
    parse=parse,
    serialize_request=serialize_request,
    pack_request=pack_request,
    process_request=process_request,
    process_response=process_response,
    process_inline=True,
    extra={"on_packed": on_packed},
))
