"""LoadBalancer — server selection policies over DoublyBufferedData.

Counterpart of brpc::LoadBalancer (/root/reference/src/brpc/load_balancer.h:
35-126) and the policy set registered in global.cpp:368-376: rr, wrr,
random, wr, consistent hashing (policy/consistent_hashing_load_balancer.cpp)
and locality-aware (policy/locality_aware_load_balancer.{h,cpp} — weight =
inverse of EMA latency scaled by inflight). Server lists live in
DoublyBufferedData so select never contends with select (load_balancer.h:72).

A server here is a SocketId; health is judged through Socket.address() +
failed(), so SetFailed/health-check revival flows into selection for free.
"""
from __future__ import annotations

import hashlib
import random
import threading
import time
from bisect import bisect_right
from typing import Dict, List, Optional, Set

from brpc_tpu.butil.dbd import DoublyBufferedData
from brpc_tpu.rpc.socket import Socket


class ServerNode:
    __slots__ = ("sid", "weight", "tag")

    def __init__(self, sid: int, weight: int = 1, tag: str = ""):
        self.sid = sid
        self.weight = max(1, weight)
        self.tag = tag


def _alive(sid: int) -> bool:
    # A lame-duck socket (peer draining gracefully) is NOT selectable for
    # new calls — in-flight work completes on it, new work re-balances —
    # but it is also not "failed": no breaker/recovery alarm fires, and
    # health-check revival clears the flag when the peer returns.
    s = Socket.address(sid)
    return s is not None and not s.failed() and \
        not getattr(s, "lame_duck", False)


class LoadBalancer:
    """Interface (load_balancer.h:35-126)."""

    name = "base"

    def __init__(self):
        self._dbd: DoublyBufferedData[List[ServerNode]] = DoublyBufferedData(list)
        self._lock = threading.Lock()

    # -- membership (driven by the NamingService observer) ----------------
    def add_server(self, sid: int, weight: int = 1, tag: str = ""):
        def add(lst: List[ServerNode]):
            if all(n.sid != sid for n in lst):
                lst.append(ServerNode(sid, weight, tag))

        self._dbd.modify(add)
        self._on_membership_change()

    def remove_server(self, sid: int):
        def rm(lst: List[ServerNode]):
            lst[:] = [n for n in lst if n.sid != sid]

        self._dbd.modify(rm)
        self._on_membership_change()

    def server_ids(self) -> List[int]:
        with self._dbd.read() as lst:
            return [n.sid for n in lst]

    def server_count(self) -> int:
        with self._dbd.read() as lst:
            return len(lst)

    def _on_membership_change(self):
        pass

    # -- selection ---------------------------------------------------------
    def select_server(self, exclude: Optional[Set[int]] = None,
                      request_code: int = 0) -> Optional[int]:
        raise NotImplementedError

    def feedback(self, sid: int, error_code: int, latency_us: float):
        """CallBack after each RPC (load_balancer.h:98 Feedback)."""

    def _usable(self, lst: List[ServerNode], exclude) -> List[ServerNode]:
        out = [n for n in lst if _alive(n.sid)]
        if exclude:
            filtered = [n for n in out if n.sid not in exclude]
            if filtered:  # excluding everything beats returning nothing
                return filtered
        return out


class RoundRobinLB(LoadBalancer):
    name = "rr"

    def __init__(self):
        super().__init__()
        self._index = 0

    def select_server(self, exclude=None, request_code: int = 0):
        with self._dbd.read() as lst:
            usable = self._usable(lst, exclude)
            if not usable:
                return None
            with self._lock:
                self._index = (self._index + 1) % len(usable)
                return usable[self._index].sid


class WeightedRoundRobinLB(LoadBalancer):
    name = "wrr"

    def __init__(self):
        super().__init__()
        self._current: Dict[int, float] = {}

    def select_server(self, exclude=None, request_code: int = 0):
        # Smooth weighted RR (nginx algorithm — equivalent coverage to
        # policy/weighted_round_robin_load_balancer.cpp).
        with self._dbd.read() as lst:
            usable = self._usable(lst, exclude)
            if not usable:
                return None
            with self._lock:
                total = 0
                best = None
                for n in usable:
                    cur = self._current.get(n.sid, 0.0) + n.weight
                    self._current[n.sid] = cur
                    total += n.weight
                    if best is None or cur > self._current[best.sid]:
                        best = n
                self._current[best.sid] -= total
                return best.sid


class RandomLB(LoadBalancer):
    name = "random"

    def select_server(self, exclude=None, request_code: int = 0):
        with self._dbd.read() as lst:
            usable = self._usable(lst, exclude)
            if not usable:
                return None
            return random.choice(usable).sid


class WeightedRandomLB(LoadBalancer):
    name = "wr"

    def select_server(self, exclude=None, request_code: int = 0):
        with self._dbd.read() as lst:
            usable = self._usable(lst, exclude)
            if not usable:
                return None
            total = sum(n.weight for n in usable)
            x = random.uniform(0, total)
            acc = 0.0
            for n in usable:
                acc += n.weight
                if x <= acc:
                    return n.sid
            return usable[-1].sid


class ConsistentHashLB(LoadBalancer):
    """Ketama-style ring (policy/consistent_hashing_load_balancer.cpp +
    hasher.cpp): each server owns `replicas` virtual points hashed by md5;
    requests route by request_code."""

    name = "c_murmurhash"
    replicas = 100

    def __init__(self):
        super().__init__()
        self._ring: List[int] = []  # sorted hash points
        self._ring_sids: List[int] = []

    def _on_membership_change(self):
        points = []
        with self._dbd.read() as lst:
            for n in lst:
                for r in range(self.replicas):
                    h = hashlib.md5(f"{n.sid}-{r}".encode()).digest()
                    points.append((int.from_bytes(h[:8], "little"), n.sid))
        points.sort()
        with self._lock:
            self._ring = [p[0] for p in points]
            self._ring_sids = [p[1] for p in points]

    def select_server(self, exclude=None, request_code: int = 0):
        with self._lock:
            ring, sids = self._ring, self._ring_sids
        if not ring:
            return None
        # Hash the request code onto the ring (the Hasher of hasher.cpp).
        hcode = hashlib.md5(request_code.to_bytes(8, "little", signed=False)
                            if request_code >= 0 else str(request_code).encode()
                            ).digest()
        point = int.from_bytes(hcode[:8], "little")
        idx = bisect_right(ring, point) % len(ring)
        # walk the ring until an alive, non-excluded node
        for step in range(len(ring)):
            sid = sids[(idx + step) % len(ring)]
            if _alive(sid) and (not exclude or sid not in exclude):
                return sid
        return None


class LocalityAwareLB(LoadBalancer):
    """Latency+inflight weighted selection
    (policy/locality_aware_load_balancer.{h,cpp}): weight_i proportional to
    1 / (ema_latency_i * (inflight_i + 1)); feedback() maintains the EMA."""

    name = "la"
    _EMA_ALPHA = 0.2
    _DEFAULT_LATENCY_US = 10_000.0

    def __init__(self):
        super().__init__()
        self._stats: Dict[int, List[float]] = {}  # sid -> [ema_us, inflight]

    def select_server(self, exclude=None, request_code: int = 0):
        with self._dbd.read() as lst:
            usable = self._usable(lst, exclude)
            if not usable:
                return None
            with self._lock:
                weights = []
                for n in usable:
                    ema, inflight = self._stats.get(
                        n.sid, [self._DEFAULT_LATENCY_US, 0.0]
                    )
                    weights.append(n.weight / (ema * (inflight + 1.0)))
                total = sum(weights)
                x = random.uniform(0.0, total)
                acc = 0.0
                chosen = usable[-1].sid
                for n, w in zip(usable, weights):
                    acc += w
                    if x <= acc:
                        chosen = n.sid
                        break
                st = self._stats.setdefault(
                    chosen, [self._DEFAULT_LATENCY_US, 0.0]
                )
                st[1] += 1.0
                return chosen

    def feedback(self, sid: int, error_code: int, latency_us: float):
        with self._lock:
            st = self._stats.setdefault(sid, [self._DEFAULT_LATENCY_US, 0.0])
            st[1] = max(0.0, st[1] - 1.0)
            sample = latency_us if error_code == 0 else latency_us * 10.0
            st[0] = (1 - self._EMA_ALPHA) * st[0] + self._EMA_ALPHA * sample


class DynPartLB(LoadBalancer):
    """_dynpart (policy/dynpart_load_balancer.cpp): selection weighted by
    each member's DYNAMIC capacity — in the reference, the sub-channel
    weight of the SelectiveChannel member (schan::GetSubChannelWeight);
    here a capacity callback installed by DynamicPartitionChannel. Members
    are scheme handles, not sockets, so liveness = capacity > 0."""

    name = "_dynpart"

    def __init__(self):
        super().__init__()
        self._capacity_fn = lambda sid: 1

    def set_capacity_fn(self, fn):
        self._capacity_fn = fn

    def select_server(self, exclude=None, request_code: int = 0):
        # weighted random by live capacity (dynpart_load_balancer.cpp:
        # 104-157 total_weight walk + fast_rand_less_than); capacities are
        # sampled ONCE so a concurrent NS update cannot skew the pick.
        with self._dbd.read() as lst:
            pairs = [(n.sid, self._capacity_fn(n.sid)) for n in lst]
        pairs = [(sid, c) for sid, c in pairs if c > 0]
        if exclude:
            filtered = [(sid, c) for sid, c in pairs if sid not in exclude]
            if filtered:
                pairs = filtered
        if not pairs:
            return None
        x = random.uniform(0, sum(c for _, c in pairs))
        acc = 0.0
        for sid, c in pairs:
            acc += c
            if x <= acc:
                return sid
        return pairs[-1][0]


_registry = {
    "rr": RoundRobinLB,
    "wrr": WeightedRoundRobinLB,
    "random": RandomLB,
    "wr": WeightedRandomLB,
    "c_murmurhash": ConsistentHashLB,
    "c_md5": ConsistentHashLB,
    "la": LocalityAwareLB,
    "_dynpart": DynPartLB,
}


def register_load_balancer(name: str, cls):
    """Extension registry (global.cpp:368-376 pattern)."""
    _registry[name] = cls


def create_load_balancer(name: str) -> Optional[LoadBalancer]:
    """'name' or 'name:params' — params currently carry the cluster
    recover policy (load_balancer.h GetRecoverPolicyByParams wiring),
    e.g. 'rr:min_working_instances=2 hold_seconds=3'."""
    base, _, params = name.partition(":")
    cls = _registry.get(base)
    if cls is None:
        return None
    lb = cls()
    lb.cluster_recover_policy = None
    if params:
        from brpc_tpu.rpc.cluster_recover import recover_policy_from_params

        lb.cluster_recover_policy = recover_policy_from_params(params)
        if lb.cluster_recover_policy is None:
            return None  # malformed params reject init (reference behavior)
    return lb
