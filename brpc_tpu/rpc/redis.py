"""Redis (RESP) message types — counterpart of brpc's redis support
(/root/reference/src/brpc/redis.{h,cpp}, redis_command.cpp,
redis_reply.cpp): RedisRequest batches commands, RedisResponse holds
replies, RedisReply is the RESP value union; RedisService lets a server
SPEAK redis (the server-side capability brpc added and the monographdb
fork wires to io_uring).
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple, Union

# -- RESP encoding ----------------------------------------------------------


def encode_command(args: Tuple) -> bytes:
    """One command as a RESP array of bulk strings."""
    out = [f"*{len(args)}\r\n".encode()]
    for a in args:
        if isinstance(a, bytes):
            b = a
        else:
            b = str(a).encode()
        out.append(f"${len(b)}\r\n".encode())
        out.append(b)
        out.append(b"\r\n")
    return b"".join(out)


class RedisReply:
    """RESP value: kind in {status,error,integer,string,array,nil}."""

    __slots__ = ("kind", "value")

    def __init__(self, kind: str, value=None):
        self.kind = kind
        self.value = value

    # -- constructors used by server handlers
    @classmethod
    def status(cls, s: str) -> "RedisReply":
        return cls("status", s)

    @classmethod
    def error(cls, s: str) -> "RedisReply":
        return cls("error", s)

    @classmethod
    def integer(cls, v: int) -> "RedisReply":
        return cls("integer", int(v))

    @classmethod
    def string(cls, v: Union[str, bytes]) -> "RedisReply":
        return cls("string", v.encode() if isinstance(v, str) else v)

    @classmethod
    def nil(cls) -> "RedisReply":
        return cls("nil")

    @classmethod
    def array(cls, items: List["RedisReply"]) -> "RedisReply":
        return cls("array", items)

    def is_nil(self) -> bool:
        return self.kind == "nil"

    def is_error(self) -> bool:
        return self.kind == "error"

    def encode(self) -> bytes:
        if self.kind == "status":
            return f"+{self.value}\r\n".encode()
        if self.kind == "error":
            return f"-{self.value}\r\n".encode()
        if self.kind == "integer":
            return f":{self.value}\r\n".encode()
        if self.kind == "nil":
            return b"$-1\r\n"
        if self.kind == "string":
            return f"${len(self.value)}\r\n".encode() + self.value + b"\r\n"
        if self.kind == "array":
            out = [f"*{len(self.value)}\r\n".encode()]
            out.extend(item.encode() for item in self.value)
            return b"".join(out)
        raise ValueError(f"bad reply kind {self.kind}")

    def __repr__(self):
        return f"RedisReply({self.kind}, {self.value!r})"


def parse_reply(data: bytes, pos: int) -> Optional[Tuple[RedisReply, int]]:
    """Parse one RESP value at data[pos:]; None if incomplete."""
    nl = data.find(b"\r\n", pos)
    if nl < 0:
        return None
    line = data[pos:nl]
    if not line:
        return None
    t, rest = line[:1], line[1:]
    after = nl + 2
    if t == b"+":
        return RedisReply("status", rest.decode()), after
    if t == b"-":
        return RedisReply("error", rest.decode()), after
    if t == b":":
        return RedisReply("integer", int(rest)), after
    if t == b"$":
        n = int(rest)
        if n < 0:
            return RedisReply("nil"), after
        if len(data) < after + n + 2:
            return None
        return RedisReply("string", data[after:after + n]), after + n + 2
    if t == b"*":
        n = int(rest)
        if n < 0:
            return RedisReply("nil"), after
        items = []
        cur = after
        for _ in range(n):
            sub = parse_reply(data, cur)
            if sub is None:
                return None
            item, cur = sub
            items.append(item)
        return RedisReply("array", items), cur
    raise ValueError(f"bad RESP type byte {t!r}")


# -- request/response (redis.h RedisRequest/RedisResponse) ------------------

class RedisRequest:
    def __init__(self):
        self._commands: List[Tuple] = []

    def add_command(self, *args) -> bool:
        """add_command("SET", "k", "v") or add_command("SET k v")."""
        if len(args) == 1 and isinstance(args[0], str) and " " in args[0]:
            args = tuple(args[0].split())
        if not args:
            return False
        self._commands.append(args)
        return True

    @property
    def command_count(self) -> int:
        return len(self._commands)

    def serialize(self) -> bytes:
        return b"".join(encode_command(c) for c in self._commands)


class RedisResponse:
    def __init__(self):
        self._replies: List[RedisReply] = []

    def add(self, reply: RedisReply):
        self._replies.append(reply)

    @property
    def reply_count(self) -> int:
        return len(self._replies)

    def reply(self, index: int) -> RedisReply:
        return self._replies[index]


# -- server side (redis.h RedisService / RedisCommandHandler) ---------------

CommandHandler = Callable[[List[bytes]], RedisReply]


class RedisService:
    """Server-side redis: register handlers per command name
    (brpc::RedisService::AddCommandHandler)."""

    def __init__(self):
        self._handlers: Dict[str, CommandHandler] = {}
        self._lock = threading.Lock()
        self.add_command_handler("ping", lambda args: RedisReply.status("PONG"))
        self.add_command_handler(
            "command", lambda args: RedisReply.array([]))

    def add_command_handler(self, name: str, handler: CommandHandler):
        with self._lock:
            self._handlers[name.lower()] = handler

    def dispatch(self, args: List[bytes]) -> RedisReply:
        if not args:
            return RedisReply.error("ERR empty command")
        name = args[0].decode("utf-8", "replace").lower()
        handler = self._handlers.get(name)
        if handler is None:
            return RedisReply.error(f"ERR unknown command '{name}'")
        try:
            return handler(args[1:])
        except Exception as e:
            return RedisReply.error(f"ERR handler raised: {e}")


class DictRedisService(RedisService):
    """A SET/GET/DEL/EXISTS/INCR in-memory impl — the fixture brpc's redis
    server test uses (and a usable micro-KV)."""

    def __init__(self):
        super().__init__()
        self._data: Dict[bytes, bytes] = {}
        self._data_lock = threading.Lock()
        self.add_command_handler("set", self._set)
        self.add_command_handler("get", self._get)
        self.add_command_handler("del", self._del)
        self.add_command_handler("exists", self._exists)
        self.add_command_handler("incr", self._incr)

    def _set(self, args):
        if len(args) != 2:
            return RedisReply.error("ERR wrong number of arguments for 'set'")
        with self._data_lock:
            self._data[args[0]] = args[1]
        return RedisReply.status("OK")

    def _get(self, args):
        if len(args) != 1:
            return RedisReply.error("ERR wrong number of arguments for 'get'")
        with self._data_lock:
            v = self._data.get(args[0])
        return RedisReply.nil() if v is None else RedisReply.string(v)

    def _del(self, args):
        n = 0
        with self._data_lock:
            for k in args:
                if self._data.pop(k, None) is not None:
                    n += 1
        return RedisReply.integer(n)

    def _exists(self, args):
        with self._data_lock:
            return RedisReply.integer(
                sum(1 for k in args if k in self._data))

    def _incr(self, args):
        if len(args) != 1:
            return RedisReply.error("ERR wrong number of arguments for 'incr'")
        with self._data_lock:
            try:
                v = int(self._data.get(args[0], b"0")) + 1
            except ValueError:
                return RedisReply.error(
                    "ERR value is not an integer or out of range")
            self._data[args[0]] = str(v).encode()
            return RedisReply.integer(v)
