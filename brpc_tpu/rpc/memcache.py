"""Memcache binary protocol — counterpart of brpc's memcache support
(/root/reference/src/brpc/memcache.{h,cpp},
policy/memcache_binary_protocol.cpp): MemcacheRequest batches binary ops
(get/set/delete/incr/decr/version), MemcacheResponse pops typed results.
A minimal server-side adaptor (MemcacheService) speaks the same binary
protocol, standing in for memcached in tests the way list:// NS stands in
for BNS.
"""
from __future__ import annotations

import struct
import threading
from typing import Dict, List, Optional, Tuple

MAGIC_REQUEST = 0x80
MAGIC_RESPONSE = 0x81

OP_GET = 0x00
OP_SET = 0x01
OP_ADD = 0x02
OP_REPLACE = 0x03
OP_DELETE = 0x04
OP_INCREMENT = 0x05
OP_DECREMENT = 0x06
OP_VERSION = 0x0B

STATUS_OK = 0x0000
STATUS_KEY_NOT_FOUND = 0x0001
STATUS_KEY_EXISTS = 0x0002
STATUS_ITEM_NOT_STORED = 0x0005

_HEADER = struct.Struct(">BBHBBHIIQ")  # 24 bytes


def pack_op(opcode: int, key: bytes = b"", value: bytes = b"",
            extras: bytes = b"", opaque: int = 0, cas: int = 0,
            magic: int = MAGIC_REQUEST, status: int = 0) -> bytes:
    body_len = len(extras) + len(key) + len(value)
    return _HEADER.pack(magic, opcode, len(key), len(extras), 0, status,
                        body_len, opaque, cas) + extras + key + value


def parse_op(data: bytes, pos: int) -> Optional[Tuple[dict, int]]:
    """Parse one binary packet at data[pos:]; None if incomplete."""
    if len(data) - pos < _HEADER.size:
        return None
    (magic, opcode, key_len, extras_len, _dtype, status, body_len, opaque,
     cas) = _HEADER.unpack_from(data, pos)
    total = _HEADER.size + body_len
    if len(data) - pos < total:
        return None
    body = data[pos + _HEADER.size: pos + total]
    extras = body[:extras_len]
    key = body[extras_len: extras_len + key_len]
    value = body[extras_len + key_len:]
    return ({"magic": magic, "opcode": opcode, "status": status,
             "extras": extras, "key": key, "value": value,
             "opaque": opaque, "cas": cas}, pos + total)


class MemcacheRequest:
    """Batched ops (memcache.h MemcacheRequest::Get/Set/...)."""

    def __init__(self):
        self._ops: List[bytes] = []
        self._opcodes: List[int] = []

    def _push(self, opcode: int, packet: bytes):
        self._ops.append(packet)
        self._opcodes.append(opcode)

    def get(self, key) -> "MemcacheRequest":
        self._push(OP_GET, pack_op(OP_GET, _b(key)))
        return self

    def set(self, key, value, flags: int = 0, exptime: int = 0,
            cas: int = 0) -> "MemcacheRequest":
        extras = struct.pack(">II", flags, exptime)
        self._push(OP_SET, pack_op(OP_SET, _b(key), _b(value), extras,
                                   cas=cas))
        return self

    def add(self, key, value, flags: int = 0, exptime: int = 0):
        extras = struct.pack(">II", flags, exptime)
        self._push(OP_ADD, pack_op(OP_ADD, _b(key), _b(value), extras))
        return self

    def replace(self, key, value, flags: int = 0, exptime: int = 0):
        extras = struct.pack(">II", flags, exptime)
        self._push(OP_REPLACE, pack_op(OP_REPLACE, _b(key), _b(value), extras))
        return self

    def delete(self, key) -> "MemcacheRequest":
        self._push(OP_DELETE, pack_op(OP_DELETE, _b(key)))
        return self

    def incr(self, key, delta: int = 1, initial: int = 0,
             exptime: int = 0) -> "MemcacheRequest":
        extras = struct.pack(">QQI", delta, initial, exptime)
        self._push(OP_INCREMENT, pack_op(OP_INCREMENT, _b(key), b"", extras))
        return self

    def decr(self, key, delta: int = 1, initial: int = 0, exptime: int = 0):
        extras = struct.pack(">QQI", delta, initial, exptime)
        self._push(OP_DECREMENT, pack_op(OP_DECREMENT, _b(key), b"", extras))
        return self

    def version(self) -> "MemcacheRequest":
        self._push(OP_VERSION, pack_op(OP_VERSION))
        return self

    @property
    def op_count(self) -> int:
        return len(self._ops)

    def serialize(self) -> bytes:
        return b"".join(self._ops)


class MemcacheResponse:
    """Typed result popper (memcache.h MemcacheResponse::PopGet/...)."""

    def __init__(self):
        self._results: List[dict] = []
        self._pop_index = 0

    def add_result(self, result: dict):
        self._results.append(result)

    @property
    def result_count(self) -> int:
        return len(self._results)

    def _pop(self) -> Optional[dict]:
        if self._pop_index >= len(self._results):
            return None
        r = self._results[self._pop_index]
        self._pop_index += 1
        return r

    def pop_get(self) -> Tuple[bool, Optional[bytes]]:
        r = self._pop()
        if r is None or r["status"] != STATUS_OK:
            return False, None
        return True, r["value"]

    def pop_store(self) -> bool:  # set/add/replace/delete
        r = self._pop()
        return r is not None and r["status"] == STATUS_OK

    pop_set = pop_store
    pop_delete = pop_store

    def pop_counter(self) -> Tuple[bool, int]:  # incr/decr
        r = self._pop()
        if r is None or r["status"] != STATUS_OK or len(r["value"]) != 8:
            return False, 0
        return True, struct.unpack(">Q", r["value"])[0]

    def pop_version(self) -> Tuple[bool, str]:
        r = self._pop()
        if r is None or r["status"] != STATUS_OK:
            return False, ""
        return True, r["value"].decode()


def _b(v) -> bytes:
    return v if isinstance(v, bytes) else str(v).encode()


class MemcacheService:
    """Server-side binary-protocol KV (test double for memcached)."""

    VERSION = "brpc_tpu-memcache-0.1"

    def __init__(self):
        self._data: Dict[bytes, Tuple[bytes, int]] = {}  # key -> (value, flags)
        self._lock = threading.Lock()

    def handle(self, op: dict) -> bytes:
        opcode = op["opcode"]
        key, value, extras = op["key"], op["value"], op["extras"]
        opaque = op["opaque"]

        def resp(status=STATUS_OK, value=b"", extras=b""):
            return pack_op(opcode, b"", value, extras, opaque=opaque,
                           magic=MAGIC_RESPONSE, status=status)

        with self._lock:
            if opcode == OP_GET:
                entry = self._data.get(key)
                if entry is None:
                    return resp(STATUS_KEY_NOT_FOUND)
                v, flags = entry
                return resp(value=v, extras=struct.pack(">I", flags))
            if opcode in (OP_SET, OP_ADD, OP_REPLACE):
                flags = struct.unpack(">II", extras)[0] if len(extras) >= 8 else 0
                exists = key in self._data
                if opcode == OP_ADD and exists:
                    return resp(STATUS_KEY_EXISTS)
                if opcode == OP_REPLACE and not exists:
                    return resp(STATUS_ITEM_NOT_STORED)
                self._data[key] = (value, flags)
                return resp()
            if opcode == OP_DELETE:
                if self._data.pop(key, None) is None:
                    return resp(STATUS_KEY_NOT_FOUND)
                return resp()
            if opcode in (OP_INCREMENT, OP_DECREMENT):
                delta, initial, _exp = struct.unpack(">QQI", extras)
                entry = self._data.get(key)
                if entry is None:
                    n = initial
                else:
                    try:
                        n = int(entry[0])
                    except ValueError:
                        return resp(STATUS_ITEM_NOT_STORED)
                    n = n + delta if opcode == OP_INCREMENT else max(0, n - delta)
                self._data[key] = (str(n).encode(), 0)
                return resp(value=struct.pack(">Q", n))
            if opcode == OP_VERSION:
                return resp(value=self.VERSION.encode())
        return resp(STATUS_ITEM_NOT_STORED)
