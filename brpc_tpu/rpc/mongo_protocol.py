"""Mongo server-side protocol — counterpart of
/root/reference/src/brpc/policy/mongo_protocol.cpp: lets a server speak the
MongoDB wire protocol so mongo drivers can talk to it. Server-only, like
the reference (global.cpp registers no mongo client path); gated on
ServerOptions.mongo_service_adaptor the way ParseMongoMessage bails with
TRY_OTHERS when the server has no adaptor (mongo_protocol.cpp:110-118).
"""
from __future__ import annotations

import time

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.rpc.mongo import (
    HEAD_SIZE,
    MongoHead,
    MongoRequest,
    MongoResponse,
    is_mongo_opcode,
)
from brpc_tpu.rpc.protocol import (
    InputMessageBase,
    ParseResult,
    Protocol,
    ProtocolType,
    register_protocol,
)

MAX_BODY = 48 << 20  # mongo's own wire limit


class MongoInputMessage(InputMessageBase):
    __slots__ = ("req",)

    def __init__(self, req: MongoRequest):
        super().__init__()
        self.req = req


def parse(portal: IOBuf, sock, read_eof: bool, arg) -> ParseResult:
    server = arg
    adaptor = getattr(getattr(server, "options", None),
                      "mongo_service_adaptor", None)
    if adaptor is None:
        return ParseResult.try_others()
    if len(portal) < HEAD_SIZE:
        return ParseResult.not_enough()
    head = MongoHead.unpack(portal.copy_to_bytes(HEAD_SIZE))
    if (not is_mongo_opcode(head.op_code)
            or head.message_length < HEAD_SIZE
            or head.message_length > MAX_BODY):
        return ParseResult.try_others()
    if len(portal) < head.message_length:
        return ParseResult.not_enough()
    portal.pop_front(HEAD_SIZE)
    body = portal.cutn_bytes(head.message_length - HEAD_SIZE)
    # First message on the connection: attach the adaptor's context
    # (MongoContextMessage role, mongo_protocol.cpp:146-153).
    if getattr(sock, "mongo_context", None) is None:
        sock.mongo_context = adaptor.create_socket_context()
    try:
        req = MongoRequest(head, body)  # pre-parses OP_QUERY fields
    except Exception:
        return ParseResult.error_()  # malformed body: close the connection
    return ParseResult.ok(MongoInputMessage(req))


def process_request(msg: MongoInputMessage):
    """ProcessMongoRequest analog (mongo_protocol.cpp:173)."""
    server = msg.arg
    sock = msg.socket
    adaptor = server.options.mongo_service_adaptor
    cntl = Controller()
    cntl.server = server
    cntl.remote_side = sock.remote_side
    cntl._server_socket = sock
    cntl.server_start_time = time.monotonic()
    cntl.mongo_session_data = getattr(sock, "mongo_context", None)

    response = MongoResponse()
    responded = [False]

    def done():
        if responded[0]:
            return
        responded[0] = True
        if cntl.failed():
            out = adaptor.serialize_error(msg.req.head.request_id)
        else:
            out = response.pack(msg.req.head.request_id,
                                msg.req.head.request_id)
        sock.write(IOBuf(out))
        if cntl.close_connection_flag:
            sock.set_failed(errors.ECLOSE, "close_connection requested")

    try:
        adaptor.process_mongo_request(cntl, msg.req, response, done)
    except Exception as e:
        if not responded[0]:
            cntl.set_failed(errors.EINVAL, f"mongo adaptor raised: {e}")
            done()


register_protocol(Protocol(
    name="mongo",
    type=ProtocolType.MONGO,
    parse=parse,
    process_request=process_request,
    support_client=False,
))

