"""Cross-process fake of the jax transfer fabric — the xfer-lane test
transport.

The real lane rides `jax.experimental.transfer` (the ICI/DCN bulk fabric;
rdma_endpoint.h:55-57's role cross-host), but the CPU backend's bulk
transport is same-process-only, so the FULL pull path could not run in a
two-process test. This fake implements the same server surface over plain
TCP: `await_pull` parks published arrays, a peer's `connect(addr)` /
`pull(uid, specs)` dials back and streams the bytes, and serving a pull
releases the retained publication (the pull-completes-then-free retention
semantics). It is a test fixture in the package by design — the same
discipline as the file/list naming services doubling as fixtures
(SURVEY.md §4).

Enable with BRPC_TPU_FAKE_XFER=1 (picked up by
device_transport._global_xfer_server) or install directly.
"""
from __future__ import annotations

import socket
import struct
import threading


def _recv_exact(conn: socket.socket, n: int):
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class FakeTransferConnection:
    def __init__(self, addr: str):
        self.addr = addr

    def pull(self, uid: int, specs):
        """Dial the publisher and stream each array's bytes; materialize
        per the ShapeDtypeStructs (device placement from the sharding)."""
        import jax
        import numpy as np

        host, _, port = self.addr.rpartition(":")
        with socket.create_connection((host, int(port)), timeout=10) as c:
            c.sendall(struct.pack(">Q", uid))
            head = _recv_exact(c, 4)
            if head is None:
                raise ConnectionError("fake transfer: publisher hung up")
            (count,) = struct.unpack(">I", head)
            if count != len(specs):
                raise ValueError(
                    f"fake transfer: {count} arrays published, "
                    f"{len(specs)} requested")
            arrays = []
            for spec in specs:
                (nbytes,) = struct.unpack(">Q", _recv_exact(c, 8))
                raw = _recv_exact(c, nbytes)
                arr = np.frombuffer(raw, dtype=spec.dtype).reshape(
                    spec.shape)
                device = None
                if spec.sharding is not None:
                    device = next(iter(spec.sharding.device_set))
                arrays.append(jax.device_put(arr, device))
            return arrays


class FakeTransferServer:
    """Quacks like jax.experimental.transfer's server: address(),
    await_pull(uid, arrays), connect(addr)."""

    def __init__(self, ip: str = "127.0.0.1"):
        self._published = {}
        self._cv = threading.Condition()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((ip, 0))
        self._listener.listen(16)
        self._port = self._listener.getsockname()[1]
        self._stopping = False
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="fake_xfer_server")
        t.start()

    # wildcard on purpose: exercises the peer-facing address resolution
    # (resolve_xfer_addr substitutes the handshake connection's IP)
    def address(self) -> str:
        return f"0.0.0.0:{self._port}"

    def await_pull(self, uid: int, arrays):
        with self._cv:
            self._published[uid] = list(arrays)
            self._cv.notify_all()

    def connect(self, addr: str) -> FakeTransferConnection:
        return FakeTransferConnection(addr)

    def published_count(self) -> int:
        with self._cv:
            return len(self._published)

    # -- server side --------------------------------------------------------
    def _accept_loop(self):
        while not self._stopping:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket):
        import numpy as np

        with conn:
            head = _recv_exact(conn, 8)
            if head is None:
                return
            (uid,) = struct.unpack(">Q", head)
            deadline = 10.0
            with self._cv:
                while uid not in self._published and deadline > 0:
                    self._cv.wait(0.2)
                    deadline -= 0.2
                # serving the pull RELEASES the publication (the sender's
                # buffers are free once the peer's pull completes)
                arrays = self._published.pop(uid, None)
            if arrays is None:
                conn.sendall(struct.pack(">I", 0))
                return
            conn.sendall(struct.pack(">I", len(arrays)))
            for a in arrays:
                raw = np.ascontiguousarray(np.asarray(a)).tobytes()
                conn.sendall(struct.pack(">Q", len(raw)) + raw)

    def stop(self):
        self._stopping = True
        try:
            self._listener.close()
        except OSError:
            pass
