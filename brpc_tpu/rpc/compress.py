"""Compression registry — counterpart of brpc/compress.{h,cpp} +
policy/{gzip,snappy}_compress.cpp (registered in global.cpp:379-391). gzip
and zlib via the stdlib; snappy is a self-contained block-format codec
(the reference vendors snappy under butil/third_party/snappy); the
registry is pluggable like the reference's.
"""
from __future__ import annotations

import struct
import zlib
from typing import Callable, Dict, Tuple

# compress_type codes match controller.py / rpc_meta.proto
COMPRESS_NONE = 0
COMPRESS_GZIP = 1
COMPRESS_ZLIB = 2
COMPRESS_SNAPPY = 3

_handlers: Dict[int, Tuple[Callable[[bytes], bytes], Callable[[bytes], bytes]]] = {}


def register_compress(ctype: int, compress_fn, decompress_fn):
    _handlers[ctype] = (compress_fn, decompress_fn)


def compress(data: bytes, ctype: int) -> bytes:
    if ctype == COMPRESS_NONE or not data:
        return data
    pair = _handlers.get(ctype)
    if pair is None:
        raise ValueError(f"unknown compress type {ctype}")
    return pair[0](data)


def decompress(data: bytes, ctype: int) -> bytes:
    if ctype == COMPRESS_NONE or not data:
        return data
    pair = _handlers.get(ctype)
    if pair is None:
        raise ValueError(f"unknown compress type {ctype}")
    return pair[1](data)


def _gzip_compress(d: bytes) -> bytes:
    # zlib.compress only grew a wbits parameter in 3.11; compressobj
    # takes it everywhere, so the gzip wrapper (wbits=31) goes this way
    co = zlib.compressobj(6, zlib.DEFLATED, 31)
    return co.compress(d) + co.flush()


register_compress(
    COMPRESS_GZIP,
    _gzip_compress,
    lambda d: zlib.decompress(d, wbits=31),
)
register_compress(
    COMPRESS_ZLIB,
    lambda d: zlib.compress(d, 6),
    lambda d: zlib.decompress(d),
)


# -- snappy block format ----------------------------------------------------
# Wire-compatible with google/snappy's format description: a varint32
# uncompressed length, then literal elements (tag 00) and copy elements
# (tags 01/10/11). The encoder emits literals and 2-byte-offset copies
# found via a rolling 4-byte hash, like snappy's fast path.

def _varint_encode(n: int) -> bytes:
    out = bytearray()
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)
    return bytes(out)


def _varint_decode(data: bytes, pos: int) -> Tuple[int, int]:
    shift = n = 0
    while True:
        b = data[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7
        if shift > 35:
            raise ValueError("snappy: varint too long")


def _emit_literal(out: bytearray, data, start: int, end: int):
    length = end - start
    while length > 0:
        run = min(length, 1 << 32)
        n = run - 1
        if n < 60:
            out.append(n << 2)
        elif n < (1 << 8):
            out.append(60 << 2)
            out.append(n)
        elif n < (1 << 16):
            out.append(61 << 2)
            out += struct.pack("<H", n)
        elif n < (1 << 24):
            out.append(62 << 2)
            out += struct.pack("<I", n)[:3]
        else:
            out.append(63 << 2)
            out += struct.pack("<I", n)
        out += data[start:start + run]
        start += run
        length -= run


def snappy_compress(data: bytes) -> bytes:
    data = bytes(data)
    n = len(data)
    out = bytearray(_varint_encode(n))
    if n < 4:
        if n:
            _emit_literal(out, data, 0, n)
        return bytes(out)
    table: Dict[bytes, int] = {}
    pos = lit_start = 0
    limit = n - 4
    while pos <= limit:
        key = data[pos:pos + 4]
        cand = table.get(key)
        table[key] = pos
        if cand is None or pos - cand > 0xFFFF:
            pos += 1
            continue
        # extend the match
        mlen = 4
        while (pos + mlen < n and mlen < 64
               and data[cand + mlen] == data[pos + mlen]):
            mlen += 1
        if lit_start < pos:
            _emit_literal(out, data, lit_start, pos)
        offset = pos - cand
        out.append(((mlen - 1) << 2) | 2)  # tag 10: 2-byte offset copy
        out += struct.pack("<H", offset)
        pos += mlen
        lit_start = pos
    if lit_start < n:
        _emit_literal(out, data, lit_start, n)
    return bytes(out)


def snappy_decompress(data: bytes) -> bytes:
    total, pos = _varint_decode(data, 0)
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        elem = tag & 3
        if elem == 0:  # literal
            length = (tag >> 2) + 1
            if length > 60:
                nbytes = length - 60
                length = int.from_bytes(data[pos:pos + nbytes], "little") + 1
                pos += nbytes
            out += data[pos:pos + length]
            pos += length
        else:
            if elem == 1:  # 1-byte offset, len 4-11
                length = ((tag >> 2) & 0x7) + 4
                offset = ((tag >> 5) << 8) | data[pos]
                pos += 1
            elif elem == 2:  # 2-byte LE offset
                length = (tag >> 2) + 1
                offset = struct.unpack_from("<H", data, pos)[0]
                pos += 2
            else:  # 4-byte LE offset
                length = (tag >> 2) + 1
                offset = struct.unpack_from("<I", data, pos)[0]
                pos += 4
            if offset == 0 or offset > len(out):
                raise ValueError("snappy: bad copy offset")
            start = len(out) - offset
            if offset >= length:  # disjoint: one slice copy
                out += out[start:start + length]
            else:
                for i in range(length):  # self-overlapping (RLE-style)
                    out.append(out[start + i])
    if len(out) != total:
        raise ValueError(
            f"snappy: declared {total} bytes, decoded {len(out)}")
    return bytes(out)


register_compress(COMPRESS_SNAPPY, snappy_compress, snappy_decompress)
