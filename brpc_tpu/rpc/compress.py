"""Compression registry — counterpart of brpc/compress.{h,cpp} +
policy/gzip_compress.cpp (registered in global.cpp:379-391). gzip and zlib
via the stdlib; the registry is pluggable like the reference's.
"""
from __future__ import annotations

import zlib
from typing import Callable, Dict, Tuple

# compress_type codes match controller.py / rpc_meta.proto
COMPRESS_NONE = 0
COMPRESS_GZIP = 1
COMPRESS_ZLIB = 2

_handlers: Dict[int, Tuple[Callable[[bytes], bytes], Callable[[bytes], bytes]]] = {}


def register_compress(ctype: int, compress_fn, decompress_fn):
    _handlers[ctype] = (compress_fn, decompress_fn)


def compress(data: bytes, ctype: int) -> bytes:
    if ctype == COMPRESS_NONE or not data:
        return data
    pair = _handlers.get(ctype)
    if pair is None:
        raise ValueError(f"unknown compress type {ctype}")
    return pair[0](data)


def decompress(data: bytes, ctype: int) -> bytes:
    if ctype == COMPRESS_NONE or not data:
        return data
    pair = _handlers.get(ctype)
    if pair is None:
        raise ValueError(f"unknown compress type {ctype}")
    return pair[1](data)


register_compress(
    COMPRESS_GZIP,
    lambda d: zlib.compress(d, 6, wbits=31),
    lambda d: zlib.decompress(d, wbits=31),
)
register_compress(
    COMPRESS_ZLIB,
    lambda d: zlib.compress(d, 6),
    lambda d: zlib.decompress(d),
)
