"""MPEG-TS muxer/demuxer — the `ts.{h,cpp}` role of the reference's RTMP
family (/root/reference/src/brpc/ts.h): packetize the media that RTMP
carries into 188-byte transport-stream packets (PAT/PMT program tables
with MPEG CRC32, PES packetization with PTS, continuity counters,
adaptation-field stuffing), the container HLS segments use.

Scope matches the reference's: H.264 (stream type 0x1B) and AAC (0x0F)
elementary streams in one program. The demuxer half reassembles PES
payloads by PID — used by tests and by anything consuming the segments.
"""
from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Optional, Tuple

TS_PACKET = 188
SYNC = 0x47

PID_PAT = 0x0000
PID_PMT = 0x1000
PID_VIDEO = 0x0100
PID_AUDIO = 0x0101

STREAM_TYPE_H264 = 0x1B
STREAM_TYPE_AAC = 0x0F

PES_SID_VIDEO = 0xE0
PES_SID_AUDIO = 0xC0


def _crc32_mpeg(data: bytes) -> int:
    """CRC32/MPEG-2 (poly 0x04C11DB7, init 0xFFFFFFFF, no reflection)."""
    crc = 0xFFFFFFFF
    for b in data:
        crc ^= b << 24
        for _ in range(8):
            crc = ((crc << 1) ^ 0x04C11DB7 if crc & 0x80000000
                   else crc << 1) & 0xFFFFFFFF
    return crc


def _psi_packet(pid: int, table: bytes, cc: int) -> bytes:
    """One TS packet carrying a PSI section (pointer_field form)."""
    header = struct.pack(">BHB", SYNC, 0x4000 | pid,  # PUSI set
                         0x10 | (cc & 0x0F))          # payload only
    payload = b"\x00" + table  # pointer_field = 0
    pad = TS_PACKET - 4 - len(payload)
    return header + payload + b"\xff" * pad


def _pat_table() -> bytes:
    # one 4-byte program entry: program_number, reserved(3)+PMT PID
    body = struct.pack(">HH", 1, 0xE000 | PID_PMT)
    # table_id 0, section_syntax, length = body after this field + crc
    sec = struct.pack(">BH", 0x00, 0xB000 | (len(body) + 5 + 4))
    sec += struct.pack(">HBBB", 1, 0xC1, 0, 0)  # tsid, ver/cur, sec, last
    sec += body
    sec += struct.pack(">I", _crc32_mpeg(sec))
    return sec


def _pmt_table(has_audio: bool) -> bytes:
    streams = struct.pack(">BHH", STREAM_TYPE_H264, 0xE000 | PID_VIDEO,
                          0xF000 | 0)
    if has_audio:
        streams += struct.pack(">BHH", STREAM_TYPE_AAC, 0xE000 | PID_AUDIO,
                               0xF000 | 0)
    body = struct.pack(">HH", 0xE000 | PID_VIDEO, 0xF000 | 0)  # PCR + pinfo
    body += streams
    sec = struct.pack(">BH", 0x02, 0xB000 | (len(body) + 5 + 4))
    sec += struct.pack(">HBBB", 1, 0xC1, 0, 0)  # program, ver/cur, sec, last
    sec += body
    sec += struct.pack(">I", _crc32_mpeg(sec))
    return sec


def _pts_field(pts: int, marker: int) -> bytes:
    pts &= (1 << 33) - 1
    return bytes([
        (marker << 4) | (((pts >> 30) & 0x7) << 1) | 1,
        (pts >> 22) & 0xFF,
        (((pts >> 15) & 0x7F) << 1) | 1,
        (pts >> 7) & 0xFF,
        ((pts & 0x7F) << 1) | 1,
    ])


def _pes(stream_id: int, pts_90k: int, payload: bytes) -> bytes:
    header = b"\x00\x00\x01" + bytes([stream_id])
    flags = b"\x80\x80\x05" + _pts_field(pts_90k, 0x2)  # PTS only
    length = len(flags) + len(payload)
    if length > 0xFFFF:
        if stream_id == PES_SID_VIDEO:
            length = 0  # unbounded video PES is legal
        else:
            raise ValueError(
                f"ts: audio PES payload too large ({len(payload)} bytes); "
                "split frames above 65527 bytes")
    return header + struct.pack(">H", length) + flags + payload


class TsMuxer:
    """Streams (pid, pts_ms, es_payload) into 188-byte packets. Call
    write_video/write_audio per access unit; packets() yields the bytes
    (PAT+PMT are emitted at start and can be re-emitted via write_psi
    for segment boundaries)."""

    def __init__(self, has_audio: bool = True):
        self._cc: Dict[int, int] = {PID_PAT: 0, PID_PMT: 0,
                                    PID_VIDEO: 0, PID_AUDIO: 0}
        self._out: List[bytes] = []
        self.has_audio = has_audio
        self._pcr_sent = False  # PMT advertises PCR on the video PID:
        self.write_psi()        # at least one PCR must actually appear

    def write_psi(self):
        self._out.append(_psi_packet(PID_PAT, _pat_table(),
                                     self._bump(PID_PAT)))
        self._out.append(_psi_packet(PID_PMT, _pmt_table(self.has_audio),
                                     self._bump(PID_PMT)))

    def _bump(self, pid: int) -> int:
        cc = self._cc[pid]
        self._cc[pid] = (cc + 1) & 0x0F
        return cc

    def _emit_pes(self, pid: int, sid: int, pts_ms: int, payload: bytes,
                  pcr: bool):
        pes = _pes(sid, pts_ms * 90, payload)
        pos = 0
        first = True
        while pos < len(pes) or first:
            remaining = len(pes) - pos
            cc = self._bump(pid)
            flags2 = 0x10 | (cc & 0x0F)  # payload present
            adaptation = b""
            if first and pcr:
                pcr_base = (pts_ms * 90) & ((1 << 33) - 1)
                adaptation = bytes([7, 0x10]) + bytes([
                    (pcr_base >> 25) & 0xFF, (pcr_base >> 17) & 0xFF,
                    (pcr_base >> 9) & 0xFF, (pcr_base >> 1) & 0xFF,
                    ((pcr_base & 1) << 7) | 0x7E, 0x00])
            room = TS_PACKET - 4 - len(adaptation)
            if remaining < room:
                # stuff via adaptation field so the packet fills exactly
                stuff = room - remaining
                if adaptation:
                    adaptation = (bytes([adaptation[0] + stuff])
                                  + adaptation[1:] + b"\xff" * stuff)
                elif stuff == 1:
                    adaptation = bytes([0])
                else:
                    adaptation = bytes([stuff - 1, 0x00]) + b"\xff" * (
                        stuff - 2)
            if adaptation:
                flags2 |= 0x20
            header = struct.pack(
                ">BHB", SYNC, (0x4000 if first else 0) | pid, flags2)
            take = TS_PACKET - 4 - len(adaptation)
            chunk = pes[pos:pos + take]
            self._out.append(header + adaptation + chunk)
            pos += take
            first = False

    def write_video(self, pts_ms: int, es: bytes, keyframe: bool = False):
        # the first access unit always carries a PCR (consumers cannot
        # establish a clock from a PCR-less stream, TR 101 290), then
        # keyframes refresh it
        pcr = keyframe or not self._pcr_sent
        self._pcr_sent = True
        self._emit_pes(PID_VIDEO, PES_SID_VIDEO, pts_ms, es, pcr=pcr)

    def write_audio(self, pts_ms: int, es: bytes):
        if not self.has_audio:
            raise ValueError("muxer created without an audio stream")
        self._emit_pes(PID_AUDIO, PES_SID_AUDIO, pts_ms, es, pcr=False)

    def packets(self) -> bytes:
        out = b"".join(self._out)
        self._out = []
        return out


def demux(data: bytes) -> Iterator[Tuple[int, Optional[int], bytes]]:
    """Yields (pid, pts_ms or None, es_payload) per completed PES packet;
    PSI pids are skipped. Raises ValueError on sync loss."""
    if len(data) % TS_PACKET != 0:
        raise ValueError(
            f"ts: truncated stream ({len(data)} bytes is not a multiple "
            f"of {TS_PACKET})")
    assembling: Dict[int, List[bytes]] = {}
    for off in range(0, len(data), TS_PACKET):
        pkt = data[off:off + TS_PACKET]
        if pkt[0] != SYNC:
            raise ValueError(f"ts: sync loss at offset {off}")
        pusi = bool(pkt[1] & 0x40)
        pid = ((pkt[1] & 0x1F) << 8) | pkt[2]
        afc = (pkt[3] >> 4) & 0x3
        pos = 4
        if afc & 0x2:  # adaptation field
            pos += 1 + pkt[4]
        if not afc & 0x1:
            continue  # no payload
        if pid in (PID_PAT, PID_PMT):
            continue
        payload = pkt[pos:]
        if pusi:
            if pid in assembling:
                yield _finish_pes(pid, b"".join(assembling.pop(pid)))
            assembling[pid] = [payload]
        elif pid in assembling:
            assembling[pid].append(payload)
    for pid, parts in assembling.items():
        yield _finish_pes(pid, b"".join(parts))


def _finish_pes(pid: int, pes: bytes) -> Tuple[int, Optional[int], bytes]:
    if len(pes) < 9:
        raise ValueError("ts: truncated PES header")
    if pes[:3] != b"\x00\x00\x01":
        raise ValueError("ts: bad PES start code")
    flags = pes[7]
    hlen = pes[8]
    if len(pes) < 9 + hlen or (flags & 0x80 and hlen < 5):
        raise ValueError("ts: truncated PES optional header")
    pts_ms = None
    if flags & 0x80:
        p = pes[9:14]
        pts = (((p[0] >> 1) & 0x7) << 30) | (p[1] << 22) | \
            ((p[2] >> 1) << 15) | (p[3] << 7) | (p[4] >> 1)
        pts_ms = pts // 90
    return pid, pts_ms, pes[9 + hlen:]
