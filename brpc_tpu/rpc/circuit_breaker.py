"""CircuitBreaker — per-node EMA error recorder with isolation.

Counterpart of brpc::CircuitBreaker
(/root/reference/src/brpc/circuit_breaker.h:25-85): two EMA windows (long +
short) of error rate judged on every OnCallEnd; crossing a threshold
isolates the node (the channel then SetFaileds its socket, and health-check
revival brings it back). Repeated isolation within a short period grows the
isolation duration, as in the reference.
"""
from __future__ import annotations

import threading
import time

from brpc_tpu.butil import flags

flags.define_int("circuit_breaker_short_window_size", 128,
                 "sample count of the short EMA window")
flags.define_int("circuit_breaker_long_window_size", 1024,
                 "sample count of the long EMA window")
flags.define_int("circuit_breaker_short_window_error_percent", 10,
                 "max error percent tolerated by the short window")
flags.define_int("circuit_breaker_long_window_error_percent", 5,
                 "max error percent tolerated by the long window")
flags.define_int("circuit_breaker_min_isolation_duration_ms", 100,
                 "first isolation duration")
flags.define_int("circuit_breaker_max_isolation_duration_ms", 30000,
                 "isolation duration ceiling")


class _EmaWindow:
    def __init__(self, window_size: int, max_error_percent: int):
        self._alpha = 2.0 / (window_size + 1)
        self._threshold = max_error_percent / 100.0
        self._ema_error = 0.0

    def on_call(self, is_error: bool) -> bool:
        """Returns False when the window votes to isolate."""
        sample = 1.0 if is_error else 0.0
        self._ema_error = (1 - self._alpha) * self._ema_error + self._alpha * sample
        return self._ema_error < self._threshold

    @property
    def error_rate(self) -> float:
        return self._ema_error


class CircuitBreaker:
    def __init__(self):
        self._short = _EmaWindow(
            flags.get_flag("circuit_breaker_short_window_size"),
            flags.get_flag("circuit_breaker_short_window_error_percent"),
        )
        self._long = _EmaWindow(
            flags.get_flag("circuit_breaker_long_window_size"),
            flags.get_flag("circuit_breaker_long_window_error_percent"),
        )
        self._lock = threading.Lock()
        self._broken = False
        self._isolation_ms = flags.get_flag(
            "circuit_breaker_min_isolation_duration_ms")
        self._isolated_until = 0.0
        self._last_isolation = 0.0

    def on_call_end(self, error_code: int, latency_us: float) -> bool:
        """Feed one finished call; returns False when the node should be
        isolated (OnCallEnd, circuit_breaker.h:40)."""
        is_error = error_code != 0
        with self._lock:
            if self._broken:
                return False
            ok = self._short.on_call(is_error) and self._long.on_call(is_error)
            if not ok:
                self._mark_isolated_locked()
                return False
            return True

    def _mark_isolated_locked(self):
        now = time.monotonic()
        max_ms = flags.get_flag("circuit_breaker_max_isolation_duration_ms")
        # double the duration when re-isolated soon after the last one
        if now - self._last_isolation < 30.0 and self._last_isolation > 0:
            self._isolation_ms = min(self._isolation_ms * 2, max_ms)
        else:
            self._isolation_ms = flags.get_flag(
                "circuit_breaker_min_isolation_duration_ms")
        self._broken = True
        self._last_isolation = now
        self._isolated_until = now + self._isolation_ms / 1000.0

    def is_broken(self) -> bool:
        with self._lock:
            return self._broken

    def isolation_duration_ms(self) -> int:
        return int(self._isolation_ms)

    def remaining_isolation_s(self) -> float:
        with self._lock:
            return max(0.0, self._isolated_until - time.monotonic())

    def reset(self):
        """Called on revival (health check succeeded)."""
        with self._lock:
            self._broken = False
            self._short = _EmaWindow(
                flags.get_flag("circuit_breaker_short_window_size"),
                flags.get_flag("circuit_breaker_short_window_error_percent"),
            )
            self._long = _EmaWindow(
                flags.get_flag("circuit_breaker_long_window_size"),
                flags.get_flag("circuit_breaker_long_window_error_percent"),
            )
