"""Controller — the per-RPC god object (both sides).

Counterpart of brpc::Controller (/root/reference/src/brpc/controller.{h,cpp}):
client side it carries timeout/retry/backup state and drives IssueRPC →
OnVersionedRPCReturned; server side it exposes peer identity, attachments,
and set_failed. The CallId is a ranged bthread_id (controller.h:655-664):
version v+1+nretry addresses attempt nretry, so a late response from an
abandoned attempt and the live attempt cannot be confused, and
timeout/socket-failure/response delivery all serialize through the id lock
(the on_error path of id.py).

Tensor-native extension: request/response attachments are IOBufs, so
jax.Arrays ride them zero-copy until a host wire boundary
(butil/iobuf.py DEVICE blocks).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from brpc_tpu import bvar
from brpc_tpu.bthread import id as bthread_id
from brpc_tpu.bthread import timer_add, timer_del
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.rpc import errors

_client_count = bvar.Adder("rpc_client_calls")
_backup_count = bvar.Adder("rpc_backup_requests")
_retry_count = bvar.Adder("rpc_retries")

COMPRESS_NONE = 0
COMPRESS_GZIP = 1
COMPRESS_ZLIB = 2


class RetryPolicy:
    """Pluggable retry decision (brpc::RetryPolicy, retry_policy.h)."""

    def do_retry(self, controller: "Controller") -> bool:
        # Default: retry connection-level failures, never timeouts/app errors
        # (policy of retry_policy.cpp DefaultRetryPolicy).
        return controller.error_code in (
            errors.EFAILEDSOCKET,
            errors.ECLOSE,
            errors.ETIMEDOUT,  # connect timeout, not RPC deadline
            errors.EEOF,
        )


DEFAULT_RETRY_POLICY = RetryPolicy()


class Controller:
    def __init__(self):
        self.reset()

    def reset(self):
        # shared
        self.error_code_value = 0
        self.error_text_value = ""
        self.request_attachment = IOBuf()
        self.response_attachment = IOBuf()
        self.compress_type = COMPRESS_NONE
        self.log_id = 0
        self.remote_side = None
        self.local_side = None
        # client
        self.timeout_ms: Optional[float] = None
        self.max_retry: int = 3
        self.backup_request_ms: Optional[float] = None
        self.retry_policy: RetryPolicy = DEFAULT_RETRY_POLICY
        self.retried_count = 0
        self.has_backup_request = False
        self.latency_us = 0.0
        self._call_id = 0
        self._start_time = 0.0
        self._deadline: Optional[float] = None
        self._timeout_timer = None
        self._backup_timer = None
        self._done: Optional[Callable] = None
        self._ended = threading.Event()
        self._request = None
        self._response = None
        self._request_payload = b""
        self._method_full_name = ""
        self._channel = None
        self._current_sock = None
        self._single_server_sid = None
        self._lb = None
        self._excluded_sids = set()
        self._accessed_sids = set()
        # server
        self.server = None
        self.method_name = ""
        self.service_name = ""
        self.close_connection_flag = False
        self.server_start_time = 0.0
        self._server_meta = None
        self.auth_context = None
        self.session_local_data = None
        # streaming (stream.py): client-created stream riding the request,
        # server-side remote id + accepted stream (stream.cpp:98-115)
        self._request_stream = None
        self._remote_stream_id = 0
        self._server_socket = None
        self._accepted_stream = None
        # http (http_protocol.py): request/response objects on either side
        self.http_request = None
        self.http_response = None
        # tensor lane (device_transport.py): outbound arrays on the client,
        # inbound/outbound RpcMeta handles on the server
        self._outbound_tensors = None
        self._rpc_meta = None
        self._response_meta = None
        self._response_rpc_meta = None
        # tracing
        self.trace_id = 0
        self.span_id = 0
        self.span = None

    # -- error state -------------------------------------------------------
    @property
    def error_code(self) -> int:
        return self.error_code_value

    @property
    def error_text(self) -> str:
        return self.error_text_value

    def failed(self) -> bool:
        return self.error_code_value != 0

    def set_failed(self, error_code: int, error_text: str = ""):
        self.error_code_value = error_code or errors.EINVAL
        self.error_text_value = error_text or errors.berror(self.error_code_value)

    def close_connection(self, reason: str = ""):
        self.close_connection_flag = True

    # -- client call lifecycle --------------------------------------------
    @property
    def call_id(self) -> int:
        return self._call_id

    def _setup_call(self, channel, method_full_name: str, request, response,
                    done: Optional[Callable]):
        self._channel = channel
        self._method_full_name = method_full_name
        self._request = request
        self._response = response
        self._done = done
        self._start_time = time.monotonic()
        if self.timeout_ms is not None and self.timeout_ms >= 0:
            self._deadline = self._start_time + self.timeout_ms / 1000.0
        # range = max_retry+2: version v is the "collective" id, v+1+k is
        # attempt k (controller.h:655-664).
        self._call_id = bthread_id.create_ranged(
            self, self._on_error, self.max_retry + 2
        )
        from brpc_tpu import rpcz

        self.span = rpcz.start_client_span(method_full_name, self)
        _client_count.update(1)

    def current_attempt_id(self) -> int:
        return self._call_id + 1 + self.retried_count

    def issue_rpc(self, locked: bool = False):
        """LB select → socket → pack → write → arm timers
        (Controller::IssueRPC, controller.cpp:1010-1207). `locked` says
        whether the caller already holds the CallId lock (the retry/backup
        branches of _on_error do) so failure paths don't self-deadlock."""
        channel = self._channel
        sock, rc = channel._select_socket(self)
        if rc != 0 or sock is None:
            self.set_failed(rc or errors.EFAILEDSOCKET, "no usable server")
            self._end_rpc_locked_or_not(locked=locked)
            return
        self._current_sock = sock
        self._accessed_sids.add(sock.socket_id)
        self.remote_side = sock.remote_side
        attempt_cid = self.current_attempt_id()
        try:
            packet = channel._protocol.pack_request(
                self._request_payload, self, attempt_cid
            )
        except Exception as e:
            # e.g. authenticator refused, or esp poisoning a socket with an
            # unconsumed in-flight response — fail the RPC cleanly.
            self.set_failed(errors.EREQUEST, f"fail to pack request: {e}")
            self._end_rpc_locked_or_not(locked=locked)
            return
        # Pipelined-protocol correlation entries are pushed atomically with
        # the queue append (on_queued runs under the socket's write lock),
        # so concurrent callers on a shared connection cannot enqueue in
        # one order but write in another.
        on_packed = channel._protocol.extra.get("on_packed")
        on_queued = (
            (lambda: on_packed(sock, self, attempt_cid))
            if on_packed is not None else None)
        rc = sock.write(packet, id_wait=attempt_cid, on_queued=on_queued)
        if rc != 0:
            return  # id_wait already errored via socket failure path
        if self._deadline is not None and self._timeout_timer is None:
            remain = max(0.0, self._deadline - time.monotonic())
            self._timeout_timer = timer_add(remain, self._handle_timeout,
                                            self._call_id)
        if (self.backup_request_ms is not None
                and self.retried_count == 0
                and self._backup_timer is None):
            self._backup_timer = timer_add(
                self.backup_request_ms / 1000.0, self._handle_backup,
                self._call_id
            )

    # -- timer callbacks (run on timer thread) -----------------------------
    def _handle_timeout(self, cid: int):
        bthread_id.error(cid, errors.ERPCTIMEDOUT, "deadline exceeded")

    def _handle_backup(self, cid: int):
        bthread_id.error(cid, errors.EBACKUPREQUEST, "")

    # -- completion state machine (runs under the id lock) -----------------
    def _on_error(self, idv: int, data, error_code: int, error_text: str):
        """on_error of the CallId — the OnVersionedRPCReturned analog
        (controller.cpp:554-640). Called with the id LOCKED; must unlock or
        destroy."""
        if error_code == errors.EBACKUPREQUEST:
            # Fire a backup attempt; the original stays in flight.
            if self.retried_count < self.max_retry:
                self.retried_count += 1
                self.has_backup_request = True
                _backup_count.update(1)
                self.issue_rpc(locked=True)
            try:
                bthread_id.unlock(idv)
            except (KeyError, RuntimeError):
                pass  # issue_rpc failed synchronously and ended the RPC
            return
        self.set_failed(error_code, error_text)
        if (error_code != errors.ERPCTIMEDOUT
                and self.retried_count < self.max_retry
                and self.retry_policy.do_retry(self)
                and (self._deadline is None
                     or time.monotonic() < self._deadline)):
            self.retried_count += 1
            _retry_count.update(1)
            if self._current_sock is not None:
                self._excluded_sids.add(self._current_sock.socket_id)
            self.error_code_value = 0
            self.error_text_value = ""
            self.issue_rpc(locked=True)
            try:
                bthread_id.unlock(idv)
            except (KeyError, RuntimeError):
                pass  # issue_rpc failed synchronously and ended the RPC
            return
        self._end_rpc_locked_or_not(locked=True)

    def _on_response(self, meta, payload: bytes, attachment: IOBuf, sock):
        """Called by the protocol's process_response with the id locked."""
        self._response_rpc_meta = meta
        if meta.stream_id and self._request_stream is not None:
            # Stream setup completed: learn the peer endpoint id and bind
            # to the RPC's connection (stream.cpp SetConnected path).
            self._request_stream.peer_id = meta.stream_id
            self._request_stream.bind(sock)
        if meta.response.error_code != 0:
            self.set_failed(meta.response.error_code,
                            meta.response.error_text)
        else:
            try:
                if self._response is not None and payload:
                    self._response.ParseFromString(payload)
                self.response_attachment = attachment
            except Exception as e:
                self.set_failed(errors.EREQUEST, f"fail to parse response: {e}")
        self._end_rpc_locked_or_not(locked=True)

    def _end_rpc_locked_or_not(self, locked: bool):
        """Common tail: cancel timers, feed the LB, run done, wake joiner."""
        if self._timeout_timer is not None:
            timer_del(self._timeout_timer)
            self._timeout_timer = None
        if self._backup_timer is not None:
            timer_del(self._backup_timer)
            self._backup_timer = None
        self.latency_us = (time.monotonic() - self._start_time) * 1e6
        if self.span is not None:
            self.span.remote_side = (str(self.remote_side)
                                     if self.remote_side else None)
            self.span.end(self.error_code_value)
            self.span = None
        for sid in self._accessed_sids:
            from brpc_tpu.rpc.socket import Socket

            s = Socket.address(sid)
            if s is not None:
                s.remove_inflight(self._call_id)
                for k in range(self.max_retry + 1):
                    s.remove_inflight(self._call_id + 1 + k)
        if self._lb is not None and self._current_sock is not None:
            try:
                self._lb.feedback(self._current_sock.socket_id,
                                  self.error_code_value, self.latency_us)
            except Exception:
                pass
        if self._channel is not None:
            self._channel._on_rpc_end(self)
        cid = self._call_id
        if locked:
            bthread_id.unlock_and_destroy(cid)
        else:
            try:
                bthread_id.lock(cid)
                bthread_id.unlock_and_destroy(cid)
            except KeyError:
                pass
        done = self._done
        self._ended.set()
        if done is not None:
            done(self)

    def cancel(self):
        """StartCancel analog: abort the in-flight RPC through the CallId
        error path; done still runs, with ECANCELED."""
        if self._call_id and not self._ended.is_set():
            bthread_id.error(self._call_id, errors.ECANCELED, "cancelled")

    def join(self, timeout: Optional[float] = None) -> bool:
        """Wait for completion (synchronous CallMethod tail — the
        bthread_id_join of channel.cpp)."""
        return self._ended.wait(timeout)
