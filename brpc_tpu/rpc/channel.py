"""Channel — the client endpoint.

Counterpart of brpc::Channel (/root/reference/src/brpc/channel.{h,cpp}):
init against a single server (channel.cpp:317) or a naming-service URL + LB
policy (channel.cpp:354-393, LoadBalancerWithNaming); CallMethod sets up the
Controller then drives IssueRPC (channel.cpp:407-576). Connection types
single/pooled/short mirror socket.h:553-590 (SocketMap-pooled client
connections, details/socket_map).
"""
from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional

from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.rpc.input_messenger import InputMessenger
from brpc_tpu.rpc.protocol import (
    ProtocolType,
    find_protocol_by_name,
    globally_initialize,
    list_server_protocols,
)
from brpc_tpu.rpc.socket import Socket


@dataclass
class ChannelOptions:
    """Mirror of brpc::ChannelOptions (channel.h:41-89)."""

    connect_timeout_ms: float = 200
    timeout_ms: float = 500
    backup_request_ms: float = -1
    max_retry: int = 3
    protocol: str = "tpu_std"
    connection_type: str = "single"  # single | pooled | short
    health_check_interval_s: float = -1
    enable_circuit_breaker: bool = False
    auth: Optional[object] = None  # Authenticator (authenticator.h)
    use_ssl: bool = False
    ssl_verify: bool = False  # verify server cert (off: self-signed dev)
    # use_rdma analog (channel.h:41-89): connections run the device
    # handshake through the AppConnect seam and carry a DeviceEndpoint.
    use_device_transport: bool = False


_client_messenger: Optional[InputMessenger] = None
_client_messenger_lock = threading.Lock()


def get_client_messenger() -> InputMessenger:
    """The client-side InputMessenger shared by all channels (the role of
    the global client messenger in socket creation)."""
    global _client_messenger
    if _client_messenger is None:
        with _client_messenger_lock:
            if _client_messenger is None:
                globally_initialize()
                _client_messenger = InputMessenger(list_server_protocols())
    return _client_messenger


class Channel:
    def __init__(self, options: Optional[ChannelOptions] = None):
        import dataclasses

        # private copy: option coercions must not alias back into a
        # caller-shared ChannelOptions instance
        self.options = (dataclasses.replace(options) if options is not None
                        else ChannelOptions())
        if (self.options.use_device_transport
                and self.options.connection_type != "single"):
            # The device lane's app-level ACKs (TensorStore.Ack) must ride
            # the SAME connection whose endpoint retains the spans; pooled/
            # short connections would route them to a different endpoint.
            self.options.connection_type = "single"
        self._protocol = None
        self._server_ep: Optional[EndPoint] = None
        self._single_sid: Optional[int] = None
        self._single_lock = threading.Lock()
        self._socket_pool: deque = deque()  # pooled connection type
        self._pool_lock = threading.Lock()
        self._lb = None
        self._ns_thread = None
        self._circuit_breakers = {}  # sid -> CircuitBreaker
        self._cb_lock = threading.Lock()
        self._init_done = False
        self._mapped_key = None  # SocketMapKey held in the global SocketMap
        self._mapped_sid = None  # the shared SocketId our reference is on

    def close(self):
        """Release channel resources: NS thread + SocketMap reference."""
        if self._ns_thread is not None:
            self._ns_thread.stop()
        if self._mapped_key is not None:
            from brpc_tpu.rpc.socket_map import get_global_socket_map

            get_global_socket_map().remove(key=self._mapped_key,
                                           expected_sid=self._mapped_sid)
            self._mapped_key = None
            self._mapped_sid = None

    # -- init --------------------------------------------------------------
    def init(self, target, lb_name: str = "") -> int:
        """init('ip:port') for a single server, or
        init('list://h1:p1,h2:p2', 'rr') / init('file://...', ...) for
        NS + load balancing (channel.cpp:317,354-393)."""
        globally_initialize()
        self._protocol = find_protocol_by_name(self.options.protocol)
        if self._protocol is None:
            return errors.EPROTONOTSUP
        supported = self._protocol.supported_connection_types
        if self.options.connection_type not in supported:
            # Protocols that can't share a connection (esp: one in-flight
            # RPC per socket) get their first supported type, the
            # reference's default-from-supported_connection_type behavior.
            self.options.connection_type = supported[0]
        if "://" in str(target):
            from brpc_tpu.rpc.load_balancer import create_load_balancer
            from brpc_tpu.rpc.naming_service import start_naming_service

            self._lb = create_load_balancer(lb_name or "rr")
            if self._lb is None:
                return errors.EINVAL
            self._ns_thread = start_naming_service(
                str(target), self._lb, self.options
            )
            if self._ns_thread is None:
                return errors.EINVAL
        else:
            ep = target if isinstance(target, EndPoint) else EndPoint.parse(str(target))
            self._server_ep = ep.resolve()
        self._init_done = True
        return 0

    def init_with_filter(self, naming_url: str, lb_name: str,
                         node_filter) -> int:
        """NS init with a node filter (NamingServiceFilter role,
        naming_service_filter.h) — PartitionChannel routes partition tags
        through this."""
        globally_initialize()
        self._protocol = find_protocol_by_name(self.options.protocol)
        if self._protocol is None:
            return errors.EPROTONOTSUP
        from brpc_tpu.rpc.load_balancer import create_load_balancer
        from brpc_tpu.rpc.naming_service import start_naming_service

        self._lb = create_load_balancer(lb_name or "rr")
        if self._lb is None:
            return errors.EINVAL
        self._ns_thread = start_naming_service(
            naming_url, self._lb, self.options, node_filter
        )
        if self._ns_thread is None:
            return errors.EINVAL
        self._init_done = True
        return 0

    # -- socket selection (IssueRPC's server-selection half) ---------------
    def _client_ssl_context(self):
        if not self.options.use_ssl:
            return None
        import ssl as _ssl

        ctx = _ssl.create_default_context()
        if not self.options.ssl_verify:
            ctx.check_hostname = False
            ctx.verify_mode = _ssl.CERT_NONE
        return ctx

    def _app_connect_factory(self):
        """Per-socket app-level connect hook maker (AppConnect seam,
        socket.h:108-130). Each new connection gets its OWN transport
        endpoint, mirroring one RdmaEndpoint per Socket."""
        if not self.options.use_device_transport:
            return None
        from brpc_tpu.rpc.device_transport import DeviceEndpoint

        return lambda: DeviceEndpoint().app_connect

    def _connect_new_socket(self, ep: EndPoint) -> Optional[Socket]:
        messenger = get_client_messenger()
        factory = self._app_connect_factory()
        sid = Socket.create(
            remote_side=ep,
            on_edge_triggered_events=messenger.on_new_messages,
            health_check_interval_s=self.options.health_check_interval_s,
            ssl_context=self._client_ssl_context(),
            app_connect=factory() if factory is not None else None,
        )
        sock = Socket.address(sid)
        self._pin_protocol(sock)  # pre-connect: hook runs pre-registration
        rc = sock.connect(timeout_s=self.options.connect_timeout_ms / 1000.0)
        if rc != 0:
            return None
        return sock

    def _pin_protocol(self, sock: Socket):
        """A client connection speaks exactly one protocol — pre-match it so
        weak-magic response parsers (esp, nshead) can never misclaim bytes
        meant for another channel's protocol. Call BEFORE connecting: the
        on_pinned hook (h2 attaches its client connection + preface) must
        run before the dispatcher can deliver a speaks-first peer's bytes,
        so an unconnected socket defers it to connect()'s pre-registration
        window via sock.on_connected."""
        if sock.matched_protocol is None:
            sock.matched_protocol = self._protocol
            on_pinned = self._protocol.extra.get("on_pinned")
            if on_pinned is not None:
                if sock.fd() is not None:
                    on_pinned(sock)
                else:
                    sock.on_connected = on_pinned

    def _select_socket(self, cntl: Controller):
        """Returns (Socket, rc). Applies LB if configured, then the
        connection type (controller.cpp:1048-1112)."""
        if self._lb is not None:
            # Cluster-recover gate (load_balancer_with_naming wiring of
            # cluster_recover_policy.h): while recovering, shed load in
            # proportion to how much of the cluster is back.
            policy = getattr(self._lb, "cluster_recover_policy", None)
            if (policy is not None and policy.stop_recover_if_necessary()
                    and policy.do_reject(self._lb.server_ids())):
                return None, errors.EREJECT
            sid = self._lb.select_server(exclude=cntl._excluded_sids)
            if sid is None:
                if policy is not None:
                    policy.start_recover()
                return None, errors.EFAILEDSOCKET
            cntl._lb = self._lb
            main_sock = Socket.address(sid)
            if main_sock is None or main_sock.failed():
                return None, errors.EFAILEDSOCKET
            if self.options.connection_type == "single":
                # NS-created sockets are dialed lazily; attach the device
                # transport hook before the first connect (use_rdma analog).
                factory = self._app_connect_factory()
                if (factory is not None and main_sock.app_connect is None
                        and main_sock.fd() is None):
                    main_sock.app_connect = factory()
                self._pin_protocol(main_sock)
                if main_sock.ensure_connected(
                        self.options.connect_timeout_ms / 1000.0) != 0:
                    return None, errors.EFAILEDSOCKET
            return self._apply_connection_type(main_sock, cntl)
        if self._server_ep is None:
            return None, errors.EINVAL
        return self._apply_connection_type_ep(self._server_ep, cntl)

    def _apply_connection_type(self, main_sock: Socket, cntl: Controller):
        if self.options.connection_type == "single":
            return main_sock, 0
        return self._apply_connection_type_ep(main_sock.remote_side, cntl)

    def _apply_connection_type_ep(self, ep: EndPoint, cntl: Controller):
        ctype = self.options.connection_type
        if ctype == "short":
            sock = self._connect_new_socket(ep)
            if sock is None:
                return None, errors.EFAILEDSOCKET
            sock.connection_type = "short"
            return sock, 0
        if ctype == "pooled":
            with self._pool_lock:
                while self._socket_pool:
                    sock = self._socket_pool.popleft()
                    if not sock.failed():
                        return sock, 0
            sock = self._connect_new_socket(ep)
            if sock is None:
                return None, errors.EFAILEDSOCKET
            sock.connection_type = "pooled"
            sock.conn_data = self  # home pool
            return sock, 0
        # single (default): the PROCESS-WIDE shared connection for this
        # channel signature via SocketMap (details/socket_map role) — two
        # channels with the same (endpoint, protocol, ssl, auth, transport)
        # share one connection; any difference gets its own (SocketMapKey,
        # socket_map.h).
        from brpc_tpu.rpc.socket_map import get_global_socket_map, make_key

        with self._single_lock:
            if self._single_sid is not None:
                sock = Socket.address(self._single_sid)
                # lame-duck (peer draining): fall through to the
                # SocketMap, which hands out a FRESH shared connection —
                # in-flight RPCs keep completing on the old one
                if sock is not None and sock.usable_for_new_calls():
                    # health-check revival resets matched_protocol
                    self._pin_protocol(sock)
                    return sock, 0
            key = make_key(
                ep,
                protocol=self.options.protocol,
                ssl=self.options.use_ssl,
                auth=self.options.auth,
                app_connect_id=(
                    "device" if self.options.use_device_transport else ""),
            )
            sid = get_global_socket_map().insert(
                ep,
                health_check_interval_s=self.options.health_check_interval_s,
                ssl_context=self._client_ssl_context(),
                app_connect_factory=self._app_connect_factory(),
                key=key,
            )
            sock = Socket.address(sid) if sid is not None else None
            if sock is None:
                return None, errors.EFAILEDSOCKET
            self._pin_protocol(sock)  # pre-connect (see _pin_protocol)
            if sock.ensure_connected(
                    self.options.connect_timeout_ms / 1000.0) != 0:
                return None, errors.EFAILEDSOCKET
            self._single_sid = sock.socket_id
            self._mapped_key = key
            self._mapped_sid = sid
            return sock, 0

    def _on_rpc_end(self, cntl: Controller):
        sock = cntl._current_sock
        if sock is None:
            return
        if sock.connection_type == "short":
            if not sock.failed():
                sock.set_failed(errors.ECLOSE, "short connection done")
        elif sock.connection_type == "pooled" and not sock.failed():
            can_repool = self._protocol.extra.get("can_repool")
            if can_repool is not None and not can_repool(sock):
                # e.g. esp after a timeout: a response is still owed on
                # this connection and could complete the WRONG later RPC.
                sock.set_failed(errors.ECLOSE,
                                "unconsumed in-flight response")
            else:
                with self._pool_lock:
                    self._socket_pool.append(sock)
        if self.options.enable_circuit_breaker:
            self._feed_circuit_breaker(sock, cntl)

    def _feed_circuit_breaker(self, sock: Socket, cntl: Controller):
        from brpc_tpu.rpc.circuit_breaker import CircuitBreaker

        if getattr(sock, "lame_duck", False):
            # planned drain: errors on a draining connection (ELIMIT
            # rejections, the eventual close) are routine churn, not a
            # health signal — no breaker sample
            return
        with self._cb_lock:
            cb = self._circuit_breakers.get(sock.socket_id)
            if cb is None:
                cb = CircuitBreaker()
                self._circuit_breakers[sock.socket_id] = cb
        if not cb.on_call_end(cntl.error_code_value, cntl.latency_us):
            sock.set_failed(errors.EFAILEDSOCKET, "isolated by circuit breaker")

    # -- the RPC -----------------------------------------------------------
    def call_method(self, method_full_name: str, cntl: Controller,
                    request, response, done: Optional[Callable] = None):
        """CallMethod (channel.cpp:407-576). done=None → synchronous."""
        if not self._init_done:
            cntl.set_failed(errors.EINVAL, "channel not initialized")
            if done:
                done(cntl)
            return
        if cntl.timeout_ms is None:
            cntl.timeout_ms = self.options.timeout_ms
        if cntl.max_retry == 3:
            cntl.max_retry = self.options.max_retry
        if cntl.backup_request_ms is None and self.options.backup_request_ms > 0:
            cntl.backup_request_ms = self.options.backup_request_ms
        cntl._setup_call(self, method_full_name, request, response, done)
        try:
            cntl._request_payload = self._protocol.serialize_request(
                request, cntl
            )
        except Exception as e:
            cntl.set_failed(errors.EREQUEST, f"fail to serialize request: {e}")
            cntl._end_rpc_locked_or_not(locked=False)
            return
        from brpc_tpu.rpc.rpc_dump import maybe_dump_request

        maybe_dump_request(method_full_name, cntl._request_payload,
                           cntl.log_id)
        cntl.issue_rpc()
        if done is None:
            cntl.join()

    def call(self, method_full_name: str, request, response_class,
             timeout_ms: Optional[float] = None, **cntl_kwargs):
        """Convenience sync call returning (controller, response)."""
        cntl = Controller()
        if timeout_ms is not None:
            cntl.timeout_ms = timeout_ms
        for k, v in cntl_kwargs.items():
            setattr(cntl, k, v)
        response = response_class() if response_class is not None else None
        self.call_method(method_full_name, cntl, request, response)
        return cntl, response
