from brpc_tpu.rpc.proto import (  # noqa: F401
    echo_pb2,
    rpc_meta_pb2,
    tensor_service_pb2,
)
