from brpc_tpu.rpc.proto import echo_pb2, rpc_meta_pb2  # noqa: F401
