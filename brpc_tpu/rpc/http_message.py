"""HTTP/1.x message types + incremental parser.

Counterpart of brpc's details/http_message.{h,cpp} + http_header.h +
vendored http_parser (/root/reference/src/brpc/details/http_parser.cpp):
request/response objects with header maps and an IOBuf-fed parser that
understands Content-Length and chunked transfer encoding.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

from brpc_tpu.butil.iobuf import IOBuf

MAX_HEADER_BYTES = 64 * 1024
MAX_BODY_BYTES = 512 * 1024 * 1024

_METHODS = (b"GET ", b"POST ", b"PUT ", b"DELETE ", b"HEAD ", b"OPTIONS ",
            b"PATCH ", b"TRACE ", b"CONNECT ")


class HttpHeader:
    """Case-insensitive header map (http_header.h)."""

    def __init__(self):
        self._headers: Dict[str, str] = {}

    def set(self, key: str, value: str):
        self._headers[key.lower()] = str(value)

    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        return self._headers.get(key.lower(), default)

    def remove(self, key: str):
        self._headers.pop(key.lower(), None)

    def items(self):
        return self._headers.items()

    def __contains__(self, key: str) -> bool:
        return key.lower() in self._headers

    def __len__(self):
        return len(self._headers)


class HttpRequest:
    def __init__(self, method: str = "GET", uri: str = "/"):
        self.method = method
        self.uri = uri
        self.version = "HTTP/1.1"
        self.headers = HttpHeader()
        self.body = IOBuf()

    @property
    def path(self) -> str:
        return self.uri.split("?", 1)[0]

    @property
    def query(self) -> Dict[str, str]:
        if "?" not in self.uri:
            return {}
        out = {}
        for pair in self.uri.split("?", 1)[1].split("&"):
            k, _, v = pair.partition("=")
            if k:
                from urllib.parse import unquote_plus

                out[unquote_plus(k)] = unquote_plus(v)
        return out

    def serialize(self) -> IOBuf:
        out = IOBuf()
        body_len = len(self.body)
        lines = [f"{self.method} {self.uri} {self.version}"]
        if "content-length" not in self.headers and (
                body_len or self.method in ("POST", "PUT", "PATCH")):
            self.headers.set("content-length", body_len)
        lines.extend(f"{k}: {v}" for k, v in self.headers.items())
        out.append(("\r\n".join(lines) + "\r\n\r\n").encode())
        if body_len:
            out.append(self.body)
        return out


class HttpResponse:
    def __init__(self, status_code: int = 200, reason: str = "OK"):
        self.status_code = status_code
        self.reason = reason
        self.version = "HTTP/1.1"
        self.headers = HttpHeader()
        self.body = IOBuf()

    def set_body(self, data, content_type: str = "text/plain"):
        self.body = data if isinstance(data, IOBuf) else IOBuf(data)
        self.headers.set("content-type", content_type)

    def serialize(self) -> IOBuf:
        out = IOBuf()
        self.headers.set("content-length", len(self.body))
        lines = [f"{self.version} {self.status_code} {self.reason}"]
        lines.extend(f"{k}: {v}" for k, v in self.headers.items())
        out.append(("\r\n".join(lines) + "\r\n\r\n").encode())
        if len(self.body):
            out.append(self.body)
        return out


def looks_like_http(head: bytes) -> bool:
    if head.startswith(b"HTTP/1."):
        return True
    return any(head.startswith(m[: len(head)]) if len(head) < len(m)
               else head.startswith(m) for m in _METHODS)


def try_parse(portal: IOBuf) -> Tuple[str, Optional[object]]:
    """Incremental parse from the portal front.

    Returns (state, message): state in {"ok", "more", "not_http", "error"};
    on "ok" the message bytes are consumed from the portal.
    """
    n = len(portal)
    head = portal.copy_to_bytes(min(8, n))
    if not looks_like_http(head):
        return "not_http", None
    # find end of headers
    scan = portal.copy_to_bytes(min(n, MAX_HEADER_BYTES))
    idx = scan.find(b"\r\n\r\n")
    if idx < 0:
        if n >= MAX_HEADER_BYTES:
            return "error", None
        return "more", None
    header_bytes = scan[:idx]
    body_start = idx + 4
    try:
        lines = header_bytes.decode("latin-1").split("\r\n")
        first = lines[0]
        headers = HttpHeader()
        for line in lines[1:]:
            if not line:
                continue
            k, _, v = line.partition(":")
            headers.set(k.strip(), v.strip())
    except Exception:
        return "error", None

    chunked = (headers.get("transfer-encoding", "").lower() == "chunked")
    content_length = int(headers.get("content-length", "0") or 0)
    if content_length > MAX_BODY_BYTES:
        return "error", None

    if chunked:
        parsed = _parse_chunked(scan[body_start:])
        if parsed is None:
            return "more", None
        body_bytes, consumed = parsed
        total = body_start + consumed
    else:
        if n < body_start + content_length:
            return "more", None
        body_bytes = scan[body_start: body_start + content_length]
        total = body_start + content_length

    if first.startswith("HTTP/1."):
        parts = first.split(" ", 2)
        msg = HttpResponse(int(parts[1]), parts[2] if len(parts) > 2 else "")
        msg.version = parts[0]
    else:
        parts = first.split(" ")
        if len(parts) < 3:
            return "error", None
        msg = HttpRequest(parts[0], parts[1])
        msg.version = parts[2]
    msg.headers = headers
    msg.body = IOBuf(body_bytes)
    portal.pop_front(total)
    return "ok", msg


def _parse_chunked(data: bytes):
    """Returns (body, consumed) or None if incomplete."""
    body = bytearray()
    pos = 0
    while True:
        nl = data.find(b"\r\n", pos)
        if nl < 0:
            return None
        try:
            size = int(data[pos:nl].split(b";")[0], 16)
        except ValueError:
            return None
        chunk_start = nl + 2
        if size == 0:
            end = data.find(b"\r\n", chunk_start)
            if end < 0:
                return None
            return bytes(body), end + 2
        if len(data) < chunk_start + size + 2:
            return None
        body += data[chunk_start: chunk_start + size]
        pos = chunk_start + size + 2
