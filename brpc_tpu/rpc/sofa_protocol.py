"""sofa_pbrpc — the sofa-pbrpc protocol, wire-compatible.

Counterpart of /root/reference/src/brpc/policy/sofa_pbrpc_protocol.cpp:
24-byte header `"SOFA" + u32le(meta_size) + u64le(body_size) +
u64le(meta_size+body_size)` (PackSofaHeader, :132-138), then one
SofaRpcMeta protobuf — shared by both directions and discriminated by its
`type` field (sofa_pbrpc_meta.proto:43) — then the payload. Correlation is
`sequence_id`; methods travel as full names ("pkg.Service.Method").
"""
from __future__ import annotations

import struct

from brpc_tpu.bthread import id as bthread_id
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.rpc import compress as compress_mod
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.rpc.pb_dispatch import dispatch_pb_request
from brpc_tpu.rpc.protocol import (
    InputMessageBase,
    ParseResult,
    Protocol,
    ProtocolType,
    register_protocol,
)
from brpc_tpu.rpc.proto import legacy_meta_pb2

MAGIC = b"SOFA"
HEADER_LEN = 24
MAX_BODY = 64 << 20
MAX_META = 1 << 20

_pb = legacy_meta_pb2

# SofaCompressType (sofa_pbrpc_meta.proto:26-32) -> registry codes
_FROM_SOFA = {_pb.SOFA_COMPRESS_TYPE_NONE: compress_mod.COMPRESS_NONE,
              _pb.SOFA_COMPRESS_TYPE_GZIP: compress_mod.COMPRESS_GZIP,
              _pb.SOFA_COMPRESS_TYPE_ZLIB: compress_mod.COMPRESS_ZLIB,
              _pb.SOFA_COMPRESS_TYPE_SNAPPY: compress_mod.COMPRESS_SNAPPY}
_TO_SOFA = {v: k for k, v in _FROM_SOFA.items()}


class SofaMessage(InputMessageBase):
    __slots__ = ("meta", "payload", "is_request")

    def __init__(self, meta, payload: bytes):
        super().__init__()
        self.meta = meta
        self.payload = payload
        self.is_request = meta.type == _pb.SofaRpcMeta.REQUEST


def _pack_frame(meta, payload: bytes) -> IOBuf:
    meta_bytes = meta.SerializeToString()
    out = IOBuf()
    out.append(MAGIC + struct.pack("<IQQ", len(meta_bytes), len(payload),
                                   len(meta_bytes) + len(payload)))
    out.append(meta_bytes)
    if payload:
        out.append(payload)
    return out


def parse(portal: IOBuf, sock, read_eof: bool, arg) -> ParseResult:
    if len(portal) < HEADER_LEN:
        head = portal.copy_to_bytes(min(4, len(portal)))
        if MAGIC.startswith(head):
            return ParseResult.not_enough()
        return ParseResult.try_others()
    header = portal.copy_to_bytes(HEADER_LEN)
    if header[:4] != MAGIC:
        return ParseResult.try_others()
    meta_size, body_size, msg_size = struct.unpack("<IQQ", header[4:24])
    if msg_size != meta_size + body_size:
        return ParseResult.try_others()
    if body_size > MAX_BODY or meta_size > MAX_META:
        return ParseResult.error_()
    if len(portal) < HEADER_LEN + msg_size:
        return ParseResult.not_enough()
    portal.pop_front(HEADER_LEN)
    meta_bytes = portal.cutn_bytes(meta_size)
    payload = portal.cutn_bytes(body_size)
    meta = _pb.SofaRpcMeta()
    try:
        meta.ParseFromString(meta_bytes)
    except Exception:
        return ParseResult.error_()
    return ParseResult.ok(SofaMessage(meta, payload))


def serialize_request(request, cntl: Controller):
    if request is None:
        return b""
    if isinstance(request, (bytes, bytearray)):
        return bytes(request)
    return request.SerializeToString()


def pack_request(payload: bytes, cntl: Controller, correlation_id: int) -> IOBuf:
    meta = _pb.SofaRpcMeta()
    meta.type = _pb.SofaRpcMeta.REQUEST
    meta.sequence_id = correlation_id
    meta.method = cntl._method_full_name
    if cntl.compress_type:
        meta.compress_type = _TO_SOFA.get(cntl.compress_type,
                                          _pb.SOFA_COMPRESS_TYPE_NONE)
    payload = compress_mod.compress(payload, cntl.compress_type)
    return _pack_frame(meta, payload)


def process_response(msg: SofaMessage):
    meta = msg.meta
    cid = meta.sequence_id
    try:
        cntl = bthread_id.lock(cid)
    except (KeyError, TimeoutError):
        return
    if not isinstance(cntl, Controller):
        try:
            bthread_id.unlock(cid)
        except Exception:
            pass
        return
    try:
        if meta.failed:
            cntl.set_failed(meta.error_code or errors.EINVAL,
                            meta.reason or "sofa rpc failed")
        else:
            payload = compress_mod.decompress(
                msg.payload, _FROM_SOFA.get(meta.compress_type, 0))
            resp = cntl._response
            if resp is not None and payload:
                resp.ParseFromString(payload)
    except Exception as e:
        cntl.set_failed(errors.ERESPONSE, f"fail to parse response: {e}")
    cntl._end_rpc_locked_or_not(locked=True)


def _send_response(sock, seq: int, cntl: Controller, response):
    meta = _pb.SofaRpcMeta()
    meta.type = _pb.SofaRpcMeta.RESPONSE
    meta.sequence_id = seq
    if cntl.failed():
        meta.failed = True
        meta.error_code = cntl.error_code_value
        meta.reason = cntl.error_text_value
        payload = b""
    else:
        payload = (response.SerializeToString()
                   if response is not None else b"")
        if cntl.compress_type:
            meta.compress_type = _TO_SOFA.get(cntl.compress_type, 0)
            payload = compress_mod.compress(payload, cntl.compress_type)
    sock.write(_pack_frame(meta, payload))
    if cntl.close_connection_flag:
        sock.set_failed(errors.ECLOSE, "close_connection requested")


def process_request(msg: SofaMessage):
    server = msg.arg
    meta = msg.meta
    seq = meta.sequence_id
    sock = msg.socket
    service_name, _, method_name = meta.method.rpartition(".")
    if (server is not None and service_name
            and server.find_service(service_name) is None):
        # Stock sofa clients send the package-qualified descriptor name
        # ("pkg.EchoService.Echo"); our registry holds class names.
        unqualified = service_name.rpartition(".")[2]
        if server.find_service(unqualified) is not None:
            service_name = unqualified
    dispatch_pb_request(
        server, sock, service_name, method_name, msg.payload,
        _FROM_SOFA.get(meta.compress_type, 0),
        lambda c, response: _send_response(sock, seq, c, response))


register_protocol(Protocol(
    name="sofa_pbrpc",
    type=ProtocolType.SOFA,
    parse=parse,
    serialize_request=serialize_request,
    pack_request=pack_request,
    process_request=process_request,
    process_response=process_response,
))
