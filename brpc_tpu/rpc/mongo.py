"""Mongo wire-protocol types — counterpart of brpc's mongo support
(/root/reference/src/brpc/mongo_head.h, mongo_service_adaptor.h,
policy/mongo_protocol.cpp): the 16-byte little-endian message header,
opcodes, a minimal BSON codec (the reference leaves body decoding to the
user's adaptor; we bundle a small codec so adaptors can work with dicts),
and the MongoServiceAdaptor server hook.
"""
from __future__ import annotations

import struct
from typing import Callable, Dict, List, Optional

# mongo_head.h:29-38 opcodes
OP_REPLY = 1
OP_MSG_OLD = 1000
OP_UPDATE = 2001
OP_INSERT = 2002
OP_QUERY = 2004
OP_GET_MORE = 2005
OP_DELETE = 2006
OP_KILL_CURSORS = 2007
OP_COMMAND = 2010
OP_COMMANDREPLY = 2011
OP_MSG = 2013

_VALID_OPCODES = {OP_REPLY, OP_MSG_OLD, OP_UPDATE, OP_INSERT, OP_QUERY,
                  OP_GET_MORE, OP_DELETE, OP_KILL_CURSORS, OP_COMMAND,
                  OP_COMMANDREPLY, OP_MSG}

_HEAD = struct.Struct("<iiii")  # mongo_head_t (mongo_head.h:57-63)
HEAD_SIZE = _HEAD.size


def is_mongo_opcode(op: int) -> bool:
    return op in _VALID_OPCODES


class MongoHead:
    __slots__ = ("message_length", "request_id", "response_to", "op_code")

    def __init__(self, message_length=0, request_id=0, response_to=0,
                 op_code=OP_QUERY):
        self.message_length = message_length
        self.request_id = request_id
        self.response_to = response_to
        self.op_code = op_code

    def pack(self) -> bytes:
        return _HEAD.pack(self.message_length, self.request_id,
                          self.response_to, self.op_code)

    @classmethod
    def unpack(cls, raw: bytes) -> "MongoHead":
        return cls(*_HEAD.unpack(raw[:HEAD_SIZE]))


# -- minimal BSON ----------------------------------------------------------
# Enough of the BSON spec for command-style documents: double, string,
# embedded doc, array, binary, bool, null, int32, int64.

def bson_encode(doc: Dict) -> bytes:
    body = bytearray()
    for key, value in doc.items():
        body += _encode_element(str(key), value)
    return struct.pack("<i", len(body) + 5) + bytes(body) + b"\x00"


def _encode_element(key: str, value) -> bytes:
    k = key.encode() + b"\x00"
    if isinstance(value, bool):
        return b"\x08" + k + (b"\x01" if value else b"\x00")
    if isinstance(value, float):
        return b"\x01" + k + struct.pack("<d", value)
    if isinstance(value, str):
        vb = value.encode()
        return b"\x02" + k + struct.pack("<i", len(vb) + 1) + vb + b"\x00"
    if isinstance(value, dict):
        return b"\x03" + k + bson_encode(value)
    if isinstance(value, (list, tuple)):
        return b"\x04" + k + bson_encode(
            {str(i): v for i, v in enumerate(value)})
    if isinstance(value, (bytes, bytearray)):
        return (b"\x05" + k + struct.pack("<i", len(value)) + b"\x00"
                + bytes(value))
    if value is None:
        return b"\x0a" + k
    if isinstance(value, int):
        if -(1 << 31) <= value < (1 << 31):
            return b"\x10" + k + struct.pack("<i", value)
        return b"\x12" + k + struct.pack("<q", value)
    raise TypeError(f"bson: unsupported type {type(value)!r} for {key!r}")


def bson_decode(data: bytes, offset: int = 0):
    """Decode one document at data[offset:]; returns (dict, end_offset)."""
    (doc_len,) = struct.unpack_from("<i", data, offset)
    if doc_len < 5 or offset + doc_len > len(data):
        raise ValueError("bson: truncated document")
    end = offset + doc_len - 1  # position of trailing NUL
    pos = offset + 4
    out: Dict = {}
    while pos < end:
        etype = data[pos]
        pos += 1
        nul = data.index(b"\x00", pos)
        key = data[pos:nul].decode()
        pos = nul + 1
        if etype == 0x01:
            (out[key],) = struct.unpack_from("<d", data, pos)
            pos += 8
        elif etype == 0x02:
            (slen,) = struct.unpack_from("<i", data, pos)
            out[key] = data[pos + 4:pos + 4 + slen - 1].decode()
            pos += 4 + slen
        elif etype in (0x03, 0x04):
            sub, pos = bson_decode(data, pos)
            out[key] = ([sub[str(i)] for i in range(len(sub))]
                        if etype == 0x04 else sub)
        elif etype == 0x05:
            (blen,) = struct.unpack_from("<i", data, pos)
            out[key] = bytes(data[pos + 5:pos + 5 + blen])
            pos += 5 + blen
        elif etype == 0x08:
            out[key] = bool(data[pos])
            pos += 1
        elif etype == 0x0A:
            out[key] = None
        elif etype == 0x10:
            (out[key],) = struct.unpack_from("<i", data, pos)
            pos += 4
        elif etype == 0x12:
            (out[key],) = struct.unpack_from("<q", data, pos)
            pos += 8
        else:
            raise ValueError(f"bson: unsupported element type 0x{etype:02x}")
    return out, end + 1


# -- request/response (policy/mongo.proto's role) --------------------------

class MongoRequest:
    """Header + raw body; for OP_QUERY the flags/collection/skip/limit and
    query document are pre-parsed for the adaptor's convenience."""

    __slots__ = ("head", "body", "flags", "collection", "number_to_skip",
                 "number_to_return", "query")

    def __init__(self, head: MongoHead, body: bytes):
        self.head = head
        self.body = body
        self.flags = 0
        self.collection = ""
        self.number_to_skip = 0
        self.number_to_return = 0
        self.query: Optional[Dict] = None
        if head.op_code == OP_QUERY and len(body) >= 4:
            (self.flags,) = struct.unpack_from("<i", body, 0)
            nul = body.index(b"\x00", 4)
            self.collection = body[4:nul].decode()
            pos = nul + 1
            self.number_to_skip, self.number_to_return = struct.unpack_from(
                "<ii", body, pos)
            pos += 8
            if pos < len(body):
                self.query, _ = bson_decode(body, pos)


class MongoResponse:
    """OP_REPLY fields (mongo_protocol.cpp:64-80 SendMongoResponse)."""

    __slots__ = ("response_flags", "cursor_id", "starting_from",
                 "number_returned", "documents")

    def __init__(self):
        self.response_flags = 0
        self.cursor_id = 0
        self.starting_from = 0
        self.number_returned = 0
        self.documents: List[Dict] = []

    def pack(self, request_id: int, response_to: int) -> bytes:
        docs = b"".join(bson_encode(d) for d in self.documents)
        n = self.number_returned or len(self.documents)
        body = struct.pack("<iqii", self.response_flags, self.cursor_id,
                           self.starting_from, n) + docs
        head = MongoHead(HEAD_SIZE + len(body), request_id, response_to,
                         OP_REPLY)
        return head.pack() + body


class MongoServiceAdaptor:
    """Server hook (mongo_service_adaptor.h:27-36): process each mongo
    message; create per-connection context on first message; serialize an
    error reply that completes the client's round trip."""

    def process_mongo_request(self, cntl, request: MongoRequest,
                              response: MongoResponse, done: Callable):
        raise NotImplementedError

    def create_socket_context(self):
        return None

    def serialize_error(self, response_to: int) -> bytes:
        resp = MongoResponse()
        resp.response_flags = 2  # QueryFailure
        resp.documents = [{"$err": "internal error", "code": 1, "ok": 0.0}]
        return resp.pack(0, response_to)
