"""RPC error codes — counterpart of brpc/errno.proto + errno definitions
(/root/reference/src/brpc/errno.proto): the codes the Controller reports and
the retry policy switches on.
"""
from __future__ import annotations

import errno as _errno

# system-ish
EPERM = _errno.EPERM
EINVAL = _errno.EINVAL
ETIMEDOUT = _errno.ETIMEDOUT
ENOSERVICE = 1001  # service not found
ENOMETHOD = 1002  # method not found
EREQUEST = 1003  # bad request format
EAUTH = 1004  # authentication failed
ETOOMANYFAILS = 1005  # too many sub-channel failures (ParallelChannel)
EBACKUPREQUEST = 1007  # backup request triggered (internal)
ERPCTIMEDOUT = 1008  # RPC deadline exceeded
EFAILEDSOCKET = 1009  # the connection broke during the RPC
EHTTP = 1010  # non-2xx HTTP status
EOVERCROWDED = 1011  # too many buffered writes
ERTMPPUBLISHABLE = 1012
ERTMPCREATESTREAM = 1013
EEOF = 1014  # stream EOF
EUNUSED = 1015
ESSL = 1016
EPROTONOTSUP = 1017  # protocol not supported / mismatch
EREJECT = 1018  # request rejected (cluster recovering, errno.proto:43)
EOVERLOAD = 1019  # concurrency limit rejected the request
ELIMIT = 2004  # reached max_concurrency
ECLOSE = 2005  # connection closed by peer
EITP = 2006

ECANCELED = _errno.ECANCELED  # RPC cancelled by caller (StartCancel)

ENOBUF = 2401  # device buffer exhausted (TPU-native)
EDEVICE = 2402  # device transfer failed (TPU-native)

_DESCRIPTIONS = {
    ENOSERVICE: "service not found",
    ENOMETHOD: "method not found",
    EREQUEST: "bad request",
    EAUTH: "authentication failed",
    ETOOMANYFAILS: "too many sub-channel failures",
    EBACKUPREQUEST: "backup request",
    ERPCTIMEDOUT: "rpc timed out",
    EFAILEDSOCKET: "broken socket during rpc",
    EHTTP: "http error",
    EOVERCROWDED: "socket write buffer overcrowded",
    EEOF: "end of stream",
    ESSL: "ssl error",
    EPROTONOTSUP: "protocol mismatch",
    EOVERLOAD: "server overloaded",
    ELIMIT: "max concurrency reached",
    ECLOSE: "connection closed",
    ECANCELED: "rpc cancelled",
    ENOBUF: "device buffer exhausted",
    EDEVICE: "device transfer failed",
}


def berror(code: int) -> str:
    try:
        return _DESCRIPTIONS.get(code) or _errno.errorcode.get(code, f"error {code}")
    except Exception:
        return f"error {code}"
