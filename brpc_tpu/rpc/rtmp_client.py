"""RTMP client — connect/createStream/play/publish with the digest
handshake, plus the relay-pull helper.

Counterpart of brpc's RtmpClient / RtmpClientStream
(/root/reference/src/brpc/rtmp.h:723,797, rtmp.cpp) with the digest
handshake of policy/rtmp_protocol.cpp:149: C1 carries an HMAC-SHA256
digest keyed by the Genuine-Flash-Player constant at a position derived
from the offset bytes; the server proves itself with the Media-Server
key, and C2/S2 are HMACs chained from the peer's digest. The key bytes
and block layout are protocol constants every interoperable
implementation shares (they are in the public RTMP handshake
literature); falling back to the simple handshake keeps pre-digest
servers reachable, as the reference does.

The chunk layer is reused from rtmp_protocol.RtmpSession — the client is
a second driver of the same state machine, which is exactly what the
relay test needs (two implementations exercising each other).
"""
from __future__ import annotations

import hashlib
import hmac
import os
import struct
import threading
import time
from typing import Callable, List, Optional

from brpc_tpu.rpc import amf
from brpc_tpu.rpc import rtmp_protocol as rp
from brpc_tpu.rpc.rtmp_protocol import (
    HANDSHAKE_SIZE,
    MSG_AUDIO,
    MSG_COMMAND_AMF0,
    MSG_DATA_AMF0,
    MSG_SET_CHUNK_SIZE,
    MSG_VIDEO,
    OUT_CHUNK,
    RtmpClientSession,
)

# RTMP digest handshake constants (public protocol constants)
FP_KEY = b"Genuine Adobe Flash Player 001"          # 30 bytes
FMS_KEY = b"Genuine Adobe Flash Media Server 001"   # 36 bytes
_CRUD = bytes([
    0xF0, 0xEE, 0xC2, 0x4A, 0x80, 0x68, 0xBE, 0xE8,
    0x2E, 0x00, 0xD0, 0xD1, 0x02, 0x9E, 0x7E, 0x57,
    0x6E, 0xEC, 0x5D, 0x2D, 0x29, 0x80, 0x6F, 0xAB,
    0x93, 0xB8, 0xE6, 0x36, 0xCF, 0xEB, 0x31, 0xAE,
])
FP_KEY_FULL = FP_KEY + _CRUD    # 62 bytes, keys C2
FMS_KEY_FULL = FMS_KEY + _CRUD  # 68 bytes, keys S2


def _digest_offset(block: bytes, scheme: int) -> int:
    if scheme == 0:
        return sum(block[8:12]) % 728 + 12
    return sum(block[772:776]) % 728 + 776


def _with_digest(block: bytearray, scheme: int, key: bytes) -> bytes:
    off = _digest_offset(block, scheme)
    joined = bytes(block[:off]) + bytes(block[off + 32:])
    dig = hmac.new(key, joined, hashlib.sha256).digest()
    block[off:off + 32] = dig
    return dig


def find_digest(block: bytes, key: bytes) -> Optional[tuple]:
    """Returns (scheme, digest) when `block` carries a valid digest."""
    for scheme in (0, 1):
        off = _digest_offset(block, scheme)
        if off + 32 > len(block):
            continue
        joined = block[:off] + block[off + 32:]
        dig = hmac.new(key, joined, hashlib.sha256).digest()
        if hmac.compare_digest(dig, block[off:off + 32]):
            return scheme, dig
    return None


def make_digest_c1() -> tuple:
    """(c1_bytes, digest): time + nonzero version + digested random."""
    c1 = bytearray(struct.pack(">I", int(time.time()) & 0xFFFFFFFF)
                   + b"\x80\x00\x07\x02"
                   + os.urandom(HANDSHAKE_SIZE - 8))
    dig = _with_digest(c1, 0, FP_KEY)
    return bytes(c1), dig


def make_digest_s1(scheme: int) -> tuple:
    s1 = bytearray(struct.pack(">I", int(time.time()) & 0xFFFFFFFF)
                   + b"\x04\x05\x00\x01"
                   + os.urandom(HANDSHAKE_SIZE - 8))
    dig = _with_digest(s1, scheme, FMS_KEY)
    return bytes(s1), dig


def make_chained_reply(peer_digest: bytes, key_full: bytes) -> bytes:
    """C2/S2 in digest mode: random body + HMAC keyed by
    HMAC(key_full, peer's digest)."""
    chain_key = hmac.new(key_full, peer_digest, hashlib.sha256).digest()
    body = bytearray(os.urandom(HANDSHAKE_SIZE))
    dig = hmac.new(chain_key, bytes(body[:-32]), hashlib.sha256).digest()
    body[-32:] = dig
    return bytes(body)


def verify_chained_reply(reply: bytes, own_digest: bytes,
                         key_full: bytes) -> bool:
    chain_key = hmac.new(key_full, own_digest, hashlib.sha256).digest()
    dig = hmac.new(chain_key, reply[:-32], hashlib.sha256).digest()
    return hmac.compare_digest(dig, reply[-32:])


class RtmpClientStream:
    """One created stream on a client connection — play or publish
    (rtmp.h:797 RtmpClientStream role)."""

    def __init__(self, client: "RtmpClient", stream_id: int):
        self.client = client
        self.stream_id = stream_id
        self.name: Optional[str] = None

    # -- publisher half -----------------------------------------------------
    def publish(self, name: str, timeout: float = 5.0):
        c = self.client
        since = c._cmd_marker()
        c.sess.send_command("releaseStream", c._txn(), None, name)
        c.sess.send_command("FCPublish", c._txn(), None, name)
        c.sess.send_command("publish", c._txn(), None, name, "live",
                            stream_id=self.stream_id, csid=4)
        if not c._wait_status("NetStream.Publish.Start", timeout,
                              since=since):
            raise ConnectionError(f"rtmp: publish {name!r} refused")
        self.name = name
        return self

    def send_metadata(self, meta: dict, ts: int = 0):
        from brpc_tpu.rpc import amf

        payload = amf.encode_many("onMetaData", meta)
        self.client.sess.send_message(MSG_DATA_AMF0, ts, payload,
                                      stream_id=self.stream_id, csid=4)

    def send_audio(self, payload: bytes, ts: int):
        self.client.sess.send_message(MSG_AUDIO, ts, payload,
                                      stream_id=self.stream_id, csid=4)

    def send_video(self, payload: bytes, ts: int):
        self.client.sess.send_message(MSG_VIDEO, ts, payload,
                                      stream_id=self.stream_id, csid=4)

    # -- player half --------------------------------------------------------
    def play(self, name: str,
             on_media: Callable[[int, int, bytes], None],
             timeout: float = 5.0):
        """Start playing; on_media(msg_type, timestamp, payload) runs on
        the client's reader thread for every audio/video/data message."""
        c = self.client
        since = c._cmd_marker()
        c._media_sinks[self.stream_id] = on_media
        c.sess.send_command("play", c._txn(), None, name,
                            stream_id=self.stream_id, csid=4)
        if not c._wait_status("NetStream.Play.Start", timeout,
                              since=since):
            c._media_sinks.pop(self.stream_id, None)
            raise ConnectionError(f"rtmp: play {name!r} refused")
        self.name = name
        return self


class RtmpClient:
    """Client connection: digest handshake (simple fallback), connect,
    createStream, play/publish (rtmp.h:723 RtmpClient role)."""

    def __init__(self, host: str, port: int, app: str = "live",
                 use_digest: bool = True, timeout: float = 5.0):
        self.host, self.port, self.app = host, port, app
        self.use_digest = use_digest
        self.timeout = timeout
        self.conn = None
        self.sess: Optional[RtmpClientSession] = None
        self.digest_mode = False
        self._txn_id = 1.0
        self._media_sinks = {}
        self._reader: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        # commands the reader thread pulled out of the session inbox,
        # decoded once, bounded, tagged with a monotone seq so waiters
        # only match commands that arrived after they started caring
        # (a stale NetStream.Play.Start from stream A must not approve
        # a later, refused play on stream B)
        self._cmd_log: List[tuple] = []  # (seq, decoded command)
        self._cmd_seq = 0

    def _txn(self) -> float:
        self._txn_id += 1.0
        return self._txn_id

    # -- handshake ----------------------------------------------------------
    def _handshake(self):
        import socket as pysocket

        conn = pysocket.create_connection((self.host, self.port),
                                          timeout=self.timeout)
        if self.use_digest:
            c1, c1_digest = make_digest_c1()
        else:
            c1 = struct.pack(">II", 0, 0) + os.urandom(HANDSHAKE_SIZE - 8)
            c1_digest = b""
        conn.sendall(bytes([3]) + c1)
        buf = b""
        while len(buf) < 1 + 2 * HANDSHAKE_SIZE:
            chunk = conn.recv(65536)
            if not chunk:
                raise ConnectionError("rtmp: server hung up in handshake")
            buf += chunk
        if buf[0] != 3:
            raise ConnectionError("rtmp: bad handshake version")
        s1 = buf[1:1 + HANDSHAKE_SIZE]
        s2 = buf[1 + HANDSHAKE_SIZE:1 + 2 * HANDSHAKE_SIZE]
        found = find_digest(s1, FMS_KEY) if self.use_digest else None
        if found is not None:
            # digest mode: the server proved itself with the FMS key;
            # optionally S2 chains from OUR digest — verify when shaped so
            _, s1_digest = found
            self.digest_mode = True
            if c1_digest and not (
                    s2 == c1 or
                    verify_chained_reply(s2, c1_digest, FMS_KEY_FULL)):
                raise ConnectionError("rtmp: S2 fails digest verification")
            conn.sendall(make_chained_reply(s1_digest, FP_KEY_FULL))
        else:
            # simple mode (pre-digest server): S2 must echo C1; C2 echoes S1
            if s2 != c1:
                raise ConnectionError("rtmp: bad simple-handshake reply")
            conn.sendall(s1)
        self.conn = conn
        leftover = buf[1 + 2 * HANDSHAKE_SIZE:]
        self.sess = RtmpClientSession(conn)
        if leftover:
            self.sess.feed(leftover)

    # -- connection ---------------------------------------------------------
    def connect(self) -> "RtmpClient":
        self._handshake()
        self.sess.send_command("connect", 1.0,
                               {"app": self.app, "flashVer": "brpc_tpu",
                                "tcUrl": f"rtmp://{self.host}:{self.port}/"
                                         f"{self.app}"})
        ok = self.sess.pump_until(
            lambda s: any(c and c[0] == "_result" and len(c) > 3
                          and isinstance(c[3], dict)
                          and c[3].get("code") ==
                          "NetConnection.Connect.Success"
                          for c in s.commands()),
            timeout=self.timeout)
        if not ok:
            raise ConnectionError("rtmp: connect refused")
        self.sess.inbox.clear()
        self.sess._send_control(MSG_SET_CHUNK_SIZE,
                                struct.pack(">I", OUT_CHUNK))
        return self

    def _cmd_marker(self) -> int:
        """Watermark for _wait_command: take BEFORE sending the command
        whose reply is awaited (the reply may be logged between the send
        and the wait)."""
        with self._lock:
            return self._cmd_seq

    def _wait_command(self, pred, timeout: float, since: int = 0):
        """Wait for a command matching pred. Commands may arrive via the
        reader thread (drained once into _cmd_log) or be pumped here when
        no reader is running — never both recv'ing concurrently. Only
        log entries newer than `since` count."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                cmds = self.sess.commands() + [
                    c for q, c in self._cmd_log if q > since]
            for c in cmds:
                if c and pred(c):
                    return c
            if self._reader is None:
                self.sess.pump(want=len(self.sess.inbox) + 1, timeout=0.3)
            else:
                time.sleep(0.02)
        return None

    def create_stream(self, timeout: float = 5.0) -> RtmpClientStream:
        txn = self._txn()
        since = self._cmd_marker()
        self.sess.send_command("createStream", txn, None)
        c = self._wait_command(
            lambda c: c[0] == "_result" and len(c) > 1 and c[1] == txn,
            timeout, since=since)
        if c is None:
            raise ConnectionError("rtmp: createStream timed out")
        sid = int(c[3]) if len(c) > 3 and isinstance(c[3], (int, float)) \
            else 1
        with self._lock:
            if self._reader is None:
                self.sess.inbox.clear()
        return RtmpClientStream(self, sid)

    def _wait_status(self, code: str, timeout: float,
                     since: int = 0) -> bool:
        return self._wait_command(
            lambda c: c[0] == "onStatus" and len(c) > 3 and
            isinstance(c[3], dict) and c[3].get("code") == code,
            timeout, since=since) is not None

    # -- reader thread (player mode) ----------------------------------------
    def start_reader(self):
        """Dispatch inbound media to the per-stream sinks on a thread —
        the client-side ExecutionQueue role of rtmp.cpp's OnReceived."""
        if self._reader is not None:
            return

        def run():
            import socket as pysocket

            self.conn.settimeout(0.2)
            while not self._stop.is_set():
                try:
                    data = self.conn.recv(65536)
                except (TimeoutError, pysocket.timeout):
                    self._drain_media()
                    continue
                except OSError:
                    break
                if not data:
                    break
                try:
                    with self._lock:
                        self.sess.feed(data)
                except ValueError:
                    break
                self._drain_media()
            self._drain_media()

        self._reader = threading.Thread(target=run, daemon=True,
                                        name="rtmp_client_reader")
        self._reader.start()

    def _drain_media(self):
        with self._lock:
            items, self.sess.inbox[:] = list(self.sess.inbox), []
        for msg_type, ts, payload in items:
            if msg_type in (MSG_AUDIO, MSG_VIDEO, MSG_DATA_AMF0):
                for sink in list(self._media_sinks.values()):
                    try:
                        sink(msg_type, ts, payload)
                    except Exception:
                        pass
            elif msg_type == MSG_COMMAND_AMF0:
                # consumed once into the bounded command log (re-appending
                # to inbox would re-scan them forever and leak); decode
                # here so waiters polling the log never re-decode
                try:
                    decoded = amf.decode_all(payload)
                except amf.AmfError:
                    continue
                with self._lock:
                    self._cmd_seq += 1
                    self._cmd_log.append((self._cmd_seq, decoded))
                    del self._cmd_log[:-64]

    def close(self):
        self._stop.set()
        if self._reader is not None:
            self._reader.join(timeout=2)
        if self.conn is not None:
            try:
                self.conn.close()
            except OSError:
                pass


def pull_into_service(service: "rp.RtmpService", name: str, host: str,
                      port: int, app: str = "live",
                      remote_name: Optional[str] = None,
                      timeout: float = 5.0) -> RtmpClient:
    """Relay pull (the edge-pull topology rtmp_protocol.cpp serves):
    server B's CLIENT plays `remote_name` from server A and republishes
    it into B's own RtmpService under `name`, so B's players read a
    stream that originates on A."""

    class _PullOrigin:
        """Stands in as the publisher session for ownership accounting."""

        class _NullSock:
            def failed(self):
                return False

        sock = _NullSock()

    origin = _PullOrigin()
    if not service.on_publish(name, origin):
        raise RuntimeError(f"rtmp relay: stream {name!r} already "
                           f"has a publisher")
    client = RtmpClient(host, port, app=app, timeout=timeout)
    try:
        client.connect()
        stream = client.create_stream()

        def on_media(msg_type, ts, payload):
            service.on_media(name, msg_type, ts, payload)

        client.start_reader()
        stream.play(remote_name or name, on_media, timeout=timeout)
    except Exception:
        # release the claim or the name is wedged until process restart
        # (the origin's null sock never reports failed())
        service.release_publisher(name, origin)
        client.close()
        raise
    return client
