"""esp protocol — counterpart of /root/reference/src/brpc/policy/
esp_protocol.cpp + esp_head.h: a 32-byte packed little-endian header
`{from:u64, to:u64, msg:u32, msg_id:u64, body_len:i32}` then the body.

The reference registers esp client-side only, on pooled/short connections,
with the correlation id parked on the socket between request and response
(esp_protocol.cpp:103,124 — esp frames carry no correlation of their own,
so each pooled socket has at most one RPC in flight). We keep that client
shape and add an optional server side gated on ServerOptions.esp_service —
esp has no magic bytes, so like mongo it only claims bytes when the server
opted in.
"""
from __future__ import annotations

import struct
import time
from typing import Callable

from brpc_tpu.bthread import id as bthread_id
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.rpc.protocol import (
    InputMessageBase,
    ParseResult,
    Protocol,
    ProtocolType,
    register_protocol,
)

_HEAD = struct.Struct("<QQIQi")  # EspHead, packed (esp_head.h:20-27)
HEAD_SIZE = _HEAD.size  # 32
MAX_BODY = 64 << 20


class EspMessage:
    """EspHead fields + body (esp_message.h:35-38)."""

    __slots__ = ("from_addr", "to_addr", "msg", "msg_id", "body")

    def __init__(self, body: bytes = b"", to_addr: int = 0, msg: int = 0,
                 msg_id: int = 0, from_addr: int = 0):
        self.from_addr = from_addr
        self.to_addr = to_addr
        self.msg = msg
        self.msg_id = msg_id
        self.body = body

    def serialize(self) -> bytes:
        return _HEAD.pack(self.from_addr, self.to_addr, self.msg,
                          self.msg_id, len(self.body)) + self.body


class EspInputMessage(InputMessageBase):
    __slots__ = ("msg", "is_request")

    def __init__(self, msg: EspMessage, is_request: bool):
        super().__init__()
        self.msg = msg
        self.is_request = is_request


def parse(portal: IOBuf, sock, read_eof: bool, arg) -> ParseResult:
    if arg is not None:  # server side: only when the server opted in
        if getattr(getattr(arg, "options", None), "esp_service", None) is None:
            return ParseResult.try_others()
    elif not hasattr(sock, "esp_correlation_id"):
        # Client side: esp has zero magic, so only claim bytes on sockets
        # an esp pack_request has actually used — otherwise corrupt frames
        # on other channels' sockets would be silently swallowed here.
        return ParseResult.try_others()
    if len(portal) < HEAD_SIZE:
        return ParseResult.not_enough()
    raw = portal.copy_to_bytes(HEAD_SIZE)
    from_addr, to_addr, msg, msg_id, body_len = _HEAD.unpack(raw)
    if body_len < 0 or body_len > MAX_BODY:
        return ParseResult.error_()
    if len(portal) < HEAD_SIZE + body_len:
        return ParseResult.not_enough()
    portal.pop_front(HEAD_SIZE)
    body = portal.cutn_bytes(body_len)
    return ParseResult.ok(EspInputMessage(
        EspMessage(body, to_addr, msg, msg_id, from_addr),
        is_request=arg is not None))


def serialize_request(request, cntl: Controller):
    if isinstance(request, EspMessage):
        return request
    raise TypeError("esp channel takes an EspMessage request")


def pack_request(request: EspMessage, cntl: Controller,
                 correlation_id: int) -> IOBuf:
    # Correlation parks on the socket (esp_protocol.cpp:103): esp sockets
    # are pooled/short, so one in-flight RPC per socket.
    sock = cntl._current_sock
    if getattr(sock, "esp_correlation_id", None) is not None:
        # A previous RPC on this socket ended without its response being
        # consumed (timeout/cancel); a late reply could complete the WRONG
        # call. Poison the connection instead of risking mismatches.
        sock.set_failed(errors.ECLOSE, "esp response outstanding on socket")
        raise ValueError("esp socket has an unconsumed in-flight response")
    sock.esp_correlation_id = correlation_id
    return IOBuf(request.serialize())


def process_response(msg: EspInputMessage):
    sock = msg.socket
    cid = getattr(sock, "esp_correlation_id", None)
    if cid is None:
        return
    sock.esp_correlation_id = None
    try:
        cntl = bthread_id.lock(cid)
    except (KeyError, TimeoutError):
        return
    if not isinstance(cntl, Controller):
        try:
            bthread_id.unlock(cid)
        except Exception:
            pass
        return
    resp = cntl._response
    if isinstance(resp, EspMessage):
        src = msg.msg
        resp.from_addr = src.from_addr
        resp.to_addr = src.to_addr
        resp.msg = src.msg
        resp.msg_id = src.msg_id
        resp.body = src.body
    cntl._end_rpc_locked_or_not(locked=True)


class EspService:
    """Server-side handler (our extension; the reference is client-only):
    override process_esp_request(cntl, request, done)."""

    def process_esp_request(self, cntl, request: EspMessage,
                            done: Callable):
        done(EspMessage(request.body, msg=request.msg,
                        msg_id=request.msg_id))


def process_request(msg: EspInputMessage):
    server = msg.arg
    sock = msg.socket
    service = server.options.esp_service
    cntl = Controller()
    cntl.server = server
    cntl.remote_side = sock.remote_side
    cntl.server_start_time = time.monotonic()
    responded = [False]

    def done(response: EspMessage = None):
        if responded[0]:
            return
        responded[0] = True
        out = response or EspMessage(msg=msg.msg.msg, msg_id=msg.msg.msg_id)
        out.msg_id = msg.msg.msg_id
        sock.write(IOBuf(out.serialize()))

    try:
        service.process_esp_request(cntl, msg.msg, done)
    except Exception as e:
        if not responded[0]:
            done(EspMessage(f"error: {e}".encode(), msg=msg.msg.msg))


register_protocol(Protocol(
    name="esp",
    type=ProtocolType.ESP,
    parse=parse,
    serialize_request=serialize_request,
    pack_request=pack_request,
    process_request=process_request,
    process_response=process_response,
    supported_connection_types=("pooled", "short"),
    process_inline=True,
    extra={
        # Don't return a socket to the pool while its response is still
        # owed (RPC timed out / cancelled before process_response ran).
        "can_repool":
            lambda sock: getattr(sock, "esp_correlation_id", None) is None,
    },
))

