"""NativeCluster — the Python handle on the C++ fan-out core.

Wraps native/src/nat_cluster.cpp (ISSUE 13 / ROADMAP item 1): a
DoublyBufferedData server list with zero-lock LB selection, per-backend
lazily-dialed NatChannels carrying the PR-5 circuit breakers and PR-8
lame-duck failover, and the combo-channel verbs (selective-with-retry /
parallel / partition) issued and merged natively.

The naming feed reuses the SAME NamingService registry the Python stack
resolves through (``brpc_tpu.rpc.naming_service._ns_registry``): the
watcher re-resolves on each scheme's refresh interval and pushes the
FULL node list down through ``nat_cluster_update`` — so every scheme
(list/file/dns/consul/discovery/nacos/remotefile) drives the native
cluster day one, and a registered custom scheme works unmodified.

``brpc_tpu.rpc.combo_channels`` builds its ``native=True`` fast paths on
this class; the observatory (``/status`` + ``/brpc_metrics``) walks the
module registry below for per-backend rows.
"""
from __future__ import annotations

import threading
import weakref
from typing import Callable, List, Optional, Tuple

from brpc_tpu import native
from brpc_tpu.bthread import timer_add
from brpc_tpu.butil.endpoint import EndPoint

# live clusters, walked by the builtin consoles (/status cluster table,
# /brpc_metrics nat_cluster_* rows); weak so a dropped cluster vanishes
_registry: "weakref.WeakSet[NativeCluster]" = weakref.WeakSet()
_registry_lock = threading.Lock()


def live_clusters() -> List["NativeCluster"]:
    with _registry_lock:
        return [c for c in _registry if not c.closed]


class NativeNamingWatcher:
    """Periodic NS -> native-cluster feed (details/naming_service_thread
    role, minus the Python Socket creation: backends live natively)."""

    def __init__(self, ns, service_path: str, cluster: "NativeCluster",
                 node_filter: Optional[Callable] = None):
        self._ns = ns
        self._path = service_path
        self._cluster = cluster
        self._filter = node_filter
        self._stopped = False
        self.refresh()  # first resolution is synchronous (blocking init)
        if ns.refresh_interval_s > 0:
            timer_add(ns.refresh_interval_s, self._periodic)

    def _periodic(self):
        if self._stopped or self._cluster.closed:
            return
        try:
            self.refresh()
        finally:
            if not self._stopped:
                timer_add(self._ns.refresh_interval_s, self._periodic)

    def refresh(self):
        nodes = self._ns.get_servers(self._path)
        if self._filter is not None:
            nodes = [n for n in nodes if self._filter(n)]
        self._cluster.update(nodes)

    def stop(self):
        self._stopped = True


class NativeCluster:
    """One native cluster handle. ``lb``: rr / wrr / random / wr / la /
    c_hash (aliases c_murmurhash, c_md5)."""

    def __init__(self, lb: str = "rr", connect_timeout_ms: int = 500,
                 health_check_ms: int = 100, breaker: bool = True,
                 name: str = ""):
        self._h = native.cluster_create(lb, connect_timeout_ms,
                                        health_check_ms, breaker)
        self.lb = lb
        self.name = name or f"cluster-{id(self) & 0xffff:x}"
        self.closed = False
        self._lock = threading.Lock()
        # verb gate: close() must not free the native handle under an
        # in-flight verb (the C side documents exactly this contract) —
        # verbs enter/exit a counter, close waits for it to drain
        self._cv = threading.Condition(self._lock)
        self._inflight = 0
        self._watcher: Optional[NativeNamingWatcher] = None
        with _registry_lock:
            _registry.add(self)

    def _enter(self) -> bool:
        with self._lock:
            if self.closed:
                return False
            self._inflight += 1
            return True

    def _exit(self):
        with self._lock:
            self._inflight -= 1
            if self.closed and self._inflight == 0:
                self._cv.notify_all()

    # -- membership --------------------------------------------------------
    def update(self, nodes) -> int:
        """Push the full resolved server list: an iterable of
        (EndPoint-or-"ip:port", weight, tag) tuples or bare endpoint
        strings, or a raw spec string."""
        if not isinstance(nodes, (str, bytes)):
            flat = []
            for n in nodes:
                ep = n[0] if isinstance(n, (tuple, list)) else n
                if isinstance(ep, EndPoint):
                    n = (f"{ep.ip}:{ep.port}",) + tuple(
                        n[1:] if isinstance(n, (tuple, list)) else ())
                flat.append(native.cluster_node_entry(n))
            nodes = flat
        with self._lock:
            if self.closed:
                return 0
            return native.cluster_update(self._h, nodes)

    def watch(self, naming_url: str,
              node_filter: Optional[Callable] = None
              ) -> "NativeNamingWatcher":
        """Start the naming observer: scheme://path resolved through the
        shared NS registry, re-resolved on the scheme's interval, full
        list pushed down on every refresh."""
        from brpc_tpu.rpc.naming_service import _ns_registry

        scheme, sep, path = naming_url.partition("://")
        if not sep:
            raise ValueError(f"not a naming url: {naming_url!r}")
        factory = _ns_registry.get(scheme)
        if factory is None:
            raise ValueError(f"unknown naming scheme: {scheme!r}")
        self._watcher = NativeNamingWatcher(factory(), path, self,
                                            node_filter)
        return self._watcher

    def backend_count(self) -> int:
        return native.cluster_backend_count(self._h)

    def select_debug(self, request_code: int = 0) -> Optional[str]:
        return native.cluster_select_debug(self._h, request_code)

    # -- the verbs ---------------------------------------------------------
    _CLOSED = (1009, b"", "cluster closed")

    def call(self, service_method: str, payload: bytes = b"",
             timeout_ms: int = 1000, max_retry: int = 2,
             request_code: int = 0) -> Tuple[int, bytes, str]:
        if not self._enter():
            return self._CLOSED
        try:
            service, _, method = service_method.rpartition(".")
            return native.cluster_call(self._h, service, method, payload,
                                       timeout_ms, max_retry,
                                       request_code)
        finally:
            self._exit()

    def parallel_call(self, service_method: str, payload: bytes = b"",
                      timeout_ms: int = 1000, fail_limit: int = 0
                      ) -> Tuple[int, bytes, str, int]:
        if not self._enter():
            return self._CLOSED + (0,)
        try:
            service, _, method = service_method.rpartition(".")
            return native.cluster_parallel_call(self._h, service, method,
                                                payload, timeout_ms,
                                                fail_limit)
        finally:
            self._exit()

    def partition_call(self, service_method: str, payload: bytes = b"",
                       timeout_ms: int = 1000, partitions: int = 0,
                       fail_limit: int = 0) -> Tuple[int, bytes, str, int]:
        if not self._enter():
            return self._CLOSED + (0,)
        try:
            service, _, method = service_method.rpartition(".")
            return native.cluster_partition_call(self._h, service, method,
                                                 payload, timeout_ms,
                                                 partitions, fail_limit)
        finally:
            self._exit()

    def dynpart_call(self, service_method: str, payload: bytes = b"",
                     timeout_ms: int = 1000, fail_limit: int = 0
                     ) -> Tuple[int, bytes, str, int, int]:
        """DynamicPartitionChannel verb: scheme picked per call from the
        live "i/n" totals, capacity-weighted (_dynpart); returns
        (rc, merged, err, failed_subcalls, chosen_scheme)."""
        if not self._enter():
            return self._CLOSED + (0, 0)
        try:
            service, _, method = service_method.rpartition(".")
            return native.cluster_dynpart_call(self._h, service, method,
                                               payload, timeout_ms,
                                               fail_limit)
        finally:
            self._exit()

    def dynpart_debug(self, x01: float = 0.0) -> dict:
        """Live dynpart scheme table + the pick for point x01 (the
        native-vs-Python equivalence probe)."""
        if not self._enter():
            return {"schemes": [], "chosen": 0}
        try:
            return native.cluster_dynpart_debug(self._h, x01)
        finally:
            self._exit()

    def bench(self, mode: int = 0, seconds: float = 2.0,
              concurrency: int = 4, payload: bytes = b"x" * 16,
              timeout_ms: int = 2000, param: int = 2,
              service: str = "EchoService", method: str = "Echo") -> dict:
        if not self._enter():
            return {"qps": 0.0, "calls": 0, "failed": 0, "p99_us": 0.0}
        try:
            return native.cluster_bench(self._h, mode, service, method,
                                        payload, timeout_ms, param,
                                        seconds, concurrency)
        finally:
            self._exit()

    # -- observability -----------------------------------------------------
    def stats(self) -> list:
        if not self._enter():
            return []
        try:
            return native.cluster_stats(self._h)
        finally:
            self._exit()

    # -- lifecycle ---------------------------------------------------------
    def close(self):
        with self._lock:
            if self.closed:
                return
            self.closed = True
            if self._watcher is not None:
                self._watcher.stop()
            # wait out in-flight verbs (bounded by their own deadlines):
            # the native close frees the handle's last reference, so no
            # verb may still be inside the C surface when it runs
            while self._inflight > 0:
                self._cv.wait(timeout=1.0)
            native.cluster_close(self._h)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
