"""Service & method registry — how user code exposes RPC methods.

The reference builds its method maps from protobuf Service descriptors
(server.h:343 AddService + details/method_status); here a Service subclass
declares methods with @rpc_method(Request, Response), yielding the same
(service_name, method_name) -> (request class, response class, handler)
map, with handlers keeping brpc's CallMethod signature:

    @rpc_method(EchoRequest, EchoResponse)
    def Echo(self, controller, request, response, done):
        response.message = request.message
        done()

`done` is the response-sending closure (the SendRpcResponse closure of
baidu_rpc_protocol.cpp:507); ClosureGuard mirrors brpc::ClosureGuard.
"""
from __future__ import annotations

from typing import Callable, Dict, NamedTuple, Optional, Type


class MethodInfo(NamedTuple):
    name: str
    request_class: Type
    response_class: Type
    handler: Callable  # bound later: handler(self, cntl, req, res, done)


def rpc_method(request_class: Type, response_class: Type):
    """Mark a Service method as an RPC method."""

    def deco(fn):
        fn.__rpc_method__ = (request_class, response_class)
        return fn

    return deco


class Service:
    """Base class; service name defaults to the class name."""

    @classmethod
    def service_name(cls) -> str:
        return getattr(cls, "SERVICE_NAME", cls.__name__)

    @classmethod
    def methods(cls) -> Dict[str, MethodInfo]:
        out = {}
        for attr in dir(cls):
            fn = getattr(cls, attr, None)
            info = getattr(fn, "__rpc_method__", None)
            if info is not None:
                out[attr] = MethodInfo(attr, info[0], info[1], fn)
        return out


class ClosureGuard:
    """Runs done() on exit unless released (brpc::ClosureGuard)."""

    def __init__(self, done: Optional[Callable]):
        self._done = done

    def release(self):
        d, self._done = self._done, None
        return d

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if self._done is not None:
            self._done()
            self._done = None

    def __del__(self):
        if self._done is not None:
            try:
                self._done()
            except Exception:
                pass
            self._done = None
