"""HPACK (RFC 7541) — header compression for h2.

Counterpart of brpc's details/hpack.{h,cpp}
(/root/reference/src/brpc/details/hpack.cpp): full decoder (static table +
dynamic table + Huffman) and an encoder using static-table indexing plus
literal-without-indexing (a legal, interoperable encoder choice that keeps
the peer's dynamic table in sync trivially).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

# RFC 7541 Appendix A — static table
STATIC_TABLE: List[Tuple[str, str]] = [
    (":authority", ""), (":method", "GET"), (":method", "POST"),
    (":path", "/"), (":path", "/index.html"), (":scheme", "http"),
    (":scheme", "https"), (":status", "200"), (":status", "204"),
    (":status", "206"), (":status", "304"), (":status", "400"),
    (":status", "404"), (":status", "500"), ("accept-charset", ""),
    ("accept-encoding", "gzip, deflate"), ("accept-language", ""),
    ("accept-ranges", ""), ("accept", ""), ("access-control-allow-origin", ""),
    ("age", ""), ("allow", ""), ("authorization", ""), ("cache-control", ""),
    ("content-disposition", ""), ("content-encoding", ""),
    ("content-language", ""), ("content-length", ""), ("content-location", ""),
    ("content-range", ""), ("content-type", ""), ("cookie", ""), ("date", ""),
    ("etag", ""), ("expect", ""), ("expires", ""), ("from", ""), ("host", ""),
    ("if-match", ""), ("if-modified-since", ""), ("if-none-match", ""),
    ("if-range", ""), ("if-unmodified-since", ""), ("last-modified", ""),
    ("link", ""), ("location", ""), ("max-forwards", ""),
    ("proxy-authenticate", ""), ("proxy-authorization", ""), ("range", ""),
    ("referer", ""), ("refresh", ""), ("retry-after", ""), ("server", ""),
    ("set-cookie", ""), ("strict-transport-security", ""),
    ("transfer-encoding", ""), ("user-agent", ""), ("vary", ""), ("via", ""),
    ("www-authenticate", ""),
]
_STATIC_LOOKUP = {}
for _i, (_n, _v) in enumerate(STATIC_TABLE):
    _STATIC_LOOKUP.setdefault((_n, _v), _i + 1)
    _STATIC_LOOKUP.setdefault((_n, None), _i + 1)

# RFC 7541 Appendix B — Huffman code table (code, bit-length) per byte 0-255
# + EOS. Stored compactly; decoder built as a binary trie.
_HUFFMAN_CODES = [
    (0x1ff8, 13), (0x7fffd8, 23), (0xfffffe2, 28), (0xfffffe3, 28),
    (0xfffffe4, 28), (0xfffffe5, 28), (0xfffffe6, 28), (0xfffffe7, 28),
    (0xfffffe8, 28), (0xffffea, 24), (0x3ffffffc, 30), (0xfffffe9, 28),
    (0xfffffea, 28), (0x3ffffffd, 30), (0xfffffeb, 28), (0xfffffec, 28),
    (0xfffffed, 28), (0xfffffee, 28), (0xfffffef, 28), (0xffffff0, 28),
    (0xffffff1, 28), (0xffffff2, 28), (0x3ffffffe, 30), (0xffffff3, 28),
    (0xffffff4, 28), (0xffffff5, 28), (0xffffff6, 28), (0xffffff7, 28),
    (0xffffff8, 28), (0xffffff9, 28), (0xffffffa, 28), (0xffffffb, 28),
    (0x14, 6), (0x3f8, 10), (0x3f9, 10), (0xffa, 12), (0x1ff9, 13),
    (0x15, 6), (0xf8, 8), (0x7fa, 11), (0x3fa, 10), (0x3fb, 10), (0xf9, 8),
    (0x7fb, 11), (0xfa, 8), (0x16, 6), (0x17, 6), (0x18, 6), (0x0, 5),
    (0x1, 5), (0x2, 5), (0x19, 6), (0x1a, 6), (0x1b, 6), (0x1c, 6),
    (0x1d, 6), (0x1e, 6), (0x1f, 6), (0x5c, 7), (0xfb, 8), (0x7ffc, 15),
    (0x20, 6), (0xffb, 12), (0x3fc, 10), (0x1ffa, 13), (0x21, 6), (0x5d, 7),
    (0x5e, 7), (0x5f, 7), (0x60, 7), (0x61, 7), (0x62, 7), (0x63, 7),
    (0x64, 7), (0x65, 7), (0x66, 7), (0x67, 7), (0x68, 7), (0x69, 7),
    (0x6a, 7), (0x6b, 7), (0x6c, 7), (0x6d, 7), (0x6e, 7), (0x6f, 7),
    (0x70, 7), (0x71, 7), (0x72, 7), (0xfc, 8), (0x73, 7), (0xfd, 8),
    (0x1ffb, 13), (0x7fff0, 19), (0x1ffc, 13), (0x3ffc, 14), (0x22, 6),
    (0x7ffd, 15), (0x3, 5), (0x23, 6), (0x4, 5), (0x24, 6), (0x5, 5),
    (0x25, 6), (0x26, 6), (0x27, 6), (0x6, 5), (0x74, 7), (0x75, 7),
    (0x28, 6), (0x29, 6), (0x2a, 6), (0x7, 5), (0x2b, 6), (0x76, 7),
    (0x2c, 6), (0x8, 5), (0x9, 5), (0x2d, 6), (0x77, 7), (0x78, 7),
    (0x79, 7), (0x7a, 7), (0x7b, 7), (0x7ffe, 15), (0x7fc, 11), (0x3ffd, 14),
    (0x1ffd, 13), (0xffffffc, 28), (0xfffe6, 20), (0x3fffd2, 22),
    (0xfffe7, 20), (0xfffe8, 20), (0x3fffd3, 22), (0x3fffd4, 22),
    (0x3fffd5, 22), (0x7fffd9, 23), (0x3fffd6, 22), (0x7fffda, 23),
    (0x7fffdb, 23), (0x7fffdc, 23), (0x7fffdd, 23), (0x7fffde, 23),
    (0xffffeb, 24), (0x7fffdf, 23), (0xffffec, 24), (0xffffed, 24),
    (0x3fffd7, 22), (0x7fffe0, 23), (0xffffee, 24), (0x7fffe1, 23),
    (0x7fffe2, 23), (0x7fffe3, 23), (0x7fffe4, 23), (0x1fffdc, 21),
    (0x3fffd8, 22), (0x7fffe5, 23), (0x3fffd9, 22), (0x7fffe6, 23),
    (0x7fffe7, 23), (0xffffef, 24), (0x3fffda, 22), (0x1fffdd, 21),
    (0xfffe9, 20), (0x3fffdb, 22), (0x3fffdc, 22), (0x7fffe8, 23),
    (0x7fffe9, 23), (0x1fffde, 21), (0x7fffea, 23), (0x3fffdd, 22),
    (0x3fffde, 22), (0xfffff0, 24), (0x1fffdf, 21), (0x3fffdf, 22),
    (0x7fffeb, 23), (0x7fffec, 23), (0x1fffe0, 21), (0x1fffe1, 21),
    (0x3fffe0, 22), (0x1fffe2, 21), (0x7fffed, 23), (0x3fffe1, 22),
    (0x7fffee, 23), (0x7fffef, 23), (0xfffea, 20), (0x3fffe2, 22),
    (0x3fffe3, 22), (0x3fffe4, 22), (0x7ffff0, 23), (0x3fffe5, 22),
    (0x3fffe6, 22), (0x7ffff1, 23), (0x3ffffe0, 26), (0x3ffffe1, 26),
    (0xfffeb, 20), (0x7fff1, 19), (0x3fffe7, 22), (0x7ffff2, 23),
    (0x3fffe8, 22), (0x1ffffec, 25), (0x3ffffe2, 26), (0x3ffffe3, 26),
    (0x3ffffe4, 26), (0x7ffffde, 27), (0x7ffffdf, 27), (0x3ffffe5, 26),
    (0xfffff1, 24), (0x1ffffed, 25), (0x7fff2, 19), (0x1fffe3, 21),
    (0x3ffffe6, 26), (0x7ffffe0, 27), (0x7ffffe1, 27), (0x3ffffe7, 26),
    (0x7ffffe2, 27), (0xfffff2, 24), (0x1fffe4, 21), (0x1fffe5, 21),
    (0x3ffffe8, 26), (0x3ffffe9, 26), (0xffffffd, 28), (0x7ffffe3, 27),
    (0x7ffffe4, 27), (0x7ffffe5, 27), (0xfffec, 20), (0xfffff3, 24),
    (0xfffed, 20), (0x1fffe6, 21), (0x3fffe9, 22), (0x1fffe7, 21),
    (0x1fffe8, 21), (0x7ffff3, 23), (0x3fffea, 22), (0x3fffeb, 22),
    (0x1ffffee, 25), (0x1ffffef, 25), (0xfffff4, 24), (0xfffff5, 24),
    (0x3ffffea, 26), (0x7ffff4, 23), (0x3ffffeb, 26), (0x7ffffe6, 27),
    (0x3ffffec, 26), (0x3ffffed, 26), (0x7ffffe7, 27), (0x7ffffe8, 27),
    (0x7ffffe9, 27), (0x7ffffea, 27), (0x7ffffeb, 27), (0xffffffe, 28),
    (0x7ffffec, 27), (0x7ffffed, 27), (0x7ffffee, 27), (0x7ffffef, 27),
    (0x7fffff0, 27), (0x3ffffee, 26),
]
_EOS = (0x3fffffff, 30)

# decoder trie: dict-of-dicts is slow; use (node -> [left, right, symbol])
_trie = [[None, None, None]]


def _trie_insert(code: int, nbits: int, symbol: int):
    node = 0
    for i in range(nbits - 1, -1, -1):
        bit = (code >> i) & 1
        nxt = _trie[node][bit]
        if nxt is None:
            _trie.append([None, None, None])
            nxt = len(_trie) - 1
            _trie[node][bit] = nxt
        node = nxt
    _trie[node][2] = symbol


for _sym, (_code, _nbits) in enumerate(_HUFFMAN_CODES):
    _trie_insert(_code, _nbits, _sym)


def huffman_decode(data: bytes) -> bytes:
    out = bytearray()
    node = 0
    padding = 0
    pad_all_ones = True
    for byte in data:
        for i in range(7, -1, -1):
            bit = (byte >> i) & 1
            node = _trie[node][bit]
            if node is None:
                raise ValueError("bad huffman sequence")
            sym = _trie[node][2]
            if sym is not None:
                out.append(sym)
                node = 0
                padding = 0
                pad_all_ones = True
            else:
                padding += 1
                if bit == 0:
                    pad_all_ones = False
    if padding > 7:
        raise ValueError("huffman padding too long")
    # RFC 7541 5.2: an incomplete trailing code must be the EOS prefix.
    if padding and not pad_all_ones:
        raise ValueError("huffman padding is not an EOS prefix")
    return bytes(out)


def huffman_encode(data: bytes) -> bytes:
    acc = 0
    nbits = 0
    out = bytearray()
    for b in data:
        code, n = _HUFFMAN_CODES[b]
        acc = (acc << n) | code
        nbits += n
        while nbits >= 8:
            nbits -= 8
            out.append((acc >> nbits) & 0xFF)
    if nbits:
        pad = 8 - nbits
        out.append(((acc << pad) | ((1 << pad) - 1)) & 0xFF)
    return bytes(out)


# -- integer / string primitives (RFC 7541 §5) ------------------------------

def encode_int(value: int, prefix_bits: int, first_byte: int = 0) -> bytes:
    limit = (1 << prefix_bits) - 1
    if value < limit:
        return bytes([first_byte | value])
    out = bytearray([first_byte | limit])
    value -= limit
    while value >= 128:
        out.append((value & 0x7F) | 0x80)
        value >>= 7
    out.append(value)
    return bytes(out)


def decode_int(data: bytes, pos: int, prefix_bits: int) -> Tuple[int, int]:
    limit = (1 << prefix_bits) - 1
    value = data[pos] & limit
    pos += 1
    if value < limit:
        return value, pos
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        value += (b & 0x7F) << shift
        shift += 7
        if not (b & 0x80):
            return value, pos


def encode_str(s: str, huffman: bool = False) -> bytes:
    raw = s.encode("utf-8")
    if huffman:
        enc = huffman_encode(raw)
        if len(enc) < len(raw):
            return encode_int(len(enc), 7, 0x80) + enc
    return encode_int(len(raw), 7, 0x00) + raw


def decode_str(data: bytes, pos: int) -> Tuple[str, int]:
    huff = bool(data[pos] & 0x80)
    length, pos = decode_int(data, pos, 7)
    if pos + length > len(data):
        raise ValueError("hpack string extends past the header block")
    raw = data[pos: pos + length]
    pos += length
    if huff:
        raw = huffman_decode(raw)
    return raw.decode("utf-8", "replace"), pos


# -- encoder / decoder -------------------------------------------------------

class HpackEncoder:
    """Static-index + literal-without-indexing encoder (keeps the remote
    dynamic table untouched, so no synchronization state)."""

    def encode(self, headers: List[Tuple[str, str]]) -> bytes:
        out = bytearray()
        for name, value in headers:
            idx = _STATIC_LOOKUP.get((name, value))
            if idx is not None and STATIC_TABLE[idx - 1][1] == value:
                out += encode_int(idx, 7, 0x80)  # fully indexed
                continue
            name_idx = _STATIC_LOOKUP.get((name, None))
            if name_idx is not None:
                out += encode_int(name_idx, 4, 0x00)  # literal w/o indexing
            else:
                out += b"\x00"
                out += encode_str(name)
            out += encode_str(value)
        return bytes(out)


class HpackDecoder:
    """Full decoder: static + dynamic table + huffman + size updates."""

    def __init__(self, max_table_size: int = 4096):
        self._dynamic: List[Tuple[str, str]] = []
        self._max_size = max_table_size
        self._size = 0

    def _entry(self, index: int) -> Tuple[str, str]:
        if index <= 0:
            raise ValueError("hpack index 0")
        if index <= len(STATIC_TABLE):
            return STATIC_TABLE[index - 1]
        d = index - len(STATIC_TABLE) - 1
        if d >= len(self._dynamic):
            raise ValueError(f"hpack index {index} out of range")
        return self._dynamic[d]

    def _add(self, name: str, value: str):
        entry_size = len(name) + len(value) + 32
        self._dynamic.insert(0, (name, value))
        self._size += entry_size
        while self._size > self._max_size and self._dynamic:
            n, v = self._dynamic.pop()
            self._size -= len(n) + len(v) + 32

    def decode(self, data: bytes) -> List[Tuple[str, str]]:
        out = []
        pos = 0
        while pos < len(data):
            b = data[pos]
            if b & 0x80:  # indexed
                index, pos = decode_int(data, pos, 7)
                out.append(self._entry(index))
            elif b & 0x40:  # literal with incremental indexing
                index, pos = decode_int(data, pos, 6)
                name = self._entry(index)[0] if index else None
                if name is None:
                    name, pos = decode_str(data, pos)
                value, pos = decode_str(data, pos)
                self._add(name, value)
                out.append((name, value))
            elif b & 0x20:  # dynamic table size update
                size, pos = decode_int(data, pos, 5)
                self._max_size = size
                while self._size > self._max_size and self._dynamic:
                    n, v = self._dynamic.pop()
                    self._size -= len(n) + len(v) + 32
            else:  # literal without indexing / never indexed (4-bit prefix)
                index, pos = decode_int(data, pos, 4)
                name = self._entry(index)[0] if index else None
                if name is None:
                    name, pos = decode_str(data, pos)
                value, pos = decode_str(data, pos)
                out.append((name, value))
        return out
