"""HTTP/1.1 protocol — placeholder registration point.

Counterpart of policy/http_rpc_protocol.cpp; the full implementation
(RESTful routing + builtin console pages + pb-over-http) registers here.
"""
# Filled in by the builtin-console milestone; see http_impl.py once present.
