"""HTTP/1.1 protocol — RESTful access to services + the builtin console.

Counterpart of policy/http_rpc_protocol.cpp
(/root/reference/src/brpc/policy/http_rpc_protocol.cpp) with restful.cpp's
routing role: POST /ServiceName/Method with a JSON (or binary-pb) body
calls the same method map the tpu_std protocol serves (pb-over-http via
json2pb); any other path routes to the builtin console services registered
by brpc_tpu.builtin (server.cpp:468-563 equivalents).

Client side: channels with options.protocol="http" serialize requests as
JSON and pipeline correlation ids per connection (responses on an HTTP/1.1
connection arrive in request order).
"""
from __future__ import annotations

import time
from collections import deque

from brpc_tpu.bthread import id as bthread_id
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.json2pb import json_to_pb_inplace, pb_to_json
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.rpc.http_message import (
    HttpRequest,
    HttpResponse,
    try_parse,
)
from brpc_tpu.rpc.protocol import (
    InputMessageBase,
    ParseResult,
    Protocol,
    ProtocolType,
    register_protocol,
)

_STATUS_REASON = {200: "OK", 400: "Bad Request", 403: "Forbidden",
                  404: "Not Found", 500: "Internal Server Error",
                  503: "Service Unavailable"}


def http_status_from_error(code: int) -> int:
    """grpc.h:27-152 role: framework error -> HTTP status."""
    if code == 0:
        return 200
    return {
        errors.ENOSERVICE: 404,
        errors.ENOMETHOD: 404,
        errors.EREQUEST: 400,
        errors.EAUTH: 403,
        errors.EPERM: 403,
        errors.ELIMIT: 503,
        errors.EOVERLOAD: 503,
    }.get(code, 500)


class HttpInputMessage(InputMessageBase):
    __slots__ = ("http", "is_request")

    def __init__(self, http_msg):
        super().__init__()
        self.http = http_msg
        self.is_request = isinstance(http_msg, HttpRequest)


def parse(portal: IOBuf, sock, read_eof: bool, arg) -> ParseResult:
    state, msg = try_parse(portal)
    if state == "ok":
        return ParseResult.ok(HttpInputMessage(msg))
    if state == "more":
        return ParseResult.not_enough()
    if state == "not_http":
        return ParseResult.try_others()
    return ParseResult.error_()


# -- server side -----------------------------------------------------------

def _respond(sock, response: HttpResponse, close: bool = False):
    response.reason = _STATUS_REASON.get(response.status_code,
                                         response.reason or "")
    response.headers.set("server", "brpc_tpu")
    if close:
        response.headers.set("connection", "close")
    out = response.serialize()
    if getattr(response, "_head_only", False):
        # HEAD: status + headers (incl. the body's Content-Length) but
        # never the body bytes (RFC 9110 §9.3.2)
        body_len = len(response.body)
        if body_len:
            out = IOBuf(out.copy_to_bytes(len(out) - body_len))
    sock.write(out)
    if close:
        sock.set_failed(errors.ECLOSE, "http connection: close")


def process_request(msg: HttpInputMessage):
    """Route: /Service/Method RPC call, else builtin console page."""
    server = msg.arg
    req: HttpRequest = msg.http
    sock = msg.socket
    close = (req.headers.get("connection", "").lower() == "close")
    resp = HttpResponse()
    resp._head_only = req.method == "HEAD"
    if server is None:
        resp.status_code = 500
        resp.set_body("no server bound")
        return _respond(sock, resp, close)

    parts = [p for p in req.path.split("/") if p]
    # RESTful mapping first (restful.cpp routing role)
    mapped = server.restful_map.get(req.path)
    if mapped is not None and server.find_method(*mapped) is not None:
        return _process_http_rpc(server, req, sock, resp, mapped[0],
                                 mapped[1], close)
    # RPC-over-HTTP: /ServiceName/MethodName
    if len(parts) == 2 and server.find_method(parts[0], parts[1]) is not None:
        return _process_http_rpc(server, req, sock, resp, parts[0], parts[1],
                                 close)
    # builtin console
    handlers = getattr(server, "_builtin_handlers", None)
    if handlers:
        name = parts[0] if parts else "index"
        handler = handlers.get(name)
        if handler is not None:
            extra_headers = None
            try:
                out = handler(server, req)
                # handlers may return (status, ctype, body) or a 4-tuple
                # with extra response headers (e.g. Retry-After on the
                # busy-profiler 503)
                if len(out) == 4:
                    status, ctype, body, extra_headers = out
                else:
                    status, ctype, body = out
            except Exception as e:
                status, ctype, body = 500, "text/plain", f"handler raised: {e}"
            resp.status_code = status
            resp.set_body(body, ctype)
            if extra_headers:
                for hk, hv in extra_headers.items():
                    resp.headers.set(hk, hv)
            return _respond(sock, resp, close)
    # bad_method page (builtin/bad_method_service.cpp): a known service
    # with a missing/wrong method lists what IS callable
    svc = server.find_service(parts[0]) if parts else None
    if svc is not None:
        if len(parts) >= 2:
            first = f"fail to find method={parts[1]} in service={parts[0]}."
        else:
            first = f"Missing method name for service={parts[0]}."
        lines = [first, " Available methods are:", ""]
        for mname, minfo in sorted(svc.methods().items()):
            lines.append(f"rpc {mname} ({minfo.request_class.__name__}) "
                         f"returns ({minfo.response_class.__name__});")
        resp.status_code = 404
        resp.set_body("\n".join(lines) + "\n")
        return _respond(sock, resp, close)
    resp.status_code = 404
    resp.set_body(f"no such page or method: {req.path}\n")
    _respond(sock, resp, close)


def _process_http_rpc(server, req, sock, resp, service_name, method_name,
                      close):
    service_obj, minfo, method_status = server.find_method(service_name,
                                                           method_name)
    cntl = Controller()
    cntl.server = server
    cntl.remote_side = sock.remote_side
    cntl.service_name = service_name
    cntl.method_name = method_name
    cntl.server_start_time = time.monotonic()
    cntl.http_request = req
    cntl.http_response = resp
    if not method_status.on_requested():
        cntl.set_failed(errors.ELIMIT, "reached max_concurrency")
        resp.status_code = 503
        resp.set_body(cntl.error_text_value)
        return _respond(sock, resp, close)

    request = minfo.request_class()
    body = req.body.to_bytes()
    ctype = (req.headers.get("content-type") or "application/json").lower()
    try:
        if "proto" in ctype:
            request.ParseFromString(body)
        elif body:
            if not json_to_pb_inplace(body.decode("utf-8"), request):
                raise ValueError("malformed JSON body")
        # query params also populate fields (restful convenience)
        elif req.query:
            import json as _json

            json_to_pb_inplace(_json.dumps(req.query), request)
    except Exception as e:
        method_status.on_response(errors.EREQUEST, cntl.server_start_time)
        resp.status_code = 400
        resp.set_body(f"fail to parse request: {e}")
        return _respond(sock, resp, close)

    response_pb = minfo.response_class()
    responded = [False]

    def done():
        if responded[0]:
            return
        responded[0] = True
        method_status.on_response(cntl.error_code_value,
                                  cntl.server_start_time)
        if cntl.failed():
            resp.status_code = http_status_from_error(cntl.error_code_value)
            resp.set_body(cntl.error_text_value + "\n")
            resp.headers.set("x-error-code", cntl.error_code_value)
        else:
            if "proto" in ctype:
                resp.set_body(response_pb.SerializeToString(),
                              "application/proto")
            else:
                resp.set_body(pb_to_json(response_pb), "application/json")
        _respond(sock, resp, close)

    try:
        minfo.handler(service_obj, cntl, request, response_pb, done)
    except Exception as e:
        if not responded[0]:
            cntl.set_failed(errors.EINVAL, f"method raised: {e}")
            done()


# -- client side -----------------------------------------------------------

def serialize_request(request, cntl: Controller):
    if request is None:
        return b""
    if isinstance(request, (bytes, bytearray)):
        return bytes(request)
    return pb_to_json(request).encode("utf-8")


def pack_request(payload: bytes, cntl: Controller, correlation_id: int) -> IOBuf:
    service, _, method = cntl._method_full_name.rpartition(".")
    req = getattr(cntl, "http_request", None) or HttpRequest()
    if req.uri == "/":
        req.uri = f"/{service}/{method}"
    if payload:
        req.method = "POST"
        req.body = IOBuf(payload)
        if "content-type" not in req.headers:
            req.headers.set("content-type", "application/json")
    req.headers.set("host", str(cntl.remote_side or ""))
    req.headers.set("x-correlation-id", correlation_id)
    return req.serialize()


def on_packed(sock, cntl: Controller, correlation_id: int):
    """HTTP/1.1 responses arrive in request order: remember the cid queue
    per connection (the http pipelining correlation of
    http_rpc_protocol.cpp)."""
    q = getattr(sock, "_http_pipeline", None)
    if q is None:
        q = deque()
        sock._http_pipeline = q
    q.append(correlation_id)


def process_response(msg: HttpInputMessage):
    sock = msg.socket
    # lame duck: a previously keep-alive server answering with
    # Connection: close means it drains gracefully — new calls must
    # select another connection while this response (and any pipelined
    # predecessors) complete normally. The signal is the keep-alive ->
    # close TRANSITION: a close-per-response server (HTTP/1.0, keepalive
    # off) closes from its first response and must keep feeding the
    # circuit breaker normally, not be treated as planned churn forever.
    conn_close = (
        msg.http.headers.get("connection", "").lower().find("close") >= 0)
    if conn_close:
        if (getattr(sock, "_http_saw_keepalive", False)
                and hasattr(sock, "mark_lame_duck")):
            sock.mark_lame_duck()
    else:
        sock._http_saw_keepalive = True
    q = getattr(sock, "_http_pipeline", None)
    if not q:
        return
    cid = q.popleft()
    try:
        cntl = bthread_id.lock(cid)
    except (KeyError, TimeoutError):
        return
    if not isinstance(cntl, Controller):
        try:
            bthread_id.unlock(cid)
        except Exception:
            pass
        return
    http_resp: HttpResponse = msg.http
    cntl.http_response = http_resp
    body = http_resp.body.to_bytes()
    if http_resp.status_code != 200:
        code_hdr = http_resp.headers.get("x-error-code")
        code = int(code_hdr) if code_hdr and code_hdr.isdigit() else errors.EHTTP
        cntl.set_failed(code, body.decode("utf-8", "replace").strip()
                        or f"http status {http_resp.status_code}")
        cntl._end_rpc_locked_or_not(locked=True)
        return
    try:
        if cntl._response is not None and body:
            ctype = (http_resp.headers.get("content-type") or "").lower()
            if "proto" in ctype:
                cntl._response.ParseFromString(body)
            else:
                json_to_pb_inplace(body.decode("utf-8"), cntl._response)
    except Exception as e:
        cntl.set_failed(errors.EREQUEST, f"fail to parse http response: {e}")
    cntl._end_rpc_locked_or_not(locked=True)


register_protocol(Protocol(
    name="http",
    type=ProtocolType.HTTP,
    parse=parse,
    serialize_request=serialize_request,
    pack_request=pack_request,
    process_request=process_request,
    process_response=process_response,
    extra={"on_packed": on_packed},
))
