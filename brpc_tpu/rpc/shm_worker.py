"""Usercode worker process for the shm lane (nat_shm_lane.cpp).

The parent's native runtime parses HTTP/gRPC requests and fans kind-3/4
dispatch across N of these processes over the zero-copy descriptor-ring
transport — fixed 64-byte descriptors on lock-free per-worker rings,
payload bytes written once into a shared blob arena and handed to this
process as views (nat_req_field points straight into the arena; the copy
below into Python bytes is the only one on the worker side). Python
usercode scales past one interpreter's GIL the way the reference runs
usercode on all N workers (server.h:59-285 num_threads,
details/usercode_backup_pool.h:29-72).

This process holds its slot's ROBUST lifetime fence from attach until
death; a SIGKILL here surfaces as EOWNERDEAD on the parent's recovery
probe, which drains what this worker already answered, reaps what it
consumed, and frees the slot for a replacement.

Invocation (by brpc_tpu.rpc.server, not by hand):

    python -m brpc_tpu.rpc.shm_worker <shm_name> <module:factory>

`factory()` returns the list of Service objects to serve — the worker
rebuilds them (services must be constructible in a fresh process; the
same constraint every prefork server imposes on app state).
"""
from __future__ import annotations

import ctypes
import importlib
import sys

from brpc_tpu import bvar

# -- kind-8 tensor sink (ISSUE 15) ------------------------------------------
#
# Bulk tensor records (nat_shm_push_tensor / the device-lane fabric) used
# to hit a dead end here: no usercode hook, span silently released. A
# worker-side consumer registers a sink — called with a FabricLease whose
# view() reads the record's arena span IN PLACE; the sink OWNS the lease
# and may hold it past further takes, releasing out of order (e.g. after
# a jax.device_put completes). Unregistered records are counted, never
# silently dropped.

_tensor_sink = None
_sink_drops = bvar.Adder("shm_tensor_sink_unregistered_drops")


def set_tensor_sink(fn):
    """Register fn(lease) as this worker's bulk-tensor consumer (call it
    from the service factory — the factory runs in the worker process).
    The sink owns the lease: it must release() it, possibly out of
    order. Pass None to unregister."""
    global _tensor_sink
    _tensor_sink = fn


def tensor_sink_drops() -> int:
    """Records dropped because no sink was registered (observability —
    also exported as the shm_tensor_sink_unregistered_drops bvar)."""
    return _sink_drops.get_value()


def dispatch_tensor_record(native_mod, h) -> bool:
    """Route one kind-8 handle to the registered sink as a lease.
    Returns True when a sink consumed it (and now owns the span)."""
    lease = native_mod.FabricLease(h)
    sink = _tensor_sink
    if sink is None:
        _sink_drops.update(1)
        lease.release()
        return False
    try:
        sink(lease)
        return True
    except Exception:
        lease.release()  # idempotent: a sink that released already is fine
        return False


def main(shm_name: str, factory_spec: str) -> int:
    from brpc_tpu import native, rpc

    lib = native.load()  # signatures declared centrally in native.load()
    if lib.nat_shm_worker_attach(shm_name.encode()) != 0:
        print(f"shm_worker: cannot attach {shm_name}", file=sys.stderr)
        return 1

    # Responses ride the shm response ring; the parent's drainer feeds
    # them through the ordered per-session emitters. The module-level
    # rebind is worker-local: this process never owns sockets.
    def http_respond(sock_id, seq, data, close_after=False):
        return lib.nat_shm_respond(3, sock_id, seq, data, len(data), 0,
                                   None, 1 if close_after else 0)

    def grpc_respond(sock_id, stream_id, payload=b"", grpc_status=0,
                     grpc_message=""):
        return lib.nat_shm_respond(4, sock_id, stream_id, payload,
                                   len(payload), grpc_status,
                                   grpc_message.encode() or None, 0)

    native.http_respond = http_respond
    native.grpc_respond = grpc_respond
    native.sock_write = lambda *a, **k: -1       # no sockets here
    native.sock_set_failed = lambda *a, **k: -1

    mod_name, _, fn_name = factory_spec.partition(":")
    factory = getattr(importlib.import_module(mod_name), fn_name)
    services = factory()

    from brpc_tpu.builtin import register_builtin_services
    from brpc_tpu.rpc.native_runtime import NativeRuntimeMount

    server = rpc.Server(rpc.ServerOptions())
    for svc in services:
        server.add_service(svc)
    register_builtin_services(server)
    mount = NativeRuntimeMount(server, num_threads=1)

    def field(h, which):
        n = ctypes.c_size_t(0)
        p = lib.nat_req_field(h, which, ctypes.byref(n))
        return ctypes.string_at(p, n.value) if p and n.value else b""

    import os

    while True:
        h = lib.nat_shm_take_request(500)
        if not h:
            # attach armed PR_SET_PDEATHSIG, but belt-and-braces: a
            # reparented worker (parent hard-killed before prctl) must
            # not poll a leaked segment forever
            if os.getppid() == 1:
                return 0
            continue
        kind = lib.nat_req_kind(h)
        if kind == 8:
            # bulk tensor record: deliver to the registered tensor sink
            # as an out-of-order-releasable lease (unregistered sinks
            # count the drop instead of losing it silently)
            dispatch_tensor_record(native, h)
            continue
        sock_id = lib.nat_req_sock_id(h)
        seq = lib.nat_req_cid(h)
        verb_or_blank = field(h, 0)
        path = field(h, 1)
        headers = field(h, 4)
        payload = field(h, 2)
        lib.nat_req_free(h)  # field() copied out: the arena span frees
        try:
            if kind == 3:
                mount._handle_http(verb_or_blank, path, headers, payload,
                                   sock_id, seq)
            elif kind == 4:
                mount._handle_grpc(path, headers, payload, sock_id, seq)
        except Exception as e:  # answer rather than drop
            try:
                if kind == 3:
                    body = f"{e}\n".encode()
                    resp = (f"HTTP/1.1 500 Internal Server Error\r\n"
                            f"Content-Length: {len(body)}\r\n\r\n"
                            ).encode() + body
                    http_respond(sock_id, seq, resp)
                else:
                    grpc_respond(sock_id, seq, b"", 13, f"{e}")
            except Exception:
                pass


if __name__ == "__main__":
    sys.exit(main(sys.argv[1], sys.argv[2]) or 0)
