"""Streaming frames — wire format for Streams.

Counterpart of policy/streaming_rpc_protocol.cpp
(/root/reference/src/brpc/policy/streaming_rpc_protocol.cpp +
streaming_rpc_meta.proto): `"TSTR" + body_size` header, body =
dest_stream_id + frame_type + payload. Frame types: DATA, FEEDBACK
(consumed-bytes window update), CLOSE. Frames address the DESTINATION
endpoint's stream id (each side registered its own id during the
setup RPC).
"""
from __future__ import annotations

import struct

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.rpc.protocol import (
    InputMessageBase,
    ParseResult,
    Protocol,
    ProtocolType,
    register_protocol,
)

MAGIC = b"TSTR"
HEADER_LEN = 8  # magic + body_size
FRAME_DATA = 0
FRAME_FEEDBACK = 1
FRAME_CLOSE = 2


def _pack(dest_id: int, ftype: int, payload: IOBuf) -> IOBuf:
    body_size = 9 + len(payload)  # 8B dest + 1B type
    out = IOBuf()
    out.append(MAGIC + struct.pack(">I", body_size)
               + struct.pack(">QB", dest_id, ftype))
    if len(payload):
        out.append(payload)
    return out


def pack_data_frame(dest_id: int, payload: IOBuf) -> IOBuf:
    return _pack(dest_id, FRAME_DATA, payload)


def pack_feedback_frame(dest_id: int, consumed: int) -> IOBuf:
    return _pack(dest_id, FRAME_FEEDBACK, IOBuf(struct.pack(">Q", consumed)))


def pack_close_frame(dest_id: int) -> IOBuf:
    return _pack(dest_id, FRAME_CLOSE, IOBuf())


class StreamFrame(InputMessageBase):
    __slots__ = ("dest_id", "ftype", "payload", "is_request")

    def __init__(self, dest_id: int, ftype: int, payload: IOBuf):
        super().__init__()
        self.dest_id = dest_id
        self.ftype = ftype
        self.payload = payload
        self.is_request = True  # routed by stream id, not by role


def parse(portal: IOBuf, sock, read_eof: bool, arg) -> ParseResult:
    if len(portal) < HEADER_LEN:
        head = portal.copy_to_bytes(min(4, len(portal)))
        if MAGIC.startswith(head):
            return ParseResult.not_enough()
        return ParseResult.try_others()
    header = portal.copy_to_bytes(HEADER_LEN)
    if header[:4] != MAGIC:
        return ParseResult.try_others()
    (body_size,) = struct.unpack(">I", header[4:8])
    if body_size < 9 or body_size > (1 << 31):
        return ParseResult.error_()
    if len(portal) < HEADER_LEN + body_size:
        return ParseResult.not_enough()
    portal.pop_front(HEADER_LEN)
    dest_id, ftype = struct.unpack(">QB", portal.cutn_bytes(9))
    payload = portal.cut(body_size - 9)
    return ParseResult.ok(StreamFrame(dest_id, ftype, payload))


def process_frame(msg: StreamFrame):
    from brpc_tpu.rpc.stream import Stream

    stream = Stream.find(msg.dest_id)
    if stream is None:
        return  # already closed; drop silently (reference behavior)
    if msg.ftype == FRAME_DATA:
        stream._on_data(msg.payload)
    elif msg.ftype == FRAME_FEEDBACK:
        (consumed,) = struct.unpack(">Q", msg.payload.to_bytes())
        stream._on_feedback(consumed)
    elif msg.ftype == FRAME_CLOSE:
        stream.close(notify_remote=False)


register_protocol(Protocol(
    name="streaming",
    type=ProtocolType.STREAMING,
    parse=parse,
    process_request=process_frame,
    process_response=process_frame,
    process_inline=True,  # ordering: frames enqueue on the read loop
))
