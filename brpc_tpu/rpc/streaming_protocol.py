"""Streaming RPC frames — placeholder registration point.

Counterpart of policy/streaming_rpc_protocol.cpp; filled by the streaming
milestone (stream.py).
"""
