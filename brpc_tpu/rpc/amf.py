"""AMF0 codec — Action Message Format, the RTMP command-message payload.

Counterpart of /root/reference/src/brpc/amf.{h,cpp} (AMF0 subset used by
the RTMP protocol: rtmp_protocol.cpp encodes connect/createStream/
publish/play commands and their _result/onStatus replies as AMF0).
Types implemented: number, boolean, string, object, null, undefined,
ECMA array, strict array, long string — the set RTMP commands use.

Python mapping: float <-> number, bool <-> boolean, str <-> string,
dict <-> object (ordered), None <-> null, list <-> strict array.
"""
from __future__ import annotations

import struct
from typing import Any, List, Tuple

AMF0_NUMBER = 0x00
AMF0_BOOLEAN = 0x01
AMF0_STRING = 0x02
AMF0_OBJECT = 0x03
AMF0_NULL = 0x05
AMF0_UNDEFINED = 0x06
AMF0_ECMA_ARRAY = 0x08
AMF0_OBJECT_END = 0x09
AMF0_STRICT_ARRAY = 0x0A
AMF0_LONG_STRING = 0x0C


class AmfError(ValueError):
    pass


def _enc_str_body(s: str) -> bytes:
    raw = s.encode("utf-8")
    if len(raw) > 0xFFFF:
        raise AmfError("use long string")
    return struct.pack(">H", len(raw)) + raw


def encode(value: Any) -> bytes:
    """One AMF0 value."""
    if value is None:
        return bytes([AMF0_NULL])
    if isinstance(value, bool):
        return bytes([AMF0_BOOLEAN, 1 if value else 0])
    if isinstance(value, (int, float)):
        return bytes([AMF0_NUMBER]) + struct.pack(">d", float(value))
    if isinstance(value, str):
        raw = value.encode("utf-8")
        if len(raw) > 0xFFFF:
            return (bytes([AMF0_LONG_STRING]) + struct.pack(">I", len(raw))
                    + raw)
        return bytes([AMF0_STRING]) + _enc_str_body(value)
    if isinstance(value, dict):
        out = bytearray([AMF0_OBJECT])
        for k, v in value.items():
            out += _enc_str_body(str(k))
            out += encode(v)
        out += _enc_str_body("")
        out.append(AMF0_OBJECT_END)
        return bytes(out)
    if isinstance(value, (list, tuple)):
        out = bytearray([AMF0_STRICT_ARRAY]) + struct.pack(">I", len(value))
        for v in value:
            out += encode(v)
        return bytes(out)
    raise AmfError(f"unencodable AMF0 value: {type(value).__name__}")


def encode_many(*values: Any) -> bytes:
    return b"".join(encode(v) for v in values)


def _dec_str_body(data: bytes, pos: int) -> Tuple[str, int]:
    if pos + 2 > len(data):
        raise AmfError("truncated string length")
    (n,) = struct.unpack_from(">H", data, pos)
    pos += 2
    if pos + n > len(data):
        raise AmfError("truncated string body")
    return data[pos:pos + n].decode("utf-8", errors="replace"), pos + n


def decode(data: bytes, pos: int = 0) -> Tuple[Any, int]:
    """One AMF0 value; returns (value, next_pos)."""
    if pos >= len(data):
        raise AmfError("truncated value")
    marker = data[pos]
    pos += 1
    if marker == AMF0_NUMBER:
        if pos + 8 > len(data):
            raise AmfError("truncated number")
        (v,) = struct.unpack_from(">d", data, pos)
        return v, pos + 8
    if marker == AMF0_BOOLEAN:
        if pos >= len(data):
            raise AmfError("truncated boolean")
        return data[pos] != 0, pos + 1
    if marker == AMF0_STRING:
        return _dec_str_body(data, pos)
    if marker == AMF0_LONG_STRING:
        if pos + 4 > len(data):
            raise AmfError("truncated long string")
        (n,) = struct.unpack_from(">I", data, pos)
        pos += 4
        if pos + n > len(data):
            raise AmfError("truncated long string body")
        return data[pos:pos + n].decode("utf-8", errors="replace"), pos + n
    if marker in (AMF0_NULL, AMF0_UNDEFINED):
        return None, pos
    if marker in (AMF0_OBJECT, AMF0_ECMA_ARRAY):
        if marker == AMF0_ECMA_ARRAY:
            if pos + 4 > len(data):
                raise AmfError("truncated ecma array")
            pos += 4  # count hint; the end marker is authoritative
        obj = {}
        while True:
            key, pos = _dec_str_body(data, pos)
            if key == "" and pos < len(data) and data[pos] == AMF0_OBJECT_END:
                return obj, pos + 1
            obj[key], pos = decode(data, pos)
    if marker == AMF0_STRICT_ARRAY:
        if pos + 4 > len(data):
            raise AmfError("truncated strict array")
        (n,) = struct.unpack_from(">I", data, pos)
        pos += 4
        arr = []
        for _ in range(n):
            v, pos = decode(data, pos)
            arr.append(v)
        return arr, pos
    raise AmfError(f"unsupported AMF0 marker 0x{marker:02x}")


def decode_all(data: bytes) -> List[Any]:
    out = []
    pos = 0
    while pos < len(data):
        v, pos = decode(data, pos)
        out.append(v)
    return out
