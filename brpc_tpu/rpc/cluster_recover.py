"""Cluster recover policy — counterpart of brpc::ClusterRecoverPolicy
(/root/reference/src/brpc/cluster_recover_policy.{h,cpp}): after a whole
cluster goes down (every node isolated by the circuit breaker), letting all
traffic rush back the moment one node revives would knock it over again.
While "recovering", requests are randomly rejected in proportion to how
much of `min_working_instances` is actually usable; recovery ends once the
usable count has held stable for `hold_seconds`.

Attached to a load balancer via the LB-string params, the reference's
GetRecoverPolicyByParams grammar:
    "rr:min_working_instances=2 hold_seconds=3"
"""
from __future__ import annotations

import random
import threading
import time
from typing import List, Optional

_DETECT_INTERVAL_S = 0.01  # usable-count cache TTL (the reference's
# -detect_available_server_interval_ms)


class ClusterRecoverPolicy:
    """Interface (cluster_recover_policy.h:20-29)."""

    def start_recover(self):
        raise NotImplementedError

    def do_reject(self, server_ids: List[int]) -> bool:
        raise NotImplementedError

    def stop_recover_if_necessary(self) -> bool:
        """Returns True while still recovering."""
        raise NotImplementedError


class DefaultClusterRecoverPolicy(ClusterRecoverPolicy):
    def __init__(self, min_working_instances: int, hold_seconds: float):
        self._recovering = False
        self._min_working = max(1, int(min_working_instances))
        self._hold_s = float(hold_seconds)
        self._lock = threading.Lock()
        self._last_usable = 0
        self._last_usable_change_t = 0.0
        self._usable_cache = 0
        self._usable_cache_t = 0.0

    @property
    def recovering(self) -> bool:
        return self._recovering

    def start_recover(self):
        with self._lock:
            self._recovering = True

    def stop_recover_if_necessary(self) -> bool:
        if not self._recovering:
            return False
        now = time.monotonic()
        with self._lock:
            if (self._last_usable_change_t and self._last_usable
                    and now - self._last_usable_change_t > self._hold_s):
                self._recovering = False
                self._last_usable = 0
                self._last_usable_change_t = 0.0
                return False
        return True

    def _usable_count(self, now: float, server_ids: List[int]) -> int:
        if now - self._usable_cache_t < _DETECT_INTERVAL_S:
            return self._usable_cache
        from brpc_tpu.rpc.socket import Socket

        usable = 0
        for sid in server_ids:
            s = Socket.address(sid)
            if s is not None and not s.failed():
                usable += 1
        with self._lock:
            self._usable_cache = usable
            self._usable_cache_t = now
        return usable

    def do_reject(self, server_ids: List[int]) -> bool:
        """Reject with probability 1 - usable/min_working_instances
        (cluster_recover_policy.cpp:91-108)."""
        if not self._recovering:
            return False
        now = time.monotonic()
        usable = self._usable_count(now, server_ids)
        if self._last_usable != usable:
            with self._lock:
                if self._last_usable != usable:
                    self._last_usable = usable
                    self._last_usable_change_t = now
        return random.randrange(self._min_working) >= usable


def recover_policy_from_params(params: str) -> Optional[ClusterRecoverPolicy]:
    """GetRecoverPolicyByParams (cluster_recover_policy.cpp:110-139):
    space-separated key=value pairs; both keys required."""
    min_working = hold_seconds = None
    try:
        for pair in params.split():
            key, sep, value = pair.partition("=")
            if not sep or not value:
                continue
            if key == "min_working_instances":
                min_working = int(value)
            elif key == "hold_seconds":
                hold_seconds = float(value)
    except ValueError:
        return None  # non-numeric values reject like the reference
    if min_working is None or hold_seconds is None:
        return None
    return DefaultClusterRecoverPolicy(min_working, hold_seconds)
