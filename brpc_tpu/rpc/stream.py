"""Streaming RPC — ordered byte/tensor streams over an RPC connection.

Counterpart of brpc Streams (/root/reference/src/brpc/stream.{h,cpp},
stream_impl.h; SURVEY.md section 2.8): StreamCreate piggybacks stream setup
on a normal RPC (stream.cpp:98-115), writes go through the connection's
normal wait-free write path, receipt is serialized through a bthread
ExecutionQueue into the user's StreamInputHandler (stream_impl.h:125), and
a sliding window with explicit FEEDBACK frames provides flow control
(stream.cpp:458-586; max_buf_size default 2MB, stream.h:50-67).

This is the tensor-pipeline lane of the framework: IOBuf payloads may carry
device arrays, so a pipeline stage can stream activations to the next stage
while compute continues.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from brpc_tpu import bvar
from brpc_tpu.bthread.execution_queue import ExecutionQueue
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.rpc import errors

DEFAULT_MAX_BUF_SIZE = 2 * 1024 * 1024  # stream.h:50-67

_stream_count = bvar.Adder("stream_count")


class StreamInputHandler:
    """User callbacks (stream.h StreamInputHandler)."""

    def on_received_messages(self, stream: "Stream", messages: List[IOBuf]):
        raise NotImplementedError

    def on_idle_timeout(self, stream: "Stream"):
        pass

    def on_closed(self, stream: "Stream"):
        pass


class StreamOptions:
    def __init__(self, handler: Optional[StreamInputHandler] = None,
                 max_buf_size: int = DEFAULT_MAX_BUF_SIZE,
                 messages_in_batch: int = 128):
        self.handler = handler
        self.max_buf_size = max_buf_size
        self.messages_in_batch = messages_in_batch


class Stream:
    """One direction-agnostic stream endpoint. Writes block when the remote
    window is exhausted; the receiver's consumption feeds it back."""

    _registry: Dict[int, "Stream"] = {}
    _registry_lock = threading.Lock()
    _next_id = 1

    def __init__(self, options: StreamOptions,
                 peer_id: Optional[int] = None):
        cls = type(self)
        with cls._registry_lock:
            stream_id = cls._next_id
            cls._next_id += 1
            self.stream_id = stream_id  # OUR endpoint id (frames to us)
            cls._registry[stream_id] = self
        self.peer_id = peer_id  # the remote endpoint id (frames from us)
        self.options = options
        self._sock = None
        self._closed = False
        self._close_reason = ""
        # writer-side window accounting
        self._unconsumed = 0  # bytes sent, not yet fed back as consumed
        self._window_cond = threading.Condition()
        # receiver-side ordered delivery — created NOW so frames arriving
        # before bind() (remote may push the instant it accepts, ahead of
        # our RPC-response processing) are buffered, never dropped.
        self._exec_q: Optional[ExecutionQueue] = None
        if options.handler is not None:
            self._exec_q = ExecutionQueue(
                self._consume_batch, batch_size=options.messages_in_batch)
        self._owed_feedback = 0  # consumed before bind: flushed on bind
        self._connected = threading.Event()
        _stream_count.update(1)

    # -- registry ----------------------------------------------------------
    @classmethod
    def find(cls, stream_id: int) -> Optional["Stream"]:
        with cls._registry_lock:
            return cls._registry.get(stream_id)

    # -- binding (SetConnected analog) -------------------------------------
    def bind(self, sock):
        self._sock = sock
        self._connected.set()
        with self._window_cond:
            owed, self._owed_feedback = self._owed_feedback, 0
        if owed:
            self._send_feedback(owed)

    def wait_connected(self, timeout: Optional[float] = None) -> bool:
        return self._connected.wait(timeout)

    @property
    def connected(self) -> bool:
        return self._connected.is_set()

    # -- write path --------------------------------------------------------
    def write(self, data, timeout_s: Optional[float] = 5.0) -> int:
        """StreamWrite (stream.h:119): blocks while the window is full
        (AppendIfNotFull semantics with wait_for_writable folded in)."""
        from brpc_tpu.rpc import streaming_protocol

        if self._closed:
            return errors.EEOF
        if self._sock is None or self.peer_id is None:
            return errors.EINVAL
        if isinstance(data, IOBuf):
            buf = data
        elif isinstance(data, bytes) and len(data) >= 65536:
            # large immutable payload: share it zero-copy instead of
            # copying through 8KB blocks (the IOBuf::append(user_data)
            # path, iobuf.h:257-266) — the 1GB/s stream lane depends on it
            buf = IOBuf()
            buf.append_user_data(data)
        else:
            buf = IOBuf(data)
        size = len(buf)
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        with self._window_cond:
            while (self._unconsumed + size > self.options.max_buf_size
                   and not self._closed):
                remain = None if deadline is None else deadline - time.monotonic()
                if remain is not None and remain <= 0:
                    return errors.EOVERCROWDED  # window still full
                self._window_cond.wait(remain)
            if self._closed:
                return errors.EEOF
            self._unconsumed += size
        frame = streaming_protocol.pack_data_frame(self.peer_id, buf)
        rc = self._sock.write(frame)
        if rc != 0:
            self.close("write failed")
            return rc
        return 0

    def write_tensor(self, array) -> int:
        """Zero-copy stream write of a device array."""
        buf = IOBuf()
        buf.append_device_array(array)
        return self.write(buf)

    def _on_feedback(self, consumed_bytes: int):
        with self._window_cond:
            self._unconsumed = max(0, self._unconsumed - consumed_bytes)
            self._window_cond.notify_all()

    @property
    def unconsumed_bytes(self) -> int:
        return self._unconsumed

    # -- receive path ------------------------------------------------------
    def _on_data(self, payload: IOBuf):
        if self._exec_q is not None:
            self._exec_q.execute(payload)
        # no handler: drop (write-only remote peer misuse), still feed back
        else:
            self._send_feedback(len(payload))

    def _consume_batch(self, it) -> int:
        msgs = list(it)
        if msgs:
            total = sum(len(m) for m in msgs)
            try:
                self.options.handler.on_received_messages(self, msgs)
            finally:
                self._send_feedback(total)
        if it.is_queue_stopped():
            try:
                self.options.handler.on_closed(self)
            except Exception:
                pass
        return 0

    def _send_feedback(self, consumed: int):
        from brpc_tpu.rpc import streaming_protocol

        if self._sock is None or self.peer_id is None:
            with self._window_cond:
                self._owed_feedback += consumed  # flushed at bind()
            return
        if not self._closed:
            try:
                self._sock.write(
                    streaming_protocol.pack_feedback_frame(self.peer_id,
                                                           consumed)
                )
            except Exception:
                pass

    # -- close -------------------------------------------------------------
    def close(self, reason: str = "", notify_remote: bool = True):
        """StreamClose: CLOSE frame to the peer, local handler drained then
        on_closed."""
        from brpc_tpu.rpc import streaming_protocol

        if self._closed:
            return
        self._closed = True
        self._close_reason = reason
        with self._window_cond:
            self._window_cond.notify_all()
        if (notify_remote and self._sock is not None
                and not self._sock.failed() and self.peer_id is not None):
            try:
                self._sock.write(
                    streaming_protocol.pack_close_frame(self.peer_id)
                )
            except Exception:
                pass
        if self._exec_q is not None:
            self._exec_q.stop()
        elif self.options.handler is not None:
            try:
                self.options.handler.on_closed(self)
            except Exception:
                pass
        with type(self)._registry_lock:
            type(self)._registry.pop(self.stream_id, None)
        _stream_count.update(-1)

    @property
    def closed(self) -> bool:
        return self._closed


def stream_create(cntl, options: Optional[StreamOptions] = None) -> Stream:
    """Client side, BEFORE the call: create the local endpoint and ride the
    setup on the RPC (StreamCreate, stream.h:102)."""
    stream = Stream(options or StreamOptions())
    cntl._request_stream = stream
    return stream


def stream_accept(cntl, options: Optional[StreamOptions] = None) -> Optional[Stream]:
    """Server side, inside the handler: accept the stream riding the
    current RPC (StreamAccept, stream.h:110). The response meta carries our
    endpoint id back so the client learns its peer."""
    sid = getattr(cntl, "_remote_stream_id", 0)
    if not sid:
        return None
    stream = Stream(options or StreamOptions(), peer_id=sid)
    stream.bind(cntl._server_socket)
    cntl._accepted_stream = stream
    return stream
