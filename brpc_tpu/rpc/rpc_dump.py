"""rpc_dump — sampled request recording for replay.

Counterpart of brpc/rpc_dump.{h,cpp} (/root/reference/src/brpc/rpc_dump.h:
50-88): when -rpc_dump is on, a sampled fraction of outgoing requests is
persisted as recordio files under -rpc_dump_dir; tools/rpc_replay.py
replays them against a live server. Sampling shares the bounded-budget
philosophy of bvar::Collector.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Optional

from brpc_tpu.butil import flags
from brpc_tpu.butil.recordio import RecordWriter

flags.define_bool("rpc_dump", False, "sample and dump outgoing requests")
flags.define_string("rpc_dump_dir", "./rpc_dump", "directory for dump files")
flags.define_int("rpc_dump_sample_every", 1,
                 "dump 1 of every N requests")

_writer: Optional[RecordWriter] = None
_writer_lock = threading.Lock()
_counter = [0]


def _get_writer() -> Optional[RecordWriter]:
    global _writer
    if _writer is None:
        with _writer_lock:
            if _writer is None:
                d = flags.get_flag("rpc_dump_dir")
                try:
                    os.makedirs(d, exist_ok=True)
                    path = os.path.join(
                        d, f"rpc_dump.{os.getpid()}.{int(time.time())}.rio")
                    _writer = RecordWriter(path)
                except OSError:
                    return None
    return _writer


def maybe_dump_request(method_full_name: str, payload: bytes, log_id: int = 0):
    """Called from the client send path; cheap no-op unless -rpc_dump."""
    if not flags.get_flag("rpc_dump"):
        return
    every = max(1, flags.get_flag("rpc_dump_sample_every"))
    with _writer_lock:
        _counter[0] += 1
        if _counter[0] % every:
            return
    w = _get_writer()
    if w is None:
        return
    service, _, method = method_full_name.rpartition(".")
    with _writer_lock:
        w.write({"service": service, "method": method, "log_id": log_id,
                 "ts": time.time()}, payload)
        w.flush()


def reset_for_tests():
    global _writer
    with _writer_lock:
        if _writer is not None:
            _writer.close()
            _writer = None
        _counter[0] = 0
