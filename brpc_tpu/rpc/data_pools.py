"""Session/thread data pools — per-request user-state management.

Counterparts of brpc::SimpleDataPool + session-local/thread-local data
(/root/reference/src/brpc/simple_data_pool.{h,cpp}, server.h:137,285): a
server can own a pool of user session objects, borrowing one per request
(cntl.session_local_data) and returning it after done; thread-local data
is created per worker on demand.
"""
from __future__ import annotations

import threading
from typing import Callable, List, Optional


class DataFactory:
    """CreateData/DestroyData pair (data_factory.h)."""

    def __init__(self, create: Callable[[], object],
                 destroy: Optional[Callable[[object], None]] = None):
        self.create = create
        self.destroy = destroy or (lambda obj: None)


class SimpleDataPool:
    """Borrow/return pool with stats (simple_data_pool.h)."""

    def __init__(self, factory: DataFactory, reserve: int = 0):
        self._factory = factory
        self._free: List[object] = []
        self._lock = threading.Lock()
        self._created = 0
        for _ in range(reserve):
            self._free.append(factory.create())
            self._created += 1

    def borrow(self):
        with self._lock:
            if self._free:
                return self._free.pop()
            self._created += 1
        return self._factory.create()

    def return_(self, obj):
        if obj is None:
            return
        with self._lock:
            self._free.append(obj)

    @property
    def created_count(self) -> int:
        return self._created

    @property
    def free_count(self) -> int:
        return len(self._free)

    def destroy_all(self):
        with self._lock:
            for obj in self._free:
                self._factory.destroy(obj)
            self._free.clear()


class ThreadLocalDataFactory:
    """thread_local_data() of ServerOptions: one object per worker thread."""

    def __init__(self, factory: DataFactory):
        self._factory = factory
        self._tls = threading.local()

    def get(self):
        obj = getattr(self._tls, "obj", None)
        if obj is None:
            obj = self._factory.create()
            self._tls.obj = obj
        return obj
