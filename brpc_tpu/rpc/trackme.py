"""trackme — version ping (phone-home), off by default.

Counterpart of brpc/details/trackme.cpp (/root/reference/src/brpc/details/
trackme.cpp:36-118): when -trackme_server is set, the process periodically
reports its version to that endpoint and logs any severity notice in the
reply. tools/trackme_server.py is the receiving end.
"""
from __future__ import annotations

import json
import threading

from brpc_tpu.butil import flags

flags.define_string("trackme_server", "", "endpoint to report version to "
                    "(empty = disabled)")
flags.define_int("trackme_interval_s", 300, "seconds between pings")

_started = False
_lock = threading.Lock()


def _ping_once() -> bool:
    import http.client

    import brpc_tpu

    target = flags.get_flag("trackme_server")
    if not target:
        return False
    host, _, port = target.partition(":")
    try:
        conn = http.client.HTTPConnection(host, int(port or 80), timeout=3)
        conn.request("POST", "/trackme",
                     body=json.dumps({"version": brpc_tpu.__version__}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = resp.read()
        conn.close()
        if resp.status == 200 and body:
            notice = json.loads(body).get("notice")
            if notice:
                import logging

                logging.getLogger(__name__).warning("trackme notice: %s",
                                                    notice)
        return resp.status == 200
    except (OSError, ValueError):
        return False


def start_trackme():
    """Idempotent; no-op unless -trackme_server set."""
    global _started
    if not flags.get_flag("trackme_server"):
        return
    with _lock:
        if _started:
            return
        _started = True
    from brpc_tpu.bthread import timer_add

    def tick():
        _ping_once()
        timer_add(flags.get_flag("trackme_interval_s"), tick)

    timer_add(0.0, tick)
