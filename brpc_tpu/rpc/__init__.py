"""brpc_tpu.rpc — the RPC layer (SURVEY.md sections 2.4-2.8).

Server / Channel / Controller over a Socket + EventDispatcher +
InputMessenger core with a pluggable Protocol registry — the counterpart of
/root/reference/src/brpc/, architected for the TPU build: host TCP is the
baseline transport, the device/ICI endpoint plugs in at the Socket
app_connect seam, and attachments carry HBM-resident tensors.
"""
from brpc_tpu.rpc import errors  # noqa: F401
from brpc_tpu.rpc.acceptor import Acceptor  # noqa: F401
from brpc_tpu.rpc.channel import Channel, ChannelOptions  # noqa: F401
from brpc_tpu.rpc.controller import Controller, RetryPolicy  # noqa: F401
from brpc_tpu.rpc.event_dispatcher import EventDispatcher, get_global_dispatcher  # noqa: F401
from brpc_tpu.rpc.input_messenger import InputMessenger  # noqa: F401
from brpc_tpu.rpc.method_status import MethodStatus  # noqa: F401
from brpc_tpu.rpc.protocol import (  # noqa: F401
    ParseError,
    ParseResult,
    Protocol,
    ProtocolType,
    find_protocol_by_name,
    globally_initialize,
    register_protocol,
)
from brpc_tpu.rpc.combo_channels import (  # noqa: F401
    CallMapper,
    DynamicPartitionChannel,
    ParallelChannel,
    PartitionChannel,
    PartitionParser,
    ResponseMerger,
    SelectiveChannel,
    SubCall,
)
from brpc_tpu.rpc.server import Server, ServerOptions  # noqa: F401
from brpc_tpu.rpc.service import ClosureGuard, MethodInfo, Service, rpc_method  # noqa: F401
from brpc_tpu.rpc.socket import Socket, SocketUser  # noqa: F401
from brpc_tpu.rpc.stream import (  # noqa: F401
    Stream,
    StreamInputHandler,
    StreamOptions,
    stream_accept,
    stream_create,
)
