"""Authenticator — pluggable per-connection/per-request authentication.

Counterpart of brpc::Authenticator
(/root/reference/src/brpc/authenticator.h): the client generates a
credential that rides the request meta (auth_data); the server verifies it
before dispatch and exposes an AuthContext on the controller. Impl
registry mirrors the policy/ authenticators (giano/redis/couchbase there).
"""
from __future__ import annotations

import hashlib
import hmac
from typing import Optional, Tuple


class AuthContext:
    """What a verified credential resolves to (authenticator.h AuthContext)."""

    __slots__ = ("user", "group", "roles", "is_service")

    def __init__(self, user: str = "", group: str = "", roles: str = "",
                 is_service: bool = False):
        self.user = user
        self.group = group
        self.roles = roles
        self.is_service = is_service


class Authenticator:
    def generate_credential(self, cntl) -> Optional[str]:
        """Client side: the string to send; None = fail the call."""
        raise NotImplementedError

    def verify_credential(self, auth_str: str, remote_side) -> Tuple[bool, Optional[AuthContext]]:
        """Server side: (ok, context)."""
        raise NotImplementedError


class HmacAuthenticator(Authenticator):
    """Shared-secret HMAC credential: 'user:hexdigest(user)'. A practical
    default for intra-pod trust (the giano-style policy slot)."""

    def __init__(self, secret: bytes, user: str = "default"):
        self._secret = secret
        self._user = user

    def _digest(self, user: str) -> str:
        return hmac.new(self._secret, user.encode(), hashlib.sha256).hexdigest()

    def generate_credential(self, cntl) -> Optional[str]:
        return f"{self._user}:{self._digest(self._user)}"

    def verify_credential(self, auth_str, remote_side):
        user, _, digest = (auth_str or "").partition(":")
        if not user or not hmac.compare_digest(digest, self._digest(user)):
            return False, None
        return True, AuthContext(user=user)
