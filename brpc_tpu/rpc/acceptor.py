"""Acceptor — the listen-socket accept loop.

Counterpart of brpc::Acceptor (/root/reference/src/brpc/acceptor.{h,cpp}):
the listening fd is itself a Socket whose edge-triggered handler accepts in
a loop (OnNewConnections, acceptor.cpp:52-94) and creates one data Socket
per connection, wired to an InputMessenger.
"""
from __future__ import annotations

import socket as pysocket
import threading
from typing import Dict, Optional

from brpc_tpu import bvar
from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.rpc.input_messenger import InputMessenger
from brpc_tpu.rpc.socket import Socket


class Acceptor:
    def __init__(self, messenger: InputMessenger, ssl_context=None):
        self._messenger = messenger
        self._ssl_context = ssl_context
        self._listen_sid = 0
        self._connections: Dict[int, int] = {}  # fd -> socket_id
        self._lock = threading.Lock()
        self._stopped = False
        self._accepted = bvar.Adder()

    def start_accept(self, listen_fd: pysocket.socket) -> int:
        listen_fd.setblocking(False)
        self._listen_sid = Socket.create(
            fd=listen_fd, on_edge_triggered_events=self._on_new_connections
        )
        return 0

    def _on_new_connections(self, listen_sock: Socket):
        while not self._stopped:
            fd = listen_sock.fd()
            if fd is None:
                return
            try:
                conn, addr = fd.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            conn.setsockopt(pysocket.IPPROTO_TCP, pysocket.TCP_NODELAY, 1)
            remote = EndPoint(addr[0], addr[1])
            if self._ssl_context is not None:
                # TLS handshake must not block the accept loop: finish it in
                # a scheduler task, then hand the socket to the messenger.
                from brpc_tpu.bthread import start_background

                start_background(self._ssl_accept, conn, remote)
                continue
            sid = Socket.create(
                fd=conn,
                remote_side=remote,
                on_edge_triggered_events=self._messenger.on_new_messages,
            )
            self._accepted.update(1)
            with self._lock:
                self._connections[conn.fileno()] = sid

    def _ssl_accept(self, conn: pysocket.socket, remote: EndPoint):
        try:
            conn.settimeout(5.0)
            wrapped = self._ssl_context.wrap_socket(conn, server_side=True)
        except OSError:
            try:
                conn.close()
            except OSError:
                pass
            return
        sid = Socket.create(
            fd=wrapped,
            remote_side=remote,
            on_edge_triggered_events=self._messenger.on_new_messages,
        )
        self._accepted.update(1)
        with self._lock:
            self._connections[wrapped.fileno()] = sid

    def connection_count(self) -> int:
        with self._lock:
            alive = 0
            dead = []
            for fdno, sid in self._connections.items():
                s = Socket.address(sid)
                if s is not None and not s.failed():
                    alive += 1
                else:
                    dead.append(fdno)
            for fdno in dead:
                self._connections.pop(fdno, None)
            return alive

    def list_connections(self):
        with self._lock:
            sids = list(self._connections.values())
        out = []
        for sid in sids:
            s = Socket.address(sid)
            if s is not None and not s.failed():
                out.append(s)
        return out

    def stop_accept(self):
        self._stopped = True
        listen = Socket.address(self._listen_sid)
        if listen is not None:
            listen.set_failed(error_text="acceptor stopped")
        with self._lock:
            sids = list(self._connections.values())
            self._connections.clear()
        for sid in sids:
            s = Socket.address(sid)
            if s is not None:
                s.set_failed(error_text="server stopping")
