"""Shared server-side dispatch scaffold for pb-rpc protocols (tpu_std's
richer path stays inline; hulu/sofa and future legacy framings reuse this):
service/method lookup, concurrency gate, request decode, handler run with
a once-only done, exception guard. The per-protocol send_response closure
owns the wire format.
"""
from __future__ import annotations

import time
from typing import Callable, Optional

from brpc_tpu.rpc import compress as compress_mod
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.controller import Controller


def dispatch_pb_request(server, sock, service_name: str, method_name: str,
                        payload: bytes, compress_type: int,
                        send_response: Callable,
                        cntl: Optional[Controller] = None):
    """Runs the common ProcessXxxRequest sequence; send_response(cntl,
    response_pb_or_None) is called exactly once (possibly asynchronously,
    if the handler defers done)."""
    if cntl is None:
        cntl = Controller()
    cntl.server = server
    cntl.remote_side = sock.remote_side
    cntl.service_name = service_name
    cntl.method_name = method_name
    cntl._server_socket = sock
    cntl.server_start_time = time.monotonic()

    if server is None:
        cntl.set_failed(errors.EINVAL, "no server bound to connection")
        return send_response(cntl, None)

    entry = server.find_method(service_name, method_name)
    if entry is None:
        missing_service = server.find_service(service_name) is None
        cntl.set_failed(
            errors.ENOSERVICE if missing_service else errors.ENOMETHOD,
            f"unknown {service_name}.{method_name}")
        return send_response(cntl, None)
    service_obj, method_info, method_status = entry

    if not method_status.on_requested():
        cntl.set_failed(errors.ELIMIT, "reached max_concurrency")
        return send_response(cntl, None)

    request = method_info.request_class()
    try:
        payload = compress_mod.decompress(payload, compress_type)
        if payload:
            request.ParseFromString(payload)
    except Exception as e:
        method_status.on_response(errors.EREQUEST, cntl.server_start_time)
        cntl.set_failed(errors.EREQUEST, f"fail to parse request: {e}")
        return send_response(cntl, None)

    response = method_info.response_class()
    responded = [False]

    def done():
        if responded[0]:
            return
        responded[0] = True
        method_status.on_response(cntl.error_code_value,
                                  cntl.server_start_time)
        send_response(cntl, response)

    try:
        method_info.handler(service_obj, cntl, request, response, done)
    except Exception as e:
        if not responded[0]:
            cntl.set_failed(errors.EINVAL, f"method raised: {e}")
            done()
