"""SocketMap — process-wide client connection sharing.

Counterpart of brpc's SocketMap (/root/reference/src/brpc/details/
socket_map.{h,cpp}): "single"-type client connections to the same endpoint
are shared by every channel in the process, reference-counted; Remove drops
the ref and recycles on zero. Channels call get_client_socket instead of
dialing their own.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.rpc.socket import Socket


class _Entry:
    __slots__ = ("sid", "refcount")

    def __init__(self, sid: int):
        self.sid = sid
        self.refcount = 0


class SocketMap:
    def __init__(self):
        self._map: Dict[Tuple[str, int], _Entry] = {}
        self._lock = threading.Lock()

    def insert(self, ep: EndPoint, messenger=None,
               health_check_interval_s: float = -1,
               ssl_context=None, app_connect=None) -> Optional[int]:
        """Get-or-create the shared SocketId for this endpoint
        (SocketMap::Insert)."""
        key = (ep.ip, ep.port)
        with self._lock:
            entry = self._map.get(key)
            if entry is not None:
                sock = Socket.address(entry.sid)
                if sock is not None and not sock.failed():
                    entry.refcount += 1
                    return entry.sid
                del self._map[key]
            if messenger is None:
                from brpc_tpu.rpc.channel import get_client_messenger

                messenger = get_client_messenger()
            sid = Socket.create(
                remote_side=ep,
                on_edge_triggered_events=messenger.on_new_messages,
                health_check_interval_s=health_check_interval_s,
                ssl_context=ssl_context,
                app_connect=app_connect,
            )
            entry = _Entry(sid)
            entry.refcount = 1
            self._map[key] = entry
            return sid

    def find(self, ep: EndPoint) -> Optional[int]:
        with self._lock:
            entry = self._map.get((ep.ip, ep.port))
            return entry.sid if entry else None

    def remove(self, ep: EndPoint):
        """Drop one reference; recycle the socket at zero
        (SocketMap::Remove)."""
        key = (ep.ip, ep.port)
        with self._lock:
            entry = self._map.get(key)
            if entry is None:
                return
            entry.refcount -= 1
            if entry.refcount > 0:
                return
            del self._map[key]
            sid = entry.sid
        sock = Socket.address(sid)
        if sock is not None:
            sock.recycle()

    def count(self) -> int:
        with self._lock:
            return len(self._map)


_global_map: Optional[SocketMap] = None
_global_lock = threading.Lock()


def get_global_socket_map() -> SocketMap:
    global _global_map
    if _global_map is None:
        with _global_lock:
            if _global_map is None:
                _global_map = SocketMap()
    return _global_map
