"""SocketMap — process-wide client connection sharing.

Counterpart of brpc's SocketMap (/root/reference/src/brpc/details/
socket_map.{h,cpp}): "single"-type client connections are shared by every
channel in the process, reference-counted; Remove drops the ref and
recycles on zero. Channels call get_client_socket instead of dialing their
own.

Keying follows SocketMapKey (socket_map.h): the map key is the endpoint
PLUS the channel signature — protocol, ssl, authenticator and app-level
connect identity — so channels that differ in any of those get distinct
connections. (The reference folds ssl+auth into ChannelSignature; the
observed failure mode of a bare-endpoint key is a memcache channel being
handed a tpu_std channel's connection on a multi-protocol port.)
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Tuple

from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.rpc.socket import Socket

# (ip, port, protocol, ssl, auth_id, app_connect_id)
SocketMapKey = Tuple[str, int, str, bool, int, str]


def make_key(ep: EndPoint, protocol: str = "", ssl: bool = False,
             auth=None, app_connect_id: str = "") -> SocketMapKey:
    """Build the sharing key for one channel signature (SocketMapKey)."""
    return (ep.ip, ep.port, protocol, bool(ssl),
            id(auth) if auth is not None else 0, app_connect_id)


class _Entry:
    __slots__ = ("sid", "refcount")

    def __init__(self, sid: int):
        self.sid = sid
        self.refcount = 0


class SocketMap:
    def __init__(self):
        self._map: Dict[SocketMapKey, _Entry] = {}
        self._lock = threading.Lock()

    def insert(self, ep: EndPoint, messenger=None,
               health_check_interval_s: float = -1,
               ssl_context=None, app_connect=None,
               app_connect_factory: Optional[Callable] = None,
               key: Optional[SocketMapKey] = None) -> Optional[int]:
        """Get-or-create the shared SocketId for this key
        (SocketMap::Insert). `app_connect_factory` makes a fresh per-socket
        app-connect hook (each connection needs its own transport endpoint,
        the RdmaEndpoint-per-Socket shape of rdma_endpoint.h)."""
        if key is None:
            hook = app_connect or app_connect_factory
            key = make_key(ep, ssl=ssl_context is not None,
                           app_connect_id=f"custom:{id(hook)}" if hook else "")
        with self._lock:
            entry = self._map.get(key)
            if entry is not None:
                sock = Socket.address(entry.sid)
                # a lame-duck socket (peer draining) is replaced like a
                # failed one — but NOT recycled: its in-flight RPCs keep
                # completing while new channels dial fresh
                if sock is not None and not sock.failed() and \
                        not getattr(sock, "lame_duck", False):
                    entry.refcount += 1
                    return entry.sid
                del self._map[key]
            if messenger is None:
                from brpc_tpu.rpc.channel import get_client_messenger

                messenger = get_client_messenger()
            if app_connect is None and app_connect_factory is not None:
                app_connect = app_connect_factory()
            sid = Socket.create(
                remote_side=ep,
                on_edge_triggered_events=messenger.on_new_messages,
                health_check_interval_s=health_check_interval_s,
                ssl_context=ssl_context,
                app_connect=app_connect,
            )
            entry = _Entry(sid)
            entry.refcount = 1
            self._map[key] = entry
            return sid

    def find(self, ep: Optional[EndPoint] = None,
             key: Optional[SocketMapKey] = None) -> Optional[int]:
        if key is None:
            if ep is None:
                raise ValueError("find() needs an endpoint or a key")
            key = make_key(ep)
        with self._lock:
            entry = self._map.get(key)
            return entry.sid if entry else None

    def remove(self, ep: Optional[EndPoint] = None,
               key: Optional[SocketMapKey] = None,
               expected_sid: Optional[int] = None):
        """Drop one reference; recycle the socket at zero
        (SocketMap::Remove). `expected_sid` guards against decrementing a
        NEWER entry that replaced the one this caller referenced
        (SocketMap::Remove's expected_id)."""
        if key is None:
            if ep is None:
                raise ValueError("remove() needs an endpoint or a key")
            key = make_key(ep)
        with self._lock:
            entry = self._map.get(key)
            if entry is None:
                return
            if expected_sid is not None and entry.sid != expected_sid:
                return
            entry.refcount -= 1
            if entry.refcount > 0:
                return
            del self._map[key]
            sid = entry.sid
        sock = Socket.address(sid)
        if sock is not None:
            sock.recycle()

    def count(self) -> int:
        with self._lock:
            return len(self._map)


_global_map: Optional[SocketMap] = None
_global_lock = threading.Lock()


def get_global_socket_map() -> SocketMap:
    global _global_map
    if _global_map is None:
        with _global_lock:
            if _global_map is None:
                _global_map = SocketMap()
    return _global_map
