"""Device transport — the ICI endpoint playing brpc's RDMA role.

Counterpart of the RDMA subsystem (SURVEY.md section 2.9,
/root/reference/src/brpc/rdma/):

* DeviceBlockPool ⇔ block_pool.{h,cpp}: pre-registered arenas carved into
  size-class blocks (8KB/64KB/2MB there; byte-capacity HBM buffers here),
  plugged in where IOBuf gets its memory, so payloads are transfer-ready
  without a registration step on the hot path.
* DeviceEndpoint ⇔ RdmaEndpoint (rdma_endpoint.h:55-226): lives inside a
  Socket via the app_connect seam (socket.h:108-130); the TCP connection
  performs the credential handshake (the GID/QPN exchange analog —
  platform, device ids, process identity) through the state machine
  UNINIT→HANDSHAKING→ESTABLISHED, falling back to plain TCP when either
  side has no device (FALLBACK_TCP, rdma_endpoint.h:94-115); sends retain
  source buffers until the peer's ACK (the _sbuf retention discipline,
  rdma_endpoint.h:214), with a sliding window limiting in-flight bytes and
  window updates piggybacked on ACK frames (rdma_endpoint.h:132-138).
* device_helper ⇔ rdma_helper.{h,cpp}: device discovery/identity.

Transfer semantics by locality:
  same process  — zero-copy: the receiving side gets the SAME jax.Array
                  (the loopback-ICI stand-in; on a pod this is an ICI DMA);
  cross process — tensor bytes ride the TCP wire (the FALLBACK_TCP path),
                  re-materialized with jax.device_put on arrival.
"""
from __future__ import annotations

import struct
import threading
import uuid
from typing import Dict, List, Optional, Tuple

from brpc_tpu import bvar
from brpc_tpu.butil.iobuf import IOBuf

# -- allocator tuning (the tcmalloc role) -----------------------------------
# brpc ships with tcmalloc precisely because glibc malloc mmap()s every
# multi-MB buffer and returns it on free, so each transfer repays the full
# page-fault + munmap cost (docs/cn/memory_management.md rationale). The
# transfer lanes here allocate an N-MB landing buffer per receive; raising
# the mmap threshold keeps those on the reusable heap — measured 2x on the
# same-host copy-out path.


def _tune_allocator():
    try:
        import ctypes

        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        M_MMAP_THRESHOLD = -3
        libc.mallopt(M_MMAP_THRESHOLD, 256 << 20)
    except Exception:
        pass  # non-glibc platform: allocator stays stock


_tune_allocator()

# -- device_helper (rdma_helper analog) ------------------------------------

_process_uuid = uuid.uuid4().hex


def _host_boot_id() -> str:
    """Same-host identity: two processes share a zero-copy arena only when
    they share a kernel (the GID-subnet check analog)."""
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            return f.read().strip()
    except OSError:
        import socket as pysocket

        return pysocket.gethostname()


_boot_id = _host_boot_id()


def local_device_info(arm_fabric: bool = False) -> dict:
    """Discovery: platform + device ids (GID/LID discovery analog). The
    send arena's name rides along like the GID/QPN credentials so the peer
    can map our registered memory. With arm_fabric=True (the SERVER half
    of the handshake) the descriptor-ring tensor fabric is armed and its
    segment name advertised, so same-host peers can attach as producers
    and push payloads with zero bytes on the wire (the ring lane)."""
    arena = default_send_arena()
    info = {
        "process": _process_uuid,
        "host": _boot_id,
        "arena": arena.name if arena is not None else "",
        # descriptor-ring fabric inbox (ISSUE 15): advertised only when
        # the receiver drain is actually running — a peer that sees a
        # name will push kind-8 payloads with nothing on the wire
        "fabric": _fabric_arm_receiver() if arm_fabric else "",
        # advertised ONLY when the server actually started: a peer that
        # sees True may publish xfer-lane payloads with nothing on the
        # wire, so import success alone is not proof enough
        "xfer": _global_xfer_server() is not None,
    }
    try:
        import jax

        devs = jax.devices()
        info["platform"] = devs[0].platform if devs else "none"
        info["device_count"] = len(devs)
    except Exception:
        info["platform"] = "none"
        info["device_count"] = 0
    return info


# -- DeviceBlockPool (block_pool analog) ------------------------------------

_pool_acquired = bvar.Adder("device_block_pool_acquired")
_pool_released = bvar.Adder("device_block_pool_released")


class DeviceBlockPool:
    """Pre-allocated HBM byte-buffers by size class — the role of the
    reference's registered-memory pool (block_pool.h:29-94: arenas carved
    into 8KB/64KB/2MB blocks that ALL transfer traffic flows through).

    The jax-idiomatic rendition: incoming transfer bytes are written into
    a pooled buffer with a DONATING jitted update, so the pooled HBM is
    genuinely the memory the bytes land in (no per-transfer allocation),
    then bitcast/sliced into the typed array handed to the application.
    acquire()/release() remain available for raw leases."""

    SIZE_CLASSES = (8 << 10, 64 << 10, 2 << 20)  # block_pool's classes

    def __init__(self, blocks_per_class: int = 8, device=None):
        import jax
        import jax.numpy as jnp

        self._device = device or jax.devices()[0]
        self._free: Dict[int, List] = {}
        self._lock = threading.Lock()
        self._fill_fns = {}  # (size_class, nbytes) -> donating writer
        for size in self.SIZE_CLASSES:
            buffers = []
            for _ in range(blocks_per_class):
                buf = jax.device_put(
                    jnp.zeros((size,), dtype=jnp.uint8), self._device
                )
                buffers.append(buf)
            self._free[size] = buffers

    def acquire(self, nbytes: int):
        """Returns (size_class, buffer) or None if exhausted/oversized."""
        with self._lock:
            for size in self.SIZE_CLASSES:
                if nbytes <= size and self._free[size]:
                    _pool_acquired.update(1)
                    return size, self._free[size].pop()
        return None

    def release(self, size_class: int, buf):
        with self._lock:
            if size_class in self._free:
                self._free[size_class].append(buf)
                _pool_released.update(1)

    def stats(self) -> Dict[int, int]:
        with self._lock:
            return {k: len(v) for k, v in self._free.items()}

    def _fill_fn(self, size_class: int, padded: int):
        import jax

        key = (size_class, padded)
        with self._lock:
            fn = self._fill_fns.get(key)
        if fn is None:
            fn = jax.jit(
                lambda b, x: jax.lax.dynamic_update_slice(b, x, (0,)),
                donate_argnums=(0,))
            with self._lock:
                self._fill_fns.setdefault(key, fn)
        return fn

    @staticmethod
    def _pad_quantum(nbytes: int) -> int:
        # quantize the host-side staging length to powers of two so the
        # jit cache stays bounded (~10 entries per class) instead of one
        # compiled fill per distinct payload size
        q = 4096
        while q < nbytes:
            q <<= 1
        return q

    def put_via_pool(self, host_u8, np_dtype, shape, device=None):
        """Host->device put of raw bytes THROUGH pooled memory: the bytes
        land in a pooled buffer (donated update — same HBM each time),
        then a device-side slice+bitcast produces the typed array. Falls
        back to a plain device_put when the pool is exhausted, the
        payload is oversized, or a different target device is asked for.
        Returns a jax.Array of `np_dtype`/`shape`."""
        import jax
        import numpy as np

        nbytes = int(host_u8.size)
        target = device or self._device
        got = self.acquire(nbytes) if target == self._device else None
        if got is None:
            return jax.device_put(
                host_u8.view(np_dtype).reshape(shape), target)
        size_class, buf = got
        filled = None
        try:
            padded = min(self._pad_quantum(nbytes), size_class)
            if padded != nbytes:
                # np.empty + tail zero: one nbytes memcpy plus a small
                # tail clear, not a full padded zero-fill + copy
                staged = np.empty(padded, dtype=np.uint8)
                staged[:nbytes] = host_u8
                staged[nbytes:] = 0
            else:
                staged = host_u8
            filled = self._fill_fn(size_class, padded)(buf, staged)
            itemsize = np.dtype(np_dtype).itemsize
            head = filled[:nbytes]
            if itemsize > 1:
                head = jax.lax.bitcast_convert_type(
                    head.reshape(-1, itemsize), np_dtype)
            arr = head.reshape(shape)
            # the pooled buffer may be re-donated the moment it returns
            # to the freelist: the slice/bitcast read must be complete
            arr.block_until_ready()
            return arr
        finally:
            if filled is not None:
                # `filled` aliases the donated memory; it IS the pool
                # buffer from here on
                self.release(size_class, filled)
            else:
                # the fill failed mid-donation: buf may be dead — refill
                # the class with a fresh buffer instead of a poisoned one
                import jax.numpy as jnp

                self.release(size_class, jax.device_put(
                    jnp.zeros((size_class,), dtype=jnp.uint8),
                    self._device))


_default_pool: Optional[DeviceBlockPool] = None
_default_pool_lock = threading.Lock()


def default_block_pool() -> DeviceBlockPool:
    global _default_pool
    if _default_pool is None:
        with _default_pool_lock:
            if _default_pool is None:
                _default_pool = DeviceBlockPool()
    return _default_pool


# -- HostArena (the cross-process half of block_pool) ------------------------
#
# The reference registers big arenas with ibv_reg_mr so the NIC can DMA
# them (block_pool.h:29-94). The TPU-host translation is a PINNED-HOST
# shared-memory arena: the sender stages tensor bytes into it once
# (device->host DMA), the wire carries only an (arena, offset) descriptor,
# and a same-host peer maps the arena and hands the bytes straight to
# jax.device_put — no payload bytes on the TCP stream, no pickling.

class HostArena:
    """Shared pinned-host arena carved by a first-fit span allocator.

    Pages are PRE-FAULTED at creation/attach (one touch per 4KB page):
    on sandboxed/TPU hosts the first write to a fresh shm mapping costs
    orders of magnitude more than the copy itself (BENCH_r05 measured
    the staging lane at 0.27 GB/s while warm copies ran >1.5 GB/s —
    first-touch fault cost, not memory bandwidth). Registration-time
    prefault is exactly what ibv_reg_mr does for the reference's RDMA
    arenas: pay the pinning once, outside the transfer path."""

    def __init__(self, size: int = 64 << 20, name: Optional[str] = None,
                 create: bool = True, prefault: bool = True):
        from multiprocessing import shared_memory

        if create:
            self.shm = shared_memory.SharedMemory(create=True, size=size)
            if prefault:
                self._prefault(write=True)
        else:
            self.shm = shared_memory.SharedMemory(name=name)
            # A non-owner must NOT let Python's resource tracker unlink
            # the segment when THIS process exits (3.12 has no track=False;
            # the tracker would otherwise destroy the owner's live arena).
            try:
                from multiprocessing import resource_tracker

                resource_tracker.unregister(self.shm._name, "shared_memory")
            except Exception:
                pass
            if prefault:
                # attach side reads: fault the mapping in before the
                # receive path timing matters
                self._prefault(write=False)
        self.name = self.shm.name
        self.size = self.shm.size
        self._free = [(0, self.size)]  # sorted (offset, size) spans
        self._lock = threading.Lock()
        self.owner = create

    def _prefault(self, write: bool):
        try:
            import numpy as np

            view = np.frombuffer(self.shm.buf, dtype=np.uint8)
            if write:
                view[::4096] = 0  # one store per page
            else:
                int(view[::4096].sum())  # one load per page
        except Exception:
            pass  # numpy-less / exotic platform: pay the faults lazily

    # -- span allocator ----------------------------------------------------
    def alloc(self, nbytes: int) -> Optional[int]:
        nbytes = max(64, (nbytes + 63) & ~63)  # 64B-aligned spans
        with self._lock:
            for i, (off, sz) in enumerate(self._free):
                if sz >= nbytes:
                    if sz == nbytes:
                        self._free.pop(i)
                    else:
                        self._free[i] = (off + nbytes, sz - nbytes)
                    return off
        return None

    def free(self, offset: int, nbytes: int):
        nbytes = max(64, (nbytes + 63) & ~63)
        with self._lock:
            self._free.append((offset, nbytes))
            self._free.sort()
            merged = []
            for off, sz in self._free:
                if merged and merged[-1][0] + merged[-1][1] == off:
                    merged[-1] = (merged[-1][0], merged[-1][1] + sz)
                else:
                    merged.append((off, sz))
            self._free = [(o, s) for o, s in merged]

    def free_bytes(self) -> int:
        with self._lock:
            return sum(s for _, s in self._free)

    def view(self, offset: int, nbytes: int) -> memoryview:
        return memoryview(self.shm.buf)[offset:offset + nbytes]

    # -- blockmem_allocate adapter ------------------------------------------
    def make_block(self, capacity: int = 256 << 10):
        """A writable IOBuf Block carved from this arena (the
        blockmem_allocate hook, iobuf.cpp:163-168); freed back when the
        block is collected. Returns None when exhausted."""
        import weakref

        from brpc_tpu.butil.iobuf import Block

        off = self.alloc(capacity)
        if off is None:
            return None
        b = Block.__new__(Block)
        b.data = self.view(off, capacity)
        b.size = 0
        b.capacity = capacity
        b.kind = Block.USER
        b.deleter = None
        b.meta = off
        b.device_array = None
        weakref.finalize(b, self.free, off, capacity)
        return b

    def install_as_iobuf_allocator(self, capacity: int = 256 << 10):
        """Point IOBuf's block factory at this arena, so every appended
        payload is staged in transfer-registered memory (the 'all IOBuf
        memory is RDMA-registered' configuration of docs/cn/rdma.md)."""
        from brpc_tpu.butil import iobuf as iobuf_mod

        iobuf_mod.set_block_allocator(lambda: self.make_block(capacity))

    def close(self):
        try:
            if self.owner:
                self.shm.unlink()
        except OSError:
            pass
        try:
            self.shm.close()
        except BufferError:
            # Live memoryviews (IOBuf blocks / transfer views carved from
            # the arena) still export the mapping. DETACH instead of
            # retrying: null the SharedMemory's buf/mmap so its __del__
            # cannot re-raise (the round-2 unraisable-BufferError leak
            # seam); the orphaned mmap object unmaps itself once the last
            # exported view dies — no leak, no warning.
            try:
                self.shm._buf = None
                self.shm._mmap = None
            except Exception:
                pass
        except OSError:
            pass


_send_arena: Optional[HostArena] = None
_send_arena_lock = threading.Lock()
_send_arena_enabled = True


def default_send_arena() -> Optional[HostArena]:
    """Process-wide outbound arena (created lazily; advertised in the
    handshake)."""
    global _send_arena
    if not _send_arena_enabled:
        return None
    if _send_arena is None:
        with _send_arena_lock:
            if _send_arena is None:
                try:
                    _send_arena = HostArena()
                except OSError:
                    return None
    return _send_arena


_attached_arenas: Dict[str, HostArena] = {}
_attached_lock = threading.Lock()


def _cleanup_arenas():
    global _send_arena
    if _send_arena is not None:
        _send_arena.close()
        _send_arena = None
    with _attached_lock:
        for arena in _attached_arenas.values():
            arena.close()
        _attached_arenas.clear()


import atexit  # noqa: E402

atexit.register(_cleanup_arenas)


def attach_arena(name: str) -> Optional[HostArena]:
    """Map a peer's arena by name (their ibv_reg_mr region, our mmap)."""
    with _attached_lock:
        arena = _attached_arenas.get(name)
        if arena is None:
            try:
                arena = HostArena(name=name, create=False)
            except (OSError, FileNotFoundError):
                return None
            _attached_arenas[name] = arena
    return arena


# -- in-process tensor exchange (the loopback "ICI") ------------------------

_inproc_registry: Dict[int, List] = {}
_inproc_lock = threading.Lock()
_inproc_next = [1]

_dev_zero_copy = bvar.Adder("device_transport_zero_copy_transfers")
_dev_shm = bvar.Adder("device_transport_shm_transfers")
_dev_wire = bvar.Adder("device_transport_wire_transfers")
_dev_xfer = bvar.Adder("device_transport_xfer_transfers")
_dev_ring = bvar.Adder("device_transport_ring_transfers")


# -- descriptor-ring tensor fabric (the ring lane, ISSUE 15) ----------------
#
# The same-host cross-process lane re-plumbed onto the PR-3 descriptor
# ring (nat_shm_lane.cpp): the RECEIVER owns a shm segment whose slots
# peers claim as PRODUCERS; a send writes its payload ONCE into the
# shared blob arena (nat_shm_fabric_push, kind-8 descriptor) and the
# receiver's drain thread takes it as a LEASE consumed in place —
# producer-write -> arena -> jax.device_put/put_via_pool with no
# intermediate memcpy, and no payload bytes on the TCP wire. Leases
# release OUT OF ORDER (the arena's released-bit discipline), and a
# producer SIGKILL surfaces as EOWNERDEAD on the receiver's recovery
# probe (the robust lifetime fence the worker lane already proves).

_fabric_lock = threading.Lock()
_fabric_name: Optional[str] = None
_fabric_thread: Optional[threading.Thread] = None
_fabric_stop = threading.Event()
_fabric_cv = threading.Condition()
_fabric_records: Dict[int, object] = {}   # tag -> (FabricLease, deadline)
_fabric_sink = None                       # optional delivery override
_producer_target: Optional[str] = None    # segment we attached to
_FABRIC_RECORD_TTL_S = 30.0


def fabric_set_sink(fn):
    """Override the tag-registry delivery: every kind-8 record taken by
    the receiver drain goes to fn(lease) instead (the lease is OWNED by
    the sink — it must release, possibly out of order). Pass None to
    restore the registry."""
    global _fabric_sink
    _fabric_sink = fn


def _fabric_arm_receiver() -> str:
    """Create (or adopt) this process's fabric segment and start the
    receiver drain thread. Returns the segment name, or '' when the
    native runtime is unavailable / disabled (BRPC_TPU_FABRIC=0)."""
    global _fabric_name, _fabric_thread
    import os

    if os.environ.get("BRPC_TPU_FABRIC", "1") == "0":
        return ""
    try:
        from brpc_tpu import native

        if not native.available():
            return ""
        lib = native.load()
    except Exception:
        return ""
    with _fabric_lock:
        if _fabric_thread is not None and _fabric_thread.is_alive():
            name = lib.nat_shm_lane_name() or b""
            return name.decode() or (_fabric_name or "")
        size = int(os.environ.get("BRPC_TPU_FABRIC_ARENA",
                                  str(32 << 20)))
        if lib.nat_shm_lane_create(size) != 0:
            return ""
        _fabric_name = lib.nat_shm_lane_name().decode()
        _fabric_stop.clear()
        t = threading.Thread(target=_fabric_drain_loop, daemon=True,
                             name="tensor-fabric-drain")
        _fabric_thread = t
        t.start()
        return _fabric_name


def _fabric_drain_loop():
    from brpc_tpu import native

    import time

    while not _fabric_stop.is_set():
        try:
            lease = native.fabric_take(200)
        except Exception:
            return
        now = time.monotonic()
        with _fabric_cv:
            # purge abandoned records (a sender whose RPC failed after
            # the push): their leases must not pin the arena forever.
            # Runs on EVERY wakeup incl. empty timeouts — a pinned-full
            # arena stops new records from arriving, so an
            # arrival-gated purge could never free it.
            stale = [t for t, (_, dl) in _fabric_records.items()
                     if dl <= now]
            for t in stale:
                _fabric_records.pop(t)[0].release()
        if lease is None:
            continue
        sink = _fabric_sink
        if sink is not None:
            try:
                sink(lease)
            except Exception:
                lease.release()
            continue
        with _fabric_cv:
            _fabric_records[lease.tag] = (lease,
                                          now + _FABRIC_RECORD_TTL_S)
            _fabric_cv.notify_all()


def _fabric_claim(tag: int, timeout_s: float = 10.0):
    """Receiver side: wait for the drain thread to deliver tag's lease."""
    import time

    deadline = time.monotonic() + timeout_s
    with _fabric_cv:
        while True:
            entry = _fabric_records.pop(tag, None)
            if entry is not None:
                return entry[0]
            remain = deadline - time.monotonic()
            if remain <= 0:
                return None
            _fabric_cv.wait(remain)


def _fabric_attach_producer(name: str) -> bool:
    """Attach this process as a PRODUCER on the peer segment `name`.
    The native mapping is process-wide, so only one target segment per
    process: a process that owns its own segment (it is a receiver /
    shm-worker parent) or already attached elsewhere falls back to the
    shm-arena lane for other peers."""
    global _producer_target
    try:
        from brpc_tpu import native

        if not native.available():
            return False
        lib = native.load()
    except Exception:
        return False
    with _fabric_lock:
        if _producer_target is not None:
            return _producer_target == name
        own = (lib.nat_shm_lane_name() or b"").decode()
        if own and own != name:
            return False  # this process's mapping belongs to its own seg
        if lib.nat_shm_producer_attach(name.encode()) < 0:
            return False
        _producer_target = name
        return True

from brpc_tpu.butil import flags as _flags  # noqa: E402

_flags.define_bool(
    "device_transport_prefer_xfer", False,
    "use the jax transfer-server lane even for same-host peers (it is "
    "always used for cross-host device peers when both sides support "
    "it). CAUTION: the CPU backend's bulk transport is same-process-"
    "only — forcing this across processes needs a real device backend")


def lane_counters() -> dict:
    """Public per-lane transfer counts (also exposed as bvars under
    device_transport_*): {'inproc': N, 'ring': N, 'shm': N, 'wire': N,
    'xfer': N}."""
    return {"inproc": _dev_zero_copy.get_value(),
            "ring": _dev_ring.get_value(),
            "shm": _dev_shm.get_value(),
            "wire": _dev_wire.get_value(),
            "xfer": _dev_xfer.get_value()}


# -- jax transfer-server lane (the DEVICE-to-DEVICE cross-host path: the
# true ICI/DCN translation of the RDMA QP — rdma_endpoint.h:55-57's role
# when peers live on different machines) ------------------------------------

_xfer_server = None
_xfer_server_lock = threading.Lock()
_xfer_conns: Dict[str, object] = {}
_xfer_conns_lock = threading.Lock()


def _global_xfer_server():
    """Lazy singleton jax.experimental.transfer server; None when the
    backend/jax build lacks it. Start failures are NOT latched: an early
    failure (e.g. before jax is fully configured) retries on the next
    handshake rather than silently disabling the lane forever. Started
    eagerly by device handshakes because the advertisement must be
    truthful — a peer that sees True may put zero payload on the wire."""
    global _xfer_server
    if _xfer_server is not None:
        return _xfer_server
    with _xfer_server_lock:
        if _xfer_server is None:
            import os

            if os.environ.get("BRPC_TPU_FAKE_XFER"):
                # test transport seam: a cross-process TCP fake of the
                # transfer fabric (the CPU backend's real bulk transport
                # is same-process-only)
                from brpc_tpu.rpc.fake_transfer import FakeTransferServer

                _xfer_server = FakeTransferServer()
                return _xfer_server
            try:
                import jax
                from jax.experimental import transfer

                _xfer_server = transfer.start_transfer_server(
                    jax.devices()[0].client)
            except Exception:
                return None  # retry on a later call
    return _xfer_server


def _xfer_connect(addr: str):
    with _xfer_conns_lock:
        conn = _xfer_conns.get(addr)
    if conn is not None:
        return conn
    server = _global_xfer_server()
    if server is None:
        raise ValueError("no local transfer server to connect from")
    conn = server.connect(addr)  # dial OUTSIDE the lock: a hung peer
    with _xfer_conns_lock:       # must not block other peers' receives
        return _xfer_conns.setdefault(addr, conn)


def _xfer_evict(addr: str):
    """Drop a cached connection (e.g. after a failed pull) so the next
    receive redials — a restarted sender on the same address recovers."""
    with _xfer_conns_lock:
        _xfer_conns.pop(addr, None)


def inproc_publish(arrays: List) -> int:
    """Register device arrays for same-process zero-copy pickup; returns a
    ticket riding the wire in their place. No staging memory is needed —
    the arrays themselves are the transfer (strictly better than the
    reference's registered-block copy for this lane); the DeviceBlockPool
    serves the lanes that DO materialize bytes (shm/wire receives route
    through put_via_pool)."""
    with _inproc_lock:
        ticket = _inproc_next[0]
        _inproc_next[0] += 1
        _inproc_registry[ticket] = arrays
    return ticket


def inproc_claim(ticket: int) -> Optional[List]:
    with _inproc_lock:
        return _inproc_registry.pop(ticket, None)


# -- DeviceEndpoint (RdmaEndpoint analog) -----------------------------------

# endpoint states (rdma_endpoint.h:94-115)
UNINIT = 0
HANDSHAKING = 1
ESTABLISHED = 2
FALLBACK_TCP = 3

_HANDSHAKE_MAGIC = b"TDEV"
DEFAULT_WINDOW_BYTES = 64 << 20  # in-flight tensor bytes per endpoint


class DeviceEndpoint:
    """Attached to a Socket through app_connect; upgrades the connection
    for tensor transfer."""

    def __init__(self, window_bytes: int = DEFAULT_WINDOW_BYTES):
        self.state = UNINIT
        self.peer_info: dict = {}
        self.window_bytes = window_bytes
        self._inflight = 0
        self._window_cond = threading.Condition()
        # sends retained until ACKed (the _sbuf retention, rdma_endpoint.h:214)
        self._retained: Dict[int, Tuple[List, int]] = {}
        self._next_seq = 1
        self._lock = threading.Lock()
        # transfer-server lane: our address as reachable by THIS peer
        # (wildcard host resolved against the handshake connection), and
        # a per-endpoint uuid base so pull ids never collide
        self._my_xfer_addr = ""
        self._xfer_uuid_base = int(uuid.uuid4().int & ((1 << 62) - 1)
                                   ) & ~0xFFFFF

    def resolve_xfer_addr(self, local_ip: str):
        """Called with the handshake connection's local IP: publishes the
        transfer server's address with any wildcard host substituted, so
        the peer can dial back over the same network path."""
        server = _global_xfer_server()
        if server is None or not local_ip:
            return
        addr = server.address()
        host, _, port = addr.rpartition(":")
        if host in ("[::]", "0.0.0.0", ""):
            host = local_ip
        self._my_xfer_addr = f"{host}:{port}"

    # ---- handshake over the TCP connection (GID/QPN exchange analog) ----
    def app_connect(self, sock) -> int:
        """Blocking handshake on the freshly-connected socket. On any
        failure the connection falls back to plain TCP rather than dying
        (the FALLBACK_TCP story of rdma.md)."""
        self.state = HANDSHAKING
        # Attach to the socket up-front so even FALLBACK_TCP outcomes leave
        # the endpoint reachable via sock.app_state (window/ACK bookkeeping
        # applies to the wire path too).
        sock.app_state = self
        try:
            import json

            info = json.dumps(local_device_info()).encode()
            frame = _HANDSHAKE_MAGIC + struct.pack(">I", len(info)) + info
            fd = sock.fd()
            fd.setblocking(True)
            fd.sendall(frame)
            header = _recv_exact(fd, 8)
            if header is None or header[:4] != _HANDSHAKE_MAGIC:
                self.state = FALLBACK_TCP
                fd.setblocking(False)
                return 0
            (length,) = struct.unpack(">I", header[4:8])
            peer = _recv_exact(fd, length)
            fd.setblocking(False)
            if peer is None:
                self.state = FALLBACK_TCP
                return 0
            self.peer_info = json.loads(peer)
            mine = local_device_info()
            if (self.peer_info.get("device_count", 0) > 0
                    and mine["device_count"] > 0):
                self.state = ESTABLISHED
                if self.peer_info.get("xfer"):
                    try:
                        self.resolve_xfer_addr(fd.getsockname()[0])
                    except OSError:
                        pass
            else:
                self.state = FALLBACK_TCP
            return 0
        except OSError:
            self.state = FALLBACK_TCP
            return 0

    @property
    def same_process(self) -> bool:
        return self.peer_info.get("process") == _process_uuid

    @property
    def same_host(self) -> bool:
        return self.peer_info.get("host") == _boot_id

    # ---- send path ------------------------------------------------------
    def prepare_send(self, arrays: List, meta, attachment: IOBuf,
                     timeout_s: float = 10.0) -> bool:
        """Fill meta.tensors + attachment for `arrays` according to the
        endpoint state; blocks while the send window is full.

        Lane selection (rdma_endpoint.h:94-115 state machine applied to
        locality): same process -> pass the jax.Array itself; same host ->
        stage bytes ONCE into the shared HostArena and ship an (arena,
        offset) descriptor (no payload on the wire); otherwise ->
        FALLBACK_TCP wire bytes."""
        total = sum(int(a.nbytes) for a in arrays)
        with self._window_cond:
            import time

            deadline = time.monotonic() + timeout_s
            while self._inflight + total > self.window_bytes:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    return False
                self._window_cond.wait(remain)
            self._inflight += total
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
        meta.compress_type = 0
        for a in arrays:
            t = meta.tensors.add()
            t.dtype = str(a.dtype)
            t.shape.extend(int(d) for d in a.shape)
            t.nbytes = int(a.nbytes)

        try:
            release = self._fill_lane(arrays, meta, attachment, seq, total)
        except Exception:
            with self._window_cond:
                self._inflight -= total
                self._window_cond.notify_all()
            raise
        with self._lock:
            self._retained[seq] = (release, total)
        return True

    def _fill_lane(self, arrays, meta, attachment, seq, total):
        release = None
        if self.state == ESTABLISHED and self.same_process:
            # zero-copy: ship a ticket instead of bytes
            ticket = inproc_publish(arrays)
            meta.tensors[0].sharding_spec = f"inproc:{ticket}:{seq}"
            _dev_zero_copy.update(1)
            release = (lambda t=ticket: inproc_claim(t))
        elif (self.state == ESTABLISHED and self._my_xfer_addr
              and self.peer_info.get("xfer")
              and (not self.same_host
                   or _flags.get_flag("device_transport_prefer_xfer"))):
            # device-to-device over the transfer fabric: publish on OUR
            # transfer server; the peer pulls straight into its devices.
            # No payload bytes on the RPC wire; jax releases the source
            # buffers once the peer's pull completes.
            import jax
            import numpy as np

            server = _global_xfer_server()
            uid = self._xfer_uuid_base + seq
            jarrays = [a if isinstance(a, jax.Array)
                       else jax.device_put(np.ascontiguousarray(a))
                       for a in arrays]
            # device_put canonicalizes dtypes (float64->float32 without
            # x64): the meta must describe what was PUBLISHED
            for t, ja in zip(meta.tensors, jarrays):
                t.dtype = str(ja.dtype)
                t.nbytes = int(ja.nbytes)
            server.await_pull(uid, jarrays)
            meta.tensors[0].sharding_spec = (
                f"xfer|{self._my_xfer_addr}|{uid}|{seq}")
            _dev_xfer.update(1)
            release = (lambda: None)
        elif (self.state == ESTABLISHED and self.same_host
              and self.peer_info.get("fabric")
              and self._ring_lane_send(arrays, meta, seq)):
            # descriptor-ring fabric: payload written ONCE into the
            # receiver's blob arena (kind-8 records), consumed in place
            # on the far side — zero payload bytes on the wire, zero
            # intermediate memcpy. The receiver owns the spans (leases),
            # so there is nothing to free on ACK; the window retention
            # still bounds in-flight bytes.
            _dev_ring.update(1)
            release = (lambda: None)
        elif self.state == ESTABLISHED and self.same_host:
            arena = default_send_arena()
            offset = arena.alloc(total) if arena is not None else None
            if offset is not None:
                import numpy as np

                pos = offset
                for a in arrays:
                    n = int(a.nbytes)
                    dst = np.frombuffer(arena.shm.buf, dtype=np.uint8,
                                        count=n, offset=pos)
                    # one device->host DMA straight into registered memory
                    host = np.ascontiguousarray(np.asarray(a))
                    dst[:] = host.reshape(-1).view(np.uint8)
                    pos += n
                meta.tensors[0].sharding_spec = (
                    f"shm:{arena.name}:{offset}:{seq}")
                _dev_shm.update(1)
                release = (lambda o=offset, n=total: arena.free(o, n))
        if release is None and not (self.state == ESTABLISHED
                                    and self.same_process):
            import numpy as np

            meta.tensors[0].sharding_spec = f"wire::{seq}"
            for a in arrays:
                attachment.append(np.asarray(a).tobytes())
            _dev_wire.update(1)
        return release

    def _ring_lane_send(self, arrays, meta, seq) -> bool:
        """Push every tensor's bytes as one kind-8 fabric record each
        (tags base..base+n-1) onto the peer's descriptor ring; the spec
        `ring:<base>:<n>:<seq>` rides the RPC in place of any payload.
        False -> the caller falls through to the shm-arena/wire lanes."""
        name = self.peer_info.get("fabric") or ""
        if not name or not _fabric_attach_producer(name):
            return False
        if len(arrays) > 256:
            # the per-seq tag stride is 256 (base = uuid + (seq << 8)):
            # more tensors would collide with the next seq's tags and
            # the receiver could claim the wrong record — fall back
            return False
        import time

        import numpy as np

        from brpc_tpu import native

        base = (self._xfer_uuid_base + (seq << 8)) & ((1 << 62) - 1)
        pushed = 0
        for i, a in enumerate(arrays):
            host = np.ascontiguousarray(np.asarray(a))
            flat = host.reshape(-1).view(np.uint8)
            # Bounded backoff only: the blob arena is a RING — a receiver
            # retaining leases indefinitely head-blocks reclaim, and the
            # right response is falling back to the shm-arena lane, not
            # stalling the send path (size the fabric to the consumer's
            # retention with BRPC_TPU_FABRIC_ARENA).
            deadline = time.monotonic() + 0.25
            while native.fabric_push(flat, base + i) != 0:
                if time.monotonic() >= deadline:
                    # stranded records (tags base..base+pushed-1) are
                    # purged by the receiver's registry TTL
                    return False
                time.sleep(0.0005)
            pushed += 1
        meta.tensors[0].sharding_spec = (
            f"ring:{base}:{len(arrays)}:{seq}")
        return True

    def on_ack(self, seq: int):
        """Peer confirmed receipt: run the lane's release action (free the
        arena span / drop the unclaimed ticket) + open the window
        (piggybacked-ACK path, rdma_endpoint.h:132-138)."""
        with self._lock:
            entry = self._retained.pop(seq, None)
        if entry is not None:
            release, total = entry
            if release is not None:
                try:
                    release()
                except Exception:
                    pass
            with self._window_cond:
                self._inflight = max(0, self._inflight - total)
                self._window_cond.notify_all()

    @property
    def inflight_bytes(self) -> int:
        return self._inflight

    @property
    def retained_count(self) -> int:
        return len(self._retained)


def _recv_exact(fd, n: int) -> Optional[bytes]:
    out = b""
    while len(out) < n:
        chunk = fd.recv(n - len(out))
        if not chunk:
            return None
        out += chunk
    return out


def _np_dtype(name: str):
    import numpy as np

    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _bind_lease(arr, lease):
    """Tie a fabric lease's lifetime to the zero-copy array carved from
    it: the span releases (out of order, whenever) when the array dies.
    The finalizer itself holds the lease reference, so the arena bytes
    stay valid for exactly as long as the array is reachable."""
    import weakref

    weakref.finalize(arr, lease.release)


# -- read-side arena seam (the all-IOBuf-memory-registered config) ----------
#
# The reference points IOBuf's blockmem_allocate at its registered pool
# so EVERY buffer a socket drains into is transfer-ready (SURVEY 2.9).
# install_read_arena is that configuration for the Python stack: socket
# reads land in prefaulted HostArena blocks, growing by whole prefaulted
# arenas on exhaustion — the grow path must never reintroduce the
# first-touch fault cliff (BENCH_r05's 0.085 GB/s staging artifact), so
# every grown arena prefaults at creation exactly like the first.

_read_chain = None
_read_chain_lock = threading.Lock()


class ReadArenaChain:
    """A growable chain of prefaulted HostArenas serving IOBuf blocks."""

    MAX_ARENAS = 8

    def __init__(self, size: int = 32 << 20, capacity: int = 256 << 10):
        self.size = size
        self.capacity = capacity
        self._lock = threading.Lock()
        self.arenas = [HostArena(size=size)]
        self.grows = 0

    def alloc_block(self):
        with self._lock:
            arenas = list(self.arenas)
        for arena in reversed(arenas):
            b = arena.make_block(self.capacity)
            if b is not None:
                return b
        with self._lock:
            if len(self.arenas) >= self.MAX_ARENAS:
                return None  # plain host blocks take over
            try:
                arena = HostArena(size=self.size)  # prefaulted at create
            except OSError:
                return None
            self.arenas.append(arena)
            self.grows += 1
        return arena.make_block(self.capacity)

    def close(self):
        for arena in self.arenas:
            arena.close()


def install_read_arena(size: int = 32 << 20,
                       capacity: int = 256 << 10) -> ReadArenaChain:
    """Install a prefaulted, growable arena chain as the IOBuf block
    factory (HostArena.install_as_iobuf_allocator generalized with a
    grow path). Returns the chain; uninstall_read_arena undoes it."""
    global _read_chain
    from brpc_tpu.butil import iobuf as iobuf_mod

    with _read_chain_lock:
        if _read_chain is None:
            _read_chain = ReadArenaChain(size=size, capacity=capacity)
        iobuf_mod.set_block_allocator(_read_chain.alloc_block)
    return _read_chain


def uninstall_read_arena():
    global _read_chain
    from brpc_tpu.butil import iobuf as iobuf_mod

    with _read_chain_lock:
        iobuf_mod.set_block_allocator(None)
        chain, _read_chain = _read_chain, None
    if chain is not None:
        chain.close()


def receive_tensors(meta, attachment: IOBuf, device=None) -> Tuple[List, Optional[int]]:
    """Reconstruct arrays from a tensor-bearing message. Returns
    (arrays, ack_seq). Zero-copy when the sender published in-process;
    mapped straight out of the sender's shared arena when same-host (the
    recv-zero-copy-into-registered-blocks path, rdma_endpoint.h:214-219)."""
    if not meta.tensors:
        return [], None
    spec = meta.tensors[0].sharding_spec or ""
    if spec.startswith("xfer|"):
        # pull device-to-device from the sender's transfer server
        import jax

        _, addr, uid_s, seq_s = spec.split("|")
        conn = _xfer_connect(addr)
        dev = device if device is not None else jax.devices()[0]
        sharding = jax.sharding.SingleDeviceSharding(dev)
        specs = [jax.ShapeDtypeStruct(tuple(t.shape), _np_dtype(t.dtype),
                                      sharding=sharding)
                 for t in meta.tensors]
        try:
            arrays = conn.pull(int(uid_s), specs)
            # the sender frees its buffers once our pull completes —
            # finish it before the caller ACKs (retention-until-ACK)
            jax.block_until_ready(arrays)
        except Exception:
            _xfer_evict(addr)  # redial next time (sender restarts)
            raise
        return list(arrays), int(seq_s)
    parts = spec.split(":")
    seq = None
    if len(parts) >= 3 and parts[-1].isdigit():
        seq = int(parts[-1])
    if parts[0] == "ring" and len(parts) == 4:
        # descriptor-ring fabric: the payload arrived as kind-8 records
        # in OUR blob arena (the sender wrote it there once); consume the
        # leases IN PLACE — put_via_pool DMAs straight from the arena
        # view, and host-side consumers get zero-copy arrays that release
        # the lease when they die (out-of-order, past this drain).
        import numpy as np

        base, count = int(parts[1]), int(parts[2])
        if count != len(meta.tensors):
            raise ValueError("device transport: ring record count "
                             f"{count} != {len(meta.tensors)} tensors")
        leases = []
        for i in range(count):
            lease = _fabric_claim(base + i)
            if lease is None:
                for l in leases:
                    l.release()
                raise ValueError(
                    f"device transport: ring record {base + i} never "
                    f"arrived (fabric receiver not draining?)")
            leases.append(lease)
        arrays = []
        try:
            for t, lease in zip(meta.tensors, leases):
                dtype = _np_dtype(t.dtype)
                mv = lease.view()
                if device is not None:
                    arr = default_block_pool().put_via_pool(
                        np.frombuffer(mv, dtype=np.uint8), dtype,
                        tuple(t.shape), device)
                else:
                    # zero-copy: the array IS the arena span; the lease
                    # releases when the last view of it is collected.
                    # Bind the finalizer to the BASE frombuffer array:
                    # numpy collapses .base chains to it, so any derived
                    # view (slices of the reshaped array) keeps it — and
                    # therefore the lease — alive; binding to the
                    # reshape wrapper would let a slice outlive the span.
                    flat = np.frombuffer(mv, dtype=dtype)
                    _bind_lease(flat, lease)
                    arr = flat.reshape(tuple(t.shape))
                arrays.append(arr)
        finally:
            if device is not None:
                import jax

                # the async H2D copies must finish before the spans are
                # handed back to the producer's reclaim
                jax.block_until_ready(arrays)
                for lease in leases:
                    lease.release()
        return arrays, seq
    if parts[0] == "inproc" and parts[1].isdigit():
        arrays = inproc_claim(int(parts[1]))
        if arrays is None:
            # Ticket gone (already claimed / sender restarted). No payload
            # rode the wire for this lane — falling through would misread
            # an empty attachment, so fail loudly.
            raise ValueError(f"device transport: in-process ticket "
                             f"{parts[1]} is no longer claimable")
        return arrays, seq
    if parts[0] == "shm" and len(parts) == 4:
        arena = attach_arena(parts[1])
        if arena is None:
            raise ValueError(
                f"device transport: cannot attach shared arena "
                f"{parts[1]!r} (sender chose the same-host lane but the "
                f"shm namespace is not shared)")
        import numpy as np

        arrays = []
        pos = int(parts[2])
        for t in meta.tensors:
            dtype = _np_dtype(t.dtype)
            view = np.frombuffer(arena.shm.buf, dtype=np.uint8,
                                 count=t.nbytes, offset=pos)
            pos += t.nbytes
            if device is not None:
                # host->device DMA from the mapped arena THROUGH the
                # device block pool (block_pool.h role: transfer bytes
                # land in pooled, pre-allocated HBM)
                arr = default_block_pool().put_via_pool(
                    view, dtype, tuple(t.shape), device)
            else:
                # own the bytes before ACK lets the sender reuse them
                arr = np.array(view.view(dtype).reshape(tuple(t.shape)))
            arrays.append(arr)
        if device is not None:
            import jax

            # the async H2D copies must finish before the caller ACKs —
            # the sender reuses the span after ACK (retention-until-ACK,
            # rdma_endpoint.h:214)
            jax.block_until_ready(arrays)
        return arrays, seq
    # wire path: materialize from attachment bytes
    import numpy as np

    arrays = []
    for t in meta.tensors:
        raw = attachment.cutn_bytes(t.nbytes)
        if device is not None:
            arr = default_block_pool().put_via_pool(
                np.frombuffer(raw, dtype=np.uint8), _np_dtype(t.dtype),
                tuple(t.shape), device)
        else:
            arr = np.frombuffer(raw, dtype=_np_dtype(t.dtype)).reshape(
                tuple(t.shape))
        arrays.append(arr)
    return arrays, seq
