"""Device transport — the ICI endpoint playing brpc's RDMA role.

Counterpart of the RDMA subsystem (SURVEY.md section 2.9,
/root/reference/src/brpc/rdma/):

* DeviceBlockPool ⇔ block_pool.{h,cpp}: pre-registered arenas carved into
  size-class blocks (8KB/64KB/2MB there; byte-capacity HBM buffers here),
  plugged in where IOBuf gets its memory, so payloads are transfer-ready
  without a registration step on the hot path.
* DeviceEndpoint ⇔ RdmaEndpoint (rdma_endpoint.h:55-226): lives inside a
  Socket via the app_connect seam (socket.h:108-130); the TCP connection
  performs the credential handshake (the GID/QPN exchange analog —
  platform, device ids, process identity) through the state machine
  UNINIT→HANDSHAKING→ESTABLISHED, falling back to plain TCP when either
  side has no device (FALLBACK_TCP, rdma_endpoint.h:94-115); sends retain
  source buffers until the peer's ACK (the _sbuf retention discipline,
  rdma_endpoint.h:214), with a sliding window limiting in-flight bytes and
  window updates piggybacked on ACK frames (rdma_endpoint.h:132-138).
* device_helper ⇔ rdma_helper.{h,cpp}: device discovery/identity.

Transfer semantics by locality:
  same process  — zero-copy: the receiving side gets the SAME jax.Array
                  (the loopback-ICI stand-in; on a pod this is an ICI DMA);
  cross process — tensor bytes ride the TCP wire (the FALLBACK_TCP path),
                  re-materialized with jax.device_put on arrival.
"""
from __future__ import annotations

import struct
import threading
import uuid
from typing import Dict, List, Optional, Tuple

from brpc_tpu import bvar
from brpc_tpu.butil.iobuf import IOBuf

# -- device_helper (rdma_helper analog) ------------------------------------

_process_uuid = uuid.uuid4().hex


def local_device_info() -> dict:
    """Discovery: platform + device ids (GID/LID discovery analog)."""
    try:
        import jax

        devs = jax.devices()
        return {
            "process": _process_uuid,
            "platform": devs[0].platform if devs else "none",
            "device_count": len(devs),
        }
    except Exception:
        return {"process": _process_uuid, "platform": "none",
                "device_count": 0}


# -- DeviceBlockPool (block_pool analog) ------------------------------------

_pool_acquired = bvar.Adder("device_block_pool_acquired")
_pool_released = bvar.Adder("device_block_pool_released")


class DeviceBlockPool:
    """Pre-allocated HBM byte-buffers by size class. acquire() hands out a
    registered buffer >= nbytes; release() returns it. The reference carves
    8KB/64KB/2MB blocks out of ibv_reg_mr'd arenas (block_pool.h:29-94)."""

    SIZE_CLASSES = (8 << 10, 64 << 10, 2 << 20)  # block_pool's classes

    def __init__(self, blocks_per_class: int = 8, device=None):
        import jax
        import jax.numpy as jnp

        self._device = device or jax.devices()[0]
        self._free: Dict[int, List] = {}
        self._lock = threading.Lock()
        for size in self.SIZE_CLASSES:
            buffers = []
            for _ in range(blocks_per_class):
                buf = jax.device_put(
                    jnp.zeros((size,), dtype=jnp.uint8), self._device
                )
                buffers.append(buf)
            self._free[size] = buffers

    def acquire(self, nbytes: int):
        """Returns (size_class, buffer) or None if exhausted/oversized."""
        with self._lock:
            for size in self.SIZE_CLASSES:
                if nbytes <= size and self._free[size]:
                    _pool_acquired.update(1)
                    return size, self._free[size].pop()
        return None

    def release(self, size_class: int, buf):
        with self._lock:
            if size_class in self._free:
                self._free[size_class].append(buf)
                _pool_released.update(1)

    def stats(self) -> Dict[int, int]:
        with self._lock:
            return {k: len(v) for k, v in self._free.items()}


_default_pool: Optional[DeviceBlockPool] = None
_default_pool_lock = threading.Lock()


def default_block_pool() -> DeviceBlockPool:
    global _default_pool
    if _default_pool is None:
        with _default_pool_lock:
            if _default_pool is None:
                _default_pool = DeviceBlockPool()
    return _default_pool


# -- in-process tensor exchange (the loopback "ICI") ------------------------

_inproc_registry: Dict[int, List] = {}
_inproc_lock = threading.Lock()
_inproc_next = [1]

_dev_zero_copy = bvar.Adder("device_transport_zero_copy_transfers")
_dev_wire = bvar.Adder("device_transport_wire_transfers")


def inproc_publish(arrays: List) -> int:
    """Register device arrays for same-process zero-copy pickup; returns a
    ticket riding the wire in their place."""
    with _inproc_lock:
        ticket = _inproc_next[0]
        _inproc_next[0] += 1
        _inproc_registry[ticket] = arrays
    return ticket


def inproc_claim(ticket: int) -> Optional[List]:
    with _inproc_lock:
        return _inproc_registry.pop(ticket, None)


# -- DeviceEndpoint (RdmaEndpoint analog) -----------------------------------

# endpoint states (rdma_endpoint.h:94-115)
UNINIT = 0
HANDSHAKING = 1
ESTABLISHED = 2
FALLBACK_TCP = 3

_HANDSHAKE_MAGIC = b"TDEV"
DEFAULT_WINDOW_BYTES = 64 << 20  # in-flight tensor bytes per endpoint


class DeviceEndpoint:
    """Attached to a Socket through app_connect; upgrades the connection
    for tensor transfer."""

    def __init__(self, window_bytes: int = DEFAULT_WINDOW_BYTES):
        self.state = UNINIT
        self.peer_info: dict = {}
        self.window_bytes = window_bytes
        self._inflight = 0
        self._window_cond = threading.Condition()
        # sends retained until ACKed (the _sbuf retention, rdma_endpoint.h:214)
        self._retained: Dict[int, Tuple[List, int]] = {}
        self._next_seq = 1
        self._lock = threading.Lock()

    # ---- handshake over the TCP connection (GID/QPN exchange analog) ----
    def app_connect(self, sock) -> int:
        """Blocking handshake on the freshly-connected socket. On any
        failure the connection falls back to plain TCP rather than dying
        (the FALLBACK_TCP story of rdma.md)."""
        self.state = HANDSHAKING
        # Attach to the socket up-front so even FALLBACK_TCP outcomes leave
        # the endpoint reachable via sock.app_state (window/ACK bookkeeping
        # applies to the wire path too).
        sock.app_state = self
        try:
            import json

            info = json.dumps(local_device_info()).encode()
            frame = _HANDSHAKE_MAGIC + struct.pack(">I", len(info)) + info
            fd = sock.fd()
            fd.setblocking(True)
            fd.sendall(frame)
            header = _recv_exact(fd, 8)
            if header is None or header[:4] != _HANDSHAKE_MAGIC:
                self.state = FALLBACK_TCP
                fd.setblocking(False)
                return 0
            (length,) = struct.unpack(">I", header[4:8])
            peer = _recv_exact(fd, length)
            fd.setblocking(False)
            if peer is None:
                self.state = FALLBACK_TCP
                return 0
            self.peer_info = json.loads(peer)
            mine = local_device_info()
            if (self.peer_info.get("device_count", 0) > 0
                    and mine["device_count"] > 0):
                self.state = ESTABLISHED
            else:
                self.state = FALLBACK_TCP
            return 0
        except OSError:
            self.state = FALLBACK_TCP
            return 0

    @property
    def same_process(self) -> bool:
        return self.peer_info.get("process") == _process_uuid

    # ---- send path ------------------------------------------------------
    def prepare_send(self, arrays: List, meta, attachment: IOBuf,
                     timeout_s: float = 10.0) -> bool:
        """Fill meta.tensors + attachment for `arrays` according to the
        endpoint state; blocks while the send window is full."""
        total = sum(int(a.nbytes) for a in arrays)
        with self._window_cond:
            deadline = None
            import time

            deadline = time.monotonic() + timeout_s
            while self._inflight + total > self.window_bytes:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    return False
                self._window_cond.wait(remain)
            self._inflight += total
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            self._retained[seq] = (arrays, total)
        meta.compress_type = 0
        for a in arrays:
            t = meta.tensors.add()
            t.dtype = str(a.dtype)
            t.shape.extend(int(d) for d in a.shape)
            t.nbytes = int(a.nbytes)
        if self.state == ESTABLISHED and self.same_process:
            # zero-copy: ship a ticket instead of bytes
            ticket = inproc_publish(arrays)
            meta.tensors[0].sharding_spec = f"inproc:{ticket}:{seq}"
            _dev_zero_copy.update(1)
        else:
            import numpy as np

            meta.tensors[0].sharding_spec = f"wire::{seq}"
            for a in arrays:
                attachment.append(np.asarray(a).tobytes())
            _dev_wire.update(1)
        return True

    def on_ack(self, seq: int):
        """Peer confirmed receipt: release retained buffers + open window
        (piggybacked-ACK path, rdma_endpoint.h:132-138)."""
        with self._lock:
            entry = self._retained.pop(seq, None)
        if entry is not None:
            _, total = entry
            with self._window_cond:
                self._inflight = max(0, self._inflight - total)
                self._window_cond.notify_all()

    @property
    def inflight_bytes(self) -> int:
        return self._inflight

    @property
    def retained_count(self) -> int:
        return len(self._retained)


def _recv_exact(fd, n: int) -> Optional[bytes]:
    out = b""
    while len(out) < n:
        chunk = fd.recv(n - len(out))
        if not chunk:
            return None
        out += chunk
    return out


def receive_tensors(meta, attachment: IOBuf, device=None) -> Tuple[List, Optional[int]]:
    """Reconstruct arrays from a tensor-bearing message. Returns
    (arrays, ack_seq). Zero-copy when the sender published in-process."""
    if not meta.tensors:
        return [], None
    spec = meta.tensors[0].sharding_spec or ""
    parts = spec.split(":")
    seq = None
    if len(parts) == 3 and parts[2].isdigit():
        seq = int(parts[2])
    if parts[0] == "inproc" and parts[1].isdigit():
        arrays = inproc_claim(int(parts[1]))
        if arrays is not None:
            return arrays, seq
    # wire path: materialize from attachment bytes
    import numpy as np

    arrays = []
    for t in meta.tensors:
        raw = attachment.cutn_bytes(t.nbytes)
        try:
            dtype = np.dtype(t.dtype)
        except TypeError:
            import ml_dtypes

            dtype = np.dtype(getattr(ml_dtypes, t.dtype))
        arr = np.frombuffer(raw, dtype=dtype).reshape(tuple(t.shape))
        if device is not None:
            import jax

            arr = jax.device_put(arr, device)
        arrays.append(arr)
    return arrays, seq
