"""Native-runtime mount for the Python Server.

The native RPC runtime (native/src/nat_rpc.cpp) owns the port: accept,
epoll, fiber readers, tpu_std framing, and the Socket write queue all run
in C++ on native IOBuf blocks. Requests whose method has no NATIVE handler
are handed to this adapter's pthread pool — the usercode_backup_pool
discipline (details/usercode_backup_pool.h:29-72): Python user code runs on
Python threads, never on fiber stacks — and the full Python server path
(`process_request`: auth, interceptor, MethodStatus, rpcz spans,
compression) executes unchanged, writing its response back through the
native socket via a shim.
"""
from __future__ import annotations

import threading
from typing import Optional

from brpc_tpu import native
from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.rpc.proto import rpc_meta_pb2


class NativeSocketShim:
    """Quacks like rpc.Socket for the server-side response path: write()
    re-enters the native runtime's write queue for this connection. The
    raw fallback lane also runs full protocol sessions over it, so it
    carries the read portal / matched-protocol state the InputMessenger
    expects (protocols attach their own per-connection attributes freely,
    as they do on the real Socket)."""

    def __init__(self, sock_id: int):
        from brpc_tpu.butil.iobuf import IOPortal

        self.sock_id = sock_id
        self.remote_side: Optional[EndPoint] = None
        self.app_state = None
        self._failed = False
        self.read_portal = IOPortal()
        self.matched_protocol = None

    def write(self, buf: IOBuf, id_wait=None) -> int:
        data = buf.copy_to_bytes(len(buf))
        return native.sock_write(self.sock_id, data)

    def set_failed(self, error_code=0, error_text: str = ""):
        self._failed = True
        native.sock_set_failed(self.sock_id)

    def failed(self) -> bool:
        return self._failed

    def fd(self):
        return None


class _NativeHttpShim(NativeSocketShim):
    """Response path for a native-parsed HTTP request (kind 3): the
    serialized response rides nat_http_respond, which preserves pipelined
    request order via the native session's reorder window. Connection:
    close is honored natively (the parse records close-requesting seqs),
    so the ECLOSE set_failed from http_protocol._respond is a no-op here
    — a hard set_failed would race earlier pipelined responses."""

    def __init__(self, sock_id: int, seq: int):
        super().__init__(sock_id)
        self.seq = seq
        # rpcz: when the dispatch armed a server span, the RESPONSE write
        # is the completion point (handlers may respond long after the
        # handler function returned) — end it here with the real status
        self.span = None

    def _end_span(self, data: bytes):
        span, self.span = self.span, None
        if span is None:
            return
        try:
            status = int(data[9:12]) if data[:5] == b"HTTP/" else 0
        except ValueError:
            status = 0
        try:
            span.end(status if status >= 400 else 0)
        except Exception:
            pass

    def write(self, buf, id_wait=None) -> int:
        data = buf.copy_to_bytes(len(buf))
        self._end_span(data)
        return native.http_respond(self.sock_id, self.seq, data)

    def set_failed(self, error_code=0, error_text: str = ""):
        from brpc_tpu.rpc import errors

        self._failed = True
        if error_code == errors.ECLOSE:
            return  # native close_seqs closes after this response flushes
        # a request failed without a response write is exactly what the
        # trace exists to debug: submit the armed span with the error
        span, self.span = self.span, None
        if span is not None:
            try:
                span.end(error_code or 500)
            except Exception:
                pass
        native.sock_set_failed(self.sock_id)


class _StreamSession:
    """Per-connection dispatcher for natively-cut streaming frames
    (kind 5): frames are reassembled by per-socket sequence (py-lane
    pthreads race) and fed straight into the Python Stream objects —
    the ordered-delivery role stream.py gets from process_inline on the
    Python port, without re-parsing framing in Python."""

    FRAME_DATA = 0
    FRAME_FEEDBACK = 1
    FRAME_CLOSE = 2

    def __init__(self, sock_id: int):
        self.sock_id = sock_id
        self.lock = threading.Lock()
        self.pending = {}
        self.next_seq = 1
        self.busy = False

    def feed(self, seq: int, ftype: int, dest_id: int, payload: bytes):
        with self.lock:
            self.pending[seq] = (ftype, dest_id, payload)
            if self.busy:
                return
            self.busy = True
        while True:
            with self.lock:
                item = self.pending.pop(self.next_seq, None)
                if item is None:
                    self.busy = False
                    return
                self.next_seq += 1
            try:
                self._dispatch(*item)
            except Exception:
                import logging

                logging.getLogger(__name__).exception(
                    "native stream frame dispatch raised")

    def _dispatch(self, ftype: int, dest_id: int, payload: bytes):
        import struct

        from brpc_tpu.butil.iobuf import IOBuf
        from brpc_tpu.rpc.stream import Stream

        stream = Stream.find(dest_id)
        if stream is None:
            return  # already closed; drop silently (reference behavior)
        if ftype == self.FRAME_DATA:
            if len(payload) >= 65536:
                buf = IOBuf()  # zero-copy wrap: bytes are immutable
                buf.append_user_data(payload)
            else:
                buf = IOBuf(payload)
            stream._on_data(buf)
        elif ftype == self.FRAME_FEEDBACK:
            (consumed,) = struct.unpack(">Q", payload)
            stream._on_feedback(consumed)
        elif ftype == self.FRAME_CLOSE:
            stream.close(notify_remote=False)


class _RawSession:
    """Per-connection protocol session for the raw fallback lane (the
    native port's multi-protocol capability, input_messenger.h:33-154):
    the native runtime shovels ordered byte chunks; the Python
    InputMessenger cuts and dispatches them exactly as it would from a
    real socket. Chunks may arrive on any py-lane pthread — they are
    reassembled by sequence number and processed by a single drainer at a
    time (busy flag), preserving per-connection ordering."""

    def __init__(self, messenger, sock_id: int):
        self.messenger = messenger
        self.sock = NativeSocketShim(sock_id)
        self.lock = threading.Lock()
        self.chunks = {}
        self.next_seq = 1
        self.busy = False

    def feed(self, seq: int, data: bytes):
        with self.lock:
            self.chunks[seq] = data
            if self.busy:
                return  # the active drainer will pick it up
            self.busy = True
        while True:
            with self.lock:
                got = False
                while self.next_seq in self.chunks:
                    self.sock.read_portal.append(
                        self.chunks.pop(self.next_seq))
                    self.next_seq += 1
                    got = True
                if not got:
                    self.busy = False
                    return
            try:
                self.messenger._cut_and_process(self.sock, read_eof=False)
            except Exception:
                import logging

                logging.getLogger(__name__).exception(
                    "raw-lane protocol session raised")
                self.sock.set_failed()


class NativeRuntimeMount:
    """Runs a Python Server's services on a native port."""

    def __init__(self, server, num_threads: int = 0):
        self.server = server
        self.port = 0
        self._threads = []
        self._stopping = False
        self._num_threads = num_threads or max(2, server.options.num_threads)
        self._messenger = None
        self._raw_sessions = {}
        self._stream_sessions = {}
        self._raw_lock = threading.Lock()

    def start(self, ip: str = "127.0.0.1", port: int = 0,
              native_echo: bool = False) -> int:
        from brpc_tpu.rpc.input_messenger import InputMessenger
        from brpc_tpu.rpc.protocol import list_server_protocols

        self.port = native.rpc_server_start(ip, port,
                                            nworkers=0,
                                            native_echo=native_echo)
        # one pane of glass: the C++ stat cells become bvars (/vars,
        # /status, /brpc_metrics) and native spans drain into /rpcz
        try:
            from brpc_tpu.bvar.native_vars import register_native_bvars

            register_native_bvars()
        except Exception:
            pass
        try:
            import brpc_tpu.rpcz  # noqa: F401  (defines the rpcz flags)
            from brpc_tpu.butil import flags as _flags

            if _flags.get_flag("enable_rpcz"):
                native.stats_enable_spans(
                    max(1, _flags.get_flag("rpcz_sample_every")))
        except Exception:
            pass
        # full protocol registry for the raw fallback lane: the native
        # port keeps the Python port's one-port-all-protocols capability
        protocols = list_server_protocols()
        if self.server.options.enabled_protocols:
            protocols = [p for p in protocols
                         if p.name in self.server.options.enabled_protocols]
        self._messenger = InputMessenger(protocols, arg=self.server)
        native.rpc_server_enable_raw_fallback(True)
        # native HTTP/1.1 + h2/gRPC parse lanes (kind-3/4 requests): parse
        # native, execute Python — only when those protocols are mounted
        if any(p.name in ("http", "h2:grpc") for p in protocols):
            try:
                native.rpc_server_native_http(True)
            except AttributeError:
                pass  # older .so without the lane
        # native Redis lane (kind-6): RESP parsed in C++, commands run in
        # the Python RedisService — or, with native_redis_store, the
        # GET/SET family executes against a C++ in-memory store and only
        # unknown commands reach Python
        if self.server.redis_service is not None:
            try:
                native.rpc_server_redis(
                    2 if getattr(self.server.options,
                                 "native_redis_store", False) else 1)
            except AttributeError:
                pass
        # TLS on the native port (ServerSSLOptions role)
        if self.server.options.ssl_certfile:
            rc = native.rpc_server_ssl(self.server.options.ssl_certfile,
                                       self.server.options.ssl_keyfile)
            if rc != 0:
                native.rpc_server_stop()
                raise RuntimeError(
                    f"native TLS unavailable (rc={rc}): libssl missing or "
                    f"bad cert/key")
        for i in range(self._num_threads):
            t = threading.Thread(target=self._worker,
                                 name=f"native_py_lane_{i}", daemon=True)
            t.start()
            self._threads.append(t)
        # usercode worker processes (shm lane): kind-3/4 dispatch fans
        # out across N interpreters; the in-process lane keeps serving
        # every other kind (and is the overflow path when rings fill)
        opts = self.server.options
        if getattr(opts, "py_workers", 0) > 0 and \
                getattr(opts, "py_worker_factory", ""):
            self._start_shm_workers(opts.py_workers, opts.py_worker_factory)
        return self.port

    def _start_shm_workers(self, n: int, factory: str):
        import os
        import subprocess
        import sys

        lib = native.load()
        # the descriptor-ring lane pre-carves per-worker rings/arenas:
        # slots are bounded (asked of the library, not hand-mirrored) and
        # extra workers would fail attach and exit silently — clamp loudly
        max_workers = lib.nat_shm_lane_max_workers()
        if n > max_workers:
            import logging

            logging.getLogger("brpc_tpu.native").warning(
                "py_workers=%d exceeds the shm lane's %d worker slots; "
                "clamping", n, max_workers)
            n = max_workers
        if lib.nat_shm_lane_create(0) != 0:
            raise RuntimeError("shm lane creation failed")
        name = lib.nat_shm_lane_name().decode()
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + \
            env.get("PYTHONPATH", "")
        self._shm_workers = [
            subprocess.Popen([sys.executable, "-m",
                              "brpc_tpu.rpc.shm_worker", name, factory],
                             env=env, cwd=repo_root)
            for _ in range(n)
        ]
        # readiness barrier BEFORE the lane routes any request: a fresh
        # interpreter + .so load takes seconds on a loaded host, and
        # early requests would otherwise sit in the ring against the
        # reap deadline. A worker that dies at boot only lowers the
        # attach target (the rest still count).
        import time as _time

        deadline = _time.time() + 30
        while _time.time() < deadline:
            alive = sum(1 for p in self._shm_workers if p.poll() is None)
            if lib.nat_shm_lane_workers() >= max(alive, 1) or alive == 0:
                break
            _time.sleep(0.1)
        lib.nat_shm_lane_enable(1)

    def stop(self, quiesce_timeout_ms: int = 0):
        # Graceful quiesce FIRST, while the py lane and the shm workers
        # are still serving: stop accepting, lame-duck every connection,
        # drain admitted work (incl. shm-worker in-flight) under the
        # deadline, reject new arrivals on the wire. Only then tear the
        # serving machinery down.
        if quiesce_timeout_ms > 0:
            try:
                native.load().nat_server_quiesce(quiesce_timeout_ms)
            except Exception:
                pass  # older .so without the export: abrupt stop
        self._stopping = True
        workers = getattr(self, "_shm_workers", None)
        if workers:
            try:
                native.load().nat_shm_lane_enable(0)
            except Exception:
                pass
            for p in workers:
                p.terminate()
            for p in workers:
                try:
                    p.wait(timeout=3)
                except Exception:
                    p.kill()
            self._shm_workers = []
        native.rpc_server_stop()
        for t in self._threads:
            t.join(timeout=2.0)
        with self._raw_lock:
            self._raw_sessions.clear()

    # -- the py lane --------------------------------------------------------
    def _worker(self):
        from brpc_tpu.rpc.tpu_std_protocol import RpcMessage, process_request

        while not self._stopping:
            items = native.take_requests(16, 100)
            if not items:
                continue
            for item in items:
                self._dispatch_one(item)

    def _dispatch_one(self, item):
        from brpc_tpu.rpc.tpu_std_protocol import RpcMessage, process_request

        (handle, kind, meta_bytes, payload, attachment, sock_id, seq,
         f0, f1, aux) = item
        if kind == 5:  # native-cut streaming frame
            ftype = f0  # frame type rides in the tuple (zero-copy big
            # payloads hand their handle to a GC finalizer: handle=None)
            if handle is not None:
                native.req_free(handle)
            with self._raw_lock:
                sess = self._stream_sessions.get(sock_id)
                if sess is None:
                    sess = _StreamSession(sock_id)
                    self._stream_sessions[sock_id] = sess
            sess.feed(seq, ftype, aux, payload)
            return
        if kind == 3:  # native-parsed HTTP request
            native.req_free(handle)
            self._handle_http(f0, f1, meta_bytes, payload, sock_id, seq)
            return
        if kind == 4:  # native-parsed gRPC-over-h2 request
            native.req_free(handle)
            self._handle_grpc(f1, meta_bytes, payload, sock_id, seq)
            return
        if kind == 6:  # native-parsed RESP command
            native.req_free(handle)
            self._handle_redis(payload, sock_id, seq)
            return
        if kind == 1:  # raw protocol bytes
            native.req_free(handle)
            with self._raw_lock:
                sess = self._raw_sessions.get(sock_id)
                if sess is None:
                    sess = _RawSession(self._messenger, sock_id)
                    self._raw_sessions[sock_id] = sess
            sess.feed(seq, payload)
            return
        if kind == 2:  # connection closed: drop the sessions
            native.req_free(handle)
            with self._raw_lock:
                self._raw_sessions.pop(sock_id, None)
                self._stream_sessions.pop(sock_id, None)
            return
        if True:
            try:
                meta = rpc_meta_pb2.RpcMeta()
                meta.ParseFromString(meta_bytes)
                att = IOBuf()
                if attachment:
                    att.append(attachment)
                msg = RpcMessage(meta, payload, att)
                msg.socket = NativeSocketShim(sock_id)
                msg.arg = self.server
                process_request(msg)
            except Exception as e:  # answer rather than drop
                try:
                    native.respond(handle, 2001, f"py-lane dispatch: {e}")
                    handle = None
                except Exception:
                    pass
            finally:
                if handle is not None:
                    native.req_free(handle)

    def _handle_grpc(self, path: bytes, flat_headers: bytes, data: bytes,
                     sock_id: int, sid: int):
        """kind-4 dispatch: the native h2 session decoded HEADERS (HPACK)
        and buffered the gRPC-framed body; run the same method dispatch as
        the Python h2 stack (_dispatch_server_request semantics) and
        answer through the native response framer."""
        import time as _time

        from brpc_tpu.rpc import errors
        from brpc_tpu.rpc.controller import Controller
        from brpc_tpu.rpc.h2_protocol import (
            GRPC_INTERNAL,
            GRPC_NOT_FOUND,
            GRPC_OK,
            GRPC_RESOURCE_EXHAUSTED,
            GRPC_UNIMPLEMENTED,
            _parse_grpc_timeout,
            error_to_grpc_status,
            grpc_unwrap,
        )

        def respond(payload=b"", status=GRPC_OK, message=""):
            native.grpc_respond(sock_id, sid, payload, status, message)

        try:
            server = self.server
            pstr = path.decode("latin-1")
            parts = [p for p in pstr.split("/") if p]
            if len(parts) != 2:
                return respond(b"", GRPC_UNIMPLEMENTED, f"bad path {pstr}")
            entry = server.find_method(parts[0], parts[1])
            if entry is None:
                missing = server.find_service(parts[0]) is None
                return respond(
                    b"", GRPC_NOT_FOUND if missing else GRPC_UNIMPLEMENTED,
                    f"unknown method {pstr}")
            service_obj, minfo, method_status = entry
            headers = {}
            for line in flat_headers.decode("latin-1").split("\n"):
                if line:
                    k, _, v = line.partition(": ")
                    headers[k] = v
            # rpcz: chain this dispatch under the caller's span when the
            # request carried x-bd-trace-* gRPC metadata (the native
            # client lane stamps it; values hex)
            span = None
            try:
                tid = headers.get("x-bd-trace-id")
                if tid:
                    from brpc_tpu import rpcz as _rpcz

                    span = _rpcz.Span(
                        "server", f"grpc {pstr}", trace_id=int(tid, 16),
                        parent_span_id=int(
                            headers.get("x-bd-span-id") or "0", 16))
            except Exception:
                span = None
            cntl = Controller()
            cntl.server = server
            cntl.service_name, cntl.method_name = parts[0], parts[1]
            cntl.server_start_time = _time.monotonic()
            timeout = headers.get("grpc-timeout")
            if timeout:
                cntl.timeout_ms = _parse_grpc_timeout(timeout)
            if not method_status.on_requested():
                return respond(b"", GRPC_RESOURCE_EXHAUSTED,
                               "reached max_concurrency")
            request = minfo.request_class()
            body = grpc_unwrap(data)
            try:
                if body:
                    request.ParseFromString(body)
            except Exception as e:
                method_status.on_response(errors.EREQUEST,
                                          cntl.server_start_time)
                return respond(b"", GRPC_INTERNAL,
                               f"fail to parse request: {e}")
            response = minfo.response_class()
            responded = [False]

            def done():
                if responded[0]:
                    return
                responded[0] = True
                method_status.on_response(cntl.error_code_value,
                                          cntl.server_start_time)
                if cntl.failed():
                    respond(b"",
                            error_to_grpc_status(cntl.error_code_value),
                            cntl.error_text_value)
                else:
                    respond(response.SerializeToString(), GRPC_OK)
                # the span ends when the CALL completes (done may fire
                # from another thread long after the handler returned —
                # the async-done contract tpu_std_protocol documents),
                # so latency/error reflect the real completion
                if span is not None:
                    try:
                        span.end(cntl.error_code_value)
                    except Exception:
                        pass

            try:
                if span is not None:
                    from brpc_tpu import rpcz as _rpcz

                    with _rpcz.parent_scope(span):
                        minfo.handler(service_obj, cntl, request, response,
                                      done)
                else:
                    minfo.handler(service_obj, cntl, request, response,
                                  done)
            except Exception as e:
                if not responded[0]:
                    cntl.set_failed(errors.EINVAL, f"method raised: {e}")
                    done()
        except Exception as e:
            respond(b"", GRPC_INTERNAL, f"py-lane grpc dispatch: {e}")

    def _handle_redis(self, packed: bytes, sock_id: int, seq: int):
        """kind-6 dispatch: argv was RESP-parsed natively (count +
        (len,bytes)* packing); run the Python RedisService handler and
        answer through the native reorder window (nat_redis_respond)."""
        import struct as _struct

        from brpc_tpu.rpc.redis import RedisReply

        try:
            (count,) = _struct.unpack_from(">I", packed, 0)
            pos = 4
            args = []
            for _ in range(count):
                (n,) = _struct.unpack_from(">I", packed, pos)
                pos += 4
                args.append(packed[pos:pos + n])
                pos += n
            service = getattr(self.server, "redis_service", None)
            if service is None:
                reply = RedisReply.error("ERR no redis service")
            else:
                reply = service.dispatch(args)
        except Exception as e:
            reply = RedisReply.error(f"ERR dispatch raised: {e}")
        try:
            encoded = reply.encode()
        except Exception as e:
            # e.g. a handler returned a plain str: the seq MUST still be
            # answered or the ordered window wedges the connection
            encoded = RedisReply.error(f"ERR bad reply object: {e}").encode()
        try:
            native.redis_respond(sock_id, seq, encoded)
        except Exception:
            pass  # socket already gone; the session dies with it

    def _handle_http(self, verb: bytes, uri: bytes, flat_headers: bytes,
                     body: bytes, sock_id: int, seq: int):
        """kind-3 dispatch: rebuild the HttpRequest from natively-parsed
        fields and run the unchanged Python HTTP server path (routing,
        RESTful map, builtin console, RPC-over-HTTP). Ordering across
        pipelined requests is native-side, so workers may process
        same-connection requests concurrently."""
        from brpc_tpu.butil.iobuf import IOBuf as _IOBuf
        from brpc_tpu.rpc.http_message import HttpRequest
        from brpc_tpu.rpc.http_protocol import (
            HttpInputMessage,
            process_request as http_process_request,
        )

        span = None
        shim = None
        try:
            req = HttpRequest(verb.decode("latin-1"), uri.decode("latin-1"))
            hd = req.headers._headers
            for line in flat_headers.decode("latin-1").split("\n"):
                if line:
                    k, _, v = line.partition(": ")
                    hd[k] = v  # keys pre-lowercased natively
            if body:
                req.body = _IOBuf(body)
            msg = HttpInputMessage(req)
            shim = _NativeHttpShim(sock_id, seq)
            msg.socket = shim
            msg.arg = self.server
            # rpcz: chain under the caller's span when the request carried
            # x-bd-trace-* headers (hex; stamped by the native client lane)
            try:
                tid = hd.get("x-bd-trace-id")
                if tid:
                    from brpc_tpu import rpcz as _rpcz

                    span = _rpcz.Span(
                        "server",
                        f"{verb.decode('latin-1')} {uri.decode('latin-1')}",
                        trace_id=int(tid, 16),
                        parent_span_id=int(hd.get("x-bd-span-id") or "0",
                                           16))
            except Exception:
                span = None
            if span is not None:
                from brpc_tpu import rpcz as _rpcz

                # armed on the shim: the span ends at the RESPONSE write
                # (handlers may respond asynchronously long after this
                # function returns — ending at handler-return would
                # record phantom latency/status for them)
                shim.span = span
                with _rpcz.parent_scope(span):
                    http_process_request(msg)
            else:
                http_process_request(msg)
        except Exception as e:
            # the dispatch itself blew up before a response reached the
            # shim: the span must still submit — a failing request is
            # exactly what the trace exists to debug
            if shim is not None and shim.span is not None:
                shim.span = None
                try:
                    span.end(500)
                except Exception:
                    pass
            body = f"{e}\n".encode()
            resp = (f"HTTP/1.1 500 Internal Server Error\r\n"
                    f"Content-Length: {len(body)}\r\n\r\n").encode() + body
            try:
                native.http_respond(sock_id, seq, resp)
            except Exception:
                pass
