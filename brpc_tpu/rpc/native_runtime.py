"""Native-runtime mount for the Python Server.

The native RPC runtime (native/src/nat_rpc.cpp) owns the port: accept,
epoll, fiber readers, tpu_std framing, and the Socket write queue all run
in C++ on native IOBuf blocks. Requests whose method has no NATIVE handler
are handed to this adapter's pthread pool — the usercode_backup_pool
discipline (details/usercode_backup_pool.h:29-72): Python user code runs on
Python threads, never on fiber stacks — and the full Python server path
(`process_request`: auth, interceptor, MethodStatus, rpcz spans,
compression) executes unchanged, writing its response back through the
native socket via a shim.
"""
from __future__ import annotations

import threading
from typing import Optional

from brpc_tpu import native
from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.rpc.proto import rpc_meta_pb2


class NativeSocketShim:
    """Quacks like rpc.Socket for the server-side response path: write()
    re-enters the native runtime's write queue for this connection."""

    def __init__(self, sock_id: int):
        self.sock_id = sock_id
        self.remote_side: Optional[EndPoint] = None
        self.app_state = None
        self._failed = False

    def write(self, buf: IOBuf, id_wait=None) -> int:
        data = buf.copy_to_bytes(len(buf))
        return native.sock_write(self.sock_id, data)

    def set_failed(self, error_code=0, error_text: str = ""):
        self._failed = True
        native.sock_set_failed(self.sock_id)

    def failed(self) -> bool:
        return self._failed

    def fd(self):
        return None


class NativeRuntimeMount:
    """Runs a Python Server's services on a native port."""

    def __init__(self, server, num_threads: int = 0):
        self.server = server
        self.port = 0
        self._threads = []
        self._stopping = False
        self._num_threads = num_threads or max(2, server.options.num_threads)

    def start(self, ip: str = "127.0.0.1", port: int = 0,
              native_echo: bool = False) -> int:
        self.port = native.rpc_server_start(ip, port,
                                            nworkers=0,
                                            native_echo=native_echo)
        for i in range(self._num_threads):
            t = threading.Thread(target=self._worker,
                                 name=f"native_py_lane_{i}", daemon=True)
            t.start()
            self._threads.append(t)
        return self.port

    def stop(self):
        self._stopping = True
        native.rpc_server_stop()
        for t in self._threads:
            t.join(timeout=2.0)

    # -- the py lane --------------------------------------------------------
    def _worker(self):
        from brpc_tpu.rpc.tpu_std_protocol import RpcMessage, process_request

        while not self._stopping:
            item = native.take_request(100)
            if item is None:
                continue
            handle, meta_bytes, payload, attachment, sock_id = item
            try:
                meta = rpc_meta_pb2.RpcMeta()
                meta.ParseFromString(meta_bytes)
                att = IOBuf()
                if attachment:
                    att.append(attachment)
                msg = RpcMessage(meta, payload, att)
                msg.socket = NativeSocketShim(sock_id)
                msg.arg = self.server
                process_request(msg)
            except Exception as e:  # answer rather than drop
                try:
                    native.respond(handle, 2001, f"py-lane dispatch: {e}")
                    handle = None
                except Exception:
                    pass
            finally:
                if handle is not None:
                    native.req_free(handle)
