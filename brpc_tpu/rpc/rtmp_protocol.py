"""RTMP — the media-streaming protocol family, server side.

Counterpart of /root/reference/src/brpc/policy/rtmp_protocol.cpp (+
rtmp.{h,cpp}, amf.{h,cpp}): the simple (non-digest) handshake
(C0C1/S0S1S2/C2, rtmp_protocol.cpp's HandshakeState role), the chunk
stream layer (basic header fmt 0-3, per-csid message assembly, extended
timestamps, SetChunkSize both directions), protocol control messages
(WindowAckSize/SetPeerBW/Ack/UserControl ping-pong), AMF0 command
dispatch (connect, createStream, releaseStream/FCPublish tolerance,
publish, play, deleteStream), and a publish->play relay service
(RtmpService role) that caches metadata + AVC/AAC sequence headers for
late-joining players, exactly what a stock player needs to start
rendering mid-stream.

Server-only and gated on ServerOptions.rtmp_service (the ParseRtmpMessage
TRY_OTHERS-when-no-service discipline) — on an opted-in server the same
port keeps answering every other protocol. FLV muxing lives in
brpc_tpu/rpc/flv.py (tags are these messages' payloads verbatim).
"""
from __future__ import annotations

import os
import struct
import threading
from typing import Dict, List, Optional

from brpc_tpu import bvar
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.rpc import amf
from brpc_tpu.rpc.protocol import (
    InputMessageBase,
    ParseResult,
    Protocol,
    ProtocolType,
    register_protocol,
)

HANDSHAKE_SIZE = 1536
DEFAULT_IN_CHUNK = 128   # spec default until the peer says otherwise
OUT_CHUNK = 4096

MSG_SET_CHUNK_SIZE = 1
MSG_ABORT = 2
MSG_ACK = 3
MSG_USER_CONTROL = 4
MSG_WINDOW_ACK_SIZE = 5
MSG_SET_PEER_BW = 6
MSG_AUDIO = 8
MSG_VIDEO = 9
MSG_DATA_AMF0 = 18
MSG_COMMAND_AMF0 = 20

UC_STREAM_BEGIN = 0
UC_PING = 6
UC_PONG = 7

_rtmp_sessions = bvar.Adder("rtmp_sessions")
_rtmp_messages = bvar.Adder("rtmp_messages")
_rtmp_relayed = bvar.Adder("rtmp_relayed_messages")


class RtmpMessage(InputMessageBase):
    """Placeholder message: RTMP is handled inside parse (the protocol is
    stateful and conversational); the cut loop only counts progress."""
    __slots__ = ("is_request",)

    def __init__(self):
        super().__init__()
        self.is_request = True


# ---------------------------------------------------------------------------
# Relay service (the RtmpService / default server role)
# ---------------------------------------------------------------------------

class _LiveStream:
    def __init__(self, name: str):
        self.name = name
        self.publisher: Optional["RtmpSession"] = None
        self.players: List[RtmpSession] = []
        self.metadata: Optional[bytes] = None       # AMF0 onMetaData
        self.avc_seq_header: Optional[bytes] = None  # video config tag
        self.aac_seq_header: Optional[bytes] = None  # audio config tag


class RtmpService:
    """In-memory publish->play relay hub (the DefaultRtmpServer shape):
    one publisher per stream name, any number of players; metadata and
    codec sequence headers are cached and replayed to late joiners."""

    # a player this far behind is shed rather than buffered further
    MAX_PLAYER_BACKLOG = 8 << 20

    def __init__(self):
        self._streams: Dict[str, _LiveStream] = {}
        self._lock = threading.Lock()

    def _stream_locked(self, name: str) -> _LiveStream:
        # caller holds self._lock (get-or-create and mutation must share
        # ONE acquisition, or drop()'s reaping can orphan the object)
        st = self._streams.get(name)
        if st is None:
            st = _LiveStream(name)
            self._streams[name] = st
        return st

    def on_publish(self, name: str, sess: "RtmpSession") -> bool:
        with self._lock:
            st = self._stream_locked(name)
            cur = st.publisher
            if cur is not None and cur is not sess:
                # a dead publisher's socket releases the name (the
                # health-of-the-holder check brpc's RtmpService does)
                alive = not getattr(cur.sock, "failed", lambda: False)()
                if alive:
                    return False  # one LIVE publisher per name
            st.publisher = sess
        return True

    def release_publisher(self, name: str, sess: "RtmpSession"):
        """Frees the name (FCUnpublish / re-publish of another name) so
        other publishers can take it while this session lives."""
        with self._lock:
            st = self._streams.get(name)
            if st is not None and st.publisher is sess:
                st.publisher = None
                if not st.players:
                    del self._streams[name]

    def on_play(self, name: str, sess: "RtmpSession"):
        """Registers the player AND sends the cached priming messages
        (metadata + codec sequence headers) inside the same critical
        section — a concurrent relay can therefore never deliver a live
        frame ahead of the headers a decoder needs. A re-issued play
        moves the player, never duplicates it."""
        with self._lock:
            st = self._stream_locked(name)
            for other in self._streams.values():
                if other is not st and sess in other.players:
                    other.players.remove(sess)
            if sess not in st.players:
                st.players.append(sess)
            if st.metadata is not None:
                sess.send_message(MSG_DATA_AMF0, 0, st.metadata,
                                  stream_id=1)
            if st.avc_seq_header is not None:
                sess.send_message(MSG_VIDEO, 0, st.avc_seq_header,
                                  stream_id=1)
            if st.aac_seq_header is not None:
                sess.send_message(MSG_AUDIO, 0, st.aac_seq_header,
                                  stream_id=1)

    def on_media(self, name: str, msg_type: int, ts: int, payload: bytes):
        with self._lock:
            st = self._stream_locked(name)
            # cache what a late joiner needs (rtmp.cpp's header caching)
            if msg_type == MSG_DATA_AMF0:
                st.metadata = payload
            elif (msg_type == MSG_VIDEO and len(payload) >= 2
                    and (payload[0] & 0x0F) == 7 and payload[1] == 0):
                st.avc_seq_header = payload  # AVC sequence header
            elif (msg_type == MSG_AUDIO and len(payload) >= 2
                    and (payload[0] >> 4) == 10 and payload[1] == 0):
                st.aac_seq_header = payload  # AAC sequence header
            players = list(st.players)
        for p in players:
            try:
                if getattr(p.sock, "failed", lambda: False)():
                    self.drop(p)  # EOF'd player: sockets report failure
                    continue      # by flag, not by raising
                # Backpressure (the reference's write-overflow shedding for
                # media streams): a stalled player's queue would otherwise
                # grow without bound while the publisher keeps pushing —
                # one slow consumer must not exhaust the relay's memory.
                backlog = getattr(p.sock, "write_backlog_bytes",
                                  lambda: 0)()
                if backlog > self.MAX_PLAYER_BACKLOG:
                    p.sock.set_failed()
                    self.drop(p)
                    continue
                p.send_message(msg_type, ts, payload, stream_id=1)
                _rtmp_relayed.update(1)
            except Exception:
                self.drop(p)

    def drop(self, sess: "RtmpSession"):
        with self._lock:
            dead = []
            for name, st in self._streams.items():
                if st.publisher is sess:
                    st.publisher = None
                if sess in st.players:
                    st.players.remove(sess)
                if st.publisher is None and not st.players:
                    dead.append(name)  # reap: unbounded-name hygiene
            for name in dead:
                del self._streams[name]

    def stream_names(self) -> List[str]:
        with self._lock:
            return sorted(self._streams)


# ---------------------------------------------------------------------------
# Per-connection session: handshake + chunk stream state machine
# ---------------------------------------------------------------------------

class _CsidState:
    __slots__ = ("timestamp", "length", "msg_type", "stream_id", "delta",
                 "has_ext_ts", "buf")

    def __init__(self):
        self.timestamp = 0
        self.length = 0
        self.msg_type = 0
        self.stream_id = 0
        self.delta = 0
        self.has_ext_ts = False  # fmt3 chunks re-read the ext timestamp
        self.buf = bytearray()


class RtmpSession:
    ST_WAIT_C0C1 = 0
    ST_WAIT_C2 = 1
    ST_ESTABLISHED = 2

    def __init__(self, sock, service: RtmpService):
        self.sock = sock
        self.service = service
        self.state = self.ST_WAIT_C0C1
        self.in_chunk = DEFAULT_IN_CHUNK
        self.out_chunk = OUT_CHUNK
        self.csid_state: Dict[int, _CsidState] = {}
        self.publishing: Optional[str] = None
        self.playing: Optional[str] = None
        # accumulate-consume-trim input buffer (server parse + client
        # feed share it): bytes enter exactly once, leftovers persist
        self.pending = bytearray()
        self._wlock = threading.Lock()  # relay writers vs command replies
        _rtmp_sessions.update(1)

    # -- outbound ----------------------------------------------------------
    def _write(self, data: bytes):
        buf = IOBuf()
        buf.append(data)
        self.sock.write(buf)

    def send_message(self, msg_type: int, ts: int, payload: bytes,
                     stream_id: int = 0, csid: int = 3):
        """Chunk one message: fmt0 first, fmt3 continuations."""
        ts = ts & 0xFFFFFFFF
        out = bytearray()
        header_ts = min(ts, 0xFFFFFF)
        out.append((0 << 6) | csid)  # fmt0, one-byte basic header (csid<64)
        out += struct.pack(">I", header_ts)[1:]
        out += struct.pack(">I", len(payload))[1:]
        out.append(msg_type)
        out += struct.pack("<I", stream_id)
        if header_ts == 0xFFFFFF:
            out += struct.pack(">I", ts)
        pos = 0
        first = True
        while pos < len(payload) or first:
            if not first:
                out.append((3 << 6) | csid)  # fmt3 continuation
                if header_ts == 0xFFFFFF:
                    out += struct.pack(">I", ts)
            take = min(self.out_chunk, len(payload) - pos)
            out += payload[pos:pos + take]
            pos += take
            first = False
        with self._wlock:
            self._write(bytes(out))

    def send_command(self, *values, stream_id: int = 0, csid: int = 3):
        self.send_message(MSG_COMMAND_AMF0, 0, amf.encode_many(*values),
                          stream_id=stream_id, csid=csid)

    def _send_control(self, msg_type: int, payload: bytes):
        self.send_message(msg_type, 0, payload, stream_id=0, csid=2)

    def send_onstatus(self, code: str, level: str = "status",
                      stream_id: int = 1):
        self.send_command("onStatus", 0.0, None,
                          {"level": level, "code": code,
                           "description": code},
                          stream_id=stream_id, csid=5)

    # -- inbound -----------------------------------------------------------
    def feed_bytes(self, data: bytes) -> bool:
        """Append new bytes and consume what's complete; True when any
        handshake/chunk unit was processed."""
        self.pending += data
        used = self.consume(self.pending)
        if used:
            del self.pending[:used]
        return used > 0

    def consume(self, data: bytearray) -> int:
        """Eats as many complete handshake/chunk units as possible from
        the front of `data`; returns bytes consumed. Raises on protocol
        error (caller fails the connection)."""
        used = 0
        while True:
            n = self._consume_one(data, used)
            if n == 0:
                return used
            used += n

    def _consume_one(self, data: bytearray, pos: int) -> int:
        avail = len(data) - pos
        if self.state == self.ST_WAIT_C0C1:
            if avail < 1 + HANDSHAKE_SIZE:
                return 0
            if data[pos] != 3:
                raise ValueError("rtmp: unsupported handshake version")
            c1 = bytes(data[pos + 1:pos + 1 + HANDSHAKE_SIZE])
            # Digest handshake (policy/rtmp_protocol.cpp:149 role): a
            # nonzero version field means the client (OBS/Flash) expects
            # the server to prove itself with the Media-Server key and
            # chain S2 from C1's digest; a plain C1 gets the simple echo.
            found = None
            if c1[4:8] != b"\x00\x00\x00\x00":
                from brpc_tpu.rpc import rtmp_client as rc

                found = rc.find_digest(c1, rc.FP_KEY)
            if found is not None:
                scheme, c1_digest = found
                s1, _ = rc.make_digest_s1(scheme)
                s2 = rc.make_chained_reply(c1_digest, rc.FMS_KEY_FULL)
                self._write(bytes([3]) + s1 + s2)
            else:
                s1 = c1[:8] + os.urandom(HANDSHAKE_SIZE - 8)
                # S0 + S1 + S2(echo of C1) in one write
                self._write(bytes([3]) + s1 + c1)
            self.state = self.ST_WAIT_C2
            return 1 + HANDSHAKE_SIZE
        if self.state == self.ST_WAIT_C2:
            if avail < HANDSHAKE_SIZE:
                return 0
            self.state = self.ST_ESTABLISHED
            return HANDSHAKE_SIZE
        return self._consume_chunk(data, pos)

    def _consume_chunk(self, data: bytearray, pos: int) -> int:
        start = pos
        avail = len(data)
        if pos >= avail:
            return 0
        b0 = data[pos]
        fmt = b0 >> 6
        csid = b0 & 0x3F
        pos += 1
        if csid == 0:
            if pos >= avail:
                return 0
            csid = 64 + data[pos]
            pos += 1
        elif csid == 1:
            if pos + 2 > avail:
                return 0
            csid = 64 + data[pos] + (data[pos + 1] << 8)
            pos += 2
        st = self.csid_state.get(csid)
        if st is None:
            st = self.csid_state[csid] = _CsidState()
        need = (11, 7, 3, 0)[fmt]
        if pos + need > avail:
            return 0
        ts_field = None
        if fmt == 0:
            ts_field = int.from_bytes(data[pos:pos + 3], "big")
            st.length = int.from_bytes(data[pos + 3:pos + 6], "big")
            st.msg_type = data[pos + 6]
            st.stream_id = int.from_bytes(data[pos + 7:pos + 11], "little")
            pos += 11
        elif fmt == 1:
            ts_field = int.from_bytes(data[pos:pos + 3], "big")
            st.length = int.from_bytes(data[pos + 3:pos + 6], "big")
            st.msg_type = data[pos + 6]
            st.delta = ts_field
            pos += 7
        elif fmt == 2:
            ts_field = int.from_bytes(data[pos:pos + 3], "big")
            st.delta = ts_field
            pos += 3
        if ts_field is not None:
            st.has_ext_ts = ts_field == 0xFFFFFF
        # fmt3 chunks of a message whose header used the extended
        # timestamp carry the 4-byte ext field again (spec §5.3.1.3)
        ext = 0
        if st.has_ext_ts:
            if pos + 4 > avail:
                return 0
            ext = int.from_bytes(data[pos:pos + 4], "big")
            pos += 4
        if st.length > (64 << 20):
            raise ValueError("rtmp: message too large")
        continuation = fmt == 3 and len(st.buf) > 0
        if not continuation and len(st.buf) > 0:
            # a fresh header on a csid with an unfinished message is a
            # protocol violation (and would drive `remaining` negative)
            raise ValueError("rtmp: new message before finishing the "
                             "previous one on this chunk stream")
        new_ts = st.timestamp
        if not continuation:
            # a fresh chunk advances the timestamp (fmt3 repeats the
            # previous header: same delta applies again)
            if fmt == 0:
                new_ts = ext if st.has_ext_ts else ts_field
            else:
                new_ts = st.timestamp + (ext if st.has_ext_ts else st.delta)
        remaining = st.length - len(st.buf)
        take = min(self.in_chunk, remaining)
        if pos + take > avail:
            return 0  # incomplete: NO state committed — a reparse after
                      # more bytes arrive must not double-advance the ts
        st.timestamp = new_ts
        if fmt == 0 and not continuation:
            # spec 5.3.1.2.4 / reference rtmp_protocol.cpp:1457: fmt0's
            # absolute timestamp becomes the delta a following fmt3
            # NEW message advances by
            st.delta = ext if st.has_ext_ts else ts_field
        st.buf += data[pos:pos + take]
        pos += take
        if len(st.buf) >= st.length:
            body = bytes(st.buf)
            st.buf = bytearray()
            self._on_message(st.msg_type, st.stream_id, st.timestamp, body)
        return pos - start

    # -- message dispatch --------------------------------------------------
    def _on_message(self, msg_type: int, stream_id: int, ts: int,
                    payload: bytes):
        _rtmp_messages.update(1)
        if msg_type == MSG_SET_CHUNK_SIZE and len(payload) >= 4:
            size = struct.unpack(">I", payload[:4])[0] & 0x7FFFFFFF
            if not 1 <= size <= (16 << 20):
                raise ValueError("rtmp: bad chunk size")
            self.in_chunk = size
        elif msg_type == MSG_USER_CONTROL and len(payload) >= 2:
            event = struct.unpack(">H", payload[:2])[0]
            if event == UC_PING:
                self._send_control(MSG_USER_CONTROL,
                                   struct.pack(">H", UC_PONG) + payload[2:])
        elif msg_type == MSG_ABORT and len(payload) >= 4:
            # spec 5.4.2: discard the partially-assembled message
            csid = struct.unpack(">I", payload[:4])[0]
            stx = self.csid_state.get(csid)
            if stx is not None:
                stx.buf = bytearray()
        elif msg_type in (MSG_WINDOW_ACK_SIZE, MSG_SET_PEER_BW, MSG_ACK):
            pass  # flow-control bookkeeping we don't need to act on
        elif msg_type == MSG_COMMAND_AMF0:
            self._on_command(stream_id, payload)
        elif msg_type in (MSG_AUDIO, MSG_VIDEO, MSG_DATA_AMF0):
            if self.publishing is not None:
                self.service.on_media(self.publishing, msg_type, ts,
                                      payload)

    def _on_command(self, stream_id: int, payload: bytes):
        try:
            values = amf.decode_all(payload)
        except amf.AmfError as e:
            raise ValueError(f"rtmp: bad AMF0 command: {e}")
        if not values or not isinstance(values[0], str):
            return
        cmd = values[0]
        txn = values[1] if len(values) > 1 else 0.0
        if cmd == "connect":
            self._send_control(MSG_WINDOW_ACK_SIZE,
                               struct.pack(">I", 2500000))
            self._send_control(MSG_SET_PEER_BW,
                               struct.pack(">IB", 2500000, 2))
            self._send_control(MSG_SET_CHUNK_SIZE,
                               struct.pack(">I", self.out_chunk))
            self.send_command(
                "_result", txn,
                {"fmsVer": "FMS/3,5,3,888", "capabilities": 127.0},
                {"level": "status", "code": "NetConnection.Connect.Success",
                 "description": "Connection succeeded.",
                 "objectEncoding": 0.0})
        elif cmd == "createStream":
            self.send_command("_result", txn, None, 1.0)
        elif cmd in ("releaseStream", "FCPublish", "FCUnpublish",
                     "getStreamLength"):
            uname = values[3] if len(values) > 3 else None
            if isinstance(uname, str):
                uname = uname.split("?")[0]
            if (cmd == "FCUnpublish" and self.publishing is not None
                    and (uname is None or uname == self.publishing)):
                # a mismatched name (stale FCUnpublish mid-switch) must
                # NOT tear down the live stream
                self.service.release_publisher(self.publishing, self)
                self.publishing = None
            self.send_command("_result", txn, None, None)
        elif cmd == "publish":
            name = values[3] if len(values) > 3 else ""
            if not isinstance(name, str) or not name:
                raise ValueError("rtmp: publish without a stream name")
            name = name.split("?")[0]
            if not self.service.on_publish(name, self):
                self.send_onstatus("NetStream.Publish.BadName",
                                   level="error")
                return  # keep publishing the OLD name; nothing released
            if self.publishing is not None and self.publishing != name:
                # release only after the new claim succeeded, and forget
                # the old name so media can't route to a freed stream
                self.service.release_publisher(self.publishing, self)
            self.publishing = name
            self.send_onstatus("NetStream.Publish.Start")
        elif cmd == "play":
            name = values[3] if len(values) > 3 else ""
            if not isinstance(name, str) or not name:
                raise ValueError("rtmp: play without a stream name")
            name = name.split("?")[0]
            self._send_control(
                MSG_USER_CONTROL,
                struct.pack(">HI", UC_STREAM_BEGIN, 1))
            self.send_onstatus("NetStream.Play.Reset")
            self.send_onstatus("NetStream.Play.Start")
            self.playing = name
            self.service.on_play(name, self)
        elif cmd in ("deleteStream", "closeStream"):
            self.close()

    def close(self):
        self.service.drop(self)
        self.publishing = None
        self.playing = None


# ---------------------------------------------------------------------------
# Client-mode session (the minimal librtmp role: tests/examples use it as
# their publisher/player stand-in)
# ---------------------------------------------------------------------------

class _ClientWire:
    def __init__(self, conn):
        self.conn = conn

    def write(self, buf, id_wait=None):
        self.conn.sendall(buf.copy_to_bytes(len(buf)))
        return 0

    def failed(self):
        return False


class RtmpClientSession(RtmpSession):
    """The same chunk machinery in client mode: inbound messages are
    collected in `inbox` instead of being dispatched as server commands;
    the peer's SetChunkSize is honored automatically."""

    def __init__(self, conn):
        super().__init__(_ClientWire(conn), RtmpService())
        self.conn = conn
        self.state = self.ST_ESTABLISHED
        self.inbox: List[tuple] = []

    def _on_message(self, msg_type, stream_id, ts, payload):
        if msg_type == MSG_SET_CHUNK_SIZE and len(payload) >= 4:
            self.in_chunk = struct.unpack(">I", payload[:4])[0]
            return
        self.inbox.append((msg_type, ts, payload))

    def feed(self, data: bytes):
        self.feed_bytes(data)

    def pump(self, want: int = 1, timeout: float = 5.0):
        """Reads the socket until `want` messages are buffered."""
        import socket as pysocket
        import time as _time

        self.conn.settimeout(0.2)
        deadline = _time.monotonic() + timeout
        while len(self.inbox) < want and _time.monotonic() < deadline:
            try:
                data = self.conn.recv(65536)
            except (TimeoutError, pysocket.timeout):
                continue
            if not data:
                break
            self.feed(data)
        return self.inbox

    def pump_until(self, pred, timeout: float = 5.0):
        """Reads until pred(self) is true (robust against arbitrary
        recv segmentation, unlike fixed message counts)."""
        import time as _time

        deadline = _time.monotonic() + timeout
        while not pred(self) and _time.monotonic() < deadline:
            self.pump(want=len(self.inbox) + 1, timeout=0.3)
        return pred(self)

    def commands(self):
        return [amf.decode_all(p) for t, _, p in self.inbox
                if t == MSG_COMMAND_AMF0]


def rtmp_client_connect(host: str, port: int, app: str = "live"):
    """Dial + simple handshake + connect; returns
    (socket, RtmpClientSession) ready for createStream/publish/play."""
    import socket as pysocket
    import time as _time

    conn = pysocket.create_connection((host, port), timeout=5)
    c1 = struct.pack(">II", 0, 0) + os.urandom(HANDSHAKE_SIZE - 8)
    conn.sendall(bytes([3]) + c1)
    buf = b""
    while len(buf) < 1 + 2 * HANDSHAKE_SIZE:
        chunk = conn.recv(65536)
        if not chunk:
            raise ConnectionError("rtmp: server hung up in handshake")
        buf += chunk
    if buf[0] != 3 or buf[1 + HANDSHAKE_SIZE:1 + 2 * HANDSHAKE_SIZE] != c1:
        raise ConnectionError("rtmp: bad handshake reply")
    conn.sendall(buf[1:1 + HANDSHAKE_SIZE])  # C2 echoes S1
    sess = RtmpClientSession(conn)
    sess.feed(buf[1 + 2 * HANDSHAKE_SIZE:])
    sess.send_command("connect", 1.0, {"app": app, "flashVer": "brpc_tpu"})
    deadline = _time.monotonic() + 5
    while _time.monotonic() < deadline:
        if any(c and c[0] == "_result" for c in sess.commands()):
            break
        sess.pump(want=len(sess.inbox) + 1, timeout=0.5)
    results = [c for c in sess.commands() if c[0] == "_result"]
    if not results or results[0][3].get("code") != \
            "NetConnection.Connect.Success":
        raise ConnectionError("rtmp: connect refused")
    sess.inbox.clear()
    # chunk sizes are per-direction: announce ours before big sends
    sess._send_control(MSG_SET_CHUNK_SIZE, struct.pack(">I", OUT_CHUNK))
    return conn, sess


# ---------------------------------------------------------------------------
# Protocol registration
# ---------------------------------------------------------------------------

def parse(portal: IOBuf, sock, read_eof: bool, arg) -> ParseResult:
    service = getattr(getattr(arg, "options", None), "rtmp_service", None)
    if service is None:
        return ParseResult.try_others()
    sess: Optional[RtmpSession] = getattr(sock, "rtmp_session", None)
    if sess is None:
        if len(portal) < 1:
            return ParseResult.not_enough()
        if portal.copy_to_bytes(1)[0] != 3:
            return ParseResult.try_others()
        # claim the connection: RTMP speaks first with exactly 0x03
        sess = RtmpSession(sock, service)
        sock.rtmp_session = sess
    # drain the portal into the session ONCE per byte (re-copying the
    # whole accumulating buffer per parse would be quadratic on large
    # messages); leftovers persist in sess.pending between reads
    n = len(portal)
    data = portal.copy_to_bytes(n) if n else b""
    if n:
        portal.pop_front(n)
    try:
        progressed = sess.feed_bytes(data)
    except ValueError:
        sess.close()
        return ParseResult.error_()
    if not progressed:
        return ParseResult.not_enough()
    return ParseResult.ok(RtmpMessage())


register_protocol(Protocol(
    name="rtmp",
    type=ProtocolType.RTMP,
    parse=parse,
    process_request=None,  # conversation handled inside parse
    process_response=None,
    process_inline=True,
))
