"""Tensor transport service — RPC-carried device arrays.

The rdma_performance-shaped surface (SURVEY.md section 2.9 +
example/rdma_performance/): a TensorStore service accepts pushed tensors
and serves pulls; tensors ride the tpu_std attachment described by
RpcMeta.tensors, zero-copy in process (the loopback-ICI stand-in) and as
bytes across processes (FALLBACK_TCP path), via
brpc_tpu.rpc.device_transport.

Server-side handshake counterpart: the TDEV protocol below answers the
DeviceEndpoint.app_connect handshake on accepted connections, so both ends
of a connection know each other's device identity (the server half of the
GID/QPN exchange, rdma_endpoint.cpp).
"""
from __future__ import annotations

import json
import struct
import threading
from typing import Callable, Dict, List, Optional

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.device_transport import (
    DeviceEndpoint,
    local_device_info,
    receive_tensors,
)
from brpc_tpu.rpc.protocol import (
    InputMessageBase,
    ParseResult,
    Protocol,
    ProtocolType,
    register_protocol,
)
from brpc_tpu.rpc.proto import tensor_service_pb2 as ts_pb2
from brpc_tpu.rpc.service import Service, rpc_method

_HANDSHAKE_MAGIC = b"TDEV"


class _HandshakeMsg(InputMessageBase):
    __slots__ = ("info", "is_request")

    def __init__(self, info: dict):
        super().__init__()
        self.info = info
        self.is_request = True


def _parse_handshake(portal: IOBuf, sock, read_eof: bool, arg) -> ParseResult:
    if len(portal) < 8:
        head = portal.copy_to_bytes(min(4, len(portal)))
        if _HANDSHAKE_MAGIC.startswith(head):
            return ParseResult.not_enough()
        return ParseResult.try_others()
    header = portal.copy_to_bytes(8)
    if header[:4] != _HANDSHAKE_MAGIC:
        return ParseResult.try_others()
    (length,) = struct.unpack(">I", header[4:8])
    if length > 1 << 20:
        return ParseResult.error_()
    if len(portal) < 8 + length:
        return ParseResult.not_enough()
    portal.pop_front(8)
    try:
        info = json.loads(portal.cutn_bytes(length))
    except ValueError:
        return ParseResult.error_()
    return ParseResult.ok(_HandshakeMsg(info))


def _process_handshake(msg: _HandshakeMsg):
    """Server half of the device handshake: answer with our identity and
    attach an ESTABLISHED/FALLBACK endpoint to the connection. The
    server arms the descriptor-ring tensor fabric and advertises its
    segment name, so same-host clients push payloads straight into our
    blob arena (the ring lane) with zero bytes on the wire."""
    sock = msg.socket
    ep = DeviceEndpoint()
    ep.peer_info = msg.info
    mine = local_device_info(arm_fabric=True)
    from brpc_tpu.rpc import device_transport as dt

    if msg.info.get("device_count", 0) > 0 and mine["device_count"] > 0:
        ep.state = dt.ESTABLISHED
        if msg.info.get("xfer"):
            try:
                fd = sock.fd()
                if fd is not None:
                    ep.resolve_xfer_addr(fd.getsockname()[0])
            except OSError:
                pass
    else:
        ep.state = dt.FALLBACK_TCP
    sock.app_state = ep
    info = json.dumps(mine).encode()
    out = IOBuf()
    out.append(_HANDSHAKE_MAGIC + struct.pack(">I", len(info)) + info)
    sock.write(out)


register_protocol(Protocol(
    name="device_handshake",
    type=ProtocolType.TENSOR,
    parse=_parse_handshake,
    process_request=_process_handshake,
    process_inline=True,
    support_client=False,
))


# -- the store service ------------------------------------------------------

class TensorStoreService(Service):
    """In-memory named tensor store — push/pull over RPC."""

    SERVICE_NAME = "TensorStore"

    def __init__(self, on_push: Optional[Callable[[str, List], None]] = None):
        self._store: Dict[str, List] = {}
        self._lock = threading.Lock()
        self._on_push = on_push

    @rpc_method(ts_pb2.TensorPushRequest, ts_pb2.TensorPushResponse)
    def Push(self, cntl, request, response, done):
        meta = getattr(cntl, "_rpc_meta", None)
        if meta is None or not meta.tensors:
            cntl.set_failed(errors.EREQUEST, "no tensors in request")
            done()
            return
        arrays, seq = receive_tensors(meta, cntl.request_attachment)
        with self._lock:
            self._store[request.name] = arrays
        if self._on_push is not None:
            try:
                self._on_push(request.name, arrays)
            except Exception:
                pass
        response.ok = True
        response.ack_seq = seq or 0
        done()

    @rpc_method(ts_pb2.TensorPullRequest, ts_pb2.TensorPullResponse)
    def Pull(self, cntl, request, response, done):
        with self._lock:
            arrays = self._store.get(request.name)
        if arrays is None:
            response.found = False
            done()
            return
        response.found = True
        meta = cntl._response_meta
        if meta is not None:
            ep = (cntl._server_socket.app_state
                  if cntl._server_socket is not None else None)
            if not isinstance(ep, DeviceEndpoint):
                ep = DeviceEndpoint()
            ep.prepare_send(arrays, meta, cntl.response_attachment)
        done()

    @rpc_method(ts_pb2.TensorAckRequest, ts_pb2.TensorAckResponse)
    def Ack(self, cntl, request, response, done):
        """Explicit ACK frame for pull transfers (the non-piggybacked ACK
        of rdma_endpoint.h:222-226): releases the connection endpoint's
        retained buffers/arena spans."""
        ep = (cntl._server_socket.app_state
              if cntl._server_socket is not None else None)
        if isinstance(ep, DeviceEndpoint) and request.seq:
            ep.on_ack(request.seq)
        response.ok = True
        done()

    def get(self, name: str) -> Optional[List]:
        with self._lock:
            return self._store.get(name)


class TensorClient:
    """Client-side helper: push/pull arrays through a channel whose sockets
    carry a DeviceEndpoint."""

    def __init__(self, channel):
        self.channel = channel

    def push(self, name: str, arrays: List, timeout_ms: float = 10000):
        from brpc_tpu.rpc.controller import Controller

        cntl = Controller()
        cntl.timeout_ms = timeout_ms
        cntl._outbound_tensors = arrays
        response = ts_pb2.TensorPushResponse()
        self.channel.call_method(
            "TensorStore.Push", cntl,
            ts_pb2.TensorPushRequest(name=name), response,
        )
        if not cntl.failed() and cntl._current_sock is not None:
            ep = cntl._current_sock.app_state
            if isinstance(ep, DeviceEndpoint) and response.ack_seq:
                ep.on_ack(response.ack_seq)
        return cntl, response

    def pull(self, name: str, timeout_ms: float = 10000, device=None):
        from brpc_tpu.rpc.controller import Controller

        cntl = Controller()
        cntl.timeout_ms = timeout_ms
        response = ts_pb2.TensorPullResponse()
        self.channel.call_method(
            "TensorStore.Pull", cntl,
            ts_pb2.TensorPullRequest(name=name), response,
        )
        if cntl.failed() or not response.found:
            return cntl, None
        meta = getattr(cntl, "_response_rpc_meta", None)
        if meta is None:
            return cntl, None
        arrays, seq = receive_tensors(meta, cntl.response_attachment,
                                      device=device)
        if seq:
            # explicit ACK so the server frees its retained span/window
            ack_cntl = Controller()
            ack_cntl.timeout_ms = timeout_ms
            self.channel.call_method(
                "TensorStore.Ack", ack_cntl,
                ts_pb2.TensorAckRequest(seq=seq),
                ts_pb2.TensorAckResponse(),
            )
        return cntl, arrays


def make_device_channel(target, options=None):
    """A Channel whose connections handshake the device transport — sugar
    for ChannelOptions(use_device_transport=True), the use_rdma analog
    (channel.h:41-89)."""
    import dataclasses

    from brpc_tpu.rpc.channel import Channel, ChannelOptions

    options = (dataclasses.replace(options, use_device_transport=True)
               if options is not None
               else ChannelOptions(use_device_transport=True))
    ch = Channel(options)
    rc = ch.init(target)
    if rc != 0:
        return None
    return ch
