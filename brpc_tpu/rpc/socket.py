"""Socket — THE connection abstraction of the RPC layer.

Counterpart of brpc::Socket (/root/reference/src/brpc/socket.{h,cpp}):

* versioned 64-bit SocketId addressing into a ResourcePool, so a stale id
  can never touch a recycled connection (socket_inl.h:28-185);
* a write path shaped like the wait-free design of socket.h:293-333 — any
  thread appends to the write queue; exactly one becomes the writer, tries
  one inline write on its own thread, and hands leftovers to a KeepWrite
  scheduler task that waits for EPOLLOUT;
* SetFailed + health-check revival (socket.h:438-441,
  details/health_check.cpp:70-237): in-flight correlation ids registered on
  the socket are errored with EFAILEDSOCKET, and a timer probes the remote
  side until the socket revives;
* an app-level connect hook (`app_connect`, the AppConnect seam of
  socket.h:108-130) — the pluggable-transport seam where the device/ICI
  endpoint attaches, exactly where brpc's RDMA endpoint attaches.
"""
from __future__ import annotations

import socket as pysocket
import threading
import time
from collections import deque
from typing import Callable, Optional

from brpc_tpu import bvar
from brpc_tpu.bthread import start_background, timer_add
from brpc_tpu.bthread import id as bthread_id
from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.butil.iobuf import IOBuf, IOPortal
from brpc_tpu.butil.pools import ResourcePool
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.event_dispatcher import get_global_dispatcher

_in_bytes = bvar.Adder("socket_in_bytes")
_out_bytes = bvar.Adder("socket_out_bytes")
_conn_count = bvar.Adder("socket_connection_count")


class SocketUser:
    """Owner hook — health checking override (socket.h:74-88)."""

    def before_recycle(self, sock: "Socket"):
        pass

    def check_health(self, sock: "Socket") -> bool:
        """Return True if the remote is healthy again (default: TCP probe)."""
        try:
            probe = pysocket.create_connection(
                (sock.remote_side.ip, sock.remote_side.port), timeout=1.0
            )
            probe.close()
            return True
        except OSError:
            return False

    def on_revived(self, sock: "Socket"):
        pass


class _WriteRequest:
    __slots__ = ("buf", "id_wait")

    def __init__(self, buf: IOBuf, id_wait: Optional[int]):
        self.buf = buf
        self.id_wait = id_wait


class Socket:
    _pool: ResourcePool = None
    _pool_lock = threading.Lock()
    # attribute names a freshly-reset Socket owns; anything beyond these
    # is protocol-attached dynamic state (h2 connections, pipelined-
    # correlation queues, parked esp/nova cids, mongo contexts, ...) and
    # must be cleared on revive()/recycling — stale protocol state on a
    # fresh TCP connection corrupts the stream. Captured automatically
    # from the first reset, so protocols can never forget to register.
    _core_attrs: "frozenset[str]" = None

    def __init__(self):
        self._reset()

    def _reset(self):
        self._clear_protocol_state()  # recycled objects keep attributes
        self._fd: Optional[pysocket.socket] = None
        self._sid: int = 0
        self.remote_side: Optional[EndPoint] = None
        self.local_side: Optional[EndPoint] = None
        self._failed = False
        self.error_code = 0
        self.error_text = ""
        self._write_q: deque = deque()
        self._write_lock = threading.Lock()
        self._connect_lock = threading.Lock()
        self._writing = False
        self._epollout = threading.Event()
        self._reading = False
        self._reading_lock = threading.Lock()
        # Lame duck (graceful server churn): the peer signaled it is
        # draining — in-flight RPCs keep completing here, but selection
        # (LB _usable, the single-connection reuse paths) must send NEW
        # calls elsewhere, and the eventual close is a PLANNED removal
        # (no circuit-breaker sample). Cleared by revive/_reset.
        self.lame_duck = False
        self.on_edge_triggered_events: Optional[Callable[["Socket"], None]] = None
        self.user: Optional[SocketUser] = None
        self.health_check_interval_s: float = -1
        self._hc_running = False
        self.read_portal = IOPortal()
        self.matched_protocol = None  # remembered by InputMessenger
        self._inflight_ids = set()  # correlation ids to fail on SetFailed
        self._inflight_lock = threading.Lock()
        self.connection_type = "single"
        self._conn_ready = False  # fd usable for RPC (post-handshake)
        self.app_connect = None  # AppConnect seam (device transport attaches)
        self.on_connected = None  # protocol-pin hook, runs pre-registration
        self.app_state = None  # transport-private state (e.g. DeviceEndpoint)
        self.ssl_context = None  # client TLS context (ChannelSSLOptions)
        self.conn_data = None  # owner context (e.g. pooled-socket home)
        self.create_time = time.monotonic()
        if Socket._core_attrs is None:
            Socket._core_attrs = frozenset(self.__dict__.keys())

    # -- pool & id ---------------------------------------------------------
    @classmethod
    def _get_pool(cls) -> ResourcePool:
        if cls._pool is None:
            with cls._pool_lock:
                if cls._pool is None:
                    cls._pool = ResourcePool(Socket)
        return cls._pool

    @classmethod
    def create(cls, fd: Optional[pysocket.socket] = None,
               remote_side: Optional[EndPoint] = None,
               on_edge_triggered_events=None,
               user: Optional[SocketUser] = None,
               health_check_interval_s: float = -1,
               app_connect=None, ssl_context=None) -> int:
        """Returns a SocketId; Socket.address(sid) resolves it (or None once
        recycled)."""
        sid, sock = cls._get_pool().get_resource()
        sock._reset()
        sock._sid = sid
        sock._fd = fd
        sock.remote_side = remote_side
        sock.on_edge_triggered_events = on_edge_triggered_events
        sock.user = user
        sock.health_check_interval_s = health_check_interval_s
        sock.app_connect = app_connect
        sock.ssl_context = ssl_context
        _conn_count.update(1)
        if fd is not None:
            fd.setblocking(False)
            sock._conn_ready = True
            sock._register_with_dispatcher()
        return sid

    @classmethod
    def address(cls, sid: int) -> Optional["Socket"]:
        """Version-validated id lookup (socket_inl.h:28-185 Address): None
        once the socket is recycled. A SetFailed socket is still
        addressable — failure is a separate state callers check with
        .failed(), exactly as in the reference (health check and error
        reporting need to reach failed-but-live sockets)."""
        return cls._get_pool().address(sid)

    @property
    def socket_id(self) -> int:
        return self._sid

    def fd(self) -> Optional[pysocket.socket]:
        return self._fd

    def failed(self) -> bool:
        return self._failed

    def mark_lame_duck(self):
        """The peer signaled graceful drain (tpu_std SHUTDOWN bit, h2
        GOAWAY, HTTP Connection: close): finish in-flight work on this
        connection, route new work elsewhere. Idempotent; NOT a failure
        — in-flight correlation ids stay registered and complete."""
        self.lame_duck = True

    def usable_for_new_calls(self) -> bool:
        """Healthy AND not draining: the selection predicate the LB and
        the single-connection reuse paths share."""
        return not self._failed and not self.lame_duck

    # -- connect -----------------------------------------------------------
    def connect(self, timeout_s: float = 1.0) -> int:
        """Client-side TCP connect (blocking in the caller's task, as a
        bthread-mode connect would); then the AppConnect hook upgrades the
        transport (RDMA handshake analog)."""
        try:
            fd = pysocket.create_connection(
                (self.remote_side.ip, self.remote_side.port), timeout=timeout_s
            )
        except OSError as e:
            return e.errno or errors.EFAILEDSOCKET
        fd.setsockopt(pysocket.IPPROTO_TCP, pysocket.TCP_NODELAY, 1)
        if self.ssl_context is not None:
            try:
                fd.settimeout(timeout_s)
                fd = self.ssl_context.wrap_socket(
                    fd, server_hostname=self.remote_side.ip)
            except OSError as e:
                try:
                    fd.close()
                except OSError:
                    pass
                return errors.ESSL if not e.errno else e.errno
        fd.setblocking(False)
        self._fd = fd
        try:
            host, port = fd.getsockname()[:2]
            self.local_side = EndPoint(host, port)
        except OSError:
            pass
        # AppConnect runs BEFORE dispatcher registration so the handshake
        # owns the connection's first bytes (the RDMA TCP-handshake order,
        # rdma_endpoint.h:94-115).
        if self.app_connect is not None:
            rc = self.app_connect(self)
            if rc != 0:
                self.set_failed(rc, "app connect failed")
                return rc
        # Protocol-pinning hook, ALSO pre-registration: a speaks-first
        # peer (h2 servers send SETTINGS immediately) must find the
        # client-side protocol state attached before the dispatcher can
        # deliver its first bytes. A hook failure is a failed connect.
        if self.on_connected is not None:
            try:
                self.on_connected(self)
            except Exception as e:
                self.set_failed(errors.EFAILEDSOCKET,
                                f"on_connected hook failed: {e}")
                return errors.EFAILEDSOCKET
        self._register_with_dispatcher()
        self._conn_ready = True
        return 0

    def ensure_connected(self, timeout_s: float = 1.0) -> int:
        """Lazy connect for sockets created unconnected (NS-created LB
        nodes); thread-safe connect-once: the connect lock is held across
        the whole dial so racing callers wait instead of double-dialing.
        The lock-free fast path keys on _conn_ready, which connect()
        publishes only AFTER the app-level handshake — a racing caller must
        not write RPC bytes into a handshake in progress."""
        if self._conn_ready:
            return 0
        with self._connect_lock:
            if self._conn_ready:
                return 0
            if self._failed:
                return self.error_code or errors.EFAILEDSOCKET
            return self.connect(timeout_s)

    def _register_with_dispatcher(self):
        fdno = self._fd.fileno()
        get_global_dispatcher(fdno).add_consumer(fdno, self.start_input_event)

    # -- read entry --------------------------------------------------------
    def start_input_event(self):
        """Dispatcher callback (Socket::StartInputEvent, socket.cpp:2312):
        start one reader task unless one is already draining this socket.
        The fd's read events are suspended while the reader runs (edge
        trigger + re-arm, as the reference's EPOLLET delivers)."""
        with self._reading_lock:
            if self._reading or self._failed:
                return
            self._reading = True
        handler = self.on_edge_triggered_events
        if handler is None:
            with self._reading_lock:
                self._reading = False
            return
        fd = self._fd
        fdno = fd.fileno() if fd is not None else -1
        if fdno >= 0:
            get_global_dispatcher(fdno).suspend_read(fdno)
        start_background(self._run_input_handler, handler, fdno)

    def _run_input_handler(self, handler, fdno: int):
        try:
            handler(self)
        finally:
            with self._reading_lock:
                self._reading = False
            if fdno >= 0 and not self._failed:
                get_global_dispatcher(fdno).resume_read(fdno)

    # -- write path --------------------------------------------------------
    def write_backlog_bytes(self) -> int:
        """Bytes queued but not yet written — the write-overflow signal
        media relays use to shed slow consumers (socket.h backlog role)."""
        with self._write_lock:
            return sum(len(r.buf) for r in self._write_q)

    def write(self, buf: IOBuf, id_wait: Optional[int] = None,
              on_queued: Optional[Callable[[], None]] = None) -> int:
        """Queue a whole message; never interleaves with other writers
        (socket.h:293-333 semantics). `on_queued` runs under the queue lock
        at append time, so per-connection ordered state (pipelined
        correlation entries, as PipelinedInfo is pushed inside
        Socket::Write in the reference) matches the wire order exactly."""
        if id_wait is not None:
            with self._inflight_lock:
                self._inflight_ids.add(id_wait)
        req = _WriteRequest(buf, id_wait)
        with self._write_lock:
            # Re-check failure under the lock: a concurrent set_failed has
            # either drained the queue already (we must not append after
            # it) or will drain our request after we append.
            if self._failed:
                # Only notify if set_failed's in-flight sweep did not
                # already error this cid (double-error would look like two
                # failed attempts to the retry machinery).
                with self._inflight_lock:
                    was_present = id_wait in self._inflight_ids
                    self._inflight_ids.discard(id_wait)
                if was_present:
                    self._notify_failure(id_wait)
                return errors.EFAILEDSOCKET
            self._write_q.append(req)
            if on_queued is not None:
                on_queued()
            if self._writing:
                return 0  # current writer will flush us
            self._writing = True
        # We are the writer: one inline attempt on this thread, then hand
        # off to a KeepWrite task (socket.cpp:1287-1305,1585).
        if not self._flush_some():
            start_background(self._keep_write)
        return 0

    def _flush_some(self) -> bool:
        """Write until drained (True) or would-block (False)."""
        while True:
            with self._write_lock:
                if not self._write_q:
                    self._writing = False
                    return True
                req = self._write_q[0]
            fd = self._fd
            if fd is None:
                # Concurrently failed; set_failed drains the queue. Step
                # down as writer so a revived socket can elect a new one.
                with self._write_lock:
                    self._writing = False
                return True
            try:
                n = req.buf.cut_into_socket(fd)
            except (BlockingIOError, InterruptedError):
                return False
            except OSError as e:
                self.set_failed(e.errno or errors.EFAILEDSOCKET,
                                f"write failed: {e}")
                return True
            if n > 0:
                _out_bytes.update(n)
            if req.buf.empty():
                with self._write_lock:
                    if self._write_q and self._write_q[0] is req:
                        self._write_q.popleft()
            elif n == 0:
                return False

    def _keep_write(self):
        fdno = self._fd.fileno() if self._fd else -1
        while not self._failed:
            self._epollout.clear()
            if self._flush_some():
                return
            if self._failed or self._fd is None:
                return
            get_global_dispatcher(fdno).add_epollout(fdno, self._epollout.set)
            self._epollout.wait(timeout=1.0)

    # -- failure & revival -------------------------------------------------
    def set_failed(self, error_code: int = errors.EFAILEDSOCKET,
                   error_text: str = "") -> bool:
        with self._write_lock:
            if self._failed:
                return False
            self._failed = True
            self._conn_ready = False
        self.error_code = error_code
        self.error_text = error_text
        fd = self._fd
        if fd is not None:
            closed = False
            try:
                fdno = fd.fileno()
                if fdno >= 0:
                    # unregister AND close on the loop thread, ordered:
                    # a caller-side close would let the fd number be
                    # reused under the selector / the stale queued
                    # remove (the accept-vs-teardown race class)
                    get_global_dispatcher(fdno).remove_and_close(fdno, fd)
                    closed = True
            except OSError:
                pass
            if not closed:
                try:
                    fd.close()
                except OSError:
                    pass
            self._fd = None
        self._epollout.set()  # unblock KeepWrite
        # Fail queued writes and in-flight RPCs (socket.cpp SetFailed path).
        with self._write_lock:
            pending = list(self._write_q)
            self._write_q.clear()
        for req in pending:
            self._notify_failure(req.id_wait)
        with self._inflight_lock:
            inflight, self._inflight_ids = list(self._inflight_ids), set()
        for cid in inflight:
            bthread_id.error(cid, error_code, error_text or "socket failed")
        if self.health_check_interval_s > 0:
            self._start_health_check()
        return True

    def _notify_failure(self, id_wait: Optional[int]):
        if id_wait is not None:
            bthread_id.error(id_wait, self.error_code or errors.EFAILEDSOCKET,
                             self.error_text or "socket failed")

    def remove_inflight(self, cid: int):
        with self._inflight_lock:
            self._inflight_ids.discard(cid)

    def _start_health_check(self):
        if self.remote_side is None:
            return
        with self._write_lock:
            if self._hc_running:
                return
            self._hc_running = True
        timer_add(self.health_check_interval_s, self._health_check_once)

    def _health_check_once(self):
        user = self.user or _default_user
        try:
            healthy = user.check_health(self)
        except Exception:
            healthy = False
        if healthy:
            rc = self.revive()
            if rc == 0:
                with self._write_lock:
                    self._hc_running = False
                    failed_again = self._failed
                if failed_again:
                    # a set_failed that ran inside the revive window saw
                    # _hc_running still True and skipped scheduling — its
                    # failure is ours to cover, or the socket stays dead
                    # with no checker (seen as a rare no-revival hang in
                    # the churn test)
                    self._start_health_check()
                else:
                    user.on_revived(self)
                return
            # probe said healthy but the reconnect failed (transient):
            # keep the checker alive instead of abandoning the socket
        timer_add(self.health_check_interval_s, self._health_check_once)

    def revive(self) -> int:
        """Reconnect and clear the failed state (Socket::Revive role).

        Holds the connect lock across reset+dial: _reset_keep_identity
        clears _failed, and from that instant an ensure_connected caller
        would otherwise dial CONCURRENTLY — two fds, with _fd ending on
        one while the dispatcher delivers responses for the other (seen
        as a revived-but-deaf socket in the churn test)."""
        with self._connect_lock:
            if self._conn_ready and not self._failed:
                return 0  # a racing dial already revived it
            self._reset_keep_identity()
            rc = self.connect()
            if rc != 0:
                self._failed = True
                return rc
            return 0

    def _reset_keep_identity(self):
        self._failed = False
        self.lame_duck = False  # a revived connection serves new calls
        self.error_code = 0
        self.error_text = ""
        self.read_portal = IOPortal()
        self.matched_protocol = None
        self._epollout = threading.Event()
        self._writing = False
        self._conn_ready = False
        self._clear_protocol_state()

    def _clear_protocol_state(self):
        core = Socket._core_attrs
        if core is None:
            return
        for name in [n for n in self.__dict__ if n not in core]:
            del self.__dict__[name]

    def recycle(self):
        """Return to the pool — all outstanding SocketIds become stale."""
        if self.user:
            self.user.before_recycle(self)
        if not self._failed:
            self.set_failed(errors.ECLOSE, "recycled")
        self.health_check_interval_s = -1
        _conn_count.update(-1)
        Socket._get_pool().return_resource(self._sid)

    def __repr__(self):
        state = "failed" if self._failed else "ok"
        return f"Socket(id={self._sid:#x}, remote={self.remote_side}, {state})"


_default_user = SocketUser()
