"""Thrift framed binary protocol — counterpart of brpc's thrift support
(/root/reference/src/brpc/policy/thrift_protocol.cpp,
details/thrift_message.{h,cpp}): TBinaryProtocol codec over 4-byte frames,
a ThriftStub-style client and a server-side ThriftService dispatching by
method name. Structs are represented as {field_id: (ttype, value)} dicts —
schema-light, like brpc's pass-through thrift_binary_message, but fully
decoded.
"""
from __future__ import annotations

import struct
import threading
from typing import Callable, Dict, Tuple

VERSION_1 = 0x80010000

MSG_CALL = 1
MSG_REPLY = 2
MSG_EXCEPTION = 3
MSG_ONEWAY = 4

T_STOP = 0
T_BOOL = 2
T_BYTE = 3
T_DOUBLE = 4
T_I16 = 6
T_I32 = 8
T_I64 = 10
T_STRING = 11
T_STRUCT = 12
T_LIST = 15

# struct value := {field_id: (ttype, python_value)}
ThriftStruct = Dict[int, Tuple[int, object]]


class _Writer:
    def __init__(self):
        self._parts = []

    def write(self, b: bytes):
        self._parts.append(b)

    def i8(self, v):
        self.write(struct.pack(">b", v))

    def i16(self, v):
        self.write(struct.pack(">h", v))

    def i32(self, v):
        self.write(struct.pack(">i", v))

    def u32(self, v):
        self.write(struct.pack(">I", v & 0xFFFFFFFF))

    def i64(self, v):
        self.write(struct.pack(">q", v))

    def double(self, v):
        self.write(struct.pack(">d", v))

    def string(self, v):
        raw = v.encode() if isinstance(v, str) else bytes(v)
        self.i32(len(raw))
        self.write(raw)

    def bytes(self) -> bytes:
        return b"".join(self._parts)


class _Reader:
    def __init__(self, data: bytes, pos: int = 0):
        self.data = data
        self.pos = pos

    def take(self, n) -> bytes:
        out = self.data[self.pos:self.pos + n]
        if len(out) < n:
            raise EOFError("truncated thrift payload")
        self.pos += n
        return out

    def i8(self):
        return struct.unpack(">b", self.take(1))[0]

    def i16(self):
        return struct.unpack(">h", self.take(2))[0]

    def i32(self):
        return struct.unpack(">i", self.take(4))[0]

    def u32(self):
        return struct.unpack(">I", self.take(4))[0]

    def i64(self):
        return struct.unpack(">q", self.take(8))[0]

    def double(self):
        return struct.unpack(">d", self.take(8))[0]

    def string(self) -> bytes:
        return self.take(self.i32())


def _write_value(w: _Writer, ttype: int, value):
    if ttype == T_BOOL:
        w.i8(1 if value else 0)
    elif ttype == T_BYTE:
        w.i8(value)
    elif ttype == T_DOUBLE:
        w.double(value)
    elif ttype == T_I16:
        w.i16(value)
    elif ttype == T_I32:
        w.i32(value)
    elif ttype == T_I64:
        w.i64(value)
    elif ttype == T_STRING:
        w.string(value)
    elif ttype == T_STRUCT:
        write_struct(w, value)
    elif ttype == T_LIST:
        etype, items = value
        w.i8(etype)
        w.i32(len(items))
        for item in items:
            _write_value(w, etype, item)
    else:
        raise ValueError(f"unsupported thrift type {ttype}")


def _read_value(r: _Reader, ttype: int):
    if ttype == T_BOOL:
        return bool(r.i8())
    if ttype == T_BYTE:
        return r.i8()
    if ttype == T_DOUBLE:
        return r.double()
    if ttype == T_I16:
        return r.i16()
    if ttype == T_I32:
        return r.i32()
    if ttype == T_I64:
        return r.i64()
    if ttype == T_STRING:
        return r.string()
    if ttype == T_STRUCT:
        return read_struct(r)
    if ttype == T_LIST:
        etype = r.i8()
        n = r.i32()
        return (etype, [_read_value(r, etype) for _ in range(n)])
    raise ValueError(f"unsupported thrift type {ttype}")


def write_struct(w: _Writer, s: ThriftStruct):
    for fid in sorted(s):
        ttype, value = s[fid]
        w.i8(ttype)
        w.i16(fid)
        _write_value(w, ttype, value)
    w.i8(T_STOP)


def read_struct(r: _Reader) -> ThriftStruct:
    out: ThriftStruct = {}
    while True:
        ttype = r.i8()
        if ttype == T_STOP:
            return out
        fid = r.i16()
        out[fid] = (ttype, _read_value(r, ttype))


def pack_message(name: str, msg_type: int, seqid: int,
                 body: ThriftStruct) -> bytes:
    w = _Writer()
    w.u32(VERSION_1 | msg_type)
    w.string(name)
    w.i32(seqid)
    write_struct(w, body)
    payload = w.bytes()
    return struct.pack(">I", len(payload)) + payload


def unpack_message(payload: bytes):
    """-> (name, msg_type, seqid, struct)."""
    r = _Reader(payload)
    version = r.u32()
    if version & 0xFFFF0000 != VERSION_1 & 0xFFFF0000:  # unframed/old: reject
        raise ValueError("bad thrift version")
    msg_type = version & 0xFF
    name = r.string().decode()
    seqid = r.i32()
    body = read_struct(r)
    return name, msg_type, seqid, body


class ThriftService:
    """Server side: register python handlers per thrift method
    (ThriftService::ProcessThriftFramedRequest role)."""

    def __init__(self):
        self._methods: Dict[str, Callable[[ThriftStruct], ThriftStruct]] = {}
        self._lock = threading.Lock()

    def add_method(self, name: str, handler):
        with self._lock:
            self._methods[name] = handler

    def dispatch(self, name: str, body: ThriftStruct):
        handler = self._methods.get(name)
        if handler is None:
            raise KeyError(f"unknown thrift method {name!r}")
        return handler(body)


class ThriftMessage:
    """Client request/response carrier (thrift_message.h role)."""

    def __init__(self, method_name: str = "", body: ThriftStruct = None):
        self.method_name = method_name
        self.body: ThriftStruct = body or {}
