"""Protocol — the pluggable wire-format registry.

Counterpart of brpc::Protocol (/root/reference/src/brpc/protocol.h:77-172)
and its registry (protocol.cpp, populated by global.cpp:396-581): a protocol
is a bundle of parse / serialize_request / pack_request / process_request /
process_response functions registered under a ProtocolType. A server port
tries every registered server-side protocol on the first bytes of a
connection (multi-protocol port); a channel picks one by name.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from enum import IntEnum
from typing import Callable, Dict, List, Optional


class ProtocolType(IntEnum):
    UNKNOWN = 0
    TPU_STD = 1  # framed pb-meta protocol (baidu_std's role)
    STREAMING = 2  # stream frames (streaming_rpc's role)
    HTTP = 3  # HTTP/1.1 (+RESTful, pb-over-http)
    H2 = 4  # reserved
    REDIS = 5
    MEMCACHE = 6
    THRIFT = 7
    ESP = 8
    TENSOR = 9  # raw tensor-transport frames (ICI path)
    NSHEAD = 10  # 36-byte-header legacy family
    HULU = 11  # hulu_pbrpc
    SOFA = 12  # sofa_pbrpc
    MONGO = 13  # mongo wire protocol (server adaptor)
    NOVA = 14  # nova_pbrpc (client; server via NovaServiceAdaptor)
    PUBLIC = 15  # public_pbrpc (client; server via adaptor)
    UBRPC = 16  # ubrpc over mcpack (client; server via adaptor)
    RTMP = 17  # RTMP media streaming (server; gated on rtmp_service)


class ParseError(IntEnum):
    OK = 0
    NOT_ENOUGH_DATA = 1  # keep reading
    TRY_OTHERS = 2  # magic mismatch: not this protocol
    ERROR = 3  # corrupt stream: close the connection


@dataclass
class ParseResult:
    error: ParseError
    message: Optional[object] = None  # an InputMessageBase when OK

    @classmethod
    def ok(cls, message) -> "ParseResult":
        return cls(ParseError.OK, message)

    @classmethod
    def not_enough(cls) -> "ParseResult":
        return cls(ParseError.NOT_ENOUGH_DATA)

    @classmethod
    def try_others(cls) -> "ParseResult":
        return cls(ParseError.TRY_OTHERS)

    @classmethod
    def error_(cls) -> "ParseResult":
        return cls(ParseError.ERROR)


class InputMessageBase:
    """A cut-out wire message awaiting processing (input_messenger.h:33)."""

    __slots__ = ("socket", "protocol", "arg")

    def __init__(self, socket=None, protocol: "Protocol" = None):
        self.socket = socket
        self.protocol = protocol
        self.arg = None


@dataclass
class Protocol:
    """Function bundle (protocol.h:77-172). Server-side protocols provide
    parse+process_request; client-side provide serialize/pack/process_response.
    """

    name: str
    type: ProtocolType
    # parse(iobuf, socket, read_eof, arg) -> ParseResult
    parse: Callable = None
    # serialize_request(request, controller) -> bytes payload (or None on fail)
    serialize_request: Callable = None
    # pack_request(payload_bytes, controller, correlation_id) -> IOBuf packet
    pack_request: Callable = None
    # process_request(InputMessageBase) -> None   [server]
    process_request: Callable = None
    # process_response(InputMessageBase) -> None  [client]
    process_response: Callable = None
    # verify(InputMessageBase) -> bool            [server auth hook]
    verify: Callable = None
    supported_connection_types: tuple = ("single", "pooled", "short")
    support_client: bool = True
    support_server: bool = True
    # True: process on the read loop itself (must only enqueue, never
    # block) — required for order-sensitive frames (streaming), mirroring
    # how stream frames go straight into the stream's ExecutionQueue.
    process_inline: bool = False
    extra: dict = field(default_factory=dict)


_protocols: Dict[ProtocolType, Protocol] = {}
_lock = threading.Lock()
# RLock: a registration import that re-enters globally_initialize on the
# same thread must not deadlock
_init_lock = threading.RLock()
_globally_initialized = False


def register_protocol(protocol: Protocol):
    with _lock:
        if protocol.type in _protocols:
            raise ValueError(f"protocol {protocol.type} already registered")
        _protocols[protocol.type] = protocol


def find_protocol(ptype: ProtocolType) -> Optional[Protocol]:
    return _protocols.get(ptype)


def find_protocol_by_name(name: str) -> Optional[Protocol]:
    for p in _protocols.values():
        if p.name == name:
            return p
    return None


# Parse order for the multi-protocol port. Must be deterministic regardless
# of module import order, and magic-discriminating protocols must precede
# greedy ones (nshead cannot rule itself out on <28 bytes, thrift on <6).
_PARSE_PRIORITY = {
    ProtocolType.TPU_STD: 0,
    ProtocolType.STREAMING: 1,
    ProtocolType.TENSOR: 2,
    ProtocolType.HTTP: 3,
    ProtocolType.H2: 4,
    ProtocolType.HULU: 5,
    ProtocolType.SOFA: 6,
    ProtocolType.REDIS: 7,
    ProtocolType.MEMCACHE: 8,
    ProtocolType.THRIFT: 9,
    ProtocolType.MONGO: 10,  # weak magic (length+opcode), adaptor-gated
    ProtocolType.NSHEAD: 11,  # weak magic (checks 0xfb709394 at offset 24)
    ProtocolType.ESP: 12,  # last — zero magic, only when server opted in
}


def list_server_protocols() -> List[Protocol]:
    """Protocols a server port tries, in fixed priority order."""
    ps = [p for p in _protocols.values() if p.support_server and p.parse]
    ps.sort(key=lambda p: _PARSE_PRIORITY.get(p.type, 99))
    return ps


def globally_initialize():
    """GlobalInitializeOrDie's role (global.cpp:354-606): register every
    built-in protocol / LB / NS / compressor exactly once.

    The done flag only flips AFTER every registration import completes:
    flipping it first let a concurrent initializer return early and look
    up protocols in a half-populated registry (EPROTONOTSUP from
    Channel.init under thread races — seen in the ring storm test)."""
    global _globally_initialized
    if _globally_initialized:
        return  # fast path: flag is only ever set after full registration
    with _init_lock:
        if _globally_initialized:
            return
        _do_global_imports()
        _globally_initialized = True


def _do_global_imports():
    from brpc_tpu.rpc import tpu_std_protocol  # noqa: F401 (self-registers)
    from brpc_tpu.rpc import http_protocol  # noqa: F401
    from brpc_tpu.rpc import streaming_protocol  # noqa: F401
    from brpc_tpu.rpc import tensor_service  # noqa: F401 (device handshake)
    from brpc_tpu.rpc import redis_protocol  # noqa: F401
    from brpc_tpu.rpc import memcache_protocol  # noqa: F401
    from brpc_tpu.rpc import h2_protocol  # noqa: F401
    from brpc_tpu.rpc import thrift_protocol  # noqa: F401
    from brpc_tpu.rpc import nshead_protocol  # noqa: F401
    from brpc_tpu.rpc import hulu_protocol  # noqa: F401
    from brpc_tpu.rpc import sofa_protocol  # noqa: F401
    from brpc_tpu.rpc import mongo_protocol  # noqa: F401
    from brpc_tpu.rpc import esp_protocol  # noqa: F401
    from brpc_tpu.rpc import legacy_nshead_family  # noqa: F401
    # registered LAST: its 0x03 first-byte sniff must lose to every
    # protocol with a real magic, and it only claims bytes on servers
    # that opted in via ServerOptions.rtmp_service
    from brpc_tpu.rpc import rtmp_protocol  # noqa: F401
