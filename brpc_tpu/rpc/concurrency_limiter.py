"""ConcurrencyLimiter — admission control policies.

Counterpart of brpc::ConcurrencyLimiter (/root/reference/src/brpc/
concurrency_limiter.h) and the policies in policy/:

* ConstantLimiter — 'constant' (fixed max concurrency);
* AutoLimiter — 'auto' (policy/auto_concurrency_limiter.{h,cpp}): gradient
  limiter tracking EMA of max qps and min ("noload") latency, concurrency
  limit ≈ max_qps * min_latency * (1+alpha), re-probing min latency
  periodically;
* TimeoutLimiter — 'timeout' (policy/timeout_concurrency_limiter.*):
  rejects when the expected queueing delay exceeds the timeout budget.

MethodStatus calls on_requested/on_response around every RPC.
"""
from __future__ import annotations

import threading
import time


class ConcurrencyLimiter:
    def on_requested(self, current_concurrency: int) -> bool:
        raise NotImplementedError

    def on_response(self, error_code: int, latency_us: float):
        raise NotImplementedError

    def max_concurrency(self) -> int:
        return 0


class ConstantLimiter(ConcurrencyLimiter):
    def __init__(self, limit: int):
        self._limit = limit

    def on_requested(self, current: int) -> bool:
        return self._limit <= 0 or current < self._limit

    def on_response(self, error_code: int, latency_us: float):
        pass

    def max_concurrency(self) -> int:
        return self._limit


class AutoLimiter(ConcurrencyLimiter):
    """Gradient-style adaptive limit (auto_concurrency_limiter.h shape)."""

    ALPHA = 0.3  # headroom factor over measured capacity
    EMA_A = 0.1
    SAMPLE_WINDOW_S = 1.0
    MIN_LIMIT = 4

    def __init__(self):
        self._lock = threading.Lock()
        self._limit = 64.0
        self._min_latency_us = None  # EMA of no-load latency
        self._window_start = time.monotonic()
        self._window_count = 0
        self._window_latency_sum = 0.0
        self._probe_countdown = 10  # periodically re-probe min latency

    def on_requested(self, current: int) -> bool:
        return current < int(self._limit)

    def on_response(self, error_code: int, latency_us: float):
        if error_code != 0:
            return
        with self._lock:
            self._window_count += 1
            self._window_latency_sum += latency_us
            now = time.monotonic()
            dt = now - self._window_start
            if dt < self.SAMPLE_WINDOW_S or self._window_count == 0:
                return
            qps = self._window_count / dt
            avg_latency = self._window_latency_sum / self._window_count
            self._window_start = now
            self._window_count = 0
            self._window_latency_sum = 0.0
            if self._min_latency_us is None:
                self._min_latency_us = avg_latency
            else:
                self._probe_countdown -= 1
                if self._probe_countdown <= 0:
                    # re-probe: shrink limit briefly so min latency re-measures
                    self._probe_countdown = 10
                    self._min_latency_us = avg_latency
                else:
                    self._min_latency_us = min(
                        self._min_latency_us,
                        (1 - self.EMA_A) * self._min_latency_us
                        + self.EMA_A * avg_latency,
                    )
            capacity = qps * (self._min_latency_us / 1e6)
            self._limit = max(self.MIN_LIMIT, capacity * (1 + self.ALPHA))

    def max_concurrency(self) -> int:
        return int(self._limit)


class TimeoutLimiter(ConcurrencyLimiter):
    """Reject when estimated queue delay exceeds the budget
    (policy/timeout_concurrency_limiter.*)."""

    def __init__(self, timeout_ms: float = 500.0):
        self._timeout_s = timeout_ms / 1000.0
        self._avg_latency_s = 0.0
        self._lock = threading.Lock()

    def on_requested(self, current: int) -> bool:
        with self._lock:
            if self._avg_latency_s <= 0:
                return True
            expected_delay = current * self._avg_latency_s
            return expected_delay < self._timeout_s

    def on_response(self, error_code: int, latency_us: float):
        if error_code != 0:
            return
        with self._lock:
            sample = latency_us / 1e6
            if self._avg_latency_s == 0:
                self._avg_latency_s = sample
            else:
                self._avg_latency_s = 0.9 * self._avg_latency_s + 0.1 * sample


def create_concurrency_limiter(spec) -> ConcurrencyLimiter:
    """'constant:100' | 'auto' | 'timeout:500' | int (global.cpp:604-606
    registry shape)."""
    if isinstance(spec, int):
        return ConstantLimiter(spec)
    name, _, arg = str(spec).partition(":")
    if name == "auto":
        return AutoLimiter()
    if name == "timeout":
        return TimeoutLimiter(float(arg or 500))
    if name == "constant":
        return ConstantLimiter(int(arg or 0))
    raise ValueError(f"unknown concurrency limiter {spec!r}")
