"""InputMessenger — the per-socket read/cut/dispatch loop.

Counterpart of brpc::InputMessenger
(/root/reference/src/brpc/input_messenger.{h,cpp}): reads into the socket's
IOPortal, tries each registered protocol's parse() in order until one
matches (then remembers the match for the connection's lifetime —
input_messenger.h:33-154), and processes every cut message in a fresh
scheduler task so the read loop never blocks behind user code
(input_messenger.cpp:331).
"""
from __future__ import annotations

from typing import List, Optional

from brpc_tpu import bvar
from brpc_tpu.bthread import start_background
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.protocol import ParseError, Protocol
from brpc_tpu.rpc.socket import Socket, _in_bytes

_msg_count = bvar.Adder("input_messenger_messages")


class InputMessenger:
    def __init__(self, protocols: Optional[List[Protocol]] = None, arg=None):
        # ordered handler list (AddHandler, input_messenger.h:60); arg is
        # delivered to process_* with each message (the Server on the server
        # side, None on the client side), mirroring InputMessageHandler.arg.
        self._protocols = list(protocols or [])
        self.arg = arg

    def add_handler(self, protocol: Protocol):
        self._protocols.append(protocol)

    def on_new_messages(self, sock: Socket):
        """Entry installed as the socket's edge-triggered handler."""
        portal = sock.read_portal
        while not sock.failed():
            fd = sock.fd()
            if fd is None:
                return
            try:
                n = portal.append_from_socket(fd, 262144)
            except (BlockingIOError, InterruptedError):
                n = -1
            except OSError as e:
                sock.set_failed(e.errno or errors.EFAILEDSOCKET,
                                f"read failed: {e}")
                return
            if n == 0:  # EOF
                if portal.empty():
                    sock.set_failed(errors.ECLOSE, "remote closed")
                    return
            elif n > 0:
                _in_bytes.update(n)
            # Cut every complete message currently buffered.
            progressed = self._cut_and_process(sock, read_eof=(n == 0))
            if n == 0:
                sock.set_failed(errors.ECLOSE, "remote closed")
                return
            if n < 0 and not progressed:
                return  # would-block and nothing parseable: wait for epoll
            if n < 0:
                # parsed something; check again for leftover partial data
                if not portal.empty():
                    continue
                return

    def _cut_and_process(self, sock: Socket, read_eof: bool) -> bool:
        portal = sock.read_portal
        progressed = False
        # Deferred batch: all-but-last spawn as tasks, the last runs in
        # THIS task — the reference's process-in-place optimization saves
        # one wakeup on the common single-message read.
        deferred = []
        try:
            progressed = self._cut_loop(sock, read_eof, deferred)
        finally:
            for process, msg in deferred[:-1]:
                start_background(self._process_safely, process, msg)
            if deferred:
                self._process_safely(*deferred[-1])
        return progressed

    def _cut_loop(self, sock: Socket, read_eof: bool, deferred) -> bool:
        portal = sock.read_portal
        progressed = False
        while not portal.empty():
            protocol = sock.matched_protocol
            result = None
            if protocol is not None:
                result = protocol.parse(portal, sock, read_eof, self.arg)
                if result.error == ParseError.TRY_OTHERS:
                    # Mixed traffic on one connection (RPC frames +
                    # streaming frames): re-run handler selection.
                    result = None
                    protocol = None
                    sock.matched_protocol = None
            if protocol is None:
                # First message: try every handler in order
                # (input_messenger.cpp CutInputMessage).
                for p in self._protocols:
                    r = p.parse(portal, sock, read_eof, self.arg)
                    if r.error == ParseError.TRY_OTHERS:
                        continue
                    result = r
                    if r.error in (ParseError.OK, ParseError.NOT_ENOUGH_DATA):
                        sock.matched_protocol = p
                        protocol = p
                    break
                if result is None:
                    sock.set_failed(errors.EPROTONOTSUP,
                                    "no protocol matched input")
                    return progressed
            if result.error == ParseError.OK:
                progressed = True
                _msg_count.update(1)
                msg = result.message
                msg.socket = sock
                msg.protocol = protocol
                msg.arg = self.arg
                # Each message processed in a new task; the read loop
                # continues cutting (input_messenger.cpp:331).
                process = (protocol.process_request
                           if getattr(msg, "is_request", True)
                           else protocol.process_response)
                if process is None:
                    continue
                if protocol.process_inline:
                    self._process_safely(process, msg)
                else:
                    deferred.append((process, msg))
            elif result.error == ParseError.NOT_ENOUGH_DATA:
                return progressed
            else:
                sock.set_failed(errors.EREQUEST, "protocol parse error")
                return progressed
        return progressed

    @staticmethod
    def _process_safely(process, msg):
        try:
            process(msg)
        except Exception:
            import logging

            logging.getLogger(__name__).exception("message processing raised")
