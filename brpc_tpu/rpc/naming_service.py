"""NamingService — pushes server lists to the load balancer.

Counterpart of brpc::NamingService (/root/reference/src/brpc/naming_service.h
:36+) with the observer pattern of LoadBalancerWithNaming
(details/load_balancer_with_naming.{h,cpp}) and periodic re-resolution
(periodic_naming_service.{h,cpp}, details/naming_service_thread.{h,cpp}).

Implemented schemes (registered like global.cpp:354-365):
  list://host:port,host:port[ w][,...]  — static list (test fixture double,
                                          policy/list_naming_service)
  file:///path                          — re-read periodically
                                          (policy/file_naming_service)
  dns://hostname:port                   — re-resolve periodically
                                          (policy/domain_naming_service)
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from brpc_tpu.bthread import timer_add
from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.rpc.socket import Socket

# (endpoint, weight, tag)
NodeSpec = Tuple[EndPoint, int, str]


class NamingService:
    """One resolution strategy. refresh_interval_s <= 0 means static."""

    name = "base"
    refresh_interval_s: float = 5.0

    def get_servers(self, service_path: str) -> List[NodeSpec]:
        raise NotImplementedError


class ListNamingService(NamingService):
    name = "list"
    refresh_interval_s = -1.0

    def get_servers(self, service_path: str) -> List[NodeSpec]:
        out = []
        for part in service_path.split(","):
            part = part.strip()
            if not part:
                continue
            weight, tag = 1, ""
            if " " in part:
                part, _, tag = part.partition(" ")
                tag = tag.strip()
                if tag.isdigit():
                    weight, tag = int(tag), ""
            out.append((EndPoint.parse(part), weight, tag))
        return out


def _parse_server_lines(text: str) -> List[NodeSpec]:
    """The server-list file grammar shared by file:// and remotefile://
    (policy/file_naming_service.cpp): 'ip:port[ weight-or-tag]' per line,
    '#' comments."""
    out: List[NodeSpec] = []
    for line in text.splitlines():
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        weight, tag = 1, ""
        if " " in line:
            line, _, tag = line.partition(" ")
            tag = tag.strip()
            if tag.isdigit():
                weight, tag = int(tag), ""
        try:
            out.append((EndPoint.parse(line), weight, tag))
        except ValueError:
            continue
    return out


class FileNamingService(NamingService):
    name = "file"
    refresh_interval_s = 2.0

    def get_servers(self, service_path: str) -> List[NodeSpec]:
        try:
            with open(service_path) as f:
                text = f.read()
        except OSError:
            return []
        return _parse_server_lines(text)


class DnsNamingService(NamingService):
    name = "dns"
    refresh_interval_s = 5.0

    def get_servers(self, service_path: str) -> List[NodeSpec]:
        import socket as pysocket

        host, _, port_s = service_path.partition(":")
        port = int(port_s or 80)
        out = []
        try:
            infos = pysocket.getaddrinfo(host, port, pysocket.AF_INET,
                                         pysocket.SOCK_STREAM)
        except OSError:
            return out
        seen = set()
        for _, _, _, _, sockaddr in infos:
            ep = EndPoint(sockaddr[0], sockaddr[1])
            if ep not in seen:
                seen.add(ep)
                out.append((ep, 1, ""))
        return out


def _http_get_json(authority: str, path: str, timeout_s: float = 3.0):
    """GET http://authority/path -> parsed JSON (None on any failure).
    The HTTP-backed naming services (consul/discovery/nacos/remotefile)
    poll registry endpoints this way."""
    import json
    import urllib.request

    try:
        with urllib.request.urlopen(f"http://{authority}{path}",
                                    timeout=timeout_s) as r:
            return json.loads(r.read().decode())
    except Exception:
        return None


class ConsulNamingService(NamingService):
    """consul://host:port/service-name — polls Consul's health endpoint
    (policy/consul_naming_service.cpp: /v1/health/service/<name> with
    passing+stale, addresses from Service.Address/Port, tags kept)."""

    name = "consul"
    refresh_interval_s = 2.0

    def get_servers(self, service_path: str) -> List[NodeSpec]:
        authority, _, service = service_path.partition("/")
        data = _http_get_json(
            authority, f"/v1/health/service/{service}?stale&passing")
        out: List[NodeSpec] = []
        if not isinstance(data, list):
            return out
        for entry in data:
            try:
                svc = entry["Service"]
                ep = EndPoint(svc["Address"], int(svc["Port"]))
                tags = svc.get("Tags") or []
                out.append((ep, 1, tags[0] if tags else ""))
            except (KeyError, TypeError, ValueError):
                continue
        return out


class DiscoveryNamingService(NamingService):
    """discovery://host:port/appid — the bilibili discovery shape
    (policy/discovery_naming_service.cpp): /discovery/fetchs returns
    zone->instances with addrs like 'grpc://ip:port'."""

    name = "discovery"
    refresh_interval_s = 2.0

    def get_servers(self, service_path: str) -> List[NodeSpec]:
        authority, _, appid = service_path.partition("/")
        data = _http_get_json(
            authority, f"/discovery/fetchs?appid={appid}&status=1")
        out: List[NodeSpec] = []
        try:
            instances = data["data"][appid]["instances"]
        except (KeyError, TypeError):
            return out
        for inst in instances:
            for addr in inst.get("addrs", []):
                _, _, hostport = addr.rpartition("://")
                try:
                    out.append((EndPoint.parse(hostport), 1, ""))
                except ValueError:
                    continue
        return out


class NacosNamingService(NamingService):
    """nacos://host:port/serviceName — polls the Nacos instance list
    (policy/nacos_naming_service.cpp: /nacos/v1/ns/instance/list, healthy
    hosts with ip/port/weight)."""

    name = "nacos"
    refresh_interval_s = 2.0

    def get_servers(self, service_path: str) -> List[NodeSpec]:
        authority, _, service = service_path.partition("/")
        data = _http_get_json(
            authority,
            f"/nacos/v1/ns/instance/list?serviceName={service}&healthyOnly=true")
        out: List[NodeSpec] = []
        if not isinstance(data, dict):
            return out
        for host in data.get("hosts", []):
            try:
                if not host.get("enabled", True):
                    continue
                out.append((EndPoint(host["ip"], int(host["port"])),
                            max(1, int(float(host.get("weight", 1)))), ""))
            except (KeyError, TypeError, ValueError):
                continue
        return out


class RemoteFileNamingService(NamingService):
    """remotefile://host:port/path — fetches a server-list file over HTTP
    and parses it with the file NS grammar
    (policy/remotefile_naming_service.cpp)."""

    name = "remotefile"
    refresh_interval_s = 2.0

    def get_servers(self, service_path: str) -> List[NodeSpec]:
        import urllib.request

        authority, _, path = service_path.partition("/")
        try:
            with urllib.request.urlopen(f"http://{authority}/{path}",
                                        timeout=3.0) as r:
                text = r.read().decode()
        except Exception:
            return []
        return _parse_server_lines(text)


_ns_registry: Dict[str, Callable[[], NamingService]] = {
    "list": ListNamingService,
    "file": FileNamingService,
    "dns": DnsNamingService,
    "http": DnsNamingService,
    "consul": ConsulNamingService,
    "discovery": DiscoveryNamingService,
    "nacos": NacosNamingService,
    "remotefile": RemoteFileNamingService,
}


def register_naming_service(scheme: str, factory):
    _ns_registry[scheme] = factory


class NamingServiceThread:
    """Owns the NS → LB flow: resolves periodically, diffs the node set,
    creates/destroys client Sockets, updates the LB
    (details/naming_service_thread.{h,cpp})."""

    def __init__(self, ns: NamingService, service_path: str, lb,
                 channel_options=None,
                 node_filter: Optional[Callable[[NodeSpec], bool]] = None):
        self._ns = ns
        self._path = service_path
        self._lb = lb
        self._options = channel_options
        self._filter = node_filter
        self._sockets: Dict[EndPoint, int] = {}  # endpoint -> sid
        self._lock = threading.Lock()
        self._stopped = False
        self.refresh()  # first resolution is synchronous (blocking init)
        if ns.refresh_interval_s > 0:
            timer_add(ns.refresh_interval_s, self._periodic)

    def _periodic(self):
        if self._stopped:
            return
        try:
            self.refresh()
        finally:
            if not self._stopped:
                timer_add(self._ns.refresh_interval_s, self._periodic)

    def refresh(self):
        nodes = self._ns.get_servers(self._path)
        if self._filter is not None:
            nodes = [n for n in nodes if self._filter(n)]
        from brpc_tpu.rpc.channel import get_client_messenger

        messenger = get_client_messenger()
        hc = (self._options.health_check_interval_s
              if self._options is not None else -1)
        new_eps = {}
        for ep, weight, tag in nodes:
            new_eps[ep] = (weight, tag)
        with self._lock:
            # additions
            for ep, (weight, tag) in new_eps.items():
                if ep not in self._sockets:
                    sid = Socket.create(
                        remote_side=ep,
                        on_edge_triggered_events=messenger.on_new_messages,
                        health_check_interval_s=hc,
                    )
                    self._sockets[ep] = sid
                    self._lb.add_server(sid, weight, tag)
            # removals
            for ep in [e for e in self._sockets if e not in new_eps]:
                sid = self._sockets.pop(ep)
                self._lb.remove_server(sid)
                s = Socket.address(sid)
                if s is not None:
                    s.recycle()

    def endpoints(self) -> List[EndPoint]:
        with self._lock:
            return list(self._sockets)

    def stop(self):
        self._stopped = True


def start_naming_service(url: str, lb, channel_options=None,
                         node_filter=None) -> Optional[NamingServiceThread]:
    """Parse scheme://path, build the NS, start its thread."""
    scheme, sep, path = url.partition("://")
    if not sep:
        return None
    factory = _ns_registry.get(scheme)
    if factory is None:
        return None
    return NamingServiceThread(factory(), path, lb, channel_options,
                               node_filter)
