"""HTTP/2 + gRPC — counterpart of policy/http2_rpc_protocol.cpp +
grpc.{h,cpp} (/root/reference/src/brpc/policy/http2_rpc_protocol.cpp,
grpc.h:27-152): full client+server h2 framing (HEADERS/DATA/SETTINGS/PING/
WINDOW_UPDATE/RST/GOAWAY), HPACK header blocks (hpack.py), connection and
per-stream flow control with queued sends, and the gRPC unary mapping
(5-byte message frames, grpc-status trailers, grpc-timeout propagation)
over the same service/method map every other protocol serves.

Channels select it with options.protocol = "h2:grpc".
"""
from __future__ import annotations

import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from brpc_tpu.bthread import id as bthread_id
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.rpc.hpack import HpackDecoder, HpackEncoder
from brpc_tpu.rpc.protocol import (
    InputMessageBase,
    ParseResult,
    Protocol,
    ProtocolType,
    register_protocol,
)

PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"

F_DATA = 0x0
F_HEADERS = 0x1
F_PRIORITY = 0x2
F_RST_STREAM = 0x3
F_SETTINGS = 0x4
F_PUSH_PROMISE = 0x5
F_PING = 0x6
F_GOAWAY = 0x7
F_WINDOW_UPDATE = 0x8
F_CONTINUATION = 0x9

FLAG_END_STREAM = 0x1
FLAG_END_HEADERS = 0x4
FLAG_ACK = 0x1
FLAG_PADDED = 0x8
FLAG_PRIORITY_F = 0x20

SETTINGS_INITIAL_WINDOW_SIZE = 0x4
SETTINGS_MAX_FRAME_SIZE = 0x5

DEFAULT_WINDOW = 65535
OUR_WINDOW = 1 << 28  # generous receive window we advertise
MAX_FRAME = 16384

# gRPC status <-> framework errors (grpc.h:27-152)
GRPC_OK = 0
GRPC_CANCELLED = 1
GRPC_DEADLINE_EXCEEDED = 4
GRPC_NOT_FOUND = 5
GRPC_RESOURCE_EXHAUSTED = 8
GRPC_UNIMPLEMENTED = 12
GRPC_INTERNAL = 13
GRPC_UNAVAILABLE = 14
GRPC_UNAUTHENTICATED = 16

_ERR_TO_GRPC = {
    0: GRPC_OK,
    errors.ECANCELED: GRPC_CANCELLED,
    errors.ERPCTIMEDOUT: GRPC_DEADLINE_EXCEEDED,
    errors.ENOSERVICE: GRPC_NOT_FOUND,
    errors.ENOMETHOD: GRPC_UNIMPLEMENTED,
    errors.ELIMIT: GRPC_RESOURCE_EXHAUSTED,
    errors.EOVERLOAD: GRPC_RESOURCE_EXHAUSTED,
    errors.EAUTH: GRPC_UNAUTHENTICATED,
    errors.EFAILEDSOCKET: GRPC_UNAVAILABLE,
}
_GRPC_TO_ERR = {
    GRPC_OK: 0,
    GRPC_CANCELLED: errors.ECANCELED,
    GRPC_DEADLINE_EXCEEDED: errors.ERPCTIMEDOUT,
    GRPC_NOT_FOUND: errors.ENOSERVICE,
    GRPC_UNIMPLEMENTED: errors.ENOMETHOD,
    GRPC_RESOURCE_EXHAUSTED: errors.ELIMIT,
    GRPC_UNAUTHENTICATED: errors.EAUTH,
    GRPC_UNAVAILABLE: errors.EFAILEDSOCKET,
    GRPC_INTERNAL: errors.EINVAL,
}


def error_to_grpc_status(code: int) -> int:
    return _ERR_TO_GRPC.get(code, GRPC_INTERNAL)


def grpc_status_to_error(status: int) -> int:
    return _GRPC_TO_ERR.get(status, errors.EINVAL)


def pack_frame(ftype: int, flags: int, stream_id: int, payload: bytes) -> bytes:
    n = len(payload)
    return (bytes([(n >> 16) & 0xFF, (n >> 8) & 0xFF, n & 0xFF, ftype,
                   flags]) + struct.pack(">I", stream_id & 0x7FFFFFFF)
            + payload)


def grpc_wrap(message: bytes) -> bytes:
    """5-byte gRPC message frame: compressed flag + length."""
    return b"\x00" + struct.pack(">I", len(message)) + message


def grpc_unwrap(data: bytes) -> Optional[bytes]:
    if len(data) < 5:
        return None
    (length,) = struct.unpack(">I", data[1:5])
    if len(data) < 5 + length:
        return None
    return data[5:5 + length]


class H2Stream:
    __slots__ = ("stream_id", "headers", "trailers", "data", "remote_end",
                 "cid", "send_window", "pending_out", "headers_done")

    def __init__(self, stream_id: int, initial_window: int):
        self.stream_id = stream_id
        self.headers: Optional[List[Tuple[str, str]]] = None
        self.trailers: Optional[List[Tuple[str, str]]] = None
        self.data = bytearray()
        self.remote_end = False
        self.cid: Optional[int] = None
        self.send_window = initial_window
        self.pending_out: List[Tuple[bytes, bool]] = []  # (chunk, end)
        self.headers_done = False


class H2Connection:
    """Per-socket h2 state (the H2Context of http2_rpc_protocol.cpp)."""

    def __init__(self, is_client: bool):
        self.is_client = is_client
        self.encoder = HpackEncoder()
        self.decoder = HpackDecoder()
        self.streams: Dict[int, H2Stream] = {}
        self.next_stream_id = 1 if is_client else 2
        self.send_window = DEFAULT_WINDOW
        self.peer_initial_window = DEFAULT_WINDOW
        self.preface_done = not is_client  # server: consumed during parse
        self.lock = threading.Lock()
        self._header_buf: Optional[Tuple[int, int, bytearray]] = None

    def new_stream(self) -> H2Stream:
        with self.lock:
            sid = self.next_stream_id
            self.next_stream_id += 2
            s = H2Stream(sid, self.peer_initial_window)
            self.streams[sid] = s
            return s

    def get_or_create(self, sid: int) -> H2Stream:
        with self.lock:
            s = self.streams.get(sid)
            if s is None:
                s = H2Stream(sid, self.peer_initial_window)
                self.streams[sid] = s
            return s

    def initial_frames(self) -> bytes:
        """Client preface + our SETTINGS (both sides send SETTINGS)."""
        settings = struct.pack(">HI", SETTINGS_INITIAL_WINDOW_SIZE, OUR_WINDOW)
        settings += struct.pack(">HI", SETTINGS_MAX_FRAME_SIZE, MAX_FRAME)
        frames = pack_frame(F_SETTINGS, 0, 0, settings)
        # open up the connection receive window too
        frames += pack_frame(F_WINDOW_UPDATE, 0, 0,
                             struct.pack(">I", OUR_WINDOW - DEFAULT_WINDOW))
        if self.is_client:
            return PREFACE + frames
        return frames

    # -- sending with flow control ----------------------------------------
    def send_data(self, sock, stream: H2Stream, data: bytes, end: bool):
        """Split into MAX_FRAME chunks, respecting windows; queue remainder
        (flushed by WINDOW_UPDATE)."""
        chunks: List[Tuple[bytes, bool]] = []
        pos = 0
        if not data:
            chunks.append((b"", end))
        while pos < len(data):
            take = min(MAX_FRAME, len(data) - pos)
            chunk = data[pos:pos + take]
            pos += take
            chunks.append((chunk, end and pos >= len(data)))
        out = IOBuf()
        with self.lock:
            for i, (chunk, is_end) in enumerate(chunks):
                if (self.send_window >= len(chunk)
                        and stream.send_window >= len(chunk)
                        and not stream.pending_out):
                    self.send_window -= len(chunk)
                    stream.send_window -= len(chunk)
                    out.append(pack_frame(
                        F_DATA, FLAG_END_STREAM if is_end else 0,
                        stream.stream_id, chunk))
                else:
                    stream.pending_out.append((chunk, is_end))
        if not out.empty():
            sock.write(out)

    def flush_pending(self, sock):
        out = IOBuf()
        with self.lock:
            for s in self.streams.values():
                while s.pending_out:
                    chunk, is_end = s.pending_out[0]
                    if (self.send_window < len(chunk)
                            or s.send_window < len(chunk)):
                        break
                    s.pending_out.pop(0)
                    self.send_window -= len(chunk)
                    s.send_window -= len(chunk)
                    out.append(pack_frame(
                        F_DATA, FLAG_END_STREAM if is_end else 0,
                        s.stream_id, chunk))
        if not out.empty():
            sock.write(out)


class H2Message(InputMessageBase):
    __slots__ = ("frames", "is_request")

    def __init__(self, frames):
        super().__init__()
        self.frames = frames
        self.is_request = True  # routed by connection role internally


def parse(portal: IOBuf, sock, read_eof: bool, arg) -> ParseResult:
    conn: Optional[H2Connection] = getattr(sock, "h2_conn", None)
    if conn is None:
        # Server side: detect the client preface.
        head = portal.copy_to_bytes(min(len(PREFACE), len(portal)))
        if not PREFACE.startswith(head):
            return ParseResult.try_others()
        if len(portal) < len(PREFACE):
            return ParseResult.not_enough()
        portal.pop_front(len(PREFACE))
        conn = H2Connection(is_client=False)
        sock.h2_conn = conn
        sock.write(IOBuf(conn.initial_frames()))
    frames = []
    while len(portal) >= 9:
        header = portal.copy_to_bytes(9)
        length = (header[0] << 16) | (header[1] << 8) | header[2]
        if len(portal) < 9 + length:
            break
        portal.pop_front(9)
        ftype, flags = header[3], header[4]
        (sid,) = struct.unpack(">I", header[5:9])
        sid &= 0x7FFFFFFF
        payload = portal.cutn_bytes(length)
        frames.append((ftype, flags, sid, payload))
    if not frames:
        return ParseResult.not_enough()
    return ParseResult.ok(H2Message(frames))


def process_frames(msg: H2Message):
    sock = msg.socket
    conn: H2Connection = sock.h2_conn
    if conn is None:
        return
    for ftype, flags, sid, payload in msg.frames:
        if ftype == F_SETTINGS:
            if not (flags & FLAG_ACK):
                pos = 0
                while pos + 6 <= len(payload):
                    ident, value = struct.unpack_from(">HI", payload, pos)
                    pos += 6
                    if ident == SETTINGS_INITIAL_WINDOW_SIZE:
                        with conn.lock:
                            delta = value - conn.peer_initial_window
                            conn.peer_initial_window = value
                            for s in conn.streams.values():
                                s.send_window += delta
                sock.write(IOBuf(pack_frame(F_SETTINGS, FLAG_ACK, 0, b"")))
        elif ftype == F_PING:
            if not (flags & FLAG_ACK):
                sock.write(IOBuf(pack_frame(F_PING, FLAG_ACK, 0, payload)))
        elif ftype == F_WINDOW_UPDATE:
            (incr,) = struct.unpack(">I", payload[:4])
            with conn.lock:
                if sid == 0:
                    conn.send_window += incr
                else:
                    s = conn.streams.get(sid)
                    if s is not None:
                        s.send_window += incr
            conn.flush_pending(sock)
        elif ftype in (F_HEADERS, F_CONTINUATION):
            block = payload
            if ftype == F_HEADERS:
                if flags & FLAG_PRIORITY_F:
                    block = block[5:]
                if flags & FLAG_PADDED:
                    pad = block[0]
                    block = block[1:len(block) - pad]
            if not (flags & FLAG_END_HEADERS):
                conn._header_buf = (sid, flags, bytearray(block))
                continue
            if conn._header_buf is not None and conn._header_buf[0] == sid:
                prev_sid, prev_flags, buf = conn._header_buf
                conn._header_buf = None
                buf.extend(block)
                block = bytes(buf)
                flags |= prev_flags
            headers = conn.decoder.decode(bytes(block))
            stream = conn.get_or_create(sid)
            if stream.headers_done:
                stream.trailers = headers
            else:
                stream.headers = headers
                stream.headers_done = True
            if flags & FLAG_END_STREAM:
                stream.remote_end = True
                _on_stream_complete(sock, conn, stream)
        elif ftype == F_DATA:
            stream = conn.get_or_create(sid)
            body = payload
            if flags & FLAG_PADDED:
                pad = body[0]
                body = body[1:len(body) - pad]
            stream.data.extend(body)
            if len(payload):
                # replenish both windows (we advertise a large one)
                upd = struct.pack(">I", len(payload))
                out = IOBuf(pack_frame(F_WINDOW_UPDATE, 0, 0, upd))
                out.append(pack_frame(F_WINDOW_UPDATE, 0, sid, upd))
                sock.write(out)
            if flags & FLAG_END_STREAM:
                stream.remote_end = True
                _on_stream_complete(sock, conn, stream)
        elif ftype == F_RST_STREAM:
            stream = conn.streams.get(sid)
            if stream is not None and stream.cid is not None:
                bthread_id.error(stream.cid, errors.EFAILEDSOCKET,
                                 "h2 stream reset")
            with conn.lock:
                conn.streams.pop(sid, None)
        elif ftype == F_GOAWAY:
            last_sid = goaway_err = 0
            if len(payload) >= 8:
                (last_sid,) = struct.unpack(">I", payload[:4])
                last_sid &= 0x7FFFFFFF
                (goaway_err,) = struct.unpack(">I", payload[4:8])
            if conn.is_client and goaway_err == 0 and \
                    hasattr(sock, "mark_lame_duck"):
                # graceful drain (RFC 7540 §6.8): streams <= last_sid
                # are still served — keep them completing here; refuse
                # the rest (retryable) and stop opening new streams
                sock.mark_lame_duck()
                refused = []
                with conn.lock:
                    for rsid in [i for i in conn.streams if i > last_sid]:
                        st = conn.streams.pop(rsid)
                        if st.cid is not None:
                            refused.append(st.cid)
                for cid in refused:
                    bthread_id.error(cid, errors.EFAILEDSOCKET,
                                     "stream refused by GOAWAY")
            else:
                sock.set_failed(errors.ECLOSE, "h2 goaway")


def _headers_dict(headers) -> Dict[str, str]:
    return {k: v for k, v in (headers or [])}


def _on_stream_complete(sock, conn: H2Connection, stream: H2Stream):
    if conn.is_client:
        _complete_client_call(sock, conn, stream)
    else:
        _dispatch_server_request(sock, conn, stream)


# -- server side ------------------------------------------------------------

def _send_grpc_response(sock, conn: H2Connection, sid: int, payload: bytes,
                        grpc_status: int, grpc_message: str = ""):
    headers = [(":status", "200"), ("content-type", "application/grpc")]
    block = conn.encoder.encode(headers)
    out = IOBuf(pack_frame(F_HEADERS, FLAG_END_HEADERS, sid, block))
    sock.write(out)
    stream = conn.get_or_create(sid)
    if payload:
        conn.send_data(sock, stream, grpc_wrap(payload), end=False)
    trailers = [("grpc-status", str(grpc_status))]
    if grpc_message:
        trailers.append(("grpc-message", grpc_message))
    tblock = conn.encoder.encode(trailers)
    sock.write(IOBuf(pack_frame(
        F_HEADERS, FLAG_END_HEADERS | FLAG_END_STREAM, sid, tblock)))
    with conn.lock:
        conn.streams.pop(sid, None)


def _dispatch_server_request(sock, conn: H2Connection, stream: H2Stream):
    from brpc_tpu.rpc.input_messenger import InputMessenger  # noqa: F401

    server = getattr(sock, "_h2_server", None)
    headers = _headers_dict(stream.headers)
    sid = stream.stream_id
    path = headers.get(":path", "/")
    parts = [p for p in path.split("/") if p]
    if server is None or len(parts) != 2:
        return _send_grpc_response(sock, conn, sid, b"", GRPC_UNIMPLEMENTED,
                                   f"bad path {path}")
    entry = server.find_method(parts[0], parts[1])
    if entry is None:
        missing_service = server.find_service(parts[0]) is None
        return _send_grpc_response(
            sock, conn, sid, b"",
            GRPC_NOT_FOUND if missing_service else GRPC_UNIMPLEMENTED,
            f"unknown method {path}")
    service_obj, minfo, method_status = entry
    cntl = Controller()
    cntl.server = server
    cntl.remote_side = sock.remote_side
    cntl.service_name, cntl.method_name = parts[0], parts[1]
    cntl.server_start_time = time.monotonic()
    timeout = headers.get("grpc-timeout")
    if timeout:
        cntl.timeout_ms = _parse_grpc_timeout(timeout)
    if not method_status.on_requested():
        return _send_grpc_response(sock, conn, sid, b"",
                                   GRPC_RESOURCE_EXHAUSTED,
                                   "reached max_concurrency")
    request = minfo.request_class()
    body = grpc_unwrap(bytes(stream.data))
    try:
        if body:
            request.ParseFromString(body)
    except Exception as e:
        method_status.on_response(errors.EREQUEST, cntl.server_start_time)
        return _send_grpc_response(sock, conn, sid, b"", GRPC_INTERNAL,
                                   f"fail to parse request: {e}")
    response = minfo.response_class()
    responded = [False]

    def done():
        if responded[0]:
            return
        responded[0] = True
        method_status.on_response(cntl.error_code_value,
                                  cntl.server_start_time)
        if cntl.failed():
            _send_grpc_response(sock, conn, sid, b"",
                                error_to_grpc_status(cntl.error_code_value),
                                cntl.error_text_value)
        else:
            _send_grpc_response(sock, conn, sid,
                                response.SerializeToString(), GRPC_OK)

    try:
        minfo.handler(service_obj, cntl, request, response, done)
    except Exception as e:
        if not responded[0]:
            cntl.set_failed(errors.EINVAL, f"method raised: {e}")
            done()


def _parse_grpc_timeout(text: str) -> float:
    unit = text[-1]
    value = float(text[:-1])
    scale = {"H": 3600e3, "M": 60e3, "S": 1e3, "m": 1.0, "u": 1e-3,
             "n": 1e-6}.get(unit, 1.0)
    return value * scale


# -- client side ------------------------------------------------------------

def serialize_request(request, cntl: Controller):
    if request is None:
        return b""
    if isinstance(request, (bytes, bytearray)):
        return bytes(request)
    return request.SerializeToString()


_client_conn_lock = threading.Lock()  # guards ATTACHMENT only, not IO


def ensure_client_conn(sock) -> "H2Connection":
    """Attach the client H2Connection + send the preface. Called at
    protocol-pin time (channel._pin_protocol): a speaks-first peer (grpcio
    sends SETTINGS immediately) must find sock.h2_conn already attached,
    or its frames race pack_request and fail protocol selection."""
    conn = getattr(sock, "h2_conn", None)  # unlocked fast path (hot calls)
    if conn is not None:
        return conn
    with _client_conn_lock:
        conn = getattr(sock, "h2_conn", None)
        if conn is None:
            conn = H2Connection(is_client=True)
            # queue the preface BEFORE publishing the conn: a fast-path
            # reader that sees h2_conn may immediately queue HEADERS, and
            # the FIFO write queue must already hold the preface ahead of
            # them. sock.write never blocks (non-blocking fd; leftovers go
            # to the KeepWrite task), so holding the lock here is fine.
            sock.write(IOBuf(conn.initial_frames()))
            sock.h2_conn = conn
    return conn


def pack_request(payload: bytes, cntl: Controller, correlation_id: int) -> IOBuf:
    sock = cntl._current_sock
    conn = ensure_client_conn(sock)  # preface sent at pin/first use
    out = IOBuf()
    stream = conn.new_stream()
    stream.cid = correlation_id
    service, _, method = cntl._method_full_name.rpartition(".")
    headers = [
        (":method", "POST"), (":scheme", "http"),
        (":path", f"/{service}/{method}"),
        (":authority", str(cntl.remote_side or "")),
        ("content-type", "application/grpc"),
        ("te", "trailers"),
    ]
    if cntl._deadline is not None:
        remain_ms = max(1, int((cntl._deadline - time.monotonic()) * 1000))
        headers.append(("grpc-timeout", f"{remain_ms}m"))
    block = conn.encoder.encode(headers)
    out.append(pack_frame(F_HEADERS, FLAG_END_HEADERS, stream.stream_id,
                          block))
    body = grpc_wrap(payload)
    # split at MAX_FRAME (SETTINGS_MAX_FRAME_SIZE conformance)
    pos = 0
    while True:
        take = min(MAX_FRAME, len(body) - pos)
        chunk = body[pos:pos + take]
        pos += take
        is_end = pos >= len(body)
        out.append(pack_frame(F_DATA, FLAG_END_STREAM if is_end else 0,
                              stream.stream_id, chunk))
        if is_end:
            break
    with conn.lock:
        conn.send_window -= len(body)
        stream.send_window -= len(body)
    return out


def _complete_client_call(sock, conn: H2Connection, stream: H2Stream):
    cid = stream.cid
    with conn.lock:
        conn.streams.pop(stream.stream_id, None)
    if cid is None:
        return
    try:
        cntl = bthread_id.lock(cid)
    except (KeyError, TimeoutError):
        return
    if not isinstance(cntl, Controller):
        try:
            bthread_id.unlock(cid)
        except Exception:
            pass
        return
    trailers = _headers_dict(stream.trailers or stream.headers)
    status = int(trailers.get("grpc-status", "0") or 0)
    if status != GRPC_OK:
        cntl.set_failed(grpc_status_to_error(status),
                        trailers.get("grpc-message",
                                     f"grpc status {status}"))
        cntl._end_rpc_locked_or_not(locked=True)
        return
    body = grpc_unwrap(bytes(stream.data))
    try:
        if cntl._response is not None and body:
            cntl._response.ParseFromString(body)
    except Exception as e:
        cntl.set_failed(errors.EREQUEST, f"fail to parse grpc response: {e}")
    cntl._end_rpc_locked_or_not(locked=True)


def process_message(msg: H2Message):
    # Server-side connections learn their server from the message arg.
    if msg.arg is not None:
        msg.socket._h2_server = msg.arg
    process_frames(msg)


register_protocol(Protocol(
    name="h2:grpc",
    type=ProtocolType.H2,
    parse=parse,
    serialize_request=serialize_request,
    pack_request=pack_request,
    process_request=process_message,
    process_response=process_message,
    process_inline=True,  # frame ordering is load-bearing
    extra={"on_pinned": ensure_client_conn},
))

