"""Progressive attachment — chunked server push after the response.

Counterpart of brpc::ProgressiveAttachment / ProgressiveReader
(/root/reference/src/brpc/progressive_attachment.{h,cpp},
progressive_reader.h): the server responds immediately, keeps the
connection, and appends body chunks as they become available; the client
consumes them through a ProgressiveReader. Implemented over the Stream
machinery (a progressive body IS a one-directional stream), which gives the
same flow-control for free.
"""
from __future__ import annotations

import threading
from typing import List, Optional

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.stream import Stream, StreamInputHandler, StreamOptions


class ProgressiveAttachment:
    """Server side: returned by Controller.create_progressive_attachment();
    write chunks after done(), close when finished
    (progressive_attachment.h Write/n)."""

    def __init__(self, stream: Stream):
        self._stream = stream

    def write(self, data) -> int:
        return self._stream.write(data)

    def close(self):
        self._stream.close()

    @property
    def closed(self) -> bool:
        return self._stream.closed


class ProgressiveReader(StreamInputHandler):
    """Client side: receives chunks (progressive_reader.h OnReadOnePart /
    OnEndOfMessage). Subclass or use iter_chunks()."""

    def __init__(self):
        self._chunks: List[bytes] = []
        self._cond = threading.Condition()
        self._ended = False
        self._error: Optional[str] = None

    # StreamInputHandler
    def on_received_messages(self, stream, messages):
        with self._cond:
            for m in messages:
                part = m.to_bytes()
                self._chunks.append(part)
                self.on_read_one_part(part)
            self._cond.notify_all()

    def on_closed(self, stream):
        with self._cond:
            self._ended = True
            self._cond.notify_all()
        self.on_end_of_message()

    # overridable callbacks (reader.h names)
    def on_read_one_part(self, data: bytes):
        pass

    def on_end_of_message(self):
        pass

    # pull-style consumption
    def next_chunk(self, timeout: float = 5.0) -> Optional[bytes]:
        with self._cond:
            while not self._chunks and not self._ended:
                if not self._cond.wait(timeout):
                    return None
            if self._chunks:
                return self._chunks.pop(0)
            return None  # ended

    def read_all(self, timeout: float = 10.0) -> bytes:
        out = []
        while True:
            c = self.next_chunk(timeout)
            if c is None:
                break
            out.append(c)
        return b"".join(out)

    @property
    def ended(self) -> bool:
        return self._ended


def create_progressive_attachment(cntl, max_buf_size: int = 2 << 20
                                  ) -> Optional[ProgressiveAttachment]:
    """Server handler API (Controller::CreateProgressiveAttachment role):
    requires the client to have attached a reader (which rides the stream
    setup)."""
    from brpc_tpu.rpc.stream import stream_accept

    s = stream_accept(cntl, StreamOptions(max_buf_size=max_buf_size))
    if s is None:
        return None
    return ProgressiveAttachment(s)


def attach_progressive_reader(cntl, reader: ProgressiveReader):
    """Client side, BEFORE the call (Controller::ReadProgressiveAttachmentBy
    role): the reader rides the stream-create lane."""
    from brpc_tpu.rpc.stream import stream_create

    stream = stream_create(cntl, StreamOptions(handler=reader))
    return stream
