"""nova_pbrpc / public_pbrpc / ubrpc — the remaining Baidu legacy pb-rpc
variants, all riding nshead framing.

Counterparts of /root/reference/src/brpc/policy/{nova_pbrpc_protocol.cpp,
public_pbrpc_protocol.cpp, ubrpc2pb_protocol.cpp}. Like the reference
(global.cpp:449,460,537 register NULL process_request), these are
CLIENT-side protocols; servers answer them through NsheadService adaptors
(the NovaServiceAdaptor shape, nova_pbrpc_protocol.cpp:52-111) installed
as ServerOptions.nshead_service.

Wire shapes:
  nova   — nshead + pb body; method index rides nshead.reserved; the
           snappy flag rides nshead.version (nova_pbrpc_protocol.cpp:
           43-51); correlation parks on the socket (pooled/short).
  public — nshead + PublicPbrpcRequest/Response envelope pb; correlation
           is requestBody.id, so single connections work.
  ubrpc  — nshead + mcpack object {method, params:[{request...}]}
           (ubrpc2pb_protocol.cpp's compack/mcpack unboxing); correlation
           parks on the socket.
"""
from __future__ import annotations

from brpc_tpu.bthread import id as bthread_id
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.rpc import compress as compress_mod
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.controller import Controller
from brpc_tpu.rpc.nshead_protocol import (
    NsheadInputMessage,
    NsheadMessage,
    NsheadService,
    parse as nshead_parse,
)
from brpc_tpu.rpc.protocol import (
    ParseResult,
    Protocol,
    ProtocolType,
    register_protocol,
)
from brpc_tpu.rpc.proto import legacy_meta_pb2 as _pb

_NOVA_SNAPPY_VERSION = 1  # nshead.version flag value for snappy bodies


def _pb_serialize_request(request, cntl: Controller):
    if isinstance(request, (bytes, bytearray)):
        return bytes(request)
    return request.SerializeToString()


def _stale_guard(sock, attr: str, correlation_id: int):
    """esp's socket-parked-correlation discipline: a previous RPC whose
    response was never consumed poisons the connection — a late reply
    could complete the WRONG call (esp_protocol.py pack_request)."""
    if getattr(sock, attr, None) is not None:
        sock.set_failed(errors.ECLOSE,
                        f"{attr.split('_')[0]} response outstanding")
        raise ValueError("socket has an unconsumed in-flight response")
    setattr(sock, attr, correlation_id)


def _client_parse(portal: IOBuf, sock, read_eof: bool, arg) -> ParseResult:
    """These protocols never serve a port: claim frames only on client
    connections (arg None), with nshead's own framing."""
    if arg is not None:
        return ParseResult.try_others()
    res = nshead_parse(portal, sock, read_eof, arg)
    if res.error == 0 and res.message is not None:
        res.message.is_request = False  # responses on a client socket
    return res


def _lock_controller(cid: int):
    try:
        cntl = bthread_id.lock(cid)
    except (KeyError, TimeoutError):
        return None
    if not isinstance(cntl, Controller):
        try:
            bthread_id.unlock(cid)
        except Exception:
            pass
        return None
    return cntl


# -- nova_pbrpc --------------------------------------------------------------

def _nova_pack_request(payload: bytes, cntl: Controller,
                       correlation_id: int) -> IOBuf:
    _stale_guard(cntl._current_sock, "nova_correlation_id", correlation_id)
    version = 0
    if cntl.compress_type == compress_mod.COMPRESS_SNAPPY:
        payload = compress_mod.compress(payload, cntl.compress_type)
        version = _NOVA_SNAPPY_VERSION
    _, _, method = cntl._method_full_name.rpartition(".")
    # The method NAME rides provider (our adaptor dispatches by it);
    # stock nova servers dispatch by descriptor index in nshead.reserved,
    # which a name-addressed client cannot derive — callers targeting a
    # stock server must set cntl.nova_method_index explicitly.
    idx = getattr(cntl, "nova_method_index", None)
    msg = NsheadMessage(payload, version=version,
                        log_id=cntl.log_id & 0xFFFFFFFF,
                        provider=method.encode(),
                        reserved=idx if idx is not None else 0)
    return IOBuf(msg.serialize())


def _nova_process_response(msg: NsheadInputMessage):
    sock = msg.socket
    cid = getattr(sock, "nova_correlation_id", None)
    if cid is None:
        return
    sock.nova_correlation_id = None
    cntl = _lock_controller(cid)
    if cntl is None:
        return
    if msg.msg.id:
        # our adaptor signals failure in the (otherwise unused) id field
        cntl.set_failed(msg.msg.id, "nova server error")
        cntl._end_rpc_locked_or_not(locked=True)
        return
    try:
        body = msg.msg.body
        if msg.msg.version == _NOVA_SNAPPY_VERSION:
            body = compress_mod.decompress(body,
                                           compress_mod.COMPRESS_SNAPPY)
        resp = cntl._response
        if resp is not None and body:
            resp.ParseFromString(body)
    except Exception as e:
        cntl.set_failed(errors.ERESPONSE, f"fail to parse response: {e}")
    cntl._end_rpc_locked_or_not(locked=True)


class NovaServiceAdaptor(NsheadService):
    """Server half (nova_pbrpc_protocol.cpp:52-111): resolve the method
    from nshead.reserved (or the provider-field name our client sends),
    body = pb, snappy via nshead.version."""

    def __init__(self, service):
        self.service = service
        self._by_index = sorted(service.methods().keys())

    def process_nshead_request(self, cntl, request: NsheadMessage, done):
        methods = self.service.methods()
        name = request.provider.rstrip(b"\x00").decode("utf-8", "replace")
        minfo = methods.get(name)
        if minfo is None:
            # index dispatch only when the name is absent, or when the
            # 16-byte provider field truncated it (prefix check) — an
            # unknown name must FAIL, not run method 0
            idx = request.reserved
            if 0 <= idx < len(self._by_index):
                cand = self._by_index[idx]
                if not name or (len(name) == 16 and cand.startswith(name)):
                    minfo = methods.get(cand)
        if minfo is None:
            done(NsheadMessage(b"", id_=errors.ENOMETHOD))
            return
        body = request.body
        if request.version == _NOVA_SNAPPY_VERSION:
            body = compress_mod.decompress(body,
                                           compress_mod.COMPRESS_SNAPPY)
        req = minfo.request_class()
        req.ParseFromString(body)
        resp = minfo.response_class()

        def inner_done():
            out = resp.SerializeToString()
            version = 0
            if request.version == _NOVA_SNAPPY_VERSION:
                out = compress_mod.compress(out,
                                            compress_mod.COMPRESS_SNAPPY)
                version = _NOVA_SNAPPY_VERSION
            done(NsheadMessage(out, version=version,
                               log_id=request.log_id))

        minfo.handler(self.service, cntl, req, resp, inner_done)


register_protocol(Protocol(
    name="nova_pbrpc",
    type=ProtocolType.NOVA,
    parse=_client_parse,
    serialize_request=_pb_serialize_request,
    pack_request=_nova_pack_request,
    process_response=_nova_process_response,
    support_server=False,
    supported_connection_types=("pooled", "short"),
    process_inline=True,
    extra={"can_repool": lambda sock: getattr(
        sock, "nova_correlation_id", None) is None},
))


# -- public_pbrpc ------------------------------------------------------------

def _public_pack_request(payload: bytes, cntl: Controller,
                         correlation_id: int) -> IOBuf:
    env = _pb.PublicPbrpcRequest()
    env.requestHead.log_id = cntl.log_id
    env.requestHead.compress_type = 0
    body = env.requestBody.add()
    service, _, method = cntl._method_full_name.rpartition(".")
    body.service = service.rpartition(".")[2]
    body.method_id = 0
    body.version = method  # name rides version for OUR peer
    body.id = correlation_id
    body.serialized_request = payload
    msg = NsheadMessage(env.SerializeToString(),
                        log_id=cntl.log_id & 0xFFFFFFFF)
    return IOBuf(msg.serialize())


def _public_process_response(msg: NsheadInputMessage):
    env = _pb.PublicPbrpcResponse()
    try:
        env.ParseFromString(msg.msg.body)
    except Exception:
        return
    for body in env.responseBody:
        cntl = _lock_controller(body.id)
        if cntl is None:
            continue
        if env.responseHead.code != 0 or body.error:
            cntl.set_failed(body.error or env.responseHead.code,
                            env.responseHead.text or "public_pbrpc error")
        else:
            resp = cntl._response
            try:
                if resp is not None and body.serialized_response:
                    resp.ParseFromString(body.serialized_response)
            except Exception as e:
                cntl.set_failed(errors.ERESPONSE,
                                f"fail to parse response: {e}")
        cntl._end_rpc_locked_or_not(locked=True)


class PublicPbrpcServiceAdaptor(NsheadService):
    """Server half: unwrap PublicPbrpcRequest, dispatch each body, answer
    with a PublicPbrpcResponse carrying matching ids."""

    def __init__(self, service):
        self.service = service
        self._by_index = sorted(service.methods().keys())

    def process_nshead_request(self, cntl, request: NsheadMessage, done):
        env = _pb.PublicPbrpcRequest()
        try:
            env.ParseFromString(request.body)
        except Exception as e:
            done(NsheadMessage(f"bad envelope: {e}".encode()))
            return
        import threading

        out = _pb.PublicPbrpcResponse()
        out.responseHead.code = 0
        methods = self.service.methods()
        lock = threading.Lock()
        pending = [len(env.requestBody)]

        def finish():
            done(NsheadMessage(out.SerializeToString(),
                               log_id=request.log_id))

        def dec():
            with lock:
                pending[0] -= 1
                return pending[0] == 0

        if not env.requestBody:
            finish()
            return
        for body in env.requestBody:
            # resolve strictly: the NAME our client sends (in .version),
            # else the method_id index when no name is present — an
            # unknown name fails with ENOMETHOD, never index fallback
            name = body.version or ""
            minfo = methods.get(name)
            if minfo is None and not name and 0 <= body.method_id < len(
                    self._by_index):
                minfo = methods.get(self._by_index[body.method_id])
            rb = out.responseBody.add()
            rb.id = body.id
            if minfo is None:
                rb.error = errors.ENOMETHOD
                if dec():
                    finish()
                continue
            req = minfo.request_class()
            try:
                req.ParseFromString(body.serialized_request)
            except Exception:
                rb.error = errors.EREQUEST
                if dec():
                    finish()
                continue
            resp = minfo.response_class()

            def inner_done(rb=rb, resp=resp):
                rb.serialized_response = resp.SerializeToString()
                if dec():
                    finish()

            minfo.handler(self.service, cntl, req, resp, inner_done)


register_protocol(Protocol(
    name="public_pbrpc",
    type=ProtocolType.PUBLIC,
    parse=_client_parse,
    serialize_request=_pb_serialize_request,
    pack_request=_public_pack_request,
    process_response=_public_process_response,
    support_server=False,
    process_inline=True,
))


# -- ubrpc (over mcpack) ------------------------------------------------------

def _ubrpc_serialize_request(request, cntl: Controller):
    from brpc_tpu.mcpack2pb import _pb_to_dict

    if isinstance(request, dict):
        return request
    return _pb_to_dict(request)


def _ubrpc_pack_request(req_obj: dict, cntl: Controller,
                        correlation_id: int) -> IOBuf:
    from brpc_tpu import mcpack2pb as mp

    _stale_guard(cntl._current_sock, "ubrpc_correlation_id",
                 correlation_id)
    _, _, method = cntl._method_full_name.rpartition(".")
    obj = {"method": method, "params": [req_obj]}
    msg = NsheadMessage(mp.dumps(obj), log_id=cntl.log_id & 0xFFFFFFFF)
    return IOBuf(msg.serialize())


def _ubrpc_process_response(msg: NsheadInputMessage):
    from brpc_tpu import mcpack2pb as mp
    from brpc_tpu.mcpack2pb import _dict_to_pb

    sock = msg.socket
    cid = getattr(sock, "ubrpc_correlation_id", None)
    if cid is None:
        return
    sock.ubrpc_correlation_id = None
    cntl = _lock_controller(cid)
    if cntl is None:
        return
    try:
        obj = mp.loads(msg.msg.body)
        err = obj.get("error_code", 0)
        if err:
            cntl.set_failed(int(err), str(obj.get("error_text", "ubrpc")))
        else:
            result = obj.get("result")
            resp = cntl._response
            if resp is not None and isinstance(result, dict):
                if isinstance(resp, dict):
                    resp.update(result)
                else:
                    _dict_to_pb(result, resp)
    except Exception as e:
        cntl.set_failed(errors.ERESPONSE, f"fail to parse response: {e}")
    cntl._end_rpc_locked_or_not(locked=True)


class UbrpcServiceAdaptor(NsheadService):
    """Server half (ubrpc2pb_protocol.cpp): body is an mcpack object with
    'method' and a params array; reply is {error_code, result}."""

    def __init__(self, service):
        self.service = service

    def process_nshead_request(self, cntl, request: NsheadMessage, done):
        from brpc_tpu import mcpack2pb as mp
        from brpc_tpu.mcpack2pb import _dict_to_pb, _pb_to_dict

        try:
            obj = mp.loads(request.body)
            method = obj.get("method")
            if isinstance(method, bytes):
                method = method.decode()
            params = obj.get("params") or [{}]
        except Exception as e:
            done(NsheadMessage(mp.dumps(
                {"error_code": errors.EREQUEST,
                 "error_text": f"bad mcpack: {e}"})))
            return
        minfo = self.service.methods().get(method or "")
        if minfo is None:
            done(NsheadMessage(mp.dumps(
                {"error_code": errors.ENOMETHOD,
                 "error_text": f"unknown method {method!r}"})))
            return
        req = minfo.request_class()
        _dict_to_pb(params[0] if params else {}, req)
        resp = minfo.response_class()

        def inner_done():
            done(NsheadMessage(mp.dumps(
                {"error_code": 0, "result": _pb_to_dict(resp)}),
                log_id=request.log_id))

        minfo.handler(self.service, cntl, req, resp, inner_done)


register_protocol(Protocol(
    name="ubrpc",
    type=ProtocolType.UBRPC,
    parse=_client_parse,
    serialize_request=_ubrpc_serialize_request,
    pack_request=_ubrpc_pack_request,
    process_response=_ubrpc_process_response,
    support_server=False,
    supported_connection_types=("pooled", "short"),
    process_inline=True,
    extra={"can_repool": lambda sock: getattr(
        sock, "ubrpc_correlation_id", None) is None},
))

