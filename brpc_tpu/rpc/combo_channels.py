"""Combo channels — fan-out / shard / failover composition of channels.

Counterparts of brpc's combo channels (SURVEY.md section 2.6):

* ParallelChannel (/root/reference/src/brpc/parallel_channel.h:94-218):
  one call fans out to every sub-channel, each mapped by a CallMapper and
  merged by a ResponseMerger; the call fails when failed sub-calls reach
  fail_limit (default: all).
* PartitionChannel (/root/reference/src/brpc/partition_channel.h:41-103):
  one channel per partition drawn from a single naming service whose server
  tags name partitions like "2/4" (index/total).
* DynamicPartitionChannel (partition_channel.h:136-142): servers may belong
  to different partitioning schemes (4-way and 8-way mixed during
  migration); a call picks a scheme weighted by its capacity and fans to
  that scheme's partitions.
* SelectiveChannel (/root/reference/src/brpc/selective_channel.h:52-72):
  picks ONE sub-channel per call with health-based failover retry.

These are the RPC-call-shaped counterparts of DP/TP-style fan-out; the mesh
fusion (fan-out as one XLA collective over ICI) lives in
brpc_tpu.parallel.mesh_channel and composes with these.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from brpc_tpu.rpc import errors
from brpc_tpu.rpc.channel import Channel, ChannelOptions
from brpc_tpu.rpc.controller import Controller

# ---------------------------------------------------------------------------
# Native fast path (ISSUE 13): the combo channels grow `native=True` —
# same API shape as the Python path, but the server list, the LB, the
# fan-out sub-calls and the response merge run in the C++ core
# (native/src/nat_cluster.cpp via brpc_tpu.rpc.native_cluster). The
# native merge concatenates successful sub-responses in sub-call order,
# which for serialized protobufs IS MergeFrom — the default
# ResponseMerger semantics — so response.MergeFromString(merged) yields
# the same result the Python merger produces.
# ---------------------------------------------------------------------------


def _native_cluster_init(naming_url: str, lb_name: str,
                         options: Optional[ChannelOptions],
                         node_filter=None, name: str = ""):
    from brpc_tpu.rpc.native_cluster import NativeCluster

    connect_ms = int(options.connect_timeout_ms) if options else 500
    hc_ms = (int(options.health_check_interval_s * 1000)
             if options is not None and options.health_check_interval_s > 0
             else 100)
    cluster = NativeCluster(lb=lb_name or "rr",
                            connect_timeout_ms=connect_ms,
                            health_check_ms=hc_ms, name=name)
    cluster.watch(naming_url, node_filter)
    return cluster


def _native_run(cntl: Controller, done, fn):
    """Run one native combo verb: sync inline, async on a thread (combo
    fan-out is already parallel natively; the thread only carries the
    done-callback contract)."""
    if done is None:
        fn()
        return
    t = threading.Thread(target=fn, daemon=True)
    t.start()


def _native_finish(cntl: Controller, response, rc: int, body: bytes,
                   err: str, start_time: float, done):
    import time as _t

    if rc == 0:
        if response is not None and body:
            response.MergeFromString(body)
    else:
        cntl.set_failed(rc, err or errors.berror(rc))
    cntl.latency_us = (_t.monotonic() - start_time) * 1e6
    if done is not None:
        done(cntl)


class SubCall:
    """What a CallMapper returns for one sub-channel
    (parallel_channel.h SubCall)."""

    __slots__ = ("method", "request", "response", "skip")

    def __init__(self, method=None, request=None, response=None,
                 skip: bool = False):
        self.method = method
        self.request = request
        self.response = response
        self.skip = skip

    @classmethod
    def skip_call(cls) -> "SubCall":
        return cls(skip=True)


class CallMapper:
    """Maps the main call onto sub-channel i (parallel_channel.h:94)."""

    def map(self, channel_index: int, method: str, request, response) -> SubCall:
        # Default: broadcast the same request; fresh response per sub-call.
        sub_resp = type(response)() if response is not None else None
        return SubCall(method, request, sub_resp)


class ResponseMerger:
    """Merges one sub-response into the main response
    (parallel_channel.h:185). Return 0 on success, <0 to count as failed."""

    def merge(self, main_response, sub_response) -> int:
        if main_response is None or sub_response is None:
            return 0
        try:
            main_response.MergeFrom(sub_response)
            return 0
        except Exception:
            return -1


class ParallelChannel:
    def __init__(self, fail_limit: int = -1, native: bool = False):
        self._subs: List[Tuple[Channel, Optional[CallMapper], Optional[ResponseMerger]]] = []
        self.fail_limit = fail_limit
        self.native = native
        self._cluster = None

    def init(self, naming_url: str, lb_name: str = "rr",
             options: Optional[ChannelOptions] = None) -> int:
        """Native-mode init (same shape as Channel.init): the naming url
        feeds the C++ cluster; every resolved server is a sub-channel.
        The Python path keeps using add_channel()."""
        if not self.native:
            raise ValueError("init(naming_url) requires native=True; "
                             "use add_channel() on the Python path")
        self._cluster = _native_cluster_init(naming_url, lb_name, options,
                                             name="parallel")
        self._options = options
        return 0

    def add_channel(self, channel: Channel,
                    call_mapper: Optional[CallMapper] = None,
                    response_merger: Optional[ResponseMerger] = None):
        if self._cluster is not None:
            raise ValueError("native ParallelChannel fans to its naming "
                             "service's servers; add_channel is the "
                             "Python path")
        self._subs.append((channel, call_mapper, response_merger))

    @property
    def channel_count(self) -> int:
        if self._cluster is not None:
            return self._cluster.backend_count()
        return len(self._subs)

    def stop(self):
        if self._cluster is not None:
            self._cluster.close()

    def _call_method_native(self, method: str, cntl: Controller, request,
                            response, done: Optional[Callable]):
        import time as _t

        payload = request.SerializeToString() if request is not None else b""
        timeout_ms = int(cntl.timeout_ms or 1000)
        fail_limit = self.fail_limit if self.fail_limit > 0 else 0
        start_time = _t.monotonic()

        def run():
            rc, body, err, _failed = self._cluster.parallel_call(
                method, payload, timeout_ms=timeout_ms,
                fail_limit=fail_limit)
            _native_finish(cntl, response, rc, body, err, start_time,
                           done)

        _native_run(cntl, done, run)

    def call_method(self, method: str, cntl: Controller, request, response,
                    done: Optional[Callable] = None):
        if self._cluster is not None:
            self._call_method_native(method, cntl, request, response, done)
            return
        n = len(self._subs)
        if n == 0:
            cntl.set_failed(errors.EINVAL, "no sub channels")
            if done:
                done(cntl)
            return
        fail_limit = self.fail_limit if self.fail_limit > 0 else n
        default_mapper = CallMapper()
        default_merger = ResponseMerger()
        state = {
            "pending": 0, "failed": 0, "merge_failed": 0,
            "first_error": (0, ""), "lock": threading.Lock(),
            "finished": False,
        }
        sub_cntls: List[Controller] = []
        calls = []
        for i, (ch, mapper, merger) in enumerate(self._subs):
            sub = (mapper or default_mapper).map(i, method, request, response)
            if sub.skip:
                continue
            calls.append((i, ch, sub, merger or default_merger))
        if not calls:
            cntl.set_failed(errors.EINVAL, "all sub calls skipped")
            if done:
                done(cntl)
            return
        state["pending"] = len(calls)
        finished_ev = threading.Event()

        def finalize():
            if state["failed"] >= min(fail_limit, len(calls)):
                code, text = state["first_error"]
                cntl.set_failed(errors.ETOOMANYFAILS,
                                f"{state['failed']}/{len(calls)} sub calls "
                                f"failed, first: {errors.berror(code)} {text}")
            import time as _t

            cntl.latency_us = (_t.monotonic() - start_time) * 1e6
            if done is not None:
                done(cntl)
            finished_ev.set()

        def make_done(index, sub, merger):
            def sub_done(sub_cntl: Controller):
                run_final = False
                with state["lock"]:
                    if sub_cntl.failed():
                        state["failed"] += 1
                        if state["first_error"][0] == 0:
                            state["first_error"] = (sub_cntl.error_code,
                                                    sub_cntl.error_text)
                    else:
                        rc = merger.merge(response, sub.response)
                        if rc < 0:
                            state["failed"] += 1
                            if state["first_error"][0] == 0:
                                state["first_error"] = (
                                    errors.EREQUEST, "response merge failed")
                    state["pending"] -= 1
                    if state["pending"] == 0 and not state["finished"]:
                        state["finished"] = True
                        run_final = True
                if run_final:
                    finalize()

            return sub_done

        import time as _t

        start_time = _t.monotonic()
        for index, ch, sub, merger in calls:
            sub_cntl = Controller()
            sub_cntl.timeout_ms = cntl.timeout_ms
            sub_cntl.max_retry = cntl.max_retry
            sub_cntl.compress_type = cntl.compress_type
            sub_cntl.request_attachment.append(cntl.request_attachment)
            sub_cntls.append(sub_cntl)
            ch.call_method(sub.method or method, sub_cntl, sub.request,
                           sub.response, make_done(index, sub, merger))
        if done is None:
            finished_ev.wait()

    def call(self, method: str, request, response_class,
             timeout_ms: Optional[float] = None):
        cntl = Controller()
        if timeout_ms is not None:
            cntl.timeout_ms = timeout_ms
        response = response_class() if response_class else None
        self.call_method(method, cntl, request, response)
        return cntl, response


class PartitionParser:
    """Parses a server tag into (partition_index, partition_count)
    (partition_channel.h PartitionParser). Default syntax: 'N/M'."""

    def parse(self, tag: str) -> Optional[Tuple[int, int]]:
        try:
            idx_s, _, total_s = tag.partition("/")
            idx, total = int(idx_s), int(total_s)
            if 0 <= idx < total:
                return idx, total
        except ValueError:
            pass
        return None


class PartitionChannel(ParallelChannel):
    """N sub-channels fed by ONE naming service; server tag picks the
    partition (partition_channel.h:41-103)."""

    def __init__(self, fail_limit: int = -1, native: bool = False):
        super().__init__(fail_limit, native=native)
        self._ns_threads = []
        self._partition_count = 0

    def init(self, partition_count: int, naming_url: str, lb_name: str = "rr",
             parser: Optional[PartitionParser] = None,
             options: Optional[ChannelOptions] = None) -> int:
        if self.native:
            # the C++ core groups backends by the default "i/n" tag
            # grammar; a custom parser needs the Python path
            if parser is not None and type(parser) is not PartitionParser:
                raise ValueError("native PartitionChannel supports the "
                                 "default 'i/n' tag grammar only")
            self._partition_count = partition_count
            self._cluster = _native_cluster_init(naming_url, lb_name,
                                                 options,
                                                 name="partition")
            return 0
        parser = parser or PartitionParser()
        for part in range(partition_count):
            ch = Channel(options)

            def node_filter(node, part=part):
                _, _, tag = node
                parsed = parser.parse(tag)
                return (parsed is not None and parsed[0] == part
                        and parsed[1] == partition_count)

            rc = ch.init_with_filter(naming_url, lb_name, node_filter)
            if rc != 0:
                return rc
            self._ns_threads.append(ch._ns_thread)
            self.add_channel(ch)
        return 0

    def _call_method_native(self, method: str, cntl: Controller, request,
                            response, done: Optional[Callable]):
        import time as _t

        payload = request.SerializeToString() if request is not None else b""
        timeout_ms = int(cntl.timeout_ms or 1000)
        fail_limit = self.fail_limit if self.fail_limit > 0 else 0
        start_time = _t.monotonic()

        def run():
            rc, body, err, _failed = self._cluster.partition_call(
                method, payload, timeout_ms=timeout_ms,
                partitions=self._partition_count, fail_limit=fail_limit)
            _native_finish(cntl, response, rc, body, err, start_time,
                           done)

        _native_run(cntl, done, run)

    def stop(self):
        super().stop()
        for t in self._ns_threads:
            if t is not None:
                t.stop()


class DynamicPartitionChannel:
    """Multiple partitioning schemes co-existing; scheme chosen per call,
    weighted by its server capacity (partition_channel.h:136-142).

    native=True rides nat_cluster_dynpart_call: ONE C++ cluster holds
    every "i/n"-tagged backend, the scheme pick (_dynpart, capacity-
    weighted) and the per-group fan happen under one zero-lock server-
    list pin, and a resize (naming update changing the scheme layout)
    publishes a new list version while in-flight calls finish against
    their pinned one — never caller-visible."""

    def __init__(self, fail_limit: int = -1, native: bool = False):
        self.fail_limit = fail_limit
        self.native = native
        self._cluster = None
        self._schemes: Dict[int, PartitionChannel] = {}
        self._lock = threading.Lock()
        self._url = ""
        self._lb_name = "rr"
        self._parser: Optional[PartitionParser] = None
        self._options: Optional[ChannelOptions] = None

    def init(self, naming_url: str, lb_name: str = "rr",
             parser: Optional[PartitionParser] = None,
             options: Optional[ChannelOptions] = None,
             schemes: Optional[List[int]] = None) -> int:
        """schemes: partition counts to serve (discovered from tags when
        omitted requires a first resolution; explicit list keeps it simple
        and deterministic). The native path ignores `schemes` — the C++
        cluster derives the live scheme set from the tags on every naming
        refresh, which is what makes the partition count truly dynamic."""
        self._url = naming_url
        self._lb_name = lb_name
        self._parser = parser or PartitionParser()
        self._options = options
        if self.native:
            # the C++ core groups backends by the default "i/n" tag
            # grammar; a custom parser needs the Python path
            if parser is not None and type(parser) is not PartitionParser:
                raise ValueError("native DynamicPartitionChannel supports "
                                 "the default 'i/n' tag grammar only")
            self._cluster = _native_cluster_init(naming_url, "_dynpart",
                                                 options, name="dynpart")
            return 0
        if not schemes:
            from brpc_tpu.rpc.naming_service import start_naming_service  # noqa: F401
            from brpc_tpu.rpc.naming_service import _ns_registry

            scheme, _, path = naming_url.partition("://")
            factory = _ns_registry.get(scheme)
            if factory is None:
                return errors.EINVAL
            nodes = factory().get_servers(path)
            found = set()
            for _, _, tag in nodes:
                parsed = self._parser.parse(tag)
                if parsed:
                    found.add(parsed[1])
            schemes = sorted(found)
        if not schemes:
            return errors.EINVAL
        # Scheme selection rides the real _dynpart LB policy (the reference
        # wires DynamicPartitionChannel through a SelectiveChannel whose LB
        # is "_dynpart", partition_channel.cpp:462): members are scheme
        # handles, weight = live server capacity of that scheme.
        from brpc_tpu.rpc.load_balancer import create_load_balancer

        self._dynlb = create_load_balancer("_dynpart")
        self._dynlb.set_capacity_fn(self._scheme_capacity)
        for total in schemes:
            pc = PartitionChannel(self.fail_limit)
            rc = pc.init(total, naming_url, lb_name, self._parser, options)
            if rc != 0:
                return rc
            self._schemes[total] = pc
            self._dynlb.add_server(total)  # sid = scheme handle
        return 0

    def _scheme_capacity(self, total: int) -> int:
        pc = self._schemes.get(total)
        if pc is None:
            return 0
        return sum(ch._lb.server_count() for ch, _, _ in pc._subs
                   if ch._lb is not None)

    def _pick_scheme(self) -> Optional[PartitionChannel]:
        total = self._dynlb.select_server()
        return self._schemes.get(total) if total is not None else None

    def _call_method_native(self, method: str, cntl: Controller, request,
                            response, done: Optional[Callable]):
        import time as _t

        payload = request.SerializeToString() if request is not None else b""
        timeout_ms = int(cntl.timeout_ms or 1000)
        fail_limit = self.fail_limit if self.fail_limit > 0 else 0
        start_time = _t.monotonic()

        def run():
            rc, body, err, _failed, scheme = self._cluster.dynpart_call(
                method, payload, timeout_ms=timeout_ms,
                fail_limit=fail_limit)
            cntl.partition_count = scheme
            _native_finish(cntl, response, rc, body, err, start_time,
                           done)

        _native_run(cntl, done, run)

    def call_method(self, method: str, cntl: Controller, request, response,
                    done: Optional[Callable] = None):
        if self._cluster is not None:
            self._call_method_native(method, cntl, request, response, done)
            return
        pc = self._pick_scheme()
        if pc is None:
            cntl.set_failed(errors.EFAILEDSOCKET, "no usable partition scheme")
            if done:
                done(cntl)
            return
        pc.call_method(method, cntl, request, response, done)

    def call(self, method: str, request, response_class,
             timeout_ms: Optional[float] = None):
        cntl = Controller()
        if timeout_ms is not None:
            cntl.timeout_ms = timeout_ms
        response = response_class() if response_class else None
        self.call_method(method, cntl, request, response)
        return cntl, response

    def stop(self):
        if self._cluster is not None:
            self._cluster.close()
        for pc in self._schemes.values():
            pc.stop()


class SelectiveChannel:
    """LB over channels with failover (selective_channel.h:52-72): each call
    goes to ONE sub-channel; failure retries another."""

    def __init__(self, max_retry: int = 2, native: bool = False):
        self._channels: List[Channel] = []
        self._health: Dict[int, int] = {}  # index -> consecutive failures
        self._index = 0
        self._lock = threading.Lock()
        self.max_retry = max_retry
        self.native = native
        self._cluster = None

    def init(self, naming_url: str, lb_name: str = "rr",
             options: Optional[ChannelOptions] = None) -> int:
        """Native-mode init: LB + failover retry run in the C++ cluster
        (selection excludes already-tried backends, the per-backend
        breakers fail dead peers fast, lame-duck peers re-balance)."""
        if not self.native:
            raise ValueError("init(naming_url) requires native=True; "
                             "use add_channel() on the Python path")
        self._cluster = _native_cluster_init(naming_url, lb_name, options,
                                             name="selective")
        return 0

    def stop(self):
        if self._cluster is not None:
            self._cluster.close()

    def add_channel(self, channel: Channel) -> int:
        if self._cluster is not None:
            raise ValueError("native SelectiveChannel balances over its "
                             "naming service's servers; add_channel is "
                             "the Python path")
        with self._lock:
            self._channels.append(channel)
            return len(self._channels) - 1

    @property
    def channel_count(self) -> int:
        if self._cluster is not None:
            return self._cluster.backend_count()
        return len(self._channels)

    def _select(self, exclude: set) -> Optional[int]:
        with self._lock:
            n = len(self._channels)
            if n == 0:
                return None
            # prefer channels with fewest consecutive failures (health)
            order = sorted(
                (i for i in range(n) if i not in exclude),
                key=lambda i: self._health.get(i, 0),
            )
            if not order:
                return None
            healthiest = self._health.get(order[0], 0)
            candidates = [i for i in order
                          if self._health.get(i, 0) == healthiest]
            self._index = (self._index + 1) % len(candidates)
            return candidates[self._index]

    def _call_method_native(self, method: str, cntl: Controller, request,
                            response, done: Optional[Callable]):
        import time as _t

        payload = request.SerializeToString() if request is not None else b""
        timeout_ms = int(cntl.timeout_ms or 1000)
        start_time = _t.monotonic()
        request_code = int(getattr(cntl, "request_code", 0) or 0)

        def run():
            rc, body, err = self._cluster.call(
                method, payload, timeout_ms=timeout_ms,
                max_retry=self.max_retry, request_code=request_code)
            if rc == 0 and response is not None and body:
                response.Clear()  # one backend answered: replace, not merge
            _native_finish(cntl, response, rc, body, err, start_time,
                           done)

        _native_run(cntl, done, run)

    def call_method(self, method: str, cntl: Controller, request, response,
                    done: Optional[Callable] = None):
        if self._cluster is not None:
            self._call_method_native(method, cntl, request, response, done)
            return
        tried = set()
        last_cntl = None
        for _ in range(self.max_retry + 1):
            idx = self._select(tried)
            if idx is None:
                break
            tried.add(idx)
            sub_cntl = Controller()
            sub_cntl.timeout_ms = cntl.timeout_ms
            sub_cntl.max_retry = cntl.max_retry
            sub_cntl.compress_type = cntl.compress_type
            sub_cntl.request_attachment.append(cntl.request_attachment)
            self._channels[idx].call_method(method, sub_cntl, request,
                                            response, None)
            last_cntl = sub_cntl
            with self._lock:
                if sub_cntl.failed():
                    self._health[idx] = self._health.get(idx, 0) + 1
                else:
                    self._health[idx] = 0
            if not sub_cntl.failed():
                cntl.latency_us = sub_cntl.latency_us
                cntl.remote_side = sub_cntl.remote_side
                if done:
                    done(cntl)
                return
        if last_cntl is not None:
            cntl.set_failed(last_cntl.error_code, last_cntl.error_text)
        else:
            cntl.set_failed(errors.EFAILEDSOCKET, "no usable sub channel")
        if done:
            done(cntl)

    def call(self, method: str, request, response_class,
             timeout_ms: Optional[float] = None):
        cntl = Controller()
        if timeout_ms is not None:
            cntl.timeout_ms = timeout_ms
        response = response_class() if response_class else None
        self.call_method(method, cntl, request, response)
        return cntl, response
