"""Version-drift shims for the jax surface the repo leans on.

jax moved ``shard_map`` twice in the window we support: it lives at
``jax.experimental.shard_map.shard_map`` on older releases (0.4.x, with a
``check_rep`` kwarg), graduated to ``jax.shard_map`` later, and the
replication-check kwarg was renamed ``check_rep`` -> ``check_vma`` along
the way.  Every in-repo caller goes through :func:`shard_map` below so the
probe happens in exactly one place instead of a try/except at each site.
"""
from __future__ import annotations

import jax

try:  # newer jax: top-level export
    _shard_map_impl = jax.shard_map
except AttributeError:  # 0.4.x: experimental namespace only
    from jax.experimental.shard_map import shard_map as _shard_map_impl

# The replication-check kwarg name drifted: probe once, lazily, and pin it.
_CHECK_KW: list = [None]  # [None]=unprobed, ["check_vma"/"check_rep"/""]=pinned


def shard_map(f, mesh, in_specs, out_specs, check=None):
    """``jax.shard_map`` across the supported jax versions.

    ``check`` maps onto whichever of ``check_vma``/``check_rep`` this jax
    accepts (``None`` leaves the library default in place).
    """
    if check is None:
        return _shard_map_impl(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs
        )
    if _CHECK_KW[0] is None:
        for kw in ("check_vma", "check_rep"):
            try:
                out = _shard_map_impl(
                    f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                    **{kw: check}
                )
                _CHECK_KW[0] = kw
                return out
            except TypeError:
                continue
        _CHECK_KW[0] = ""  # neither kwarg: drop the flag entirely
    if _CHECK_KW[0]:
        return _shard_map_impl(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            **{_CHECK_KW[0]: check}
        )
    return _shard_map_impl(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
