"""mcpack2pb code generator — the generator.cpp role.

Counterpart of /root/reference/src/mcpack2pb/generator.cpp (the bulk of
the mcpack2pb satellite): given protobuf message classes, EMIT Python
source with a specialized serializer/parser per message — each field
encoded with its exact mcpack type via the typed primitives
(mcpack2pb.enc_*), mirroring how the reference's generated C++ calls
serializer put_int32/put_str per field — plus an nshead service adaptor
whose per-method dispatch is unrolled at generation time, replacing the
hand-wired NsheadPbServiceAdaptor.

Usage (also exposed as tools/mcpack2pb_gen.py):

    src = generate_codec_source([echo_pb2.EchoRequest, ...])
    module = compile_codec(src, "echo_mcpack")
    wire = module.serialize_echo_request(req)

    src = generate_nshead_adaptor_source(EchoService)
    adaptor_cls = compile_codec(src, "echo_adaptor").EchoServiceNsheadAdaptor
    server options: nshead_service=adaptor_cls(EchoService())
"""
from __future__ import annotations

import re
from typing import List

from google.protobuf.descriptor import FieldDescriptor as FD

# pb type -> (enc primitive, parse coercion) — generator.cpp's
# field-type table (mcpack2pb/field_type.h mapping)
_TYPE_MAP = {
    FD.TYPE_INT32: ("enc_int32", "int"),
    FD.TYPE_SINT32: ("enc_int32", "int"),
    FD.TYPE_SFIXED32: ("enc_int32", "int"),
    FD.TYPE_INT64: ("enc_int64", "int"),
    FD.TYPE_SINT64: ("enc_int64", "int"),
    FD.TYPE_SFIXED64: ("enc_int64", "int"),
    FD.TYPE_UINT32: ("enc_uint32", "int"),
    FD.TYPE_FIXED32: ("enc_uint32", "int"),
    FD.TYPE_UINT64: ("enc_uint64", "int"),
    FD.TYPE_FIXED64: ("enc_uint64", "int"),
    FD.TYPE_BOOL: ("enc_bool", "bool"),
    FD.TYPE_FLOAT: ("enc_float", "float"),
    FD.TYPE_DOUBLE: ("enc_double", "float"),
    FD.TYPE_STRING: ("enc_str", "_to_str"),
    FD.TYPE_BYTES: ("enc_bytes", "_to_bytes"),
    FD.TYPE_ENUM: ("enc_int32", "int"),
}


def _snake(name: str) -> str:
    s = re.sub(r"(?<=[a-z0-9])([A-Z])", r"_\1", name)
    return s.lower()


from brpc_tpu.mcpack2pb import _is_repeated  # shared compat shim


def _has_presence(field) -> bool:
    try:
        return field.has_presence
    except AttributeError:  # older protobuf
        syntax = getattr(field.file, "syntax", None)
        return bool(field.label in (FD.LABEL_OPTIONAL, FD.LABEL_REQUIRED)
                    and (syntax == "proto2"
                         or field.containing_oneof is not None))


def _is_map(field) -> bool:
    return (field.type == FD.TYPE_MESSAGE
            and field.message_type.GetOptions().map_entry)


def _defining_module(cls) -> str:
    """The importable module that registers a pb2 class's descriptors.
    cls.__module__ on upb-generated classes is the bare file stem (e.g.
    'echo_pb2'), which is often NOT importable — find the real sys.modules
    entry exposing the class instead."""
    import sys as _sys

    candidates = [n for n, m in list(_sys.modules.items())
                  if m is not None
                  and getattr(m, cls.__name__, None) is cls]
    # Prefer the fully-qualified (dotted) name: a bare stem like
    # 'echo_pb2' only imports when the proto dir itself is on sys.path,
    # which a fresh consumer process usually doesn't have.
    return max(candidates, key=len) if candidates else ""


def _collect_and_name(message_classes):
    """Collect message descriptors (plus nested) and assign each a unique
    symbol stem — the short snake name, or the package-qualified one when
    two packages declare the same message name."""
    seen = {}

    def collect(desc):
        if desc.full_name in seen:
            return
        seen[desc.full_name] = desc
        for f in desc.fields:
            if _is_map(f):
                value_field = f.message_type.fields_by_name["value"]
                if value_field.type == FD.TYPE_MESSAGE:
                    collect(value_field.message_type)
            elif f.type == FD.TYPE_MESSAGE:
                collect(f.message_type)

    for cls in message_classes:
        collect(cls.DESCRIPTOR)
    names = {}
    taken = set()
    for full_name, desc in seen.items():
        stem = _snake(desc.name)
        if stem in taken:
            stem = _snake(full_name.replace(".", "_"))
        taken.add(stem)
        names[full_name] = stem
    return seen, names


def _emit_serializer(lines: List[str], desc, fn_name: str, names):
    lines.append(f"def {fn_name}(msg):")
    lines.append(f'    """Serialize {desc.full_name} as mcpack '
                 '(generated)."""')
    lines.append("    fields = []")
    for field in desc.fields:
        name = field.name
        if _is_map(field):
            # map<K,V> -> an mcpack OBJECT keyed by str(K)
            value_field = field.message_type.fields_by_name["value"]
            if value_field.type == FD.TYPE_MESSAGE:
                sub = (f"serialize_"
                       f"{names[value_field.message_type.full_name]}"
                       "_fields")
                item = f"mp.enc_object(str(k), {sub}(v))"
            else:
                venc, _ = _TYPE_MAP[value_field.type]
                item = f"mp.{venc}(str(k), v)"
            lines.append(f"    if msg.{name}:")
            lines.append(
                f"        fields.append(mp.enc_object({name!r}, "
                f"[{item} for k, v in msg.{name}.items()]))")
            continue
        if field.type == FD.TYPE_MESSAGE:
            sub = (f"serialize_{names[field.message_type.full_name]}"
                   "_fields")
            if _is_repeated(field):
                lines.append(f"    if msg.{name}:")
                lines.append(
                    f"        fields.append(mp.enc_array({name!r}, "
                    f"[mp.enc_object('', {sub}(v)) for v in msg.{name}]))")
            else:
                lines.append(f"    if msg.HasField({name!r}):")
                lines.append(
                    f"        fields.append(mp.enc_object({name!r}, "
                    f"{sub}(msg.{name})))")
            continue
        enc, _ = _TYPE_MAP[field.type]
        if _is_repeated(field):
            gate = f"    if msg.{name}:"
        elif _has_presence(field):
            # explicit presence (proto2/proto3-optional): an explicitly
            # set zero/empty value must still reach the wire
            gate = f"    if msg.HasField({name!r}):"
        else:
            gate = f"    if msg.{name}:"
        lines.append(gate)
        if _is_repeated(field):
            lines.append(
                f"        fields.append(mp.enc_array({name!r}, "
                f"[mp.{enc}('', v) for v in msg.{name}]))")
        else:
            lines.append(
                f"        fields.append(mp.{enc}({name!r}, msg.{name}))")
    lines.append("    return fields")
    lines.append("")
    lines.append("")


def _emit_parser(lines: List[str], desc, fn_name: str, cls_expr: str,
                 names):
    lines.append(f"def {fn_name}_into(obj, msg):")
    lines.append(f'    """Fill a {desc.full_name} from a decoded mcpack '
                 'object (generated)."""')
    for field in desc.fields:
        name = field.name
        lines.append(f"    v = obj.get({name!r})")
        lines.append("    if v is not None:")
        if _is_map(field):
            key_field = field.message_type.fields_by_name["key"]
            if key_field.type == FD.TYPE_BOOL:
                kcoerce = "_bool_key"  # bool('False') is True; compare
            else:
                _, kcoerce = _TYPE_MAP[key_field.type]
            value_field = field.message_type.fields_by_name["value"]
            lines.append("        for k, item in v.items():")
            if value_field.type == FD.TYPE_MESSAGE:
                sub = (f"parse_{names[value_field.message_type.full_name]}"
                       "_into")
                lines.append(
                    f"            {sub}(item, msg.{name}[{kcoerce}(k)])")
            else:
                _, vcoerce = _TYPE_MAP[value_field.type]
                lines.append(
                    f"            msg.{name}[{kcoerce}(k)] = "
                    f"{vcoerce}(item)")
            continue
        if field.type == FD.TYPE_MESSAGE:
            sub = f"parse_{names[field.message_type.full_name]}_into"
            if _is_repeated(field):
                lines.append("        for item in v:")
                lines.append(f"            {sub}(item, msg.{name}.add())")
            else:
                lines.append(f"        {sub}(v, msg.{name})")
            continue
        _, coerce = _TYPE_MAP[field.type]
        if _is_repeated(field):
            lines.append(
                f"        msg.{name}.extend({coerce}(x) for x in v)")
        else:
            lines.append(f"        msg.{name} = {coerce}(v)")
    lines.append("    return msg")
    lines.append("")
    lines.append("")
    lines.append(f"def {fn_name}(data):")
    lines.append(f"    return {fn_name}_into(mp.loads(data), {cls_expr}())")
    lines.append("")
    lines.append("")


_PRELUDE = '''\
"""GENERATED by brpc_tpu.mcpack2pb_gen — do not edit.
Specialized mcpack codecs (mcpack2pb/generator.cpp analog)."""
from brpc_tpu import mcpack2pb as mp


def _to_str(v):
    return v if isinstance(v, str) else bytes(v).decode()


def _to_bytes(v):
    return v.encode() if isinstance(v, str) else bytes(v)


def _bool_key(v):
    return v == "True" if isinstance(v, str) else bool(v)


'''


def generate_codec_source(message_classes) -> str:
    """Emit a module with serialize_<msg>/parse_<msg> per message class
    (nested message types are pulled in automatically)."""
    seen, names = _collect_and_name(message_classes)

    lines = [_PRELUDE]
    imports = sorted({d.file.name for d in seen.values()})
    lines.append(f"# sources: {', '.join(imports)}")
    # importing the defining pb2 modules registers the descriptors, so the
    # generated module is importable in a fresh process
    for module_name in sorted({m for m in map(_defining_module,
                                              message_classes) if m}):
        lines.append(f"import {module_name}  # noqa: F401 (registers pb2)")
    lines.append("from google.protobuf import symbol_database as _sdb")
    lines.append("_sym = _sdb.Default()")
    for full_name in seen:
        lines.append(f"_cls_{names[full_name]} = "
                     f"_sym.GetSymbol({full_name!r})")
    lines.append("")
    lines.append("")
    out = ["\n".join(lines)]
    body: List[str] = []
    for full_name, desc in seen.items():
        sn = names[full_name]
        _emit_serializer(body, desc, f"serialize_{sn}_fields", names)
        body.append(f"def serialize_{sn}(msg):")
        body.append(
            f"    return mp.enc_object('', serialize_{sn}_fields(msg))")
        body.append("")
        body.append("")
        _emit_parser(body, desc, f"parse_{sn}", f"_cls_{sn}", names)
    out.append("\n".join(body))
    return "".join(out)


def generate_nshead_adaptor_source(service_class) -> str:
    """Emit an NsheadService adaptor for an rpc.Service subclass: bodies
    are mcpack objects carrying a 'method' member plus the request fields;
    dispatch and codecs are unrolled per method (the generated
    ::brpc::NsheadPbServiceAdaptor of the reference)."""
    methods = service_class.methods()
    message_classes = []
    for minfo in methods.values():
        message_classes.extend([minfo.request_class, minfo.response_class])
    src = generate_codec_source(message_classes)
    _, names = _collect_and_name(message_classes)  # same stems as src
    name = re.sub(r"\W", "_", service_class.__name__)
    lines = [
        "",
        "",
        "from brpc_tpu.rpc.nshead_protocol import NsheadMessage, "
        "NsheadService",
        "",
        "",
        f"class {name}NsheadAdaptor(NsheadService):",
        f'    """Generated pb front-end for {name} over nshead-mcpack."""',
        "",
        "    def __init__(self, service):",
        "        self.service = service",
        "",
        "    def process_nshead_request(self, cntl, request, done):",
        "        try:",
        "            obj = mp.loads(request.body)",
        "        except (ValueError, IndexError, KeyError) as e:",
        "            done(NsheadMessage(('bad mcpack body: %s' % e)"
        ".encode()))",
        "            return",
        "        method = obj.get('method')",
        "        if isinstance(method, bytes):",
        "            method = method.decode()",
    ]
    for i, (mname, minfo) in enumerate(sorted(methods.items())):
        req_sn = names[minfo.request_class.DESCRIPTOR.full_name]
        resp_sn = names[minfo.response_class.DESCRIPTOR.full_name]
        cond = "if" if i == 0 else "elif"
        default = " or method is None" if len(methods) == 1 else ""
        lines += [
            f"        {cond} method == {mname!r}{default}:",
            f"            req = parse_{req_sn}_into(obj, "
            f"_cls_{req_sn}())",
            f"            resp = _cls_{resp_sn}()",
            "            def _done(resp=resp):",
            f"                body = mp.enc_object('', "
            f"serialize_{resp_sn}_fields(resp))",
            "                done(NsheadMessage(body, "
            "log_id=request.log_id))",
            f"            self.service.{mname}(cntl, req, resp, _done)",
        ]
    lines += [
        "        else:",
        "            done(NsheadMessage(b'unknown method'))",
        "",
    ]
    return src + "\n".join(lines)


def compile_codec(source: str, module_name: str):
    """exec the generated source into a fresh module object."""
    import types

    module = types.ModuleType(module_name)
    exec(compile(source, f"<generated {module_name}>", "exec"),
         module.__dict__)
    return module
