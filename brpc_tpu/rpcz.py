"""rpcz — per-call tracing spans.

Counterpart of brpc's rpcz (SURVEY.md section 5; span.h:47-224,
builtin/rpcz_service): a Span per server/client call; nested client calls
parent under the enclosing server span via thread-local state (the
tls_bls.rpcz_parent_span trick, span.h:76,116); trace/span ids propagate in
the RpcMeta; spans are sampled into a bounded collector (the
bvar::Collector role with its global sample budget, collector.h:40) and
browsable at /rpcz. Annotate() adds free-text timeline entries.
"""
from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Deque, List, Optional

from brpc_tpu.butil import flags

flags.define_bool("enable_rpcz", True, "collect rpcz spans")
flags.define_int("rpcz_max_spans", 4096,
                 "bounded span store (collector budget analog)")
flags.define_int("rpcz_sample_every", 1,
                 "keep 1 of every N spans (sampling rate limit)")
flags.define_string("rpcz_database_dir", "",
                    "persist sampled spans on disk (the SpanDB of "
                    "span.h:206-224); empty = in-memory only")
flags.define_int("rpcz_database_max_spans", 200000,
                 "rotate the on-disk SpanDB past this many spans")

_tls = threading.local()


class Span:
    __slots__ = (
        "trace_id", "span_id", "parent_span_id", "kind", "full_method",
        "remote_side", "start_time", "end_time", "error_code",
        "request_size", "response_size", "annotations", "log_id",
    )

    def __init__(self, kind: str, full_method: str, trace_id: int = 0,
                 parent_span_id: int = 0, log_id: int = 0):
        self.kind = kind  # "server" | "client"
        self.full_method = full_method
        self.trace_id = trace_id or random.getrandbits(63)
        self.span_id = random.getrandbits(63)
        self.parent_span_id = parent_span_id
        self.remote_side = None
        self.start_time = time.time()
        self.end_time = 0.0
        self.error_code = 0
        self.request_size = 0
        self.response_size = 0
        self.annotations: List = []
        self.log_id = log_id

    def annotate(self, text: str):
        """Free-text timeline entry (Annotate, span.h:80-84)."""
        self.annotations.append((time.time(), text))

    def end(self, error_code: int = 0):
        self.end_time = time.time()
        self.error_code = error_code
        _submit(self)

    @property
    def latency_us(self) -> float:
        if not self.end_time:
            return 0.0
        return (self.end_time - self.start_time) * 1e6

    def describe(self) -> str:
        lines = [
            f"trace={self.trace_id:016x} span={self.span_id:016x} "
            f"parent={self.parent_span_id:016x} [{self.kind}] "
            f"{self.full_method} remote={self.remote_side} "
            f"latency={self.latency_us:.0f}us error={self.error_code} "
            f"req={self.request_size}B resp={self.response_size}B"
        ]
        for ts, text in self.annotations:
            offset_us = (ts - self.start_time) * 1e6
            lines.append(f"    +{offset_us:.0f}us {text}")
        return "\n".join(lines)


# -- thread-local parenting (tls_bls analog) --------------------------------

def current_parent() -> Optional[Span]:
    return getattr(_tls, "parent_span", None)


def set_parent(span: Optional[Span]):
    _tls.parent_span = span


class parent_scope:
    """with parent_scope(server_span): handler()  — nested client calls
    chain under it."""

    def __init__(self, span: Optional[Span]):
        self._span = span
        self._prev = None

    def __enter__(self):
        self._prev = current_parent()
        set_parent(self._span)
        return self._span

    def __exit__(self, *exc):
        set_parent(self._prev)


# -- on-disk SpanDB (span.h:206-224) ----------------------------------------

class SpanDB:
    """Persists sampled spans to recordio files so traces survive the
    in-memory window; rotated in two generations like the reference's
    SpanDB keeps a bounded disk footprint."""

    def __init__(self, directory: str, max_spans: int):
        import os

        os.makedirs(directory, exist_ok=True)
        self._dir = directory
        self._max = max(1000, max_spans)
        self._lock = threading.Lock()
        self._count = 0
        self._writer = None
        # Spans are handed off to a background writer (the reference feeds
        # SpanDB from the Collector's thread) so RPC completion never
        # touches the disk while holding the CallId lock.
        self._queue: Deque[Span] = deque()
        self._queue_cond = threading.Condition()
        self._closed = False
        self._open()
        self._thread = threading.Thread(target=self._drain_loop,
                                        name="rpcz-spandb", daemon=True)
        self._thread.start()

    def _path(self, gen: int) -> str:
        import os

        return os.path.join(self._dir, f"rpcz.{gen}.recordio")

    def _open(self):
        from brpc_tpu.butil.recordio import RecordWriter

        self._writer = RecordWriter(self._path(0))

    def append(self, span: "Span"):
        """Non-blocking enqueue; the background thread persists it."""
        with self._queue_cond:
            if self._closed:
                return
            if len(self._queue) > 65536:  # backpressure: drop, don't stall
                return
            self._queue.append(span)
            self._queue_cond.notify()

    def _drain_loop(self):
        while True:
            with self._queue_cond:
                while not self._queue and not self._closed:
                    self._queue_cond.wait()
                if self._closed and not self._queue:
                    return
                batch = list(self._queue)
                self._queue.clear()
            try:
                self._write_batch(batch)
            except Exception:
                pass  # disk trouble must never kill the writer thread

    def _write_batch(self, batch):
        import json

        with self._lock:
            for span in batch:
                payload = json.dumps({
                    "trace_id": span.trace_id, "span_id": span.span_id,
                    "parent_span_id": span.parent_span_id,
                    "kind": span.kind,
                    "full_method": span.full_method,
                    "remote_side": span.remote_side,
                    "start_time": span.start_time,
                    "end_time": span.end_time,
                    "error_code": span.error_code,
                    "request_size": span.request_size,
                    "response_size": span.response_size,
                    "log_id": span.log_id,
                    "annotations": span.annotations,
                }).encode()
                self._writer.write({"trace_id": f"{span.trace_id:016x}"},
                                   payload)
                self._count += 1
                if self._count >= self._max // 2:
                    self._rotate()
            self._writer.flush()

    def drain(self, timeout_s: float = 5.0):
        """Wait for queued spans to reach disk (readers want fresh data)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._queue_cond:
                if not self._queue:
                    return
            time.sleep(0.005)

    def close(self):
        with self._queue_cond:
            self._closed = True
            self._queue_cond.notify()
        self._thread.join(5)
        with self._lock:
            self._writer.close()

    def _rotate(self):
        import os

        self._writer.close()
        try:
            os.replace(self._path(0), self._path(1))
        except OSError:
            pass
        self._count = 0
        self._open()

    def find_trace(self, trace_id: int) -> List["Span"]:
        """Read back every span of a trace from both generations."""
        import json
        import os

        from brpc_tpu.butil.recordio import RecordReader

        needle = f"{trace_id:016x}"
        out: List[Span] = []
        self.drain(1.0)
        with self._lock:
            self._writer.flush()
        for gen in (1, 0):
            path = self._path(gen)
            if not os.path.exists(path):
                continue
            reader = RecordReader(path)
            while True:
                rec = reader.read()
                if rec is None:
                    break
                meta, payload = rec
                if meta.get("trace_id") != needle:
                    continue
                d = json.loads(payload.decode())
                span = Span(d["kind"], d["full_method"],
                            trace_id=d["trace_id"],
                            parent_span_id=d["parent_span_id"],
                            log_id=d["log_id"])
                span.span_id = d["span_id"]
                span.remote_side = d["remote_side"]
                span.start_time = d["start_time"]
                span.end_time = d["end_time"]
                span.error_code = d["error_code"]
                span.request_size = d["request_size"]
                span.response_size = d["response_size"]
                span.annotations = [tuple(a) for a in d["annotations"]]
                out.append(span)
        return out


_span_db: Optional[SpanDB] = None
_span_db_lock = threading.Lock()


def _get_span_db() -> Optional[SpanDB]:
    directory = flags.get_flag("rpcz_database_dir")
    global _span_db
    if not directory and _span_db is None:
        return None  # common case: feature off — skip the lock entirely
    if not directory:
        with _span_db_lock:
            if _span_db is not None:
                try:
                    _span_db.close()
                except Exception:
                    pass
                _span_db = None
        return None
    with _span_db_lock:
        if _span_db is None or _span_db._dir != directory:
            if _span_db is not None:
                try:
                    _span_db.close()  # release the old writer's fd
                except Exception:
                    pass
            _span_db = SpanDB(directory,
                              flags.get_flag("rpcz_database_max_spans"))
    return _span_db


# -- collector --------------------------------------------------------------

_spans: Deque[Span] = deque(maxlen=4096)
_spans_lock = threading.Lock()
_counter = [0]


def _submit(span: Span):
    if not flags.get_flag("enable_rpcz"):
        return
    every = max(1, flags.get_flag("rpcz_sample_every"))
    with _spans_lock:
        _counter[0] += 1
        if _counter[0] % every:
            return
        if _spans.maxlen != flags.get_flag("rpcz_max_spans"):
            resized: Deque[Span] = deque(
                _spans, maxlen=max(16, flags.get_flag("rpcz_max_spans")))
            globals()["_spans"] = resized
        _spans.append(span)
    try:
        db = _get_span_db()
        if db is not None:
            db.append(span)
    except Exception:
        pass  # disk trouble must never fail the RPC path


def drain_native_spans() -> int:
    """Pull sampled span records out of the native runtime's bounded ring
    (nat_stats.cpp) and file them with the Python spans, so /rpcz shows
    native-handled calls beside the Python lanes. Native sampling already
    applied the rpcz_sample_every stride, so records go straight into the
    store. Returns the number drained."""
    try:
        from brpc_tpu import native

        if not native.available():
            return 0
        recs = native.stats_drain_spans(4096)
        if not recs:
            return 0
        # map CLOCK_MONOTONIC span timestamps onto wall time
        offset = time.time() - native.stats_now_ns() / 1e9
    except Exception:
        return 0
    for r in recs:
        kind = "client" if r["lane"] == "client" else "server"
        span = Span(kind, r["method"] or f"native.{r['lane']}",
                    trace_id=r["trace_id"],
                    parent_span_id=r.get("parent_span_id", 0))
        span.span_id = r["span_id"]
        span.remote_side = f"native:{r['lane']}/sock={r['sock_id']}"
        span.start_time = offset + r["recv_ns"] / 1e9
        span.end_time = offset + r["write_ns"] / 1e9
        span.error_code = r["error_code"]
        span.request_size = r["req_bytes"]
        span.response_size = r["resp_bytes"]
        span.annotations = [
            (offset + r["parse_ns"] / 1e9, "native parse done"),
            (offset + r["dispatch_ns"] / 1e9, "native usercode done"),
            (offset + r["write_ns"] / 1e9, "native response queued"),
        ]
        with _spans_lock:
            _spans.append(span)
        # persist like _submit does (sampling already happened native-side):
        # the deque ages out in seconds under load, and find_trace recovers
        # older spans from the disk store — native spans must be there too
        try:
            db = _get_span_db()
            if db is not None:
                db.append(span)
        except Exception:
            pass  # disk trouble must never fail the drain
    return len(recs)


def recent_spans(limit: int = 100) -> List[Span]:
    drain_native_spans()
    with _spans_lock:
        return list(_spans)[-limit:]


def find_trace(trace_id: int) -> List[Span]:
    drain_native_spans()
    with _spans_lock:
        found = [s for s in _spans if s.trace_id == trace_id]
    # Merge with the on-disk SpanDB: parts of the trace may have aged out
    # of the bounded memory window while others are still in it.
    try:
        db = _get_span_db()
    except Exception:
        db = None
    if db is not None:
        try:
            seen = {s.span_id for s in found}
            found.extend(s for s in db.find_trace(trace_id)
                         if s.span_id not in seen)
        except Exception:
            pass
    return found


def clear_for_tests():
    # flush the native ring too: stale native records must not resurface
    # in a later test's /rpcz listing
    try:
        from brpc_tpu import native

        if native.available():
            native.stats_drain_spans(4096)
    except Exception:
        pass
    with _spans_lock:
        _spans.clear()
        _counter[0] = 0


def describe_recent_spans(query: Optional[dict] = None) -> str:
    """/rpcz page body (builtin/rpcz_service.cpp role)."""
    query = query or {}
    if "trace_id" in query:
        try:
            spans = find_trace(int(query["trace_id"], 16))
        except ValueError:
            return "bad trace_id\n"
    else:
        limit = int(query.get("limit", "50") or 50)
        spans = recent_spans(limit)
    if not spans:
        return "no spans collected (enable_rpcz flag / traffic?)\n"
    return "\n".join(s.describe() for s in reversed(spans)) + "\n"


# -- wiring helpers ----------------------------------------------------------

def start_server_span(full_method: str, meta, remote_side) -> Optional[Span]:
    if not flags.get_flag("enable_rpcz"):
        return None
    span = Span("server", full_method,
                trace_id=meta.request.trace_id,
                parent_span_id=meta.request.span_id,
                log_id=meta.request.log_id)
    span.remote_side = str(remote_side) if remote_side else None
    return span


def start_client_span(full_method: str, cntl) -> Optional[Span]:
    if not flags.get_flag("enable_rpcz"):
        return None
    parent = current_parent()
    span = Span("client", full_method,
                trace_id=parent.trace_id if parent else 0,
                parent_span_id=parent.span_id if parent else 0,
                log_id=cntl.log_id)
    cntl.trace_id = span.trace_id
    cntl.span_id = span.span_id
    return span
