"""rpcz — per-call tracing spans.

Counterpart of brpc's rpcz (SURVEY.md section 5; span.h:47-224,
builtin/rpcz_service): a Span per server/client call; nested client calls
parent under the enclosing server span via thread-local state (the
tls_bls.rpcz_parent_span trick, span.h:76,116); trace/span ids propagate in
the RpcMeta; spans are sampled into a bounded collector (the
bvar::Collector role with its global sample budget, collector.h:40) and
browsable at /rpcz. Annotate() adds free-text timeline entries.
"""
from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Deque, List, Optional

from brpc_tpu.butil import flags

flags.define_bool("enable_rpcz", True, "collect rpcz spans")
flags.define_int("rpcz_max_spans", 4096,
                 "bounded span store (collector budget analog)")
flags.define_int("rpcz_sample_every", 1,
                 "keep 1 of every N spans (sampling rate limit)")

_tls = threading.local()


class Span:
    __slots__ = (
        "trace_id", "span_id", "parent_span_id", "kind", "full_method",
        "remote_side", "start_time", "end_time", "error_code",
        "request_size", "response_size", "annotations", "log_id",
    )

    def __init__(self, kind: str, full_method: str, trace_id: int = 0,
                 parent_span_id: int = 0, log_id: int = 0):
        self.kind = kind  # "server" | "client"
        self.full_method = full_method
        self.trace_id = trace_id or random.getrandbits(63)
        self.span_id = random.getrandbits(63)
        self.parent_span_id = parent_span_id
        self.remote_side = None
        self.start_time = time.time()
        self.end_time = 0.0
        self.error_code = 0
        self.request_size = 0
        self.response_size = 0
        self.annotations: List = []
        self.log_id = log_id

    def annotate(self, text: str):
        """Free-text timeline entry (Annotate, span.h:80-84)."""
        self.annotations.append((time.time(), text))

    def end(self, error_code: int = 0):
        self.end_time = time.time()
        self.error_code = error_code
        _submit(self)

    @property
    def latency_us(self) -> float:
        if not self.end_time:
            return 0.0
        return (self.end_time - self.start_time) * 1e6

    def describe(self) -> str:
        lines = [
            f"trace={self.trace_id:016x} span={self.span_id:016x} "
            f"parent={self.parent_span_id:016x} [{self.kind}] "
            f"{self.full_method} remote={self.remote_side} "
            f"latency={self.latency_us:.0f}us error={self.error_code} "
            f"req={self.request_size}B resp={self.response_size}B"
        ]
        for ts, text in self.annotations:
            offset_us = (ts - self.start_time) * 1e6
            lines.append(f"    +{offset_us:.0f}us {text}")
        return "\n".join(lines)


# -- thread-local parenting (tls_bls analog) --------------------------------

def current_parent() -> Optional[Span]:
    return getattr(_tls, "parent_span", None)


def set_parent(span: Optional[Span]):
    _tls.parent_span = span


class parent_scope:
    """with parent_scope(server_span): handler()  — nested client calls
    chain under it."""

    def __init__(self, span: Optional[Span]):
        self._span = span
        self._prev = None

    def __enter__(self):
        self._prev = current_parent()
        set_parent(self._span)
        return self._span

    def __exit__(self, *exc):
        set_parent(self._prev)


# -- collector --------------------------------------------------------------

_spans: Deque[Span] = deque(maxlen=4096)
_spans_lock = threading.Lock()
_counter = [0]


def _submit(span: Span):
    if not flags.get_flag("enable_rpcz"):
        return
    every = max(1, flags.get_flag("rpcz_sample_every"))
    with _spans_lock:
        _counter[0] += 1
        if _counter[0] % every:
            return
        if _spans.maxlen != flags.get_flag("rpcz_max_spans"):
            resized: Deque[Span] = deque(
                _spans, maxlen=max(16, flags.get_flag("rpcz_max_spans")))
            globals()["_spans"] = resized
        _spans.append(span)


def recent_spans(limit: int = 100) -> List[Span]:
    with _spans_lock:
        return list(_spans)[-limit:]


def find_trace(trace_id: int) -> List[Span]:
    with _spans_lock:
        return [s for s in _spans if s.trace_id == trace_id]


def clear_for_tests():
    with _spans_lock:
        _spans.clear()
        _counter[0] = 0


def describe_recent_spans(query: Optional[dict] = None) -> str:
    """/rpcz page body (builtin/rpcz_service.cpp role)."""
    query = query or {}
    if "trace_id" in query:
        try:
            spans = find_trace(int(query["trace_id"], 16))
        except ValueError:
            return "bad trace_id\n"
    else:
        limit = int(query.get("limit", "50") or 50)
        spans = recent_spans(limit)
    if not spans:
        return "no spans collected (enable_rpcz flag / traffic?)\n"
    return "\n".join(s.describe() for s in reversed(spans)) + "\n"


# -- wiring helpers ----------------------------------------------------------

def start_server_span(full_method: str, meta, remote_side) -> Optional[Span]:
    if not flags.get_flag("enable_rpcz"):
        return None
    span = Span("server", full_method,
                trace_id=meta.request.trace_id,
                parent_span_id=meta.request.span_id,
                log_id=meta.request.log_id)
    span.remote_side = str(remote_side) if remote_side else None
    return span


def start_client_span(full_method: str, cntl) -> Optional[Span]:
    if not flags.get_flag("enable_rpcz"):
        return None
    parent = current_parent()
    span = Span("client", full_method,
                trace_id=parent.trace_id if parent else 0,
                parent_span_id=parent.span_id if parent else 0,
                log_id=cntl.log_id)
    cntl.trace_id = span.trace_id
    cntl.span_id = span.span_id
    return span
