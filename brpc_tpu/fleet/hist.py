"""Mergeable log2 latency histograms — the Python twin of the native
bucket discipline (native/src/nat_stats.h): bucket ``b`` holds latencies
in ``[2^(b-1), 2^b)`` ns (bucket 0 holds 0..1ns), 44 buckets cover ~17s.

The whole point of shipping RAW buckets over the wire (builtin.stats)
instead of per-server percentiles: log2 histograms merge EXACTLY by
bucket-wise addition, so a fleet quantile computed from the merged
buckets equals the quantile of the concatenated sample stream to within
one bucket width — while an average of per-server p99s equals nothing in
particular. The quantile interpolation here is a line-for-line port of
``nat_hist_quantile`` (nat_stats.cpp); the two must never diverge, and
tests/test_fleet_observatory.py holds them together.
"""
from __future__ import annotations

from typing import Iterable, List, Sequence, Tuple

# mirrors kNatHistBuckets (nat_stats.h) — the ABI drift check pins the
# native side; test_fleet_observatory pins this twin against it
NBUCKETS = 44


def bucket_of(ns: int) -> int:
    """The bucket a latency lands in — nat_hist_bucket's twin."""
    if ns <= 0:
        return 0
    b = ns.bit_length()  # floor(log2(ns)) + 1
    return b if b < NBUCKETS else NBUCKETS - 1


def bucket_lo(b: int) -> float:
    return 0.0 if b == 0 else float(1 << (b - 1))


def bucket_hi(b: int) -> float:
    return float(1 << b)


def dense(sparse: Iterable[Sequence[int]], nb: int = NBUCKETS) -> List[int]:
    """Expand the wire form ([[bucket, count], ...]) to a dense list."""
    out = [0] * nb
    for b, c in sparse:
        if 0 <= b < nb:
            out[b] += c
    return out


def merge(*hists: Sequence[int]) -> List[int]:
    """Bucket-wise sum — the exact merge log2 histograms admit."""
    out = [0] * NBUCKETS
    for h in hists:
        for b, c in enumerate(h):
            if b >= NBUCKETS:
                break
            out[b] += c
    return out


def total(buckets: Sequence[int]) -> int:
    return sum(buckets)


def quantile(buckets: Sequence[int], q: float) -> float:
    """Quantile (ns) interpolated within the winning bucket — the exact
    port of nat_hist_quantile (nat_stats.cpp). 0.0 when empty."""
    tot = sum(buckets)
    if tot == 0:
        return 0.0
    q = min(1.0, max(0.0, q))
    target = q * float(tot)
    acc = 0.0
    for b, c in enumerate(buckets):
        if c == 0:
            continue
        if acc + float(c) >= target:
            lo = bucket_lo(b)
            hi = bucket_hi(b)
            frac = (target - acc) / float(c)
            return lo + frac * (hi - lo)
        acc += float(c)
    return float(1 << (len(buckets) - 1))


def fraction_above(buckets: Sequence[int],
                   ceiling_ns: float) -> Tuple[float, int]:
    """(bad_count, total) where bad_count is the (interpolated) number
    of samples above ``ceiling_ns`` — the latency-SLO numerator. The
    bucket straddling the ceiling contributes linearly, matching the
    quantile interpolation, so fraction_above and quantile agree to
    within one bucket width."""
    tot = sum(buckets)
    if tot == 0:
        return 0.0, 0
    bad = 0.0
    for b, c in enumerate(buckets):
        if c == 0:
            continue
        lo = bucket_lo(b)
        hi = bucket_hi(b)
        if lo >= ceiling_ns:
            bad += float(c)
        elif hi > ceiling_ns:
            bad += float(c) * (hi - ceiling_ns) / (hi - lo)
    return bad, tot
