"""SLO burn-rate engine over the fleet's merged streams (ISSUE 16c).

Declarative objectives — a p99-style latency ceiling or an error-rate
budget — evaluated as MULTI-WINDOW burn rates (the SRE-workbook
discipline): burn = (observed bad fraction / budgeted bad fraction) over
a window, and an alert fires only when BOTH the fast window (default 5m,
catches a new hard outage quickly) and the slow window (default 1h,
suppresses blips that cannot actually spend the budget) exceed their
thresholds. It clears as soon as either window recovers.

The engine consumes the fleet-merged rollups the observatory produces:
latency objectives count bad samples straight off the MERGED log2
buckets (``hist.fraction_above``), never off averaged percentiles, so
the burn rate is exactly the fleet-wide bad fraction. This is the input
signal ROADMAP item 5's autoscaling controller and item 2's flood
contract consume.

Windows are wall-clock; tests shrink them to seconds. Backend restarts
shrink cumulative merged counts — deltas clamp at zero instead of going
negative, so a rolling restart reads as "no new samples from that
member", not as a phantom recovery.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from brpc_tpu.fleet import hist as _hist


@dataclass
class SloObjective:
    """One declarative objective.

    kind="latency": requests slower than ``ceiling_ms`` are bad; the
    budget is the allowed bad fraction (0.001 = "99.9% under ceiling").
    kind="errors": completions with a nonzero error are bad.

    ``method`` scopes to one merged (lane, method) stream ("lane/Service.
    Method" keys as the observatory merges them); None aggregates every
    method of ``lane``.
    """

    name: str
    kind: str = "latency"  # "latency" | "errors"
    lane: str = "echo"
    method: Optional[str] = None  # "EchoService.Echo" or None = whole lane
    ceiling_ms: float = 100.0  # latency objectives only
    budget: float = 0.001  # allowed bad fraction of the stream
    fast_window_s: float = 300.0  # 5m
    slow_window_s: float = 3600.0  # 1h
    fast_burn: float = 14.4  # SRE-workbook 5m/1h page thresholds
    slow_burn: float = 6.0

    def __post_init__(self):
        if self.kind not in ("latency", "errors"):
            raise ValueError(f"unknown SLO kind {self.kind!r}")
        if not 0.0 < self.budget < 1.0:
            raise ValueError("budget must be a fraction in (0, 1)")


@dataclass
class _ObjState:
    # ring of (ts, cumulative_total, cumulative_bad) merged samples;
    # bounded by the slow window (plus one sample past its edge)
    samples: deque = field(default_factory=deque)
    alert: bool = False
    fired_total: int = 0
    cleared_total: int = 0
    fast: float = 0.0
    slow: float = 0.0
    bad_total: float = 0.0
    stream_total: float = 0.0


class SloEngine:
    """Evaluates objectives against successive merged rollups."""

    def __init__(self, objectives: List[SloObjective]):
        names = [o.name for o in objectives]
        if len(set(names)) != len(names):
            raise ValueError("duplicate SLO objective names")
        self._objectives = list(objectives)
        self._lock = threading.Lock()
        self._state: Dict[str, _ObjState] = {
            o.name: _ObjState() for o in objectives}

    @property
    def objectives(self) -> List[SloObjective]:
        return list(self._objectives)

    # -- ingestion ---------------------------------------------------------
    def ingest(self, merged: dict, now: Optional[float] = None):
        """Feed one merged rollup (the observatory's ``merged()`` dict:
        ``methods`` keyed "lane/Service.Method" with count/errors/
        buckets). Cheap: one pass per objective."""
        ts = time.time() if now is None else now
        with self._lock:
            for obj in self._objectives:
                st = self._state[obj.name]
                tot, bad = self._measure(obj, merged)
                st.samples.append((ts, tot, bad))
                self._trim(st.samples, ts, obj.slow_window_s)
                st.fast = self._burn(st.samples, ts, obj.fast_window_s,
                                     obj.budget)
                st.slow = self._burn(st.samples, ts, obj.slow_window_s,
                                     obj.budget)
                st.stream_total = tot
                st.bad_total = bad
                firing = (st.fast >= obj.fast_burn and
                          st.slow >= obj.slow_burn)
                if firing and not st.alert:
                    st.alert = True
                    st.fired_total += 1
                elif not firing and st.alert:
                    st.alert = False
                    st.cleared_total += 1

    @staticmethod
    def _measure(obj: SloObjective, merged: dict) -> Tuple[float, float]:
        """Cumulative (total, bad) of the objective's stream from one
        merged rollup."""
        methods = merged.get("methods", {})
        prefix = f"{obj.lane}/"
        rows = [r for key, r in methods.items()
                if key.startswith(prefix) and
                (obj.method is None or key == prefix + obj.method)]
        if obj.kind == "errors":
            tot = float(sum(r.get("count", 0) for r in rows))
            bad = float(sum(r.get("errors", 0) for r in rows))
            return tot, bad
        buckets = _hist.merge(*[r.get("buckets", []) for r in rows]) \
            if rows else [0] * _hist.NBUCKETS
        bad, tot = _hist.fraction_above(buckets,
                                        obj.ceiling_ms * 1e6)
        return float(tot), bad

    @staticmethod
    def _trim(samples: deque, ts: float, slow_window_s: float):
        # keep ONE sample at/past the slow-window edge so the slow burn
        # always has a baseline older than its window
        edge = ts - slow_window_s
        while len(samples) >= 2 and samples[1][0] <= edge:
            samples.popleft()

    @staticmethod
    def _burn(samples: deque, ts: float, window_s: float,
              budget: float) -> float:
        """Burn rate over [ts - window_s, ts]: bad-fraction of the
        window's new samples over the budgeted fraction. Deltas clamp at
        zero (backend restarts shrink cumulative merged counts)."""
        if len(samples) < 2:
            return 0.0
        edge = ts - window_s
        base = samples[0]
        for s in samples:
            if s[0] > edge:
                break
            base = s
        cur = samples[-1]
        d_tot = max(0.0, cur[1] - base[1])
        d_bad = max(0.0, cur[2] - base[2])
        if d_tot <= 0.0:
            return 0.0
        return (d_bad / d_tot) / budget

    # -- readout -----------------------------------------------------------
    def status(self) -> Dict[str, dict]:
        """Per-objective readout: burn rates, alert state, transition
        totals — the /fleet SLO section and the fleet_slo_* bvar rows."""
        out = {}
        with self._lock:
            for obj in self._objectives:
                st = self._state[obj.name]
                out[obj.name] = {
                    "kind": obj.kind,
                    "lane": obj.lane,
                    "method": obj.method,
                    "ceiling_ms": obj.ceiling_ms,
                    "budget": obj.budget,
                    "fast_burn": round(st.fast, 3),
                    "slow_burn": round(st.slow, 3),
                    "fast_threshold": obj.fast_burn,
                    "slow_threshold": obj.slow_burn,
                    "alert": st.alert,
                    "fired_total": st.fired_total,
                    "cleared_total": st.cleared_total,
                    "stream_total": st.stream_total,
                    "bad_total": round(st.bad_total, 1),
                }
        return out

    def alerts_fired_total(self) -> int:
        with self._lock:
            return sum(s.fired_total for s in self._state.values())

    def alerts_cleared_total(self) -> int:
        with self._lock:
            return sum(s.cleared_total for s in self._state.values())
