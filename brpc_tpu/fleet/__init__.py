"""Fleet observatory: wire-native stats scrape, mergeable log2
histograms, and an SLO burn-rate engine for the swarm (ISSUE 16).

- :mod:`brpc_tpu.fleet.hist` — the Python twin of the native log2
  bucket discipline; merge-by-summation, quantiles off merged buckets.
- :mod:`brpc_tpu.fleet.slo` — declarative objectives evaluated as
  multi-window (fast 5m / slow 1h) burn rates.
- :mod:`brpc_tpu.fleet.observatory` — the collector: drives a
  NativeCluster over the naming feeds, scrapes every member's
  ``builtin.stats`` endpoint, merges, drives /fleet + fleet_* rows,
  fans find_trace across the swarm.
- :mod:`brpc_tpu.fleet.autoscaler` — the elastic-capacity controller
  (ISSUE 20): consumes the observatory rollups and resizes a subprocess
  swarm live under the SLO contract (grow on band/p99 breach, graceful
  quiesce on shrink, shrink vetoed while the budget burns).
"""
from brpc_tpu.fleet import hist
from brpc_tpu.fleet.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    SwarmPool,
    swarm_tags,
)
from brpc_tpu.fleet.observatory import (
    FLEET_VAR_NAMES,
    FleetObservatory,
    active_observatories,
    register_fleet_bvars,
    render_fleet_page,
)
from brpc_tpu.fleet.slo import SloEngine, SloObjective

__all__ = [
    "FLEET_VAR_NAMES",
    "Autoscaler",
    "AutoscalerConfig",
    "FleetObservatory",
    "SloEngine",
    "SloObjective",
    "SwarmPool",
    "active_observatories",
    "hist",
    "register_fleet_bvars",
    "render_fleet_page",
    "swarm_tags",
]
