"""Self-healing autoscaling swarm under an SLO contract (ISSUE 20).

Closes the loop the fleet stack left open: the observatory (PR 16)
already scrapes every member wire-natively, merges histograms, and burns
SLO budgets — this module CONSUMES those rollups and resizes the swarm
live. Growth spawns fresh backend processes (native echo servers, extra
listeners via nat_rpc_server_add_port); retirement goes through the
PR-8 graceful quiesce, never a close under traffic. Every decision is
charged to the native counter surface (nat_autoscale_grows / shrinks /
blocked via nat_stats_counter_bump), so /vars and /brpc_metrics show
the controller's behavior next to the data plane's.

The two halves are deliberately separable:

``Autoscaler``  — the pure decision engine. Reads any observatory-shaped
                  source (``merged()`` + an SLO ``status()``), computes
                  windowed qps/p99 from CUMULATIVE merged rollups by
                  deltaing against the previous step, and drives any
                  pool-shaped executor (``size()``/``grow()``/
                  ``shrink()``). Unit tests feed it a scripted fake
                  observatory and a counting pool — no sockets.

``SwarmPool``   — the real executor: one subprocess per member, naming
                  published to a file:// feed consumed by BOTH the data
                  plane (the dynpart cluster) and the observatory. The
                  published "i/n" tags split live members into TWO
                  overlapping partition schemes, so one SIGKILLed member
                  zeroes only its own scheme's capacity and the dynpart
                  pick routes around it — the half-dead-scheme rule in
                  nat_lb_dynpart_capacity is what keeps the flood at
                  zero failed calls while the autoscaler replaces the
                  corpse.
"""
from __future__ import annotations

import math
import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

from brpc_tpu.fleet import hist as _hist


def _bump(name: str, delta: int = 1):
    """Charge a decision to the native counter surface; quietly a no-op
    when the native library is absent (pure-Python unit tests)."""
    try:
        from brpc_tpu import native

        if native.available():
            native.stats_counter_bump(name, delta)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# the decision engine
# ---------------------------------------------------------------------------

@dataclass
class AutoscalerConfig:
    """The SLO contract, as knobs.

    Capacity tracks offered load inside [band_low, band_high] utilization
    of ``target_qps_per_backend``: above band_high the swarm grows,
    below band_low it shrinks, in between it holds — that IS the
    "capacity within a band of offered load" acceptance clause. A p99
    over ``p99_ceiling_ms`` forces grow pressure regardless of
    utilization (latency is the contract, qps only the estimator).
    Shrinks are vetoed while any SLO objective burns or any member
    drains — a controller that removes capacity during an incident is
    an outage amplifier.
    """

    min_backends: int = 1
    max_backends: int = 16
    target_qps_per_backend: float = 4000.0
    band_low: float = 0.40
    band_high: float = 0.85
    p99_ceiling_ms: float = 50.0
    grow_step: int = 2
    shrink_step: int = 1
    cooldown_s: float = 2.0
    lane: str = "echo"
    method: Optional[str] = None  # None = whole lane

    def desired_for(self, qps: float) -> int:
        """Backend count that puts utilization mid-band for ``qps``."""
        mid = (self.band_low + self.band_high) / 2.0
        want = math.ceil(qps / max(1e-9, self.target_qps_per_backend * mid))
        return max(self.min_backends, min(self.max_backends, int(want)))


class Autoscaler:
    """Rollup in, resize out. One ``step()`` per observatory interval.

    ``source`` is observatory-shaped: ``merged()`` returning the PR-16
    rollup dict, and ``slo.status()`` (any object with an ``alert``
    field per objective row). ``pool`` is executor-shaped: ``size()``,
    ``grow(k) -> int`` (members actually added), ``shrink(k) -> int``.
    """

    def __init__(self, config: AutoscalerConfig, pool, source,
                 clock: Callable[[], float] = time.monotonic):
        self.config = config
        self.pool = pool
        self.source = source
        self._clock = clock
        self._lock = threading.Lock()
        self._last_rollup: Optional[dict] = None  # (for qps/p99 deltas)
        self._last_count = 0
        self._last_buckets: List[int] = [0] * _hist.NBUCKETS
        self._last_ts: Optional[float] = None
        self._last_action_ts: Optional[float] = None
        self.decisions: List[dict] = []
        self.grows = 0
        self.shrinks = 0
        self.blocked = 0

    # -- rollup readers ----------------------------------------------------
    def _stream(self, merged: dict):
        """(cumulative_count, cumulative_buckets) of the configured
        lane/method stream from one merged rollup."""
        prefix = f"{self.config.lane}/"
        rows = [r for key, r in merged.get("methods", {}).items()
                if key.startswith(prefix) and
                (self.config.method is None or
                 key == prefix + self.config.method)]
        count = sum(r.get("count", 0) for r in rows)
        buckets = _hist.merge(*[r.get("buckets", []) for r in rows]) \
            if rows else [0] * _hist.NBUCKETS
        return count, buckets

    def _window(self, merged: dict, now: float):
        """Windowed (qps, p99_ms) since the previous step: merged rollups
        are cumulative, so the delta histogram IS the window's latency
        distribution. Deltas clamp at zero — a member restart shrinks
        the cumulative sums and must read as an empty window, not a
        negative one."""
        count, buckets = self._stream(merged)
        qps, p99_ms = 0.0, 0.0
        if self._last_ts is not None and now > self._last_ts:
            d_count = max(0, count - self._last_count)
            d_buckets = [max(0, b - a) for a, b
                         in zip(self._last_buckets, buckets)]
            qps = d_count / (now - self._last_ts)
            if sum(d_buckets) > 0:
                p99_ms = _hist.quantile(d_buckets, 0.99) / 1e6
        self._last_count, self._last_buckets = count, buckets
        self._last_ts = now
        return qps, p99_ms

    @staticmethod
    def _member_state(merged: dict):
        """(healthy, draining, broken) member counts from the rollup's
        per-backend rows (both the member's own snapshot and the
        collector's breaker view)."""
        healthy = draining = broken = 0
        for row in merged.get("backends", {}).values():
            if row.get("draining"):
                draining += 1
            elif row.get("breaker_open") or not row.get("up", False):
                broken += 1
            else:
                healthy += 1
        return healthy, draining, broken

    def _slo_burning(self) -> bool:
        slo = getattr(self.source, "slo", None)
        if slo is None:
            return False
        try:
            return any(row.get("alert") for row in slo.status().values())
        except Exception:
            return False

    # -- the control step --------------------------------------------------
    def step(self, now: Optional[float] = None) -> dict:
        """One observe-decide-act round. Returns the decision record
        (also appended to ``decisions``): action grow/shrink/hold/
        blocked, the observed qps/p99/member state, and why."""
        now = self._clock() if now is None else now
        merged = self.source.merged()
        with self._lock:
            qps, p99_ms = self._window(merged, now)
            healthy, draining, broken = self._member_state(merged)
            size = self.pool.size()
            cfg = self.config
            desired = cfg.desired_for(qps)
            # latency breach forces grow pressure even when the qps
            # estimator says capacity is fine (the ceiling is the SLO)
            if p99_ms > cfg.p99_ceiling_ms > 0 and desired <= size:
                desired = min(cfg.max_backends, size + 1)
            # a broken member contributes no capacity: replace it by
            # aiming the pool at desired + broken live processes
            desired = min(cfg.max_backends, desired + broken)

            rec = {"ts": now, "qps": round(qps, 1),
                   "p99_ms": round(p99_ms, 3), "size": size,
                   "healthy": healthy, "draining": draining,
                   "broken": broken, "desired": desired,
                   "action": "hold", "why": "in-band", "delta": 0}

            in_cooldown = (self._last_action_ts is not None and
                           now - self._last_action_ts < cfg.cooldown_s)
            if desired > size:
                if in_cooldown:
                    rec.update(action="blocked", why="cooldown")
                elif size >= cfg.max_backends:
                    rec.update(action="blocked", why="at-max")
                else:
                    k = min(cfg.grow_step, cfg.max_backends - size,
                            desired - size)
                    added = self.pool.grow(k)
                    rec.update(action="grow", delta=added,
                               why=("p99-ceiling"
                                    if p99_ms > cfg.p99_ceiling_ms > 0
                                    else "over-band"))
                    if added > 0:
                        self._last_action_ts = now
            elif desired < size:
                if in_cooldown:
                    rec.update(action="blocked", why="cooldown")
                elif size <= cfg.min_backends:
                    rec.update(action="blocked", why="at-min")
                elif self._slo_burning():
                    rec.update(action="blocked", why="slo-burning")
                elif draining > 0:
                    rec.update(action="blocked", why="member-draining")
                elif p99_ms > cfg.p99_ceiling_ms > 0:
                    rec.update(action="blocked", why="p99-ceiling")
                else:
                    k = min(cfg.shrink_step, size - cfg.min_backends,
                            size - desired)
                    removed = self.pool.shrink(k)
                    rec.update(action="shrink", delta=removed,
                               why="under-band")
                    if removed > 0:
                        self._last_action_ts = now

            if rec["action"] == "grow":
                self.grows += 1
                _bump("nat_autoscale_grows")
            elif rec["action"] == "shrink":
                self.shrinks += 1
                _bump("nat_autoscale_shrinks")
            elif rec["action"] == "blocked":
                self.blocked += 1
                _bump("nat_autoscale_blocked")
            self.decisions.append(rec)
            return rec

    # -- background loop ---------------------------------------------------
    def run(self, interval_s: float, stop: threading.Event):
        """Step until ``stop`` is set (the drill's controller thread)."""
        while not stop.wait(interval_s):
            try:
                self.step()
            except Exception:
                # a wedged scrape must not kill the controller; the next
                # interval retries against a fresh rollup
                pass


# ---------------------------------------------------------------------------
# the real executor: a subprocess swarm behind a file:// naming feed
# ---------------------------------------------------------------------------

def swarm_tags(ports: List[int]) -> List[str]:
    """Partition tags for the live port list, laid out so ONE member
    crash can never fail a dynpart verb:

    - the first two members form the ANCHOR scheme "0/1" — a single
      redundant group, so it stays usable through any one crash;
    - every further member joins the ELASTIC scheme "i/(n-2)" — one
      member per group, so a crash there loses one sub-response
      (partial merge, fail_limit=0 still succeeds) until the cool-down
      zeroes the scheme's capacity (nat_lb_dynpart_capacity's
      no-usable-member rule) and the pick routes to the anchor.

    Growth/shrink appends/pops elastic members, so every resize changes
    the elastic scheme's total — a real dynpart layout change
    (nat_dynpart_resizes) per scale event. n == 3 degenerates to one
    "0/1" scheme of three (the elastic total would collide with the
    anchor's and the groups merge — still fully redundant)."""
    n = len(ports)
    if n == 0:
        return []
    if n <= 2:
        return ["0/1"] * n
    return ["0/1", "0/1"] + [f"{i}/{n - 2}" for i in range(n - 2)]


@dataclass
class _Member:
    port: int
    proc: subprocess.Popen


class SwarmPool:
    """One native echo backend process per member, membership published
    to ``naming_path`` (the file:// feed both the dynpart cluster and
    the observatory watch). ``extra_ports`` listeners per member ride
    nat_rpc_server_add_port inside the member process. Spawned members
    honor BRPC_TPU_CHURN_FAULT (the PR-8 chaos hook: the spec lands in
    NAT_FAULT at library load), so the chaos lane runs the whole
    autoscale drill with destructive seeds armed in the backends."""

    def __init__(self, naming_path: str, base_port: int = 26100,
                 extra_ports: int = 0,
                 publish_cb: Optional[Callable[[], None]] = None,
                 env: Optional[dict] = None):
        self.naming_path = naming_path
        self._base = base_port
        self._extra = max(0, extra_ports)
        self._publish_cb = publish_cb
        self._env = dict(env if env is not None else os.environ)
        self._env.setdefault("JAX_PLATFORMS", "cpu")
        self._members: List[_Member] = []
        self._next_port = base_port
        self._lock = threading.Lock()
        self.spawn_failures = 0

    # -- membership --------------------------------------------------------
    def size(self) -> int:
        with self._lock:
            return len(self._members)

    def ports(self) -> List[int]:
        with self._lock:
            return [m.port for m in self._members]

    def publish(self):
        """Rewrite the naming feed from the live member list, with the
        two-scheme "i/n" tag split. The write is atomic (tmp + rename)
        so a naming refresh never reads a half-written list."""
        with self._lock:
            ports = [m.port for m in self._members]
        tags = swarm_tags(ports)
        tmp = self.naming_path + ".tmp"
        with open(tmp, "w") as f:
            for p, t in zip(ports, tags):
                f.write(f"127.0.0.1:{p} {t}\n")
        os.replace(tmp, self.naming_path)
        if self._publish_cb is not None:
            self._publish_cb()

    # -- spawn/retire ------------------------------------------------------
    def _spawn(self) -> Optional[_Member]:
        ports_per = 1 + self._extra
        for _ in range(32):  # walk past ports taken by other suites
            with self._lock:
                base = self._next_port
                self._next_port += ports_per
            churn = self._env.get("BRPC_TPU_CHURN_FAULT") or \
                os.environ.get("BRPC_TPU_CHURN_FAULT")
            env = dict(self._env)
            if churn:
                env["NAT_FAULT"] = churn
            script = (
                "import os, signal, sys\n"
                "sys.path.insert(0, '.')\n"
                "import jax\n"
                "jax.config.update('jax_platforms', 'cpu')\n"
                "from brpc_tpu import native\n"
                f"base, count = {base}, {ports_per}\n"
                "try:\n"
                "    native.rpc_server_start('127.0.0.1', base, 2, True)\n"
                "    for p in range(base + 1, base + count):\n"
                "        native.rpc_server_add_port('127.0.0.1', p)\n"
                "except Exception:\n"
                "    print('BINDFAIL', flush=True)\n"
                "    sys.exit(17)\n"
                "print('READY', flush=True)\n"
                "def _term(sig, frm):\n"
                "    native.server_quiesce(3000)\n"
                "    native.rpc_server_stop()\n"
                "    os._exit(0)\n"
                "signal.signal(signal.SIGTERM, _term)\n"
                "while True:\n"
                "    signal.pause()\n")
            repo_root = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            proc = subprocess.Popen([sys.executable, "-c", script],
                                    stdout=subprocess.PIPE, text=True,
                                    cwd=repo_root, env=env)
            line = proc.stdout.readline().strip()
            if line == "READY":
                return _Member(base, proc)
            proc.kill()
            proc.wait(timeout=10)
            self.spawn_failures += 1
        return None

    def grow(self, k: int) -> int:
        """Spawn ``k`` members, publish once all are READY. Returns the
        count actually added (port exhaustion degrades, not raises)."""
        added = 0
        for _ in range(max(0, k)):
            m = self._spawn()
            if m is None:
                break
            with self._lock:
                self._members.append(m)
            added += 1
        if added:
            self.publish()
        return added

    def shrink(self, k: int, quiesce_timeout_s: float = 10.0) -> int:
        """Retire ``k`` members gracefully: UNPUBLISH first (the naming
        refresh stops new picks landing on them), then SIGTERM — the
        member runs nat_server_quiesce (lame-duck + drain, PR 8) before
        exiting, so in-flight calls complete. Returns the count
        retired."""
        victims: List[_Member] = []
        with self._lock:
            for _ in range(max(0, min(k, len(self._members)))):
                victims.append(self._members.pop())
        if not victims:
            return 0
        self.publish()
        for m in victims:
            if m.proc.poll() is None:
                m.proc.send_signal(signal.SIGTERM)
        for m in victims:
            try:
                m.proc.wait(timeout=quiesce_timeout_s)
            except Exception:
                m.proc.kill()
                m.proc.wait(timeout=10)
        return len(victims)

    def kill_one(self, publish: bool = False) -> Optional[int]:
        """SIGKILL the NEWEST member WITHOUT unpublishing it (the chaos
        arm of the drill: a crash is never announced, and killing the
        freshest member lands the crash mid-resize when a grow just
        seated it). The autoscaler sees the corpse as a broken member in
        the next rollup and replaces it; the dynpart capacity rule
        routes around its half-dead scheme in the meantime. Returns the
        killed port."""
        with self._lock:
            if not self._members:
                return None
            m = self._members.pop()
        m.proc.kill()
        try:
            m.proc.wait(timeout=10)
        except Exception:
            pass
        if publish:
            self.publish()
        return m.port

    def close(self):
        with self._lock:
            victims, self._members = self._members, []
        for m in victims:
            if m.proc.poll() is None:
                m.proc.kill()
            try:
                m.proc.wait(timeout=10)
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
