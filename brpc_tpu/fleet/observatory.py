"""Fleet observatory — the cross-process observability plane (ISSUE 16).

Every per-process surface (per-method stats, breakers, the nat_mem
ledger, /rpcz) ends at one server's console; the fleet twin drives a
NativeCluster over the SAME naming feeds the data plane resolves
through, scrapes every backend's wire-native ``builtin.stats`` endpoint
(one tpu_std call returning the versioned snapshot JSON with RAW log2
histogram buckets), and merges:

- counters by summation, histograms by bucket-wise addition (exact for
  log2 buckets — fleet quantiles come from the MERGED histogram, never
  from averaged per-server percentiles);
- per-method rollups with per-backend drill-down;
- breaker / lame-duck / overload / quiesce state per member, from both
  sides: the member's own snapshot (server draining, inflight/limit,
  its client channels) and the collector's cluster view (breaker_open /
  lame_duck per backend).

On top ride the ``/fleet`` console page, ``fleet_*{backend=}``
Prometheus rows, an :class:`~brpc_tpu.fleet.slo.SloEngine` evaluating
declarative objectives as multi-window burn rates over the merged
streams, and ``find_trace`` fan-out: one trace id queried against every
member's /rpcz returns the stitched cross-process chain.
"""
from __future__ import annotations

import http.client
import json
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from brpc_tpu.fleet import hist as _hist
from brpc_tpu.fleet.slo import SloEngine, SloObjective

# live observatories, walked by /fleet and the fleet_* bvar rows (weak:
# a dropped observatory vanishes from the console like a dropped cluster)
_registry: "weakref.WeakSet[FleetObservatory]" = weakref.WeakSet()
_registry_lock = threading.Lock()


def active_observatories() -> List["FleetObservatory"]:
    with _registry_lock:
        return [o for o in _registry if not o.closed]


class BackendSnapshot:
    """Latest scrape result of one member."""

    __slots__ = ("endpoint", "ok", "ts", "data", "error")

    def __init__(self, endpoint: str, ok: bool, ts: float,
                 data: Optional[dict], error: str = ""):
        self.endpoint = endpoint
        self.ok = ok
        self.ts = ts
        self.data = data
        self.error = error


class FleetObservatory:
    """Scrape -> merge -> evaluate, on an interval or on demand.

    ``naming_url`` (e.g. ``file:///tmp/fleet.ns``) resolves membership
    through the shared NamingService registry exactly like the data
    plane; a static ``endpoints`` list works for tests. ``console_map``
    maps a backend endpoint to the address serving its /rpcz page for
    find_trace fan-out (defaults to the backend endpoint itself).
    """

    def __init__(self, naming_url: Optional[str] = None,
                 endpoints: Optional[Sequence[str]] = None,
                 interval_s: float = 1.0,
                 objectives: Sequence[SloObjective] = (),
                 name: str = "fleet",
                 scrape_timeout_ms: int = 1000,
                 console_map: Optional[Dict[str, str]] = None,
                 register_bvars: bool = True):
        from brpc_tpu.rpc.native_cluster import NativeCluster

        self.name = name
        self.closed = False
        self._interval = max(0.05, float(interval_s))
        self._timeout_ms = scrape_timeout_ms
        self._console_map = dict(console_map or {})
        self._lock = threading.Lock()
        self._channels: Dict[str, object] = {}  # endpoint -> native handle
        self._snapshots: Dict[str, BackendSnapshot] = {}
        self._merged: dict = {"ts": 0.0, "backends": {}, "counters": {},
                              "methods": {}, "lanes": {}, "mem": {}}
        self._scrapes = 0
        self._scrape_errors = 0
        self.slo = SloEngine(list(objectives))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

        self._cluster = NativeCluster(lb="rr", connect_timeout_ms=500,
                                      health_check_ms=200, breaker=True,
                                      name=f"{name}-observatory")
        if naming_url is not None:
            self._cluster.watch(naming_url)
        elif endpoints:
            self._cluster.update(list(endpoints))
        with _registry_lock:
            _registry.add(self)
        if register_bvars:
            register_fleet_bvars()

    # -- membership --------------------------------------------------------
    def backends(self) -> List[dict]:
        """The collector-side member view: cluster rows (endpoint,
        breaker_open, lame_duck, selects, ...)."""
        return self._cluster.stats()

    def update(self, endpoints: Sequence[str]) -> int:
        return self._cluster.update(list(endpoints))

    # -- scraping ----------------------------------------------------------
    def _channel(self, endpoint: str):
        from brpc_tpu import native

        ch = self._channels.get(endpoint)
        if ch is not None:
            return ch
        ip, _, port = endpoint.rpartition(":")
        ch = native.channel_open(ip, int(port))
        if ch:
            self._channels[endpoint] = ch
        return ch

    def _drop_channel(self, endpoint: str):
        from brpc_tpu import native

        ch = self._channels.pop(endpoint, None)
        if ch is not None:
            try:
                native.channel_close(ch)
            except Exception:
                pass

    def _scrape_backend(self, endpoint: str) -> BackendSnapshot:
        from brpc_tpu import native

        now = time.time()
        try:
            ch = self._channel(endpoint)
            if not ch:
                return BackendSnapshot(endpoint, False, now, None, "dial")
            rc, body, err = native.channel_call(
                ch, "builtin", "stats", b"", timeout_ms=self._timeout_ms)
            if rc != 0:
                self._drop_channel(endpoint)
                return BackendSnapshot(endpoint, False, now, None,
                                       f"rc={rc} {err or ''}".strip())
            return BackendSnapshot(endpoint, True, now, json.loads(body))
        except Exception as exc:  # parse error, native unload, ...
            self._drop_channel(endpoint)
            return BackendSnapshot(endpoint, False, now, None, str(exc))

    def scrape_once(self) -> dict:
        """One scrape round over the current membership: refresh every
        member's snapshot, rebuild the merged rollup, feed the SLO
        engine. Returns the merged rollup."""
        rows = self._cluster.stats()
        snaps: Dict[str, BackendSnapshot] = {}
        for row in rows:
            snap = self._scrape_backend(row["endpoint"])
            snaps[row["endpoint"]] = snap
        with self._lock:
            self._scrapes += 1
            self._scrape_errors += sum(1 for s in snaps.values()
                                       if not s.ok)
            self._snapshots = snaps
            merged = _merge_snapshots(snaps, rows)
            self._merged = merged
        self.slo.ingest(merged)
        return merged

    # -- background loop ---------------------------------------------------
    def start(self) -> "FleetObservatory":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name=f"fleet-{self.name}", daemon=True)
            self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self._interval):
            if self.closed:
                return
            try:
                self.scrape_once()
            except Exception:
                with self._lock:
                    self._scrape_errors += 1

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10)

    # -- readout -----------------------------------------------------------
    def merged(self) -> dict:
        with self._lock:
            return self._merged

    def snapshots(self) -> Dict[str, BackendSnapshot]:
        with self._lock:
            return dict(self._snapshots)

    def scrape_counts(self) -> Tuple[int, int]:
        with self._lock:
            return self._scrapes, self._scrape_errors

    def method_quantile(self, method: str, q: float,
                        lane: str = "echo") -> float:
        """Fleet quantile (ns) of one merged method stream — computed
        from the MERGED buckets."""
        row = self.merged().get("methods", {}).get(f"{lane}/{method}")
        if not row:
            return 0.0
        return _hist.quantile(row["buckets"], q)

    # -- find_trace fan-out ------------------------------------------------
    def console_of(self, endpoint: str) -> str:
        return self._console_map.get(endpoint, endpoint)

    def find_trace(self, trace_id: int,
                   timeout_s: float = 3.0) -> List[dict]:
        """Fan one trace id out across every member's /rpcz (plus the
        local span store): [{"backend", "body"}] for each member that
        holds part of the chain — the stitched cross-process view."""
        out: List[dict] = []
        needle = f"{trace_id:x}"
        try:
            from brpc_tpu import rpcz

            local = rpcz.describe_recent_spans({"trace_id": needle})
            if _has_spans(local):
                out.append({"backend": "(local)", "body": local})
        except Exception:
            pass
        seen = set()
        for row in self._cluster.stats():
            console = self.console_of(row["endpoint"])
            if console in seen:
                continue
            seen.add(console)
            body = _http_get(console, f"/rpcz?trace_id={needle}",
                             timeout_s)
            if body is not None and _has_spans(body):
                out.append({"backend": console, "body": body})
        return out

    def stitched_trace(self, trace_id: int, timeout_s: float = 3.0) -> str:
        parts = self.find_trace(trace_id, timeout_s)
        if not parts:
            return f"trace {trace_id:x}: no spans on any member\n"
        lines = [f"trace {trace_id:x}: spans on {len(parts)} member(s)"]
        for p in parts:
            lines.append(f"--- {p['backend']} ---")
            lines.append(p["body"].rstrip("\n"))
        return "\n".join(lines) + "\n"

    # -- lifecycle ---------------------------------------------------------
    def close(self):
        if self.closed:
            return
        self.closed = True
        self.stop()
        for ep in list(self._channels):
            self._drop_channel(ep)
        self._cluster.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _has_spans(body: str) -> bool:
    return "trace=" in body


def _http_get(endpoint: str, path: str,
              timeout_s: float) -> Optional[str]:
    ip, _, port = endpoint.rpartition(":")
    try:
        conn = http.client.HTTPConnection(ip, int(port),
                                          timeout=timeout_s)
        try:
            conn.request("GET", path)
            r = conn.getresponse()
            if r.status != 200:
                return None
            return r.read().decode(errors="replace")
        finally:
            conn.close()
    except Exception:
        return None


# ---------------------------------------------------------------------------
# merge
# ---------------------------------------------------------------------------

def _merge_snapshots(snaps: Dict[str, BackendSnapshot],
                     cluster_rows: List[dict]) -> dict:
    """One merged rollup: counters summed, histograms bucket-summed,
    per-method rows keyed "lane/Service.Method" with per-backend
    drill-down, per-member state from both the member's own snapshot and
    the collector's cluster view."""
    by_ep = {r["endpoint"]: r for r in cluster_rows}
    merged: dict = {"ts": time.time(), "backends": {}, "counters": {},
                    "methods": {}, "lanes": {}, "mem": {}}
    for ep, snap in snaps.items():
        crow = by_ep.get(ep, {})
        brow = {
            "up": snap.ok,
            "age_s": round(time.time() - snap.ts, 3),
            "error": snap.error,
            # collector-side view (its own channels to this member)
            "breaker_open": bool(crow.get("breaker_open", False)),
            "lame_duck": bool(crow.get("lame_duck", False)),
            "selects": crow.get("selects", 0),
            "errors": crow.get("errors", 0),
        }
        if snap.ok and snap.data:
            d = snap.data
            srv = d.get("server", {})
            brow["draining"] = bool(srv.get("draining", 0))
            brow["inflight"] = srv.get("inflight", 0)
            brow["limit"] = srv.get("limit", 0)
            brow["elimit_rejects"] = d.get("counters", {}).get(
                "nat_elimit_rejects", 0)
            brow["channels"] = d.get("channels", [])
            for cname, v in d.get("counters", {}).items():
                merged["counters"][cname] = \
                    merged["counters"].get(cname, 0) + v
            for lane, sparse in d.get("lanes", {}).items():
                dense = _hist.dense(sparse)
                cur = merged["lanes"].get(lane)
                merged["lanes"][lane] = (
                    _hist.merge(cur, dense) if cur else dense)
            for m in d.get("methods", []):
                key = f"{m['lane']}/{m['method']}"
                dense = _hist.dense(m.get("buckets", []))
                row = merged["methods"].get(key)
                if row is None:
                    row = {"lane": m["lane"], "method": m["method"],
                           "count": 0, "errors": 0, "concurrency": 0,
                           "max_concurrency": 0,
                           "buckets": [0] * _hist.NBUCKETS,
                           "per_backend": {}}
                    merged["methods"][key] = row
                row["count"] += m.get("count", 0)
                row["errors"] += m.get("errors", 0)
                row["concurrency"] += max(0, m.get("concurrency", 0))
                row["max_concurrency"] = max(
                    row["max_concurrency"], m.get("max_concurrency", 0))
                row["buckets"] = _hist.merge(row["buckets"], dense)
                row["per_backend"][ep] = {
                    "count": m.get("count", 0),
                    "errors": m.get("errors", 0),
                    "p99_us": round(_hist.quantile(dense, 0.99) / 1e3, 1),
                }
            for sub, r in d.get("mem", {}).items():
                cur = merged["mem"].setdefault(
                    sub, {"live_bytes": 0, "live_objects": 0,
                          "hwm_bytes": 0})
                cur["live_bytes"] += r.get("live_bytes", 0)
                cur["live_objects"] += r.get("live_objects", 0)
                cur["hwm_bytes"] += r.get("hwm_bytes", 0)
        merged["backends"][ep] = brow
    return merged


# ---------------------------------------------------------------------------
# /fleet page + fleet_* bvar rows
# ---------------------------------------------------------------------------

def render_fleet_page(query: Optional[dict] = None) -> str:
    """/fleet body: fleet rollup + per-backend drill-down + SLO burn
    table, over every active observatory. ``?backend=ip:port`` drills
    into one member's latest snapshot; ``?json=1`` dumps the rollup."""
    query = query or {}
    obs_list = active_observatories()
    if not obs_list:
        return ("no fleet observatory running (construct "
                "brpc_tpu.fleet.FleetObservatory and start() it)\n")
    if query.get("json"):
        return json.dumps({o.name: o.merged() for o in obs_list},
                          default=str) + "\n"
    lines: List[str] = []
    for obs in obs_list:
        merged = obs.merged()
        scrapes, errors = obs.scrape_counts()
        lines.append(f"[fleet.{obs.name}]")
        lines.append(f"backends: {len(merged.get('backends', {}))}  "
                     f"scrapes: {scrapes}  scrape_errors: {errors}")
        drill = query.get("backend")
        if drill:
            lines += _render_drilldown(obs, drill)
            lines.append("")
            continue
        lines.append("")
        lines.append("-- members --")
        for ep, b in sorted(merged.get("backends", {}).items()):
            state = []
            if not b.get("up"):
                state.append(f"DOWN({b.get('error', '?')})")
            if b.get("draining"):
                state.append("draining")
            if b.get("breaker_open"):
                state.append("breaker_open")
            if b.get("lame_duck"):
                state.append("lame_duck")
            lines.append(
                f"{ep}  {'|'.join(state) or 'up'}  "
                f"inflight={b.get('inflight', '-')} "
                f"limit={b.get('limit', '-')} "
                f"elimit_rejects={b.get('elimit_rejects', '-')}")
        lines.append("")
        lines.append("-- merged methods (quantiles from MERGED log2 "
                     "buckets) --")
        for key, m in sorted(merged.get("methods", {}).items()):
            p50 = _hist.quantile(m["buckets"], 0.50) / 1e3
            p99 = _hist.quantile(m["buckets"], 0.99) / 1e3
            lines.append(
                f"{key}  count={m['count']} errors={m['errors']} "
                f"p50_us={p50:.1f} p99_us={p99:.1f} "
                f"members={len(m['per_backend'])}")
        slo = obs.slo.status()
        if slo:
            lines.append("")
            lines.append("-- SLO burn rates (fast/slow windows) --")
            for name, st in sorted(slo.items()):
                lines.append(
                    f"{name} [{st['kind']}] "
                    f"{'FIRING' if st['alert'] else 'ok'}  "
                    f"fast={st['fast_burn']:.2f}/{st['fast_threshold']} "
                    f"slow={st['slow_burn']:.2f}/{st['slow_threshold']} "
                    f"fired={st['fired_total']} "
                    f"cleared={st['cleared_total']}")
        lines.append("")
    return "\n".join(lines)


def _render_drilldown(obs: "FleetObservatory", endpoint: str) -> List[str]:
    snap = obs.snapshots().get(endpoint)
    if snap is None:
        return [f"backend {endpoint}: unknown member"]
    if not snap.ok or not snap.data:
        return [f"backend {endpoint}: DOWN ({snap.error})"]
    d = snap.data
    lines = [f"-- {endpoint} (snapshot v{d.get('v')}) --",
             f"server: {json.dumps(d.get('server', {}))}"]
    for m in d.get("methods", []):
        dense = _hist.dense(m.get("buckets", []))
        lines.append(
            f"{m['lane']}/{m['method']}  count={m['count']} "
            f"errors={m['errors']} "
            f"p99_us={_hist.quantile(dense, 0.99) / 1e3:.1f}")
    chans = d.get("channels", [])
    if chans:
        lines.append(f"channels: {json.dumps(chans)}")
    return lines


# one-time idempotent registration (the native_vars discipline):
# PassiveStatus rows reading the active observatories; labeled dicts for
# per-backend / per-method / per-objective dimensions
_fleet_vars: List[object] = []
_fleet_vars_lock = threading.Lock()


def _merged_of_all() -> List[Tuple["FleetObservatory", dict]]:
    return [(o, o.merged()) for o in active_observatories()]


def _backend_dim(field: str, as_int=True):
    out = {}
    for obs, merged in _merged_of_all():
        for ep, b in merged.get("backends", {}).items():
            v = b.get(field, 0)
            out[(("fleet", obs.name), ("backend", ep))] = \
                int(v) if as_int else v
    return out


def _method_dim(field: str):
    out = {}
    for obs, merged in _merged_of_all():
        for key, m in merged.get("methods", {}).items():
            out[(("fleet", obs.name), ("method", key))] = m.get(field, 0)
    return out


def _method_p99_dim():
    out = {}
    for obs, merged in _merged_of_all():
        for key, m in merged.get("methods", {}).items():
            out[(("fleet", obs.name), ("method", key))] = \
                round(_hist.quantile(m["buckets"], 0.99) / 1e3, 1)
    return out


def _slo_dim(field: str, as_int=False):
    out = {}
    for obs in active_observatories():
        for name, st in obs.slo.status().items():
            v = st.get(field, 0)
            out[(("fleet", obs.name), ("slo", name))] = \
                int(v) if as_int else v
    return out


def register_fleet_bvars() -> bool:
    """Idempotently expose the fleet_* bvar surface (scraped into
    /brpc_metrics beside the nat_* rows)."""
    from brpc_tpu.bvar.variable import PassiveStatus, find_exposed

    with _fleet_vars_lock:
        scalars = (
            ("fleet_observatories",
             lambda: len(active_observatories())),
            ("fleet_backends",
             lambda: sum(len(m.get("backends", {}))
                         for _, m in _merged_of_all())),
            ("fleet_scrapes_total",
             lambda: sum(o.scrape_counts()[0]
                         for o in active_observatories())),
            ("fleet_scrape_errors_total",
             lambda: sum(o.scrape_counts()[1]
                         for o in active_observatories())),
            ("fleet_slo_alerts_fired_total",
             lambda: sum(o.slo.alerts_fired_total()
                         for o in active_observatories())),
            ("fleet_slo_alerts_cleared_total",
             lambda: sum(o.slo.alerts_cleared_total()
                         for o in active_observatories())),
        )
        labeled = (
            ("fleet_backend_up", lambda: _backend_dim("up")),
            ("fleet_backend_draining", lambda: _backend_dim("draining")),
            ("fleet_backend_breaker_open",
             lambda: _backend_dim("breaker_open")),
            ("fleet_backend_lame_duck",
             lambda: _backend_dim("lame_duck")),
            ("fleet_backend_inflight",
             lambda: _backend_dim("inflight")),
            ("fleet_backend_elimit_rejects",
             lambda: _backend_dim("elimit_rejects")),
            ("fleet_method_count", lambda: _method_dim("count")),
            ("fleet_method_errors", lambda: _method_dim("errors")),
            ("fleet_method_latency_p99_us", _method_p99_dim),
            ("fleet_slo_burn_fast",
             lambda: _slo_dim("fast_burn")),
            ("fleet_slo_burn_slow",
             lambda: _slo_dim("slow_burn")),
            ("fleet_slo_alert",
             lambda: _slo_dim("alert", as_int=True)),
        )
        for vname, fn in scalars + labeled:
            if find_exposed(vname) is None:
                _fleet_vars.append(PassiveStatus(fn, vname))
    return True


# the drift test walks this: every fleet_* / SLO var the module exposes
FLEET_VAR_NAMES = (
    "fleet_observatories", "fleet_backends", "fleet_scrapes_total",
    "fleet_scrape_errors_total", "fleet_slo_alerts_fired_total",
    "fleet_slo_alerts_cleared_total", "fleet_backend_up",
    "fleet_backend_draining", "fleet_backend_breaker_open",
    "fleet_backend_lame_duck", "fleet_backend_inflight",
    "fleet_backend_elimit_rejects", "fleet_method_count",
    "fleet_method_errors", "fleet_method_latency_p99_us",
    "fleet_slo_burn_fast", "fleet_slo_burn_slow", "fleet_slo_alert",
)
