"""brpc_tpu.parallel — XLA-collective fan-out over a device mesh.

The ICI-native realization of the combo channels (SURVEY.md section 2.12):
ParallelChannel -> allreduce, PartitionChannel -> partition/all_to_all,
cascade/streaming -> ring ppermute, all as single fused XLA programs over
jax.sharding.Mesh axes.
"""
from brpc_tpu.parallel.collectives import (  # noqa: F401
    all_to_all,
    allgather,
    allreduce,
    ici_bandwidth_probe,
    make_mesh,
    reduce_scatter,
    ring_shift,
)
from brpc_tpu.parallel.mesh_channel import MeshChannel, default_mesh  # noqa: F401
