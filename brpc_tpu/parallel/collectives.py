"""XLA collective wrappers over a jax.sharding.Mesh axis.

These are the ICI-native transport verbs of the framework: where brpc moves
bytes through sockets/RDMA (SURVEY.md section 2.9), a TPU pod moves tensors
through ICI collectives. Each wrapper builds a shard_map'd, jitted closure
(cached per mesh/axis/shape/dtype) so repeated transfers hit the XLA
executable cache. Shapes are static and control flow is trace-free, keeping
everything on the MXU/ICI fast path.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from brpc_tpu.jaxcompat import shard_map


def make_mesh(axis_sizes: dict, devices=None) -> Mesh:
    """Build a Mesh from {axis_name: size}; sizes must multiply to the
    device count used."""
    import numpy as np

    names = tuple(axis_sizes.keys())
    sizes = tuple(axis_sizes.values())
    n = 1
    for s in sizes:
        n *= s
    devs = devices if devices is not None else jax.devices()[:n]
    if len(devs) < n:
        raise ValueError(f"need {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]).reshape(sizes), names)


def _axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]


@functools.lru_cache(maxsize=256)
def _allreduce_fn(mesh: Mesh, axis: str, shape: Tuple[int, ...], dtype, op: str):
    def local(x):
        # x local: (1, ...) — this participant's contribution; drop the
        # participant dim so the reduction has the contribution's shape.
        x = x[0]
        if op == "add":
            return lax.psum(x, axis)
        if op == "max":
            return lax.pmax(x, axis)
        if op == "min":
            return lax.pmin(x, axis)
        if op == "mean":
            return lax.pmean(x, axis)
        raise ValueError(f"unknown op {op}")

    spec_in = P(axis)
    spec_out = P()  # replicated result
    return jax.jit(shard_map(local, mesh=mesh, in_specs=spec_in,
                             out_specs=spec_out))


def allreduce(mesh: Mesh, axis: str, x, op: str = "add"):
    """Every participant contributes its shard (dim 0 sharded over `axis`);
    all receive the reduction. The ParallelChannel+ResponseMerger fusion of
    SURVEY.md section 2.12."""
    x = jnp.asarray(x)
    x = jax.device_put(x, NamedSharding(mesh, P(axis)))
    return _allreduce_fn(mesh, axis, x.shape, x.dtype.name, op)(x)


@functools.lru_cache(maxsize=256)
def _allgather_fn(mesh: Mesh, axis: str, shape, dtype):
    def local(x):
        return lax.all_gather(x, axis, axis=0, tiled=True)

    return jax.jit(shard_map(local, mesh=mesh, in_specs=P(axis),
                             out_specs=P(), check=False))


def allgather(mesh: Mesh, axis: str, x):
    """Shards (dim 0) gathered to every participant."""
    x = jnp.asarray(x)
    x = jax.device_put(x, NamedSharding(mesh, P(axis)))
    return _allgather_fn(mesh, axis, x.shape, x.dtype.name)(x)


@functools.lru_cache(maxsize=256)
def _reduce_scatter_fn(mesh: Mesh, axis: str, shape, dtype):
    def local(x):
        # x local: (1, L) — this participant's full-length contribution;
        # result: its L/N slice of the sum.
        out = lax.psum_scatter(x[0], axis, scatter_dimension=0, tiled=True)
        return out[None, :]

    return jax.jit(shard_map(local, mesh=mesh, in_specs=P(axis),
                             out_specs=P(axis)))


def reduce_scatter(mesh: Mesh, axis: str, x):
    """x: (N, L) — row i is participant i's contribution; returns (N, L/N)
    where row i is the summed slice owned by participant i."""
    x = jnp.asarray(x)
    x = jax.device_put(x, NamedSharding(mesh, P(axis)))
    return _reduce_scatter_fn(mesh, axis, x.shape, x.dtype.name)(x)


@functools.lru_cache(maxsize=256)
def _ppermute_fn(mesh: Mesh, axis: str, shape, dtype, shift: int):
    n = _axis_size(mesh, axis)
    perm = [(i, (i + shift) % n) for i in range(n)]

    def local(x):
        return lax.ppermute(x, axis, perm)

    return jax.jit(shard_map(local, mesh=mesh, in_specs=P(axis),
                             out_specs=P(axis)))


def ring_shift(mesh: Mesh, axis: str, x, shift: int = 1):
    """Neighbor exchange along the ring — the cascade/pipeline hop and the
    building block of ring attention (tensor/ring_attention.py)."""
    x = jnp.asarray(x)
    x = jax.device_put(x, NamedSharding(mesh, P(axis)))
    return _ppermute_fn(mesh, axis, x.shape, x.dtype.name, shift)(x)


@functools.lru_cache(maxsize=256)
def _all_to_all_fn(mesh: Mesh, axis: str, shape, dtype):
    def local(x):
        # x local: (1, N, ...) — slot j is this participant's message to j.
        # result local: (1, N, ...) — slot j is the message FROM j.
        y = lax.all_to_all(x, axis, split_axis=1, concat_axis=0, tiled=False)
        # y: (N, 1, ...) -> (1, N, ...)
        return jnp.swapaxes(y, 0, 1)

    return jax.jit(shard_map(local, mesh=mesh, in_specs=P(axis),
                             out_specs=P(axis)))


def all_to_all(mesh: Mesh, axis: str, x):
    """x: (N, N, ...) — x[i, j] is i's message to j; returns y with
    y[i, j] = x[j, i]. The PartitionChannel/expert-dispatch verb (MoE)."""
    x = jnp.asarray(x)
    x = jax.device_put(x, NamedSharding(mesh, P(axis)))
    return _all_to_all_fn(mesh, axis, x.shape, x.dtype.name)(x)


def ici_bandwidth_probe(mesh: Mesh, axis: str, nbytes: int = 1 << 24,
                        iters: int = 10) -> dict:
    """Measure achieved collective bandwidth on this mesh — the
    rdma_performance harness analog (example/rdma_performance/client.cpp)."""
    import time

    n = _axis_size(mesh, axis)
    elems = max(n, nbytes // 4 // n * n)
    x = jnp.ones((elems,), jnp.float32)
    fn = _allreduce_fn(mesh, axis, x.shape, x.dtype.name, "add")
    x = jax.device_put(x, NamedSharding(mesh, P(axis)))
    fn(x).block_until_ready()  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(x)
    out.block_until_ready()
    dt = time.perf_counter() - t0
    total_bytes = x.nbytes * iters
    # allreduce moves 2*(n-1)/n of the data per link (ring algorithm)
    algo_bytes = total_bytes * 2 * (n - 1) / n
    return {
        "axis_size": n,
        "payload_bytes": int(x.nbytes),
        "iters": iters,
        "seconds": dt,
        "allreduce_GBps": total_bytes / dt / 1e9,
        "algo_GBps": algo_bytes / dt / 1e9,
    }
