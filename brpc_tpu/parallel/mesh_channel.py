"""MeshChannel — combo-channel semantics fused onto a mesh axis.

The honest TPU translation of the combo channels (SURVEY.md section 2.12):
where ParallelChannel issues N socket writes and merges N responses
(parallel_channel.h:94-218), a MeshChannel performs ONE XLA collective over
an ICI mesh axis — the fan-out, the "responses," and the merge are a single
fused device program. The RPC-shaped API is kept deliberately:

    mc = MeshChannel(mesh, "dp")
    out = mc.parallel_call(fn, x, merger="add")   # ParallelChannel
    y   = mc.ring_call(fn, x)                     # cascade/pipeline hop
    z   = mc.partition_call(fns, x)               # PartitionChannel

so code written against combo channels ports directly onto silicon.
"""
from __future__ import annotations

import functools
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from brpc_tpu.jaxcompat import shard_map
from brpc_tpu.parallel import collectives


class MeshChannel:
    """One mesh axis treated as a set of N sub-channels.

    Two fan-out axes compose here (ISSUE 13): the DEVICE axis keeps its
    XLA-collective lowering (parallel_call/ring_call/partition_call
    below — one fused device program), while the HOST axis goes native:
    attach_host_cluster() binds a brpc_tpu.rpc.native_cluster
    NativeCluster, and host_parallel_call() fans an RPC across that
    cluster's backends through the C++ fan-out core (DoublyBufferedData
    LB select, sub-calls on fibers, native merge) — the cross-host hop
    of a host×device 2D mesh without touching Python per sub-call.
    """

    def __init__(self, mesh: Mesh, axis: str, host_cluster=None):
        if axis not in mesh.shape:
            raise ValueError(f"axis {axis!r} not in mesh {tuple(mesh.shape)}")
        self.mesh = mesh
        self.axis = axis
        self.n = mesh.shape[axis]
        self._cache = {}
        self.host_cluster = host_cluster

    # -- host axis (native fan-out) ---------------------------------------
    def attach_host_cluster(self, cluster):
        """Bind the host axis: a NativeCluster whose backends are the
        peer hosts of this mesh slice."""
        self.host_cluster = cluster
        return self

    def host_parallel_call(self, service_method: str, payload: bytes,
                           timeout_ms: int = 1000, fail_limit: int = 0):
        """ParallelChannel semantics over the HOST axis: the request
        fans to every host backend natively; returns (error_code,
        merged_bytes, error_text, failed_subcalls)."""
        if self.host_cluster is None:
            raise ValueError("no host cluster attached "
                             "(attach_host_cluster)")
        return self.host_cluster.parallel_call(service_method, payload,
                                               timeout_ms=timeout_ms,
                                               fail_limit=fail_limit)

    # -- ParallelChannel analog -------------------------------------------
    def parallel_call(self, fn: Callable, x, merger: Optional[str] = "add"):
        """Apply fn to each participant's shard (dim 0 sharded over the
        axis), then merge with the named reduction — fn is the sub-call,
        merger the ResponseMerger. merger=None returns per-shard results
        (still sharded)."""
        key = ("par", id(fn), jnp.shape(x), str(jnp.result_type(x)), merger)
        run = self._cache.get(key)
        if run is None:
            axis = self.axis

            def local(s):
                r = fn(s)
                if merger is None:
                    return r
                if merger == "add":
                    return lax.psum(r, axis)
                if merger == "mean":
                    return lax.pmean(r, axis)
                if merger == "max":
                    return lax.pmax(r, axis)
                if merger == "concat":
                    return lax.all_gather(r, axis, axis=0, tiled=True)
                raise ValueError(f"unknown merger {merger}")

            out_spec = P(axis) if merger is None else P()
            run = jax.jit(shard_map(local, mesh=self.mesh,
                                    in_specs=P(axis),
                                    out_specs=out_spec,
                                    check=False))
            self._cache[key] = run
        x = jax.device_put(jnp.asarray(x),
                           NamedSharding(self.mesh, P(self.axis)))
        return run(x)

    def allreduce(self, x, op: str = "add"):
        return collectives.allreduce(self.mesh, self.axis, x, op)

    def allgather(self, x):
        return collectives.allgather(self.mesh, self.axis, x)

    def reduce_scatter(self, x):
        return collectives.reduce_scatter(self.mesh, self.axis, x)

    # -- cascade / pipeline analog ----------------------------------------
    def ring_call(self, fn: Callable, x, shift: int = 1):
        """Apply fn to the local shard then pass the result to the next
        participant on the ring — the cascade_echo / pipeline-stage hop."""
        key = ("ring", id(fn), jnp.shape(x), str(jnp.result_type(x)), shift)
        run = self._cache.get(key)
        if run is None:
            axis, n = self.axis, self.n
            perm = [(i, (i + shift) % n) for i in range(n)]

            def local(s):
                return lax.ppermute(fn(s), axis, perm)

            run = jax.jit(shard_map(local, mesh=self.mesh,
                                    in_specs=P(axis),
                                    out_specs=P(axis)))
            self._cache[key] = run
        x = jax.device_put(jnp.asarray(x),
                           NamedSharding(self.mesh, P(self.axis)))
        return run(x)

    # -- PartitionChannel analog ------------------------------------------
    def partition_call(self, fn: Callable, x, gather: bool = True):
        """Each participant computes fn on ITS partition of the data (the
        partitioned request of partition_channel.h); gather=True returns
        the concatenated full result to all."""
        return self.parallel_call(fn, x, merger="concat" if gather else None)

    def all_to_all(self, x):
        return collectives.all_to_all(self.mesh, self.axis, x)

    def bandwidth_probe(self, nbytes: int = 1 << 22, iters: int = 5) -> dict:
        return collectives.ici_bandwidth_probe(self.mesh, self.axis,
                                               nbytes, iters)


@functools.lru_cache(maxsize=8)
def default_mesh(axis: str = "dp", size: Optional[int] = None) -> Mesh:
    n = size or len(jax.devices())
    return collectives.make_mesh({axis: n})
