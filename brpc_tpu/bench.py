"""Benchmark harnesses — the multi_threaded_echo + rdma_performance analogs.

echo_bench(): in-process loopback echo QPS with several client threads,
instrumented with a bvar LatencyRecorder exactly like
example/multi_threaded_echo_c++/client.cpp; reported against the
reference's 500k+ QPS production claim (docs/en/overview.md:88,
BASELINE.md).

collective_bench(): achieved allreduce bandwidth on the available device
mesh — the rdma_performance role (example/rdma_performance/client.cpp)
with ICI collectives in place of verbs.
"""
from __future__ import annotations

import threading
import time

BASELINE_QPS = 500_000.0  # docs/en/overview.md:88


def _loopback_stabilize(max_wait_s: float = 45.0) -> None:
    """Wait out the axon-tunnel DMA cooldown before loopback benches.

    The tunnel's DMA sections (and the driver's dryrun/compile steps
    right before bench.py runs) depress host loopback throughput for
    tens of seconds — BENCH_r04 captured shm_push at 0.04 GB/s while
    the same run's native_bulk (measured a minute later) did 1.35.
    Probe a socketpair and wait while throughput is still RECOVERING
    (improving >15% per 2s); exit as soon as it plateaus."""

    def _probe() -> float:
        import socket as _socket
        import threading as _th

        a, b = _socket.socketpair()
        chunk = b"x" * (1 << 20)
        total = 24 << 20
        got = [0]

        def _rd():
            while got[0] < total:
                d = b.recv(1 << 20)
                if not d:
                    break
                got[0] += len(d)

        t = _th.Thread(target=_rd)
        t.start()
        t0 = time.perf_counter()
        sent = 0
        while sent < total:
            a.sendall(chunk)
            sent += len(chunk)
        t.join()
        dt = time.perf_counter() - t0
        a.close()
        b.close()
        return total / dt / 1e9

    try:
        prev = _probe()
        deadline = time.time() + max_wait_s
        while time.time() < deadline:
            time.sleep(2)
            cur = _probe()
            if cur <= prev * 1.15:
                break  # no longer recovering
            prev = cur
    except Exception:
        pass


def echo_bench(n_threads: int = 8, duration_s: float = 3.0,
               payload: int = 16) -> dict:
    from brpc_tpu import bvar, rpc
    from brpc_tpu.rpc.proto import echo_pb2

    class EchoService(rpc.Service):
        @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
        def Echo(self, cntl, request, response, done):
            response.message = request.message
            done()

    srv = rpc.Server(rpc.ServerOptions(num_threads=8,
                                       has_builtin_services=False))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0

    recorder = bvar.LatencyRecorder()
    stop = threading.Event()
    counts = [0] * n_threads
    errors_seen = [0] * n_threads
    msg = "x" * payload

    def client_thread(idx: int):
        ch = rpc.Channel(rpc.ChannelOptions(timeout_ms=2000))
        ch.init(str(srv.listen_endpoint))
        req = echo_pb2.EchoRequest(message=msg)
        while not stop.is_set():
            t0 = time.monotonic()
            cntl, resp = ch.call("EchoService.Echo", req,
                                 echo_pb2.EchoResponse)
            if cntl.failed():
                errors_seen[idx] += 1
                continue
            recorder.update((time.monotonic() - t0) * 1e6)
            counts[idx] += 1

    threads = [threading.Thread(target=client_thread, args=(i,))
               for i in range(n_threads)]
    t_start = time.monotonic()
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(5)
    elapsed = time.monotonic() - t_start
    srv.stop()

    total = sum(counts)
    qps = total / elapsed
    return {
        "metric": "echo_qps_loopback",
        "value": round(qps, 1),
        "unit": "qps",
        "vs_baseline": round(qps / BASELINE_QPS, 4),
        "extra": {
            "threads": n_threads,
            "requests": total,
            "errors": sum(errors_seen),
            "avg_latency_us": round(recorder.latency(), 1),
            "p99_latency_us": round(recorder.latency_percentile(0.99), 1),
        },
    }


def http_lane_bench(seconds: float = 1.5) -> dict:
    """The native HTTP/1.1 lane (VERDICT r3 #1): HTTP parses in the native
    cut loop of a use_native_runtime port; usercode is C++ for /echo
    (builtin-native-service discipline, server.cpp:468-563) and Python for
    /EchoService/Echo (py lane, RPC-over-HTTP with JSON body). Reference
    counterpart: policy/http_rpc_protocol.cpp + details/http_parser.cpp.

    Returns {http_qps, http_py_qps}: native-usercode and Python-usercode
    throughput through the same native parse path.
    """
    import json as _json

    from brpc_tpu import native, rpc
    from brpc_tpu.rpc.proto import echo_pb2

    class EchoService(rpc.Service):
        @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
        def Echo(self, cntl, request, response, done):
            response.message = request.message
            done()

    class PyEchoService(rpc.Service):
        """Distinct name so the native EchoService.Echo handler can't
        shadow it — the Python-usercode gRPC lane."""

        @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
        def Echo(self, cntl, request, response, done):
            response.message = request.message
            done()

    srv = rpc.Server(rpc.ServerOptions(num_threads=4,
                                       use_native_runtime=True,
                                       native_builtin_echo=True))
    srv.add_service(EchoService())
    srv.add_service(PyEchoService())
    assert srv.start("127.0.0.1:0") == 0
    try:
        port = srv.listen_endpoint.port
        nat = native.http_client_bench("127.0.0.1", port, nconn=4,
                                       pipeline=128, seconds=seconds,
                                       path="/echo", post_body=b"x" * 16)
        body = _json.dumps({"message": "x" * 16}).encode()
        py = native.http_client_bench("127.0.0.1", port, nconn=2,
                                      pipeline=32, seconds=seconds,
                                      path="/EchoService/Echo",
                                      post_body=body,
                                      content_type="application/json")
        # gRPC-over-h2 through the same native parse path: native
        # usercode (the registered EchoService.Echo native handler) and
        # Python usercode (PyEchoService on the py lane)
        grpc_nat = native.grpc_client_bench("127.0.0.1", port, nconn=4,
                                            window=128, seconds=seconds,
                                            path="/EchoService/Echo",
                                            payload=b"x" * 16)
        req = echo_pb2.EchoRequest(message="x" * 16)
        grpc_py = native.grpc_client_bench(
            "127.0.0.1", port, nconn=2, window=32, seconds=seconds,
            path="/PyEchoService/Echo", payload=req.SerializeToString())
        # CLIENT lanes (nat_client.cpp): same loopback server, but the
        # load generator is the REAL framework client — NatChannel + h2
        # session / pipelined HTTP FIFO (reference client half:
        # policy/http2_rpc_protocol.h:133, http_rpc_protocol.cpp:663)
        grpc_cli = native.grpc_channel_bench(
            "127.0.0.1", port, nconn=2, window=128, seconds=seconds,
            path="/EchoService/Echo", payload=req.SerializeToString())
        http_cli = native.http_channel_bench(
            "127.0.0.1", port, nconn=2, window=128, seconds=seconds,
            path="/echo", body=b"x" * 16)
    finally:
        srv.stop()
    return {"http_qps": round(nat["qps"], 1),
            "http_py_qps": round(py["qps"], 1),
            "grpc_qps": round(grpc_nat["qps"], 1),
            "grpc_py_qps": round(grpc_py["qps"], 1),
            "grpc_client_qps": round(grpc_cli["qps"], 1),
            "http_client_qps": round(http_cli["qps"], 1)}


def _worker_echo_factory():
    """Service factory for the py-worker bench lane (imported by worker
    subprocesses as brpc_tpu.bench:_worker_echo_factory)."""
    from brpc_tpu import rpc
    from brpc_tpu.rpc.proto import echo_pb2

    class EchoService(rpc.Service):
        @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
        def Echo(self, cntl, request, response, done):
            response.message = request.message
            done()

    return [EchoService()]


def py_workers_lane_bench(seconds: float = 1.5, workers: int = 2) -> dict:
    """Python usercode across WORKER PROCESSES (the shm lane,
    nat_shm_lane.cpp): same workload as http_py_qps but dispatched to
    `workers` interpreters. On a 1-CPU host this matches the in-process
    number (CPU-bound, not GIL-bound); on multicore hosts it scales with
    the worker count — the reference's usercode-concurrency product."""
    import json as _json
    import time as _time

    from brpc_tpu import native, rpc

    srv = rpc.Server(rpc.ServerOptions(
        num_threads=2, use_native_runtime=True, py_workers=workers,
        py_worker_factory="brpc_tpu.bench:_worker_echo_factory"))
    for s in _worker_echo_factory():
        srv.add_service(s)
    assert srv.start("127.0.0.1:0") == 0
    try:
        port = srv.listen_endpoint.port
        body = _json.dumps({"message": "x" * 16}).encode()
        # readiness: a worker answering 200 proves the lane is up (boot
        # includes a fresh interpreter + .so load; a fixed sleep flaked)
        import urllib.request as _url

        deadline = _time.time() + 20
        while _time.time() < deadline:
            try:
                req = _url.Request(
                    f"http://127.0.0.1:{port}/EchoService/Echo",
                    data=body,
                    headers={"Content-Type": "application/json"})
                if _url.urlopen(req, timeout=3).status == 200:
                    break
            except Exception:
                _time.sleep(0.3)
        r = native.http_client_bench("127.0.0.1", port, nconn=2,
                                     pipeline=32, seconds=seconds,
                                     path="/EchoService/Echo",
                                     post_body=body,
                                     content_type="application/json")
    finally:
        srv.stop()
    return {"http_py_workers_qps": round(r["qps"], 1),
            "py_workers": workers}


def redis_lane_bench(seconds: float = 1.5) -> dict:
    """Native Redis lane (VERDICT r4 #6, policy/redis_protocol.cpp role):
    RESP parsed in the native cut loop. redis_qps = native in-memory
    store execute (fully native); redis_py_qps = Python RedisService
    handlers behind the native parse (kind-6 py lane)."""
    from brpc_tpu import native, rpc
    from brpc_tpu.rpc.redis import DictRedisService, RedisService

    srv = rpc.Server(rpc.ServerOptions(num_threads=4,
                                       use_native_runtime=True,
                                       redis_service=RedisService(),
                                       native_redis_store=True))
    assert srv.start("127.0.0.1:0") == 0
    try:
        port = srv.listen_endpoint.port
        nat = native.redis_client_bench("127.0.0.1", port, nconn=2,
                                        pipeline=64, seconds=seconds)
    finally:
        srv.stop()
    srv2 = rpc.Server(rpc.ServerOptions(num_threads=4,
                                        use_native_runtime=True,
                                        redis_service=DictRedisService()))
    assert srv2.start("127.0.0.1:0") == 0
    try:
        port2 = srv2.listen_endpoint.port
        py = native.redis_client_bench("127.0.0.1", port2, nconn=2,
                                       pipeline=64, seconds=seconds)
    finally:
        srv2.stop()
    return {"redis_qps": round(nat["qps"], 1),
            "redis_py_qps": round(py["qps"], 1)}


def stream_lane_bench(total_mb: int = 64, chunk_mb: int = 4) -> dict:
    """Streaming over the native port (VERDICT r3 #2): DATA frames are cut
    in the native loop (kind-5 lane) and land in the Python Stream via
    zero-copy wraps; the client writes zero-copy user blocks. Reference
    counterpart: stream.cpp:98-115,307 write path + 458-586 window.

    Returns {stream_GBps} for a one-direction 64MB push, window 64MB.
    """
    import threading

    from brpc_tpu import rpc
    from brpc_tpu.rpc import errors
    from brpc_tpu.rpc.proto import echo_pb2

    class CountingSink(rpc.StreamInputHandler):
        def __init__(self):
            self.nbytes = 0
            self.done = threading.Event()
            self.target = total_mb << 20

        def on_received_messages(self, stream, messages):
            for m in messages:
                self.nbytes += len(m)
            if self.nbytes >= self.target:
                self.done.set()

    sink = CountingSink()

    class StreamSinkService(rpc.Service):
        @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
        def OpenStream(self, cntl, request, response, done):
            s = rpc.stream_accept(
                cntl, rpc.StreamOptions(handler=sink,
                                        max_buf_size=64 << 20))
            if s is None:
                cntl.set_failed(errors.EINVAL, "no stream")
            response.message = "ok"
            done()

    srv = rpc.Server(rpc.ServerOptions(num_threads=4,
                                       use_native_runtime=True))
    srv.add_service(StreamSinkService())
    assert srv.start("127.0.0.1:0") == 0
    try:
        ch = rpc.Channel()
        assert ch.init(str(srv.listen_endpoint)) == 0
        cntl = rpc.Controller()
        cntl.timeout_ms = 5000
        stream = rpc.stream_create(
            cntl, rpc.StreamOptions(max_buf_size=64 << 20))
        resp = echo_pb2.EchoResponse()
        ch.call_method("StreamSinkService.OpenStream", cntl,
                       echo_pb2.EchoRequest(message="open"), resp)
        assert not cntl.failed(), cntl.error_text
        assert stream.wait_connected(3)
        chunk = b"x" * (chunk_mb << 20)
        total = total_mb << 20
        t0 = time.perf_counter()
        sent = 0
        while sent < total:
            rc = stream.write(chunk, timeout_s=15)
            if rc != 0:
                break
            sent += len(chunk)
        sink.done.wait(30)
        dt = time.perf_counter() - t0
        stream.close()
    finally:
        srv.stop()
    return {"stream_GBps": round(total / dt / 1e9, 3) if dt > 0 else 0.0}


def native_echo_bench(nconn: int = 2, seconds: float = 3.0,
                      payload: int = 16, pipeline: int = 128) -> dict:
    """Native C++ data path: epoll echo server + pipelined clients, both
    speaking the tpu_std wire format (native/src/echo_runtime.cpp). The
    pipelined window plays the role of the reference's many concurrent
    client bthreads (docs/cn/benchmark.md 单机1 setup)."""
    from brpc_tpu import native

    port = native.echo_server_start()
    try:
        sync = native.echo_client_bench("127.0.0.1", port, nconn=1,
                                        seconds=1.0, payload=payload,
                                        pipeline=1)
        piped = native.echo_client_bench("127.0.0.1", port, nconn=nconn,
                                         seconds=seconds, payload=payload,
                                         pipeline=pipeline)
    finally:
        native.echo_server_stop()
    qps = piped["qps"]
    return {
        "metric": "echo_qps_native",
        "value": round(qps, 1),
        "unit": "qps",
        "vs_baseline": round(qps / BASELINE_QPS, 4),
        "extra": {
            "connections": nconn,
            "pipeline_depth": pipeline,
            "payload_bytes": payload,
            "requests": piped["requests"],
            "sync_single_conn_qps": round(sync["qps"], 1),
        },
    }


def framework_echo_bench(nconn: int = 4, fibers_per_conn: int = 64,
                         seconds: float = 3.0, payload: int = 16) -> dict:
    """THE headline: echo through the native FRAMEWORK path — Channel
    pending table -> Socket write queue -> epoll dispatcher -> reader
    fibers -> Server dispatch -> response completion, all on the fiber
    scheduler and native IOBuf (nat_rpc.cpp). The multi_threaded_echo
    shape: many synchronous callers, shared connections.

    Extra fields report the pure-Python stack and the raw epoll bypass
    (ceiling probe, echo_runtime.cpp) honestly alongside."""
    import ctypes
    import os as _os_env

    from brpc_tpu import native

    # In-process loopback: server and client runtimes share one process,
    # so their sockets would multiplex through the same dispatcher loops.
    # NAT_DISP_SPLIT=1 partitions the pool (accepted sockets on even
    # loops, dialed on odd) so the numbers stop including cross-runtime
    # interference — see pick_dispatcher in native/src/nat_server.cpp.
    # Dedicated-process lanes (scaling_bench) leave it off. Must be set
    # before the first native runtime use in this process.
    _os_env.environ.setdefault("NAT_DISP_SPLIT", "1")

    # the driver invokes bench.py fresh after TPU-heavy steps: make sure
    # the loopback path is out of the tunnel-DMA cooldown before ANY
    # throughput number is recorded
    _loopback_stabilize()

    # tail latency rides the native stat cells (nat_stats.cpp log2
    # histograms): zero them so the per-lane percentiles reported at the
    # end describe THIS run only
    try:
        native.stats_reset()
    except Exception:
        pass

    # flight recorder: BRPC_TPU_BENCH_PROF=1 attaches the in-process
    # native profiler (nat_prof) to the loopback lanes — the standing
    # replacement for the hand-run PROFILE_r*.md rounds; the gate
    # (tools/check.sh --bench) stores the flat profile in the artifact
    # so a lane regression arrives with its own profile attached.
    import os as _os

    prof_attached = False
    mu_prof_attached = False
    if _os.environ.get("BRPC_TPU_BENCH_PROF") == "1":
        try:
            prof_attached = native.prof_start(99) == 0
        except Exception:
            prof_attached = False
        # contention flight recorder rides the same knob: every
        # contended NatMutex wait in the loopback window is sampled
        # (threshold 0 — the slow path only fires on contention, so the
        # uncontended hot path cost is unchanged)
        try:
            mu_prof_attached = native.mu_prof_start(0, 1, 42) == 0
        except Exception:
            mu_prof_attached = False

    def _async_lane(port_, conns, window=256):
        """One async-windowed measurement; (qps, requests)."""
        out = ctypes.c_uint64(0)
        q = native.load().nat_rpc_client_bench_async(
            b"127.0.0.1", port_, conns, int(window),
            max(1.0, seconds / 2), payload, ctypes.byref(out))
        return q, out.value

    port = native.rpc_server_start(native_echo=True)
    try:
        fw = native.rpc_client_bench("127.0.0.1", port, nconn=nconn,
                                     fibers_per_conn=fibers_per_conn,
                                     seconds=seconds, payload=payload)
    finally:
        native.rpc_server_stop()

    # the io_uring lane (RingListener: provided-buffer recvs +
    # fixed-buffer sends, poller-inline drains), when the kernel allows
    # it — measured with both client shapes (sync fibers and the async
    # window)
    ring_qps = 0.0
    ring_async_qps = 0.0
    ring_async_requests = 0
    ring_async_shape = f"{nconn}conn"
    try:
        if native.use_io_uring(True) == 1:
            port_r = native.rpc_server_start(native_echo=True)
            try:
                ring = native.rpc_client_bench(
                    "127.0.0.1", port_r, nconn=nconn,
                    fibers_per_conn=fibers_per_conn,
                    seconds=seconds, payload=payload)
                ring_qps = ring["qps"]
                # shape sweep: more connections shard across the
                # dispatcher pool on many-core hosts, deeper windows
                # amortize per-burst costs; keep the best
                for shape_conns, win in ((nconn, 256), (nconn * 2, 256),
                                         (nconn, 512)):
                    q, reqs = _async_lane(port_r, shape_conns, win)
                    if q > ring_async_qps:
                        ring_async_qps = q
                        ring_async_requests = reqs
                        ring_async_shape = f"{shape_conns}conn/w{win}"
            finally:
                native.rpc_server_stop()
    except Exception:
        pass
    finally:
        try:
            native.use_io_uring(False)
        except Exception:
            pass

    # ceiling probe: purpose-built epoll loop, no scheduler/IOBuf/Socket
    bypass_qps = 0.0
    try:
        port2 = native.echo_server_start()
        try:
            bypass = native.echo_client_bench("127.0.0.1", port2, nconn=2,
                                              seconds=1.5, payload=payload,
                                              pipeline=128)
            bypass_qps = bypass["qps"]
        finally:
            native.echo_server_stop()
    except Exception:
        pass

    # async windowed lane: done-callback completions instead of parked
    # fibers (the brpc async-call usage pattern). Two connection shapes:
    # the narrow one wins on few cores, the wide one on many (sockets
    # shard across the dispatcher pool) — report the better.
    async_qps = 0.0
    async_requests = 0
    async_shape = f"{nconn}conn"
    try:
        port3 = native.rpc_server_start(native_echo=True)
        try:
            for shape_conns in (nconn, nconn * 2):
                q, reqs = _async_lane(port3, shape_conns)
                if q > async_qps:
                    async_qps = q
                    async_requests = reqs
                    async_shape = f"{shape_conns}conn"
        finally:
            native.rpc_server_stop()
    except Exception:
        pass

    # The pure-Python framework figure, honestly reported — measured in a
    # CLEAN subprocess: in-process it runs after every native lane has
    # started scheduler workers, dispatcher loops and py-lane threads in
    # this process, and that contamination (not the Python stack) moved
    # the number round over round (VERDICT r3 weak #2 root cause).
    python_qps = 0.0
    try:
        import os
        import subprocess
        import sys

        script = (
            "import sys; sys.path.insert(0, '.')\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "from brpc_tpu.bench import echo_bench\n"
            f"r = echo_bench(n_threads=4, duration_s=1.5, "
            f"payload={payload})\n"
            "print(r['value'], flush=True)\n")
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        res = subprocess.run([sys.executable, "-c", script],
                             capture_output=True, text=True, timeout=120,
                             cwd=repo_root)
        if res.returncode == 0:
            python_qps = float(res.stdout.strip().splitlines()[-1])
    except Exception:
        pass

    # ALL pure-loopback lanes run BEFORE any tunnel-DMA section: the
    # device lanes' h2d/d2h probes depress host loopback throughput for
    # tens of seconds afterwards (the shm_push 0.04 artifact of r4 —
    # same mechanism, and a stream/worker row captured mid-cooldown
    # reads as a lane regression).

    # the native HTTP/1.1 lane (VERDICT r3 #1): native parse + native
    # usercode (/echo) and native parse + Python usercode (RPC-over-HTTP)
    http_lanes = {}
    try:
        http_lanes = http_lane_bench(seconds=max(1.0, seconds / 2))
    except Exception:
        pass

    # native Redis lane (VERDICT r4 #6)
    redis_lanes = {}
    try:
        redis_lanes = redis_lane_bench(seconds=max(1.0, seconds / 2))
    except Exception:
        pass

    # flight-recorder replay lane (ISSUE 12): the committed golden
    # capture re-fired through the native replay client in press mode
    replay_lanes = {}
    try:
        replay_lanes = replay_lane_bench()
    except Exception:
        pass

    # native fan-out lanes (ISSUE 13, ROADMAP item 1): 32-backend
    # parallel fan-out + the Python-ParallelChannel comparison, then the
    # 1000-backend swarm churned by rolling SIGTERM restarts and live
    # naming updates (the zero-failed-RPC acceptance drill)
    fanout_lanes = {}
    try:
        fanout_lanes = fanout_lane_bench(seconds=max(1.0, seconds / 2))
    except Exception:
        pass
    swarm_lanes = {}
    try:
        swarm_lanes = fanout_swarm_bench()
    except Exception:
        pass

    # fleet-observatory scrape overhead (ISSUE 16): 1Hz builtin.stats
    # scrape armed vs unarmed — the <=3% always-on-scraping contract
    fleet_lanes = {}
    try:
        fleet_lanes = fleet_scrape_bench(round_s=max(1.0, seconds / 2))
    except Exception:
        pass

    # elastic-capacity drill (ISSUE 20): the fleet autoscaler resizing
    # a dynpart swarm live under the replayed golden-capture ramp, with
    # a mid-resize SIGKILL — zero failed RPCs, p99 under the ceiling,
    # capacity tracking the offered load, or the lane reports 0
    autoscale_lanes = {}
    try:
        autoscale_lanes = autoscale_drill_bench()
    except Exception:
        pass

    # connection-scale drill (ISSUE 14, ROADMAP item 5): 20k mostly-idle
    # keep-alive connections from client subprocesses, per-connection
    # bytes/fd/wakeup cost from the nat_res accounting, accept-storm
    # recovery, zero failed RPCs on the live subset
    conn_lanes = {}
    try:
        conn_lanes = conn_scale_bench()
    except Exception:
        pass

    # py-usercode across worker processes (VERDICT r4 #2, shm lane)
    worker_lanes = {}
    try:
        worker_lanes = py_workers_lane_bench(seconds=max(1.0, seconds / 2))
    except Exception:
        pass

    # streaming over the native port (VERDICT r3 #2)
    stream_lanes = {}
    try:
        stream_lanes = stream_lane_bench()
    except Exception:
        pass

    # the profiler window covers exactly the loopback lanes above (the
    # device/model sections below are DMA + XLA, a different profile)
    nat_prof = {}
    if prof_attached:
        try:
            native.prof_stop()
            flat = native.prof_report(collapsed=False)
            nat_prof = {
                "samples": native.prof_samples(),
                "flat": flat.splitlines()[:48],
            }
            native.prof_reset()
        except Exception:
            nat_prof = {}
    # top lock-wait stacks of the loopback window (extra.contention):
    # a lane regression caused by a lock reintroduced into the
    # write/dispatch path arrives with the contended stack attached
    contention = {}
    if mu_prof_attached:
        try:
            native.mu_prof_stop()
            collapsed = native.mu_prof_report(collapsed=True)
            contention = {
                "samples": native.mu_prof_samples(),
                "ranks": sorted(native.mu_rank_stats(),
                                key=lambda r: -r["wait_us"])[:16],
                "collapsed": collapsed.splitlines()[:32],
            }
            native.mu_prof_reset()
        except Exception:
            contention = {}

    # device-transport bandwidth (the rdma_performance analog): tracked
    # round over round in the artifact. Runs AFTER the loopback lanes
    # (its DMA sections poison them); shm_push runs first inside it.
    device_lanes = {}
    try:
        device_lanes = device_lane_bench()
    except Exception:
        pass

    # model step + collective rows (VERDICT r3 #6) — TPU work, last
    model_rows = {}
    try:
        model_rows = model_collective_bench()
    except Exception:
        pass

    # per-lane tail latency from the native log2 histograms (us): every
    # loopback lane above ran in this process, so the combined cells hold
    # echo/http/redis/grpc server latency (parse-complete -> response-
    # write) and the client-lane round trips. Tracked round over round so
    # a tail regression is visible even when qps holds.
    native_latency_us = {}
    try:
        for idx, lane_name in enumerate(native.stats_lane_names()):
            if not any(native.stats_hist(idx)):
                continue
            native_latency_us[lane_name] = {
                "p50": round(native.stats_quantile(idx, 0.50) / 1e3, 1),
                "p99": round(native.stats_quantile(idx, 0.99) / 1e3, 1),
                "p999": round(native.stats_quantile(idx, 0.999) / 1e3, 1),
            }
    except Exception:
        pass

    lanes = {"epoll": (fw["qps"], fw["requests"]),
             "io_uring": (ring_qps,
                          ring["requests"] if ring_qps > 0 else 0),
             "io_uring_async": (ring_async_qps, ring_async_requests),
             "async_windowed": (async_qps, async_requests)}
    lane = max(lanes, key=lambda k: lanes[k][0])
    qps, requests = lanes[lane]
    # per-lane client shape, so the headline's config is reproducible
    # (sync lanes park fibers_per_conn fibers; async keeps a 256-deep
    # window per connection with no per-call fiber)
    lane_config = {"epoll": f"{fibers_per_conn} sync fibers/conn",
                   "io_uring": f"{fibers_per_conn} sync fibers/conn",
                   "io_uring_async":
                       f"{ring_async_shape}, done-callbacks",
                   "async_windowed":
                       f"{async_shape}, window=256/conn, done-callbacks"}
    import os

    return {
        "metric": "echo_qps_framework_native",
        "value": round(qps, 1),
        "unit": "qps",
        "vs_baseline": round(qps / BASELINE_QPS, 4),
        "extra": {
            # client + server + py lanes share these cores; on 1 core the
            # absolute numbers carry the whole pipeline on one CPU.
            # Affinity, not cpu_count: the scaling lane keys on
            # sched_getaffinity, and benchgate's cpus2_scaling_x
            # unmeasurable-skip must agree with it (taskset/cgroup
            # cpusets shrink affinity without shrinking cpu_count).
            "host_cpus": len(os.sched_getaffinity(0)),
            "connections": nconn,
            "payload_bytes": payload,
            "requests": requests,
            "lane": lane,
            "lane_client_shape": lane_config[lane],
            "epoll_qps": round(fw["qps"], 1),
            "io_uring_qps": round(ring_qps, 1),
            "io_uring_async_qps": round(ring_async_qps, 1),
            "async_windowed_qps": round(async_qps, 1),
            "python_framework_qps": round(python_qps, 1),
            "bypass_ceiling_qps": round(bypass_qps, 1),
            "native_latency_us": native_latency_us,
            **({"nat_prof": nat_prof} if nat_prof else {}),
            **({"contention": contention} if contention else {}),
            "device_lanes": device_lanes,
            **http_lanes,
            **redis_lanes,
            **replay_lanes,
            **fanout_lanes,
            **swarm_lanes,
            **fleet_lanes,
            **autoscale_lanes,
            **conn_lanes,
            **worker_lanes,
            **stream_lanes,
            **model_rows,
        },
    }


def replay_lane_bench(times: int = 3, concurrency: int = 8) -> dict:
    """replay_qps: the committed 1k-request golden capture
    (tests/data/golden_capture_1k.rio, regenerate with
    tools/make_golden_capture.py) re-fired through the native replay
    client in press mode against a fresh native echo server — the
    flight recorder turned standing bench lane (any production-shaped
    capture can stand in for the golden file the same way). Zero failed
    RPCs is part of the lane's contract: a run with failures reports
    0 qps so the bench gate trips on it."""
    import os

    from brpc_tpu import native

    golden = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tests", "data", "golden_capture_1k.rio")
    if not os.path.exists(golden):
        return {}
    port = native.rpc_server_start(native_echo=True)
    try:
        res = native.replay_run("127.0.0.1", port, golden, times=times,
                                concurrency=concurrency, timeout_ms=5000)
    finally:
        native.rpc_server_stop()
    if res["failed"]:
        return {"replay_qps": 0.0, "replay_failed": res["failed"]}
    return {"replay_qps": round(res["qps"], 1),
            "replay_p99_us": round(res["p99_us"], 1)}


def fleet_scrape_bench(round_s: float = 1.5, rounds: int = 3,
                       nconn: int = 2, fibers_per_conn: int = 32,
                       payload: int = 16) -> dict:
    """fleet_scrape_overhead_pct (ISSUE 16): headline echo qps with a
    1Hz fleet observatory scraping the SAME server (builtin.stats over
    the wire, full snapshot JSON each tick) versus unarmed, as a
    percent. The acceptance bar is <= 3% — the snapshot must stay cheap
    enough that always-on fleet scraping is free. Alternating
    unarmed/armed rounds, MAX qps per arm (host-noise discipline: a
    depressed round in either arm cannot fake an overhead or mask one
    that is real)."""
    from brpc_tpu import native
    from brpc_tpu.fleet import FleetObservatory

    port = native.rpc_server_start(native_echo=True)
    unarmed = 0.0
    armed = 0.0
    scrapes = 0
    try:
        # discarded warmup: the first round after server start runs cold
        # (fiber pool, dispatcher, client sockets) and measures ~25%
        # low on this host — an outlier in either arm would fake or
        # mask an overhead
        native.rpc_client_bench("127.0.0.1", port, nconn=nconn,
                                fibers_per_conn=fibers_per_conn,
                                seconds=min(1.0, round_s), payload=payload)
        for _ in range(rounds):
            r = native.rpc_client_bench("127.0.0.1", port, nconn=nconn,
                                        fibers_per_conn=fibers_per_conn,
                                        seconds=round_s, payload=payload)
            unarmed = max(unarmed, r["qps"])
            obs = FleetObservatory(endpoints=[f"127.0.0.1:{port}"],
                                   interval_s=1.0, register_bvars=False)
            try:
                obs.scrape_once()  # the loop ticks at 1Hz; prime now so
                obs.start()        # even a sub-second round is scraped
                r = native.rpc_client_bench(
                    "127.0.0.1", port, nconn=nconn,
                    fibers_per_conn=fibers_per_conn,
                    seconds=round_s, payload=payload)
                armed = max(armed, r["qps"])
                scrapes += obs.scrape_counts()[0]
            finally:
                obs.close()
    finally:
        native.rpc_server_stop()
    if unarmed <= 0:
        return {}
    overhead = max(0.0, (1.0 - armed / unarmed) * 100.0)
    return {"fleet_scrape_overhead_pct": round(overhead, 2),
            "fleet_scrape_unarmed_qps": round(unarmed, 1),
            "fleet_scrape_armed_qps": round(armed, 1),
            "fleet_scrape_count": scrapes}


def fanout_lane_bench(seconds: float = 1.5, backends: int = 32) -> dict:
    """Native fan-out lanes (ISSUE 13, ROADMAP item 1): one native echo
    server listening on `backends` ports (each port a distinct LB
    backend), fanned to by the C++ cluster's ParallelChannel verb —
    every call issues `backends` concurrent sub-calls over the
    DoublyBufferedData LB and merges responses natively.

    fanout_qps / fanout_p99_us: native parallel fan-out verb rate and
    tail. fanout_py_qps: the SAME fan-out through the pure-Python
    ParallelChannel against the same server (the path every fan-out
    paid before the native cluster); fanout_native_vs_py_x is the
    speedup the acceptance bar holds at >= 5x. Zero failed sub-calls is
    part of the lane contract: failures report 0 qps so the gate trips.
    """
    from brpc_tpu import native, rpc
    from brpc_tpu.rpc.proto import echo_pb2

    out: dict = {}
    port = native.rpc_server_start(native_echo=True)
    try:
        ports = [port]
        for _ in range(backends - 1):
            ports.append(native.rpc_server_add_port())
        h = native.cluster_create("rr", connect_timeout_ms=1000,
                                  health_check_ms=100, breaker=True)
        try:
            native.cluster_update(h, [f"127.0.0.1:{p}" for p in ports])
            r = native.cluster_bench(h, mode=1, param=0, seconds=seconds,
                                     concurrency=4, timeout_ms=3000)
            out["fanout_backends"] = backends
            if r["failed"]:
                out["fanout_qps"] = 0.0
                out["fanout_failed"] = r["failed"]
            else:
                out["fanout_qps"] = round(r["qps"], 1)
                out["fanout_p99_us"] = round(r["p99_us"], 1)
        finally:
            native.cluster_close(h)

        # the honest comparison: the pure-Python ParallelChannel fanning
        # to the SAME backends on the same host (sub-calls through the
        # Python Channel/Socket stack, threading.Event merge)
        from brpc_tpu.rpc.combo_channels import ParallelChannel

        pch = ParallelChannel()
        chans = []
        for p in ports:
            ch = rpc.Channel(rpc.ChannelOptions(timeout_ms=3000))
            ch.init(f"127.0.0.1:{p}")
            chans.append(ch)
            pch.add_channel(ch)
        req = echo_pb2.EchoRequest(message="x" * 16)
        py_seconds = max(1.0, seconds / 2)
        stop_at = time.monotonic() + py_seconds
        py_calls = 0
        py_failed = 0
        while time.monotonic() < stop_at:
            cntl = rpc.Controller()
            cntl.timeout_ms = 3000
            resp = echo_pb2.EchoResponse()
            pch.call_method("EchoService.Echo", cntl, req, resp)
            py_calls += 1
            if cntl.failed():
                py_failed += 1
        for ch in chans:
            ch.close()
        py_qps = py_calls / py_seconds
        out["fanout_py_qps"] = round(py_qps, 1)
        out["fanout_py_failed"] = py_failed
        if py_qps > 0 and out.get("fanout_qps", 0) > 0:
            out["fanout_native_vs_py_x"] = round(
                out["fanout_qps"] / py_qps, 2)
    finally:
        native.rpc_server_stop()
    return out


def conn_scale_bench(target_conns: int = 20000, client_procs: int = 4,
                     idle_s: float = 2.0) -> dict:
    """The connection-scale drill (ISSUE 14, ROADMAP item 5's last
    half): hold `target_conns` mostly-idle keep-alive tpu_std
    connections from client SUBPROCESSES against one in-process native
    server and measure what a connection COSTS from the nat_res
    accounting — bytes (accounted live delta / connection), fds, and
    idle wakeups/s — plus the accept-storm recovery time (spawn ->
    every connection accepted and answered) with a live RPC subset
    flooding throughout (zero failed calls is part of the contract:
    any failure, an unfinished storm, or a post-teardown leak in the
    transient subsystems reports conn_scale_conns 0 so the bench gate
    trips).

    The target is clamped to RLIMIT_NOFILE minus headroom (the server
    process holds one fd per connection); conn_scale_target records the
    CLAMPED target the drill actually ran (conn_scale_requested keeps
    the pre-clamp ask, so a fd-limited host is distinguishable from a
    failing drill). BRPC_TPU_CONN_SCALE overrides the target
    (0 disables the lane)."""
    import ctypes
    import os
    import resource
    import subprocess
    import sys
    import threading

    from brpc_tpu import native

    env_target = os.environ.get("BRPC_TPU_CONN_SCALE")
    if env_target is not None:
        try:
            target_conns = int(env_target)
        except ValueError:
            pass
        if target_conns <= 0:
            return {}
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    try:
        resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
        soft = hard
    except (ValueError, OSError):
        pass
    conns = max(100, min(target_conns, soft - 1000))
    per_proc = max(1, conns // client_procs)
    conns = per_proc * client_procs

    lib = native.load()
    port = native.rpc_server_start(native_echo=True)
    out = {"conn_scale_target": conns,
           "conn_scale_requested": target_conns}
    procs = []
    live_stop = threading.Event()
    live_ok = [0]
    live_fail = [0]

    def _live_loop():
        # the live subset: continuous echo RPCs through the accept storm
        # and the idle window — the "zero failed RPCs on the live
        # subset" half of the acceptance contract
        h = lib.nat_channel_open(b"127.0.0.1", port, 0, 0, 0, 0)
        if not h:
            live_fail[0] += 1
            return
        resp = ctypes.c_char_p()
        rlen = ctypes.c_size_t(0)
        err = ctypes.c_char_p()
        while not live_stop.is_set():
            rc = lib.nat_channel_call(h, b"EchoService", b"Echo",
                                      b"live", 4, 3000,
                                      ctypes.byref(resp),
                                      ctypes.byref(rlen),
                                      ctypes.byref(err))
            if rc == 0 and rlen.value == 4:
                live_ok[0] += 1
            else:
                live_fail[0] += 1
            if resp:
                lib.nat_buf_free(resp)
                resp = ctypes.c_char_p()
            if err:
                lib.nat_buf_free(err)
                err = ctypes.c_char_p()
        lib.nat_channel_close(h)

    client_src = (
        "import socket, struct, sys, time\n"
        "port, n = int(sys.argv[1]), int(sys.argv[2])\n"
        "from brpc_tpu.rpc.proto import rpc_meta_pb2\n"
        "meta = rpc_meta_pb2.RpcMeta()\n"
        "meta.request.service_name = 'EchoService'\n"
        "meta.request.method_name = 'Echo'\n"
        "meta.correlation_id = 7\n"
        "mb = meta.SerializeToString()\n"
        "frame = (b'TRPC' + struct.pack('>II', len(mb) + 1, len(mb))\n"
        "         + mb + b'k')\n"
        "socks, failed = [], 0\n"
        "for i in range(n):\n"
        "    try:\n"
        "        s = socket.create_connection(('127.0.0.1', port),\n"
        "                                     timeout=20)\n"
        "        s.sendall(frame)\n"
        "        socks.append(s)\n"
        "    except OSError:\n"
        "        failed += 1\n"
        "# one echo answered per connection proves each was accepted\n"
        "# AND served through the storm (not just SYN-queued)\n"
        "answered = 0\n"
        "for s in socks:\n"
        "    try:\n"
        "        s.settimeout(30)\n"
        "        buf = b''\n"
        "        while len(buf) < 12:\n"
        "            got = s.recv(4096)\n"
        "            if not got:\n"
        "                raise OSError('eof')\n"
        "            buf += got\n"
        "        body, _ = struct.unpack('>II', buf[4:12])\n"
        "        while len(buf) < 12 + body:\n"
        "            got = s.recv(65536)\n"
        "            if not got:\n"
        "                raise OSError('eof')\n"
        "            buf += got\n"
        "        answered += 1\n"
        "    except OSError:\n"
        "        failed += 1\n"
        "print('READY %d %d' % (answered, failed), flush=True)\n"
        "sys.stdin.readline()  # parent closes stdin -> teardown\n"
        "for s in socks:\n"
        "    try:\n"
        "        s.close()\n"
        "    except OSError:\n"
        "        pass\n"
        "print('CLOSED', flush=True)\n")

    try:
        time.sleep(0.3)
        fd0 = len(os.listdir("/proc/self/fd"))
        res0 = {r["subsystem"]: r for r in native.res_stats()}
        live_thread = threading.Thread(target=_live_loop, daemon=True)
        live_thread.start()
        t_storm = time.perf_counter()
        for _ in range(client_procs):
            procs.append(subprocess.Popen(
                [sys.executable, "-c", client_src, str(port),
                 str(per_proc)],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                cwd=os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__)))))
        answered = failed = 0
        for p in procs:
            line = p.stdout.readline().decode().split()
            if len(line) == 3 and line[0] == "READY":
                answered += int(line[1])
                failed += int(line[2])
            else:
                failed += per_proc
        accept_storm_s = time.perf_counter() - t_storm
        # the live subset proved the storm; stop it BEFORE the idle
        # window so the wakeup figure measures what HOLDING the
        # connections costs, not the flood
        live_stop.set()
        live_thread.join(timeout=10)
        time.sleep(0.5)  # settle: in-flight drains, pools quiesce
        wake0 = native.stats_counters().get("nat_dispatcher_wakeups", 0)
        time.sleep(idle_s)
        wake1 = native.stats_counters().get("nat_dispatcher_wakeups", 0)
        fd1 = len(os.listdir("/proc/self/fd"))
        res1 = {r["subsystem"]: r for r in native.res_stats()}
        held = int(lib.nat_rpc_server_connections())
        out.update({
            "conn_scale_answered": answered,
            "conn_scale_failed": failed,
            "conn_held": held,
            "conn_accept_storm_s": round(accept_storm_s, 2),
            # positive subsystem deltas only: in a full bench run the
            # PRECEDING lanes' pools may still be draining through the
            # drill, and a negative total would poison the ceiling
            # band's baseline (the attribution dict below keeps the
            # signed per-subsystem truth)
            "conn_per_conn_bytes": round(
                sum(max(0, res1[s]["live_bytes"] - res0[s]["live_bytes"])
                    for s in res1) / max(1, answered), 1),
            "conn_per_conn_fds": round((fd1 - fd0) / max(1, answered), 3),
            "conn_idle_wakeups_per_s": round(
                max(0, wake1 - wake0) / idle_s, 1),
            "conn_live_ok": live_ok[0],
            "conn_live_failed": live_fail[0],
            # where the bytes sit: per-subsystem live deltas over the
            # drill (the accounting's attribution, not a guess)
            "conn_mem_by_subsystem": {
                sub: int(res1[sub]["live_bytes"]
                         - res0[sub]["live_bytes"])
                for sub in res1
                if res1[sub]["live_bytes"] != res0[sub]["live_bytes"]},
        })
        # teardown + churn balance: close every client and wait for the
        # transient subsystems to return (socket slots recycle to the
        # freelist but their slabs stay live BY DESIGN — ResourcePool)
        for p in procs:
            try:
                p.stdin.close()
            except OSError:
                pass
        for p in procs:
            p.wait(timeout=60)
        deadline = time.time() + 30
        while time.time() < deadline and \
                int(lib.nat_rpc_server_connections()) > 4:
            time.sleep(0.1)
        time.sleep(0.5)
        res2 = {r["subsystem"]: r for r in native.res_stats()}
        leaks = {}
        for sub in ("srv.pyreq", "dump.spill"):
            d = res2[sub]["live_objects"] - res0[sub]["live_objects"]
            if d > max(8, answered * 0.01):
                leaks[sub] = int(d)
        out["conn_balance_leaked"] = leaks
        ok = (failed == 0 and answered == conns and live_fail[0] == 0
              and live_ok[0] > 0 and not leaks)
        out["conn_scale_conns"] = answered if ok else 0
    except Exception as e:  # a wedged drill must not kill the artifact
        out["conn_scale_error"] = repr(e)
        out["conn_scale_conns"] = 0
        live_stop.set()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        native.rpc_server_stop()
    return out


def _spawn_swarm_server(base: int, count: int, repo_root: str, env: dict):
    """One swarm backend process: a native echo server listening on
    `count` consecutive ports from `base`. Returns the Popen (READY
    already seen) or None when a port in the range was taken.

    BRPC_TPU_CHURN_FAULT (the PR-8 chaos hook): when set, the SERVER
    process arms that NAT_FAULT spec at library load — the chaos lane's
    swarm round runs the whole drill with destructive seeds in the
    backends while the client side stays clean."""
    import os
    import subprocess
    import sys

    churn_spec = env.get("BRPC_TPU_CHURN_FAULT") or \
        os.environ.get("BRPC_TPU_CHURN_FAULT")
    if churn_spec:
        env = dict(env)
        env["NAT_FAULT"] = churn_spec
    # BRPC_TPU_SWARM_LIMITER (ISSUE 16 drill hook): arm the native
    # admission limiter in the SERVER process ("constant:1", "auto", ...)
    # so a fleet drill can inject real ELIMIT overload on a member —
    # py-lane floods past the limit shed with 2004 on the wire while the
    # native echo path keeps serving
    limiter_spec = env.get("BRPC_TPU_SWARM_LIMITER") or \
        os.environ.get("BRPC_TPU_SWARM_LIMITER") or ""

    script = (
        "import os, signal, sys\n"
        "sys.path.insert(0, '.')\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from brpc_tpu import native\n"
        f"base, count = {base}, {count}\n"
        "try:\n"
        "    native.rpc_server_start('127.0.0.1', base, 2, True)\n"
        "    for p in range(base + 1, base + count):\n"
        "        native.rpc_server_add_port('127.0.0.1', p)\n"
        f"    if {limiter_spec!r}:\n"
        f"        native.rpc_server_limiter({limiter_spec!r})\n"
        "except Exception:\n"
        "    print('BINDFAIL', flush=True)\n"
        "    sys.exit(17)\n"
        "print('READY', flush=True)\n"
        "def _term(sig, frm):\n"
        "    native.server_quiesce(3000)\n"  # graceful: lame-duck + drain
        "    native.rpc_server_stop()\n"
        "    os._exit(0)\n"
        "signal.signal(signal.SIGTERM, _term)\n"
        "while True:\n"
        "    signal.pause()\n")
    proc = subprocess.Popen([sys.executable, "-c", script],
                            stdout=subprocess.PIPE, text=True,
                            cwd=repo_root, env=env)
    line = proc.stdout.readline().strip()
    if line != "READY":
        proc.kill()
        proc.wait(timeout=10)
        return None
    return proc


def fanout_swarm_bench(backends: int = 1000, servers: int = 3,
                       bench_seconds: float = 12.0,
                       concurrency: int = 4) -> dict:
    """The ROADMAP acceptance drill: a `backends`-port in-process swarm
    (`servers` subprocesses, each hosting backends/servers native echo
    ports) behind one native cluster, churned by ROLLING SIGTERM
    restarts (graceful quiesce + lame-duck, PR 8) and LIVE naming
    add/remove (the file naming service rewritten mid-run) while the
    selective-with-retry verb floods it from C threads. The contract is
    ZERO failed RPCs once failover/retry settles — a run with failures
    reports swarm_qps 0 so the bench gate trips — with the per-backend
    qps distribution recorded in the artifact.

    Also records fanout1000_qps: the parallel verb fanning one call to
    all `backends` backends (measured before the churn starts)."""
    import json as _json
    import os
    import signal as _signal
    import threading as _threading

    from brpc_tpu import native
    from brpc_tpu.rpc.native_cluster import NativeCluster

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    per = backends // servers
    out: dict = {}
    procs = []
    bases = []
    nf_path = None
    cluster = None
    try:
        base_candidates = [21000, 23000, 25000, 27000, 29000, 19000]
        ci = 0
        for _ in range(servers):
            proc = None
            while proc is None and ci < len(base_candidates):
                base = base_candidates[ci]
                ci += 1
                proc = _spawn_swarm_server(base, per, repo_root, env)
                if proc is not None:
                    procs.append(proc)
                    bases.append(base)
        if len(procs) < servers:
            raise RuntimeError("swarm port ranges unavailable")
        all_ports = [b + i for b in bases for i in range(per)]
        import tempfile

        nf = tempfile.NamedTemporaryFile("w", suffix=".swarm.ns",
                                         delete=False)
        nf_path = nf.name

        def write_naming(ports):
            with open(nf_path, "w") as f:
                for p in ports:
                    f.write(f"127.0.0.1:{p}\n")

        write_naming(all_ports)
        nf.close()
        cluster = NativeCluster(lb="rr", connect_timeout_ms=1000,
                                health_check_ms=200, breaker=True,
                                name="swarm")
        cluster.watch(f"file://{nf_path}")
        n = cluster.backend_count()
        out["swarm_backends"] = n

        # parallel fan-out to the WHOLE swarm (pre-churn): one verb =
        # `backends` concurrent sub-calls + native merge
        r1000 = cluster.bench(mode=1, param=0, seconds=1.0,
                              concurrency=2, timeout_ms=8000)
        out["fanout1000_qps"] = (0.0 if r1000["failed"]
                                 else round(r1000["qps"], 1))

        # churn window: selective flood from C threads while this thread
        # SIGTERMs each server in turn and flaps the naming file
        result: dict = {}

        def flood():
            result.update(cluster.bench(mode=0, param=12,
                                        seconds=bench_seconds,
                                        concurrency=concurrency,
                                        timeout_ms=5000))

        flood_t = _threading.Thread(target=flood)
        flood_t.start()
        time.sleep(0.5)
        # live naming remove (the tail 5% of backends)...
        drop = max(1, n // 20)
        write_naming(all_ports[:-drop])
        restarts = 0
        for i in range(len(procs)):
            procs[i].send_signal(_signal.SIGTERM)
            try:
                procs[i].wait(timeout=20)
            except Exception:
                procs[i].kill()
                procs[i].wait(timeout=10)
            fresh = _spawn_swarm_server(bases[i], per, repo_root, env)
            if fresh is None:
                break
            procs[i] = fresh
            restarts += 1
        # ...and live naming re-add
        write_naming(all_ports)
        flood_t.join(timeout=bench_seconds + 60)
        out["swarm_restarts"] = restarts
        out["swarm_calls"] = result.get("calls", 0)
        out["swarm_p99_us"] = round(result.get("p99_us", 0.0), 1)
        failed = result.get("failed", -1)
        out["swarm_failed"] = failed
        # the zero-failed contract IS the lane value
        out["swarm_qps"] = (round(result.get("qps", 0.0), 1)
                            if failed == 0 and restarts == len(procs)
                            else 0.0)
        # per-backend qps distribution (the artifact's evidence that the
        # LB spread the flood): selects quantiles across live backends
        selects = sorted(row["selects"] for row in cluster.stats())
        if selects:
            out["swarm_selects_per_backend"] = {
                "min": selects[0],
                "p50": selects[len(selects) // 2],
                "max": selects[-1],
            }
        out["swarm_stats_note"] = _json.dumps(
            {"servers": len(procs), "ports_per_server": per})
    finally:
        if cluster is not None:
            cluster.close()
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
            try:
                proc.wait(timeout=10)
            except Exception:
                pass
        if nf_path is not None:
            try:
                os.unlink(nf_path)
            except OSError:
                pass
    return out


def autoscale_drill_bench(ramp_times: int = 4,
                          qps_from: float = 150.0,
                          qps_to: float = 1200.0,
                          settle_s: float = 5.0,
                          p99_ceiling_ms: float = 250.0,
                          tracking_floor: float = 0.4) -> dict:
    """The ISSUE-20 elastic-capacity drill: a dynpart cluster over a
    live subprocess swarm, resized by the fleet autoscaler while the
    committed golden capture replays through the native replay client
    in RAMP mode (the offered-load curve) and a paced dynpart probe
    exercises the resize path end to end. One member is SIGKILLed
    mid-resize (never announced — the controller must notice the corpse
    in the rollup and replace it; the dynpart capacity rule routes
    around its half-dead scheme meanwhile).

    The SLO contract IS the lane value: autoscale_qps reports the
    replay's achieved qps only when the probe saw ZERO failed RPCs
    across every grow/shrink/crash, probe p99 stayed under the ceiling,
    the controller actually scaled both ways (>= 1 grow AND >= 1
    shrink), and capacity tracked the offered load (pool size within
    one member of the controller's desired size on >= tracking_floor of
    post-warmup decisions). Any breach reports 0 qps so the bench gate
    trips."""
    import os
    import tempfile
    import threading as _threading

    from brpc_tpu import native
    from brpc_tpu.fleet.autoscaler import (Autoscaler, AutoscalerConfig,
                                           SwarmPool)
    from brpc_tpu.fleet.observatory import FleetObservatory
    from brpc_tpu.fleet.slo import SloObjective
    from brpc_tpu.rpc.native_cluster import NativeCluster

    golden = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tests", "data", "golden_capture_1k.rio")
    if not os.path.exists(golden):
        return {}

    out: dict = {}
    nf = tempfile.NamedTemporaryFile("w", suffix=".autoscale.ns",
                                     delete=False)
    nf_path = nf.name
    nf.close()
    cluster = None
    obs = None
    pool = None
    stop = _threading.Event()
    try:
        cluster = NativeCluster(lb="_dynpart", connect_timeout_ms=1000,
                                health_check_ms=200, breaker=True,
                                name="autoscale")
        obs = FleetObservatory(
            naming_url=f"file://{nf_path}", interval_s=0.4,
            objectives=[SloObjective(name="autoscale-p99",
                                     kind="latency", lane="echo",
                                     ceiling_ms=p99_ceiling_ms,
                                     budget=0.05)],
            name="autoscale", register_bvars=False)

        def publish_cb():
            # push the fresh list NOW (the file watchers' 2s poll is an
            # eternity against a 0.5s control loop)
            for w in (cluster._watcher, obs._cluster._watcher):
                if w is not None:
                    try:
                        w.refresh()
                    except Exception:
                        pass

        pool = SwarmPool(nf_path, base_port=26100, publish_cb=publish_cb)
        if pool.grow(2) < 2:
            raise RuntimeError("autoscale swarm port range unavailable")
        cluster.watch(f"file://{nf_path}")
        publish_cb()
        obs.start()
        anchor_port = pool.ports()[0]  # never retired above min=2

        cfg = AutoscalerConfig(min_backends=2, max_backends=6,
                               target_qps_per_backend=400.0,
                               p99_ceiling_ms=p99_ceiling_ms,
                               grow_step=2, shrink_step=2,
                               cooldown_s=0.6)
        scaler = Autoscaler(cfg, pool, obs)
        ctrl = _threading.Thread(target=scaler.run, args=(0.5, stop),
                                 daemon=True)
        ctrl.start()

        # the zero-failed probe: paced dynpart verbs through every
        # resize, with the same bounded client retry the swarm churn
        # lane rides (its selective verb retries in-verb; the fan verbs
        # have no failover, so an unannounced corpse can be the SOLE
        # seat of a one-group scheme for the 2-3 calls its transport
        # cool-down takes — the retry re-picks, the rr cursor moves to
        # a live member). fail_limit=0 = the verb fails only when EVERY
        # seated sub fails; a call that exhausts its retries is a
        # failed RPC and zeroes the lane.
        probe_lat_us: list = []
        probe_failed = [0]
        probe_retries = [0]
        probe_schemes: dict = {}

        def probe():
            while not stop.is_set():
                t0 = time.monotonic()
                rc = -1
                for attempt in range(3):
                    rc, _body, _err, _nfail, scheme = \
                        cluster.dynpart_call(
                            "EchoService.Echo", b"autoscale-probe",
                            timeout_ms=4000, fail_limit=0)
                    if rc == 0:
                        break
                    probe_retries[0] += 1
                if rc != 0:
                    probe_failed[0] += 1
                else:
                    probe_lat_us.append(
                        (time.monotonic() - t0) * 1e6)
                    probe_schemes[scheme] = \
                        probe_schemes.get(scheme, 0) + 1
                time.sleep(0.02)

        probe_t = _threading.Thread(target=probe, daemon=True)
        probe_t.start()

        # chaos arm: SIGKILL the newest member the moment the first
        # grow lands (mid-resize by construction)
        killed = [0]

        def assassin():
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and not stop.is_set():
                if scaler.grows >= 1 and pool.size() >= 3:
                    if pool.kill_one() is not None:
                        killed[0] += 1
                    return
                time.sleep(0.1)

        kill_t = _threading.Thread(target=assassin, daemon=True)
        kill_t.start()

        # offered load: the golden capture ramped qps_from -> qps_to
        # against the anchor member (PR-11 replay, ramp mode)
        replay = native.replay_run("127.0.0.1", anchor_port, golden,
                                   times=ramp_times, qps=qps_from,
                                   qps_to=qps_to, concurrency=4,
                                   timeout_ms=5000)
        # load gone: the settle window is where the shrinks happen
        deadline = time.monotonic() + settle_s
        while time.monotonic() < deadline and \
                not (scaler.shrinks >= 1 and pool.size() <= 3):
            time.sleep(0.25)
        kill_t.join(timeout=5)
        stop.set()
        ctrl.join(timeout=10)
        probe_t.join(timeout=10)

        # capacity-tracking score: post-warmup decisions where the pool
        # sat within one member of the controller's own desired size
        recs = [r for r in scaler.decisions if r["qps"] > 0]
        tracked = sum(1 for r in recs
                      if abs(r["size"] - r["desired"]) <= 1)
        tracking = (tracked / len(recs)) if recs else 0.0

        probe_lat_us.sort()
        p99_us = (probe_lat_us[int(len(probe_lat_us) * 0.99)]
                  if probe_lat_us else 0.0)
        counters = native.stats_counters()
        out.update({
            "autoscale_replay_qps": round(replay["qps"], 1),
            "autoscale_probe_calls": len(probe_lat_us),
            "autoscale_probe_retries": probe_retries[0],
            "autoscale_failed": probe_failed[0] + replay["failed"],
            "autoscale_grows": scaler.grows,
            "autoscale_shrinks": scaler.shrinks,
            "autoscale_blocked": scaler.blocked,
            "autoscale_kills": killed[0],
            "autoscale_peak_size": max(r["size"] for r in
                                       scaler.decisions),
            "autoscale_tracking": round(tracking, 3),
            "autoscale_schemes": {str(k): v for k, v
                                  in sorted(probe_schemes.items())},
            "autoscale_resizes": counters.get("nat_dynpart_resizes", 0),
            "autoscale_p99_us": round(p99_us, 1),
        })
        contract_ok = (probe_failed[0] == 0 and replay["failed"] == 0
                       and scaler.grows >= 1 and scaler.shrinks >= 1
                       and killed[0] == 1
                       and p99_us <= p99_ceiling_ms * 1000
                       and tracking >= tracking_floor
                       and len(probe_lat_us) > 50)
        out["autoscale_qps"] = (round(replay["qps"], 1)
                                if contract_ok else 0.0)
    except Exception as e:  # a wedged drill must not kill the artifact
        out["autoscale_error"] = repr(e)
        out["autoscale_qps"] = 0.0
    finally:
        stop.set()
        if obs is not None:
            obs.close()
        if cluster is not None:
            cluster.close()
        if pool is not None:
            pool.close()
        try:
            os.unlink(nf_path)
        except OSError:
            pass
    return out


def _host_parallel_probe(seconds: float = 1.5) -> float:
    """Effective parallel CPU capacity of this host: total pure-CPU work
    of one pinned burner process per cpu, over one burner alone. ~N on a
    dedicated N-core host; shared/overcommitted containers measure well
    below N (this 2-vCPU dev container: 1.3-2.2x run over run) — the
    denominator that says whether a flat scaling curve is the runtime's
    fault or the host's."""
    import multiprocessing as mp
    import os
    import time as _t

    def burn(cpu, q):
        try:
            os.sched_setaffinity(0, {cpu})
        except OSError:
            pass
        t0 = _t.perf_counter()
        n = 0
        x = 1.0
        while _t.perf_counter() - t0 < seconds:
            for _ in range(10000):
                x = x * 1.0000001
            n += 10000
        q.put(n)

    cpus = sorted(os.sched_getaffinity(0))
    q = mp.Queue()
    p = mp.Process(target=burn, args=(cpus[0], q))
    p.start()
    p.join()
    single = q.get()
    procs = [mp.Process(target=burn, args=(c, q)) for c in cpus]
    for p in procs:
        p.start()
    for p in procs:
        p.join()
    total = sum(q.get() for _ in procs)
    return round(total / max(1, single), 2)


def scaling_bench(max_cpus: int, seconds: float = 2.0,
                  payload: int = 16) -> dict:
    """Multicore scaling lane (``bench.py --cpus N``, ROADMAP item 1):
    native framework echo qps measured at {1, 2, ..., N} CPUs. At each
    point the SERVER process is pinned (sched_setaffinity) to the first
    n host cpus and runs n dispatcher loops (NAT_DISPATCHERS=n, no
    dispatcher split — a dedicated server shards over its whole pool),
    and n CLIENT processes are pinned one per cpu driving async-windowed
    load. Separate processes mean the single-core point is the honest
    everything-on-one-core number and the curve measures the server
    runtime's own scale-out, not in-process cross-runtime interference.

    Artifact schema notes (ride as ``extra.scaling``):
      "1".."N"          qps at that cpu count
      cpu_sets          the exact server/client pin sets per point
      disp_stats        per-point per-dispatcher rows from the SERVER
                        process after the load ({sockets, wakeups,
                        sqpoll} per loop via nat_disp_stat) — a
                        sublinear-scaling finding arrives with the
                        dispatcher-balance evidence attached
      host_parallel_x   pure-CPU capacity control: one pinned burner per
                        cpu vs one alone — the ceiling ANY workload can
                        scale to on this host (overcommitted containers
                        sit far below the cpu count)
    The bench gate derives ``cpus2_scaling_x`` = qps(2)/qps(1) and holds
    a scaling-efficiency band against the committed baseline: sublinear
    scaling beyond tolerance fails the gate like any regression.
    """
    import json as _json
    import os
    import subprocess
    import sys

    host_cpus = sorted(os.sched_getaffinity(0))
    n_avail = len(host_cpus)
    out: dict = {"cpu_sets": {}}
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    server_script = (
        "import json, os, sys\n"
        "os.sched_setaffinity(0, {server_cpus})\n"
        "sys.path.insert(0, '.')\n"
        "from brpc_tpu import native\n"
        "port = native.rpc_server_start(nworkers={server_n},"
        " native_echo=True)\n"
        "print(port, flush=True)\n"
        "sys.stdin.readline()\n"
        # per-dispatcher evidence for the scaling artifact: wakeup/
        # SQPOLL/socket counts per loop AFTER the load, so a sublinear
        # finding shows whether the loops were actually balanced
        "print('DISP ' + json.dumps(native.dispatcher_stats()),"
        " flush=True)\n"
        "native.rpc_server_stop()\n")
    client_script = (
        "import os, sys, ctypes\n"
        "os.sched_setaffinity(0, {client_cpus})\n"
        "sys.path.insert(0, '.')\n"
        "from brpc_tpu import native\n"
        "lib = native.load()\n"
        "got = ctypes.c_uint64(0)\n"
        "q = lib.nat_rpc_client_bench_async(b'127.0.0.1', {port},"
        " {conns}, 256, {seconds}, {payload}, ctypes.byref(got))\n"
        "print('QPS', q, flush=True)\n")

    try:
        out["host_parallel_x"] = _host_parallel_probe()
    except Exception:
        pass

    # clamp to the cpus that actually exist: points beyond n_avail would
    # silently re-measure the full-host configuration and read as a
    # flat curve (the cpu_sets field records the real pin sets)
    for n in range(1, min(max(1, max_cpus), n_avail) + 1):
        cpus = host_cpus[:n]
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["NAT_DISPATCHERS"] = str(len(cpus))
        env.pop("NAT_DISP_SPLIT", None)  # dedicated processes: no split
        srv = subprocess.Popen(
            [sys.executable, "-c", server_script.format(
                server_cpus=set(cpus), server_n=len(cpus))],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
            cwd=repo_root, env=env)
        try:
            port = int(srv.stdout.readline())
            cenv = dict(env)
            cenv["NAT_DISPATCHERS"] = "1"
            clients = []
            try:
                for cpu in cpus:
                    clients.append(subprocess.Popen(
                        [sys.executable, "-c", client_script.format(
                            client_cpus={cpu}, port=port, conns=2,
                            seconds=seconds, payload=payload)],
                        stdout=subprocess.PIPE, text=True, cwd=repo_root,
                        env=cenv))
                qps = 0.0
                for cli in clients:
                    cout, _ = cli.communicate(timeout=120 + seconds)
                    for line in cout.splitlines():
                        if line.startswith("QPS "):
                            qps += float(line.split()[1])
                out[str(n)] = round(qps, 1)
                out["cpu_sets"][str(n)] = {
                    "server": sorted(cpus),
                    "clients": [[c] for c in cpus]}
            finally:
                # a wedged client must not outlive its point: a stray
                # PINNED load generator would contaminate every later
                # bench lane in this process
                for cli in clients:
                    if cli.poll() is None:
                        cli.kill()
                    try:
                        cli.wait(timeout=10)
                    except Exception:
                        pass
        finally:
            try:
                srv.stdin.close()
                # the server answers the stdin EOF with one
                # "DISP [...]" line of per-dispatcher counters, the
                # balance evidence for this point; read it on a helper
                # thread so a wedged server cannot hang the gate past
                # the 15s bound below
                def _read_disp(stream=srv.stdout, point=str(n)):
                    for line in stream:
                        if line.startswith("DISP "):
                            out.setdefault("disp_stats", {})[point] = \
                                _json.loads(line[5:])
                            break

                reader = threading.Thread(target=_read_disp, daemon=True)
                reader.start()
                reader.join(timeout=15)
                srv.wait(timeout=15)
            except Exception:
                srv.kill()
    return out


def device_lane_bench() -> dict:
    """Device-transport bandwidth numbers — the rdma_performance analog
    (example/rdma_performance/client.cpp:50-52,136-183 measures verbs
    GB/s; here each lane of the device transport is measured on the real
    chip): host<->device DMA, the in-process zero-copy lane, shm-arena
    staging, a two-process shm push, and the native bulk data path."""
    import time

    import numpy as np

    out = {}

    # The axon-tunnel DMA sections (and the driver's dryrun/compile
    # steps right before bench.py) leave the host in a state that
    # depresses LOOPBACK throughput for tens of seconds — BENCH_r04
    # captured shm_push at 0.04 GB/s while the same run's native_bulk
    # (measured a minute later) did 1.35. Gate the first loopback
    # measurement on a cheap socketpair probe: wait while throughput is
    # still RECOVERING (improving >15% every 2s), bounded at 45s.
    _loopback_stabilize()

    # two-process shm push: full RPC + descriptor-ring fabric path
    # (ISSUE 15: payload written ONCE into the server's blob arena as
    # kind-8 records, consumed in place as zero-copy lease-backed
    # arrays — no payload bytes on the wire, no staging copy on either
    # side). Runs FIRST: the client must not own a fabric segment of its
    # own (the shm_desc lane below creates one in this process), and the
    # tunnel-DMA lanes must not depress it.
    try:
        import os
        import subprocess
        import sys

        from brpc_tpu.rpc import device_transport as dt
        from brpc_tpu.rpc.tensor_service import (TensorClient,
                                                 make_device_channel)

        # the receiving server rides the NATIVE runtime: descriptor RPCs
        # parse in the C++ loop, usercode (lease consume) on the py lane
        script = (
            "import os, sys; sys.path.insert(0, '.')\n"
            "os.environ.setdefault('BRPC_TPU_FABRIC_ARENA',"
            " str(128 << 20))\n"
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "from brpc_tpu import rpc, native\n"
            "from brpc_tpu.rpc.tensor_service import TensorStoreService\n"
            "use_nat = native.available()\n"
            "srv = rpc.Server(rpc.ServerOptions(num_threads=2,\n"
            "                 use_native_runtime=use_nat))\n"
            "srv.add_service(TensorStoreService())\n"
            "assert srv.start('127.0.0.1:0') == 0\n"
            "print(srv.listen_endpoint.port, flush=True)\n"
            "sys.stdin.readline()\n")
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        proc = subprocess.Popen([sys.executable, "-c", script],
                                stdin=subprocess.PIPE,
                                stdout=subprocess.PIPE, text=True,
                                cwd=repo_root)
        try:
            port = int(proc.stdout.readline())
            ch = make_device_channel(f"127.0.0.1:{port}")
            client = TensorClient(ch)
            arr = np.random.randint(0, 255, 8 << 20,
                                    dtype=np.uint8)
            # ONE name throughout: the fabric's blob arena is a RING, so
            # the store must keep replacing (= releasing) its zero-copy
            # lease-backed entries — a store retaining every name would
            # head-block arena reclaim (leases release out of order, but
            # the head only advances past released spans)
            client.push("serial", [arr])  # handshake + warm
            rounds = 8
            t0 = time.perf_counter()
            for i in range(rounds):
                cntl, resp = client.push("serial", [arr])
                assert not cntl.failed(), cntl.error_text
            dt_s = time.perf_counter() - t0
            out["shm_push_serial_GBps"] = round(
                arr.nbytes * rounds / dt_s / 1e9, 3)
            # concurrent pushes — the rdma_performance measurement shape
            # (client.cpp:136-183 runs many streams at once): arena
            # write, descriptor RPC and lease consume of different
            # pushes overlap, which is what the send window exists for
            import threading as _threading

            K, per = 3, 6
            errs = []

            def _pusher(tid):
                for i in range(per):
                    c, _ = client.push("serial", [arr])
                    if c.failed():
                        errs.append(c.error_text)

            t0 = time.perf_counter()
            ts = [_threading.Thread(target=_pusher, args=(t,))
                  for t in range(K)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            dt_s = time.perf_counter() - t0
            assert not errs, errs
            out["shm_push_GBps"] = round(
                arr.nbytes * per * K / dt_s / 1e9, 3)
            out["shm_push_lane"] = (
                "ring" if dt.lane_counters()["ring"] > 0 else "shm")
            ch.close()
        finally:
            proc.stdin.close()
            proc.wait(timeout=10)
    except Exception:
        pass

    # zero-copy descriptor-ring lane (nat_shm_lane.cpp): two-process push
    # through the lock-free descriptor rings + blob arena — the native
    # transport the shm usercode lane and bulk-tensor staging ride
    # (nat_shm_push_tensor). The small/large record split separates
    # per-record overhead from raw staging bandwidth: the round-4 byte
    # rings paid a robust-mutex lock + double memcpy + futex wake per
    # record, which is exactly what the small-record number would expose.
    try:
        import subprocess
        import sys

        from brpc_tpu import native

        lib = native.load()
        lib.nat_shm_lane_enable(0)  # retire any earlier lane/segment
        if lib.nat_shm_lane_create(16 << 20) == 0:
            name = lib.nat_shm_lane_name().decode()
            import os as _os

            repo_root = _os.path.dirname(_os.path.dirname(
                _os.path.abspath(__file__)))
            child = subprocess.Popen(
                [sys.executable, "-c", (
                    "import sys; sys.path.insert(0, '.')\n"
                    "from brpc_tpu import native\n"
                    "lib = native.load()\n"
                    f"assert lib.nat_shm_worker_attach("
                    f"{name!r}.encode()) == 0\n"
                    "lib.nat_shm_worker_drain_bench(8000)\n")],
                cwd=repo_root)
            deadline = time.time() + 30
            while (lib.nat_shm_lane_workers() < 1
                   and time.time() < deadline):
                time.sleep(0.05)
            if lib.nat_shm_lane_workers() >= 1:
                small = native.shm_push_bench(16 << 10, 1.0)
                large = native.shm_push_bench(1 << 20, 1.5)
                out["shm_desc_small_GBps"] = round(small["GBps"], 3)
                out["shm_desc_GBps"] = round(large["GBps"], 3)
            lib.nat_shm_lane_enable(0)  # shutdown: child drain exits
            child.wait(timeout=20)
    except Exception:
        pass

    # per-hop breakdown of the fabric path (ISSUE 15 satellite): where a
    # regression in the zero-copy pipeline lives — arena write (the ONE
    # producer memcpy), ring latency (push -> take), consume (zero-copy
    # lease -> np view), device_put (put_via_pool from the arena view).
    # In-process: the hops are the same code the two-process lanes run.
    try:
        from brpc_tpu import native

        if native.available():
            lib = native.load()
            lib.nat_shm_lane_enable(0)
            if lib.nat_shm_lane_create(32 << 20) == 0 and \
                    lib.nat_shm_producer_attach(
                        lib.nat_shm_lane_name()) >= 0:
                src = np.random.randint(0, 255, 1 << 20, dtype=np.uint8)
                hops = {"arena_write_us": [], "ring_us": [],
                        "consume_us": [], "device_put_us": []}
                from brpc_tpu.rpc.device_transport import \
                    default_block_pool

                pool = default_block_pool()
                for i in range(20):
                    t0 = time.perf_counter()
                    rc = native.fabric_push(src, i)
                    t1 = time.perf_counter()
                    if rc != 0:
                        continue
                    lease = native.fabric_take(2000)
                    t2 = time.perf_counter()
                    if lease is None:
                        continue
                    view = np.frombuffer(lease.view(), dtype=np.uint8)
                    t3 = time.perf_counter()
                    arr = pool.put_via_pool(view, np.uint8,
                                            (view.size,))
                    t4 = time.perf_counter()
                    del arr
                    lease.release()
                    hops["arena_write_us"].append((t1 - t0) * 1e6)
                    hops["ring_us"].append((t2 - t1) * 1e6)
                    hops["consume_us"].append((t3 - t2) * 1e6)
                    hops["device_put_us"].append((t4 - t3) * 1e6)
                if hops["arena_write_us"]:
                    import statistics

                    out["hops"] = {
                        k: round(statistics.median(v), 1)
                        for k, v in hops.items() if v}
            lib.nat_shm_lane_enable(0)
    except Exception:
        pass

    # read-arena grow prefault (drive-by satellite): the growable
    # read-side allocator seam (install_read_arena) must not
    # reintroduce the first-touch fault cliff on grow (the r05
    # 0.085->1.0 GB/s class) — a GROWN arena's first block writes must
    # run within a small factor of warm writes (every arena prefaults
    # at creation). Contract: a cliff reports 0 so the gate trips.
    try:
        from brpc_tpu.rpc import device_transport as dt

        chain = dt.ReadArenaChain(size=4 << 20, capacity=1 << 20)
        try:
            pinned = []  # hold the blocks: a dropped block's finalizer
            while True:  # would free its span and un-exhaust the arena
                b = chain.arenas[0].make_block(1 << 20)
                if b is None:
                    break
                pinned.append(b)
            grows0 = chain.grows
            blk = chain.alloc_block()  # forces a prefaulted grow
            assert blk is not None and chain.grows == grows0 + 1
            src = np.random.randint(0, 255, 1 << 20, dtype=np.uint8)

            def _write_bw(rounds):
                t0 = time.perf_counter()
                for _ in range(rounds):
                    np.frombuffer(blk.data, dtype=np.uint8)[:] = src
                return (1 << 20) * rounds / (
                    time.perf_counter() - t0) / 1e9

            first = _write_bw(1)   # includes any residual fault cost
            warm = _write_bw(8)
            gbps = round(first, 3)
            if first < warm / 6:   # the r05 cliff was ~12x
                gbps = 0.0
            out["read_arena_grow_GBps"] = gbps
            out["read_arena_warm_GBps"] = round(warm, 3)
        finally:
            chain.close()
    except Exception:
        pass

    # host <-> device DMA (the raw registered-memory bandwidth analog)
    try:
        import jax

        nbytes = 64 << 20
        host = np.random.randint(0, 255, nbytes, dtype=np.uint8)
        dev = jax.device_put(host)
        dev.block_until_ready()  # warm
        iters = 3
        t0 = time.perf_counter()
        for _ in range(iters):
            jax.device_put(host).block_until_ready()
        out["h2d_GBps"] = round(nbytes * iters / (time.perf_counter() - t0)
                                / 1e9, 3)
        t0 = time.perf_counter()
        for _ in range(iters):
            np.asarray(dev)
        d2h = round(nbytes * iters / (time.perf_counter() - t0) / 1e9, 3)
        # On the axon-tunneled chip, device->host readback crosses the
        # tunnel at single-digit MB/s — an environment artifact, not a
        # lane capability. Label it so round-over-round comparison
        # doesn't read it as a regression (VERDICT r3 weak #3).
        # the axon plugin registers as platform "tpu"; the tunnel is in
        # play exactly when the xla_bridge backend is the axon plugin
        from jax._src import xla_bridge as _xb

        tunneled = "axon" in str(
            getattr(_xb.get_backend(), "platform_version", "")).lower()
        if not tunneled:
            try:
                tunneled = "axon" in _xb.canonicalize_platform(
                    _xb.default_backend())
            except Exception:
                pass
        if tunneled or d2h < 0.1:  # single-digit MB/s readback IS the
            # tunnel signature; no real lane reads back that slow
            out["d2h_GBps_tunnel_limited"] = d2h
        else:
            out["d2h_GBps"] = d2h
    except Exception:
        pass

    # in-process zero-copy lane: ticket round trips carrying a real array
    try:
        import jax

        from brpc_tpu.rpc import device_transport as dt

        arr = jax.device_put(np.zeros(16 << 20, dtype=np.uint8))
        arr.block_until_ready()
        rounds = 200
        t0 = time.perf_counter()
        for _ in range(rounds):
            ticket = dt.inproc_publish([arr])
            got = dt.inproc_claim(ticket)
        dt_s = time.perf_counter() - t0
        assert got is not None
        out["inproc_GBps"] = round(int(arr.nbytes) * rounds / dt_s / 1e9, 3)
    except Exception:
        pass

    # shm-arena staging: device bytes -> pinned shared memory -> back
    # (the sender/receiver halves of the same-host lane, one process)
    try:
        from brpc_tpu.rpc import device_transport as dt

        arena = dt.HostArena(size=96 << 20)
        try:
            n = 32 << 20
            src = np.random.randint(0, 255, n, dtype=np.uint8)
            off = arena.alloc(n)
            rounds = 5
            t0 = time.perf_counter()
            for _ in range(rounds):
                dst = np.frombuffer(arena.shm.buf, dtype=np.uint8,
                                    count=n, offset=off)
                dst[:] = src
                back = np.frombuffer(arena.shm.buf, dtype=np.uint8,
                                     count=n, offset=off).copy()
            dt_s = time.perf_counter() - t0
            assert back[-1] == src[-1]
            # two copies per round; report one-direction bandwidth
            out["shm_stage_GBps"] = round(2 * n * rounds / dt_s / 1e9, 3)
        finally:
            arena.close()
    except Exception:
        pass

    # native bulk data path: 1MB attachments echoed through the full
    # native stack (socket write queue -> dispatcher -> native handler)
    try:
        import ctypes

        from brpc_tpu import native

        lib = native.load()
        lib.nat_rpc_client_bench_bulk.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_double,
            ctypes.POINTER(ctypes.c_uint64)]
        lib.nat_rpc_client_bench_bulk.restype = ctypes.c_double
        port = native.rpc_server_start(native_echo=True)
        try:
            got = ctypes.c_uint64(0)
            gbps = lib.nat_rpc_client_bench_bulk(
                b"127.0.0.1", port, 1 << 20, 1.5, ctypes.byref(got))
            out["native_bulk_GBps"] = round(gbps, 3)
        finally:
            native.rpc_server_stop()
    except Exception:
        pass

    return out


def model_collective_bench() -> dict:
    """Round-over-round model + collective rows (VERDICT r3 #6): the
    single-chip flagship train-step rate on the real device, and the
    8-virtual-device CPU-mesh collective bandwidth — the measurable proxy
    for BASELINE.md's ParallelChannel-allreduce north star (harness shape:
    example/rdma_performance/client.cpp:136-183, timed loop over a fixed
    transfer size).

    Returns {model_step_per_s, model_tokens_per_s, collective_GBps,
    a2a_GBps}."""
    import os
    import subprocess
    import sys

    out = {}
    try:
        import jax
        import jax.numpy as jnp

        from brpc_tpu.tensor import (ModelConfig, init_params,
                                     make_spmd_train_step)
        from brpc_tpu.tensor.config import MeshSpec

        def timed_steps(cfg, B, T, iters):
            """Steady-state step rate, measured honestly through the
            axon tunnel. Two traps found in round 5 (the round-4 'step
            floor' artifact): (a) host-initialized params and the step's
            device outputs have different layouts, so the SECOND call
            compiles a second executable — warm up twice, feeding the
            returned params back; (b) jax.block_until_ready returns
            before execution completes on this platform, so the sync
            must be a device-to-host read (float(loss)) — the chained
            param dependency makes the final read wait on every step."""
            params = init_params(cfg, jax.random.PRNGKey(0))
            mesh, step = make_spmd_train_step(cfg, MeshSpec())
            key = jax.random.PRNGKey(1)
            tokens = jax.random.randint(key, (B, T), 0, cfg.vocab,
                                        dtype=jnp.int32)
            labels = jnp.roll(tokens, -1, axis=1)
            loss, p = step(params, tokens, labels)   # compile #1
            float(loss)
            loss, p = step(p, tokens, labels)        # compile #2 (layouts)
            float(loss)
            t0 = time.perf_counter()
            for _ in range(iters):
                loss, p = step(p, tokens, labels)
            float(loss)  # d2h forces the whole chain
            return iters / (time.perf_counter() - t0)

        # continuity row: the round-3/4 toy config
        toy = ModelConfig(vocab=256, d_model=128, n_heads=4, d_head=32,
                          d_ff=256, n_layers=2, n_experts=2)
        sps = timed_steps(toy, B=4, T=256, iters=30)
        out["model_step_per_s"] = round(sps, 2)
        out["model_tokens_per_s"] = round(4 * 256 * sps, 1)

        # MFU row: a config big enough to be compute-dominated on one
        # chip (fits the v5e's 15.75G HBM; measured 28-31% MFU through
        # the tunnel). Analytic model FLOPs (fwd matmuls ×3 for
        # fwd+bwd) — conservative: the MoE one-hot dispatch einsums burn
        # real FLOPs that are NOT counted as model FLOPs:
        #   attn projections  2·4·d·dqkv        per token·layer
        #   attention scores  2·2·T·dqkv        per token·layer
        #   MoE (top-1)       2·2·d·d_ff        per token·layer
        #   unembed           2·d·vocab         per token
        # expert_capacity_factor 1.25 is the Switch-Transformer standard;
        # the dense one-hot dispatch einsums cost FLOPs proportional to
        # capacity, so the default 2.0 was burning ~7% MFU on dispatch
        # overhead (measured 30.9% -> 38.2% at 1.25, same loss curve)
        big = ModelConfig(vocab=32768, d_model=2048, n_heads=16,
                          d_head=128, d_ff=8192, n_layers=8, n_experts=2,
                          expert_capacity_factor=1.25)
        B, T = 4, 512
        sps_big = timed_steps(big, B, T, iters=10)
        tokens_n = B * T
        d, dq, L = big.d_model, big.d_qkv, big.n_layers
        fwd_per_tok = (L * (2 * 4 * d * dq + 2 * 2 * T * dq +
                            2 * 2 * d * big.d_ff) + 2 * d * big.vocab)
        flops_step = 3.0 * fwd_per_tok * tokens_n
        kind = jax.devices()[0].device_kind.lower()
        peak = 197e12  # bf16 peak; v5e default
        if "v4" in kind:
            peak = 275e12
        elif "v5p" in kind or "v5 p" in kind:
            peak = 459e12
        elif "v6" in kind:
            peak = 918e12
        out["model_big_config"] = (
            f"d{big.d_model}xL{big.n_layers} moe{big.n_experts} "
            f"cf{big.expert_capacity_factor} B{B}xT{T} {big.dtype}")
        out["model_big_step_per_s"] = round(sps_big, 2)
        out["model_big_tokens_per_s"] = round(tokens_n * sps_big, 1)
        out["model_flops_per_step"] = flops_step
        out["mfu"] = round(flops_step * sps_big / peak, 4)
        out["mfu_peak_assumed_tflops"] = peak / 1e12
        out["mfu_device_kind"] = jax.devices()[0].device_kind
    except Exception:
        pass
    try:
        # collectives need >1 device: virtual 8-device CPU mesh in a
        # subprocess (the dryrun_multichip environment)
        # sitecustomize pins jax_platforms through jax.config (overrides
        # the env var): override it back before the backend initializes,
        # exactly as the test conftest does
        script = (
            "import sys; sys.path.insert(0, '.')\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "from brpc_tpu import parallel\n"
            "mesh = parallel.make_mesh({'x': 8})\n"
            "s = parallel.ici_bandwidth_probe(mesh, 'x',\n"
            "                                 nbytes=1 << 24, iters=5)\n"
            "import json; print(json.dumps(s), flush=True)\n")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            " --xla_force_host_platform_device_count=8")
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        res = subprocess.run([sys.executable, "-c", script],
                             capture_output=True, text=True, timeout=300,
                             cwd=repo_root, env=env)
        if res.returncode == 0:
            import json as _json

            stats = _json.loads(res.stdout.strip().splitlines()[-1])
            out["collective_GBps"] = stats.get("allreduce_GBps")
            for k in ("allgather_GBps", "all_to_all_GBps", "a2a_GBps",
                      "reduce_scatter_GBps"):
                if k in stats:
                    out[k] = stats[k]
    except Exception:
        pass
    return out


def collective_bench(nbytes: int = 1 << 24, iters: int = 20) -> dict:
    """Allreduce bandwidth on the real device(s) — rdma_performance role."""
    import jax

    from brpc_tpu import parallel

    n = len(jax.devices())
    mesh = parallel.make_mesh({"x": n})
    stats = parallel.ici_bandwidth_probe(mesh, "x", nbytes=nbytes,
                                         iters=iters)
    return {
        "metric": "allreduce_GBps",
        "value": round(stats["allreduce_GBps"], 3),
        "unit": "GB/s",
        "vs_baseline": 0.0,  # no published RDMA GB/s in the reference
        "extra": stats,
    }
