"""brpc_tpu.native — ctypes bindings to the C++ core (native/).

The native components mirror the reference's native layers (SURVEY.md
section 2: C++ throughout): a ucontext M:N fiber scheduler with lock-free
work stealing and butex (bthread's role), a refcounted-block IOBuf, a
varint RpcMeta codec, and an epoll echo runtime wire-compatible with the
Python tpu_std protocol. Built on demand with `make` (g++); cached .so.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libbrpc_tpu_native.so")

_lib: Optional[ctypes.CDLL] = None
_lib_lock = threading.Lock()


class NativeUnavailable(RuntimeError):
    pass


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True, timeout=300)
        return True
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            FileNotFoundError):
        return False


def load() -> ctypes.CDLL:
    """Load (building if needed) the native library."""
    global _lib
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO_PATH):
            if not _build():
                raise NativeUnavailable(
                    "native core not built and toolchain unavailable")
        lib = ctypes.CDLL(_SO_PATH)
        lib.nat_sched_start.argtypes = [ctypes.c_int]
        lib.nat_sched_start.restype = ctypes.c_int
        lib.nat_sched_stop.restype = None
        lib.nat_sched_workers.restype = ctypes.c_int
        lib.nat_sched_switches.restype = ctypes.c_uint64
        lib.nat_bench_spawn_join.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.nat_bench_spawn_join.restype = ctypes.c_uint64
        lib.nat_bench_ping_pong.argtypes = [ctypes.c_int]
        lib.nat_bench_ping_pong.restype = ctypes.c_double
        lib.nat_wsq_selftest.restype = ctypes.c_int
        lib.nat_iobuf_selftest.restype = ctypes.c_int
        lib.nat_meta_selftest.restype = ctypes.c_int
        lib.nat_echo_server_start.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.nat_echo_server_start.restype = ctypes.c_int
        lib.nat_echo_server_stop.restype = None
        lib.nat_echo_server_requests.restype = ctypes.c_uint64
        lib.nat_echo_client_bench.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_double,
            ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.nat_echo_client_bench.restype = ctypes.c_double
        _lib = lib
        return lib


def available() -> bool:
    try:
        load()
        return True
    except NativeUnavailable:
        return False


# -- convenience wrappers --------------------------------------------------

def sched_start(nworkers: int = 4) -> int:
    return load().nat_sched_start(nworkers)


def sched_stop():
    load().nat_sched_stop()


def bench_spawn_join(nfibers: int, rounds: int) -> int:
    return load().nat_bench_spawn_join(nfibers, rounds)


def bench_ping_pong(rounds: int = 10000) -> float:
    """Returns ns per fiber ping-pong round trip."""
    return load().nat_bench_ping_pong(rounds)


def echo_server_start(ip: str = "127.0.0.1", port: int = 0) -> int:
    """Starts the native echo server; returns the bound port."""
    rc = load().nat_echo_server_start(ip.encode(), port)
    if rc <= 0:
        raise RuntimeError("native echo server failed to start")
    return rc


def echo_server_stop():
    load().nat_echo_server_stop()


def echo_server_requests() -> int:
    return load().nat_echo_server_requests()


def echo_client_bench(ip: str, port: int, nconn: int = 2,
                      seconds: float = 2.0, payload: int = 16,
                      pipeline: int = 32) -> dict:
    out_requests = ctypes.c_uint64(0)
    qps = load().nat_echo_client_bench(ip.encode(), port, nconn, seconds,
                                       payload, pipeline,
                                       ctypes.byref(out_requests))
    return {"qps": qps, "requests": out_requests.value}
