"""brpc_tpu.native — ctypes bindings to the C++ core (native/).

The native components mirror the reference's native layers (SURVEY.md
section 2: C++ throughout): a ucontext M:N fiber scheduler with lock-free
work stealing and butex (bthread's role), a refcounted-block IOBuf, a
varint RpcMeta codec, and an epoll echo runtime wire-compatible with the
Python tpu_std protocol. Built on demand with `make` (g++); cached .so.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import weakref
from typing import Optional

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
# BRPC_TPU_NATIVE_SO points the whole Python surface at an alternate
# build of the same library — the sanitizer soak (tools/check.sh --soak)
# runs the full pytest native matrix against
# libbrpc_tpu_native_asan.so this way (with libasan LD_PRELOADed).
_SO_PATH = os.environ.get(
    "BRPC_TPU_NATIVE_SO",
    os.path.join(_NATIVE_DIR, "libbrpc_tpu_native.so"))

_lib: Optional[ctypes.CDLL] = None
_lib_lock = threading.Lock()
# /proc/self/statm resident bytes just before the .so first loaded: the
# zero point the /status nat_mem RSS reconciliation diffs against
# (brpc_tpu.bvar.native_vars.rss_reconciliation_line).
_rss_at_load: Optional[int] = None


def _read_rss() -> int:
    try:
        with open("/proc/self/statm", "r") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGESIZE")
    except (OSError, ValueError, IndexError):
        return 0


def rss_at_load() -> int:
    """Resident bytes captured immediately before the native library
    loaded (0 when it never loaded)."""
    return _rss_at_load or 0


class NativeUnavailable(RuntimeError):
    pass


class NatSpanRec(ctypes.Structure):
    """Mirror of nat_stats.h NatSpanRec — one sampled native-handled call
    (timestamps are CLOCK_MONOTONIC ns; see stats_now_ns for mapping)."""

    _fields_ = [
        ("trace_id", ctypes.c_uint64),
        ("span_id", ctypes.c_uint64),
        ("parent_span_id", ctypes.c_uint64),
        ("sock_id", ctypes.c_uint64),
        ("recv_ns", ctypes.c_uint64),
        ("parse_ns", ctypes.c_uint64),
        ("dispatch_ns", ctypes.c_uint64),
        ("write_ns", ctypes.c_uint64),
        ("protocol", ctypes.c_int32),
        ("error_code", ctypes.c_int32),
        ("req_bytes", ctypes.c_uint32),
        ("resp_bytes", ctypes.c_uint32),
        ("method", ctypes.c_char * 48),
    ]


class NatMethodStatRow(ctypes.Structure):
    """Mirror of nat_stats.h NatMethodStatRow — one per-method stats row
    (count/errors/current+max concurrency; lane indexes the NL_* table)."""

    _fields_ = [
        ("count", ctypes.c_uint64),
        ("errors", ctypes.c_uint64),
        ("concurrency", ctypes.c_int64),
        ("max_concurrency", ctypes.c_int64),
        ("lane", ctypes.c_int32),
        ("method", ctypes.c_char * 52),
    ]


class NatConnRow(ctypes.Structure):
    """Mirror of nat_stats.h NatConnRow — one native /connections row."""

    _fields_ = [
        ("sock_id", ctypes.c_uint64),
        ("in_bytes", ctypes.c_uint64),
        ("out_bytes", ctypes.c_uint64),
        ("in_msgs", ctypes.c_uint64),
        ("out_msgs", ctypes.c_uint64),
        ("read_calls", ctypes.c_uint64),
        ("write_calls", ctypes.c_uint64),
        ("unwritten_bytes", ctypes.c_uint64),
        ("mem_bytes", ctypes.c_uint64),
        ("fd", ctypes.c_int32),
        ("disp_idx", ctypes.c_int32),
        ("server_side", ctypes.c_int32),
        ("protocol", ctypes.c_char * 12),
        ("remote", ctypes.c_char * 24),
    ]


class NatResRow(ctypes.Structure):
    """Mirror of nat_res.h NatResRow — one per-subsystem resource-ledger
    row (live bytes/objects, cumulative allocs/frees, high-water)."""

    _fields_ = [
        ("live_bytes", ctypes.c_uint64),
        ("live_objects", ctypes.c_uint64),
        ("cum_allocs", ctypes.c_uint64),
        ("cum_frees", ctypes.c_uint64),
        ("cum_alloc_bytes", ctypes.c_uint64),
        ("hwm_bytes", ctypes.c_uint64),
        ("name", ctypes.c_char * 16),
    ]


class NatLockRankRow(ctypes.Structure):
    """Mirror of nat_stats.h NatLockRankRow — always-on per-rank
    contended-wait totals of the NatMutex slow path."""

    _fields_ = [
        ("waits", ctypes.c_uint64),
        ("wait_us", ctypes.c_uint64),
        ("rank", ctypes.c_int32),
        ("name", ctypes.c_char * 20),
    ]


class NatDumpStatusRec(ctypes.Structure):
    """Mirror of nat_dump.h NatDumpStatusRec — flight-recorder status
    (counts are since the current nat_dump_start window)."""

    _fields_ = [
        ("samples", ctypes.c_uint64),
        ("written", ctypes.c_uint64),
        ("bytes", ctypes.c_uint64),
        ("drops", ctypes.c_uint64),
        ("oversize", ctypes.c_uint64),
        ("rotations", ctypes.c_uint64),
        ("max_file_bytes", ctypes.c_uint64),
        ("max_payload", ctypes.c_uint64),
        ("seed", ctypes.c_uint64),
        ("every", ctypes.c_uint32),
        ("running", ctypes.c_int32),
        ("generations", ctypes.c_int32),
        ("dir", ctypes.c_char * 192),
    ]


class NatReplayResult(ctypes.Structure):
    """Mirror of nat_dump.h NatReplayResult — one nat_replay_run's
    outcome (latency quantiles cover successful calls)."""

    _fields_ = [
        ("loaded", ctypes.c_uint64),
        ("sent", ctypes.c_uint64),
        ("ok", ctypes.c_uint64),
        ("failed", ctypes.c_uint64),
        ("skipped", ctypes.c_uint64),
        ("seconds", ctypes.c_double),
        ("qps", ctypes.c_double),
        ("p50_us", ctypes.c_double),
        ("p99_us", ctypes.c_double),
    ]


class NatClusterRow(ctypes.Structure):
    """Mirror of nat_stats.h NatClusterRow — one per-backend row of a
    native cluster's server list (selects/errors/breaker/lame-duck)."""

    _fields_ = [
        ("selects", ctypes.c_uint64),
        ("errors", ctypes.c_uint64),
        ("inflight", ctypes.c_int64),
        ("ema_latency_us", ctypes.c_uint64),
        ("weight", ctypes.c_int32),
        ("breaker_open", ctypes.c_int32),
        ("lame_duck", ctypes.c_int32),
        ("part_index", ctypes.c_int32),
        ("part_total", ctypes.c_int32),
        ("endpoint", ctypes.c_char * 24),
        ("tag", ctypes.c_char * 16),
    ]


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True, timeout=300)
        return True
    except subprocess.CalledProcessError as e:
        import sys

        # a failed rebuild with a stale .so present would otherwise die
        # later with a confusing missing-symbol AttributeError
        sys.stderr.write(
            "brpc_tpu.native: rebuild FAILED — a cached library may be "
            "stale:\n" + (e.stderr or b"").decode(errors="replace")[-2000:]
            + "\n")
        return False
    except (subprocess.TimeoutExpired, FileNotFoundError):
        return False


def load() -> ctypes.CDLL:
    """Load (building if needed) the native library."""
    global _lib
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        # incremental make keeps a cached .so in sync with newer sources
        # (a stale library would miss newly-exported symbols); harmless
        # no-op when up to date, ignored when only a prebuilt .so exists.
        # An explicit BRPC_TPU_NATIVE_SO override is loaded AS IS — the
        # soak driver builds its instrumented library itself.
        if "BRPC_TPU_NATIVE_SO" in os.environ:
            if not os.path.exists(_SO_PATH):
                raise NativeUnavailable(
                    "BRPC_TPU_NATIVE_SO points at a missing library: " +
                    _SO_PATH)
        elif not _build() and not os.path.exists(_SO_PATH):
            raise NativeUnavailable(
                "native core not built and toolchain unavailable")
        global _rss_at_load
        if _rss_at_load is None:
            _rss_at_load = _read_rss()
        lib = ctypes.CDLL(_SO_PATH)
        lib.nat_sched_start.argtypes = [ctypes.c_int]
        lib.nat_sched_start.restype = ctypes.c_int
        lib.nat_sched_stop.restype = None
        lib.nat_sched_workers.restype = ctypes.c_int
        lib.nat_sched_switches.restype = ctypes.c_uint64
        lib.nat_bench_spawn_join.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.nat_bench_spawn_join.restype = ctypes.c_uint64
        lib.nat_bench_ping_pong.argtypes = [ctypes.c_int]
        lib.nat_bench_ping_pong.restype = ctypes.c_double
        lib.nat_wsq_selftest.restype = ctypes.c_int
        lib.nat_iobuf_selftest.restype = ctypes.c_int
        lib.nat_meta_selftest.restype = ctypes.c_int
        lib.nat_echo_server_start.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.nat_echo_server_start.restype = ctypes.c_int
        lib.nat_echo_server_stop.restype = None
        lib.nat_echo_server_requests.restype = ctypes.c_uint64
        lib.nat_echo_client_bench.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_double,
            ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.nat_echo_client_bench.restype = ctypes.c_double
        # -- native RPC runtime (framework path) --
        lib.nat_rpc_server_start.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int]
        lib.nat_rpc_server_start.restype = ctypes.c_int
        lib.nat_rpc_server_stop.restype = None
        lib.nat_rpc_server_requests.restype = ctypes.c_uint64
        lib.nat_rpc_server_connections.restype = ctypes.c_uint64
        lib.nat_take_request.argtypes = [ctypes.c_int]
        lib.nat_take_request.restype = ctypes.c_void_p
        lib.nat_req_field.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_size_t)]
        lib.nat_req_field.restype = ctypes.c_void_p
        lib.nat_req_cid.argtypes = [ctypes.c_void_p]
        lib.nat_req_cid.restype = ctypes.c_int64
        lib.nat_req_aux.argtypes = [ctypes.c_void_p]
        lib.nat_req_aux.restype = ctypes.c_uint64
        lib.nat_req_compress.argtypes = [ctypes.c_void_p]
        lib.nat_req_compress.restype = ctypes.c_int32
        lib.nat_req_sock_id.argtypes = [ctypes.c_void_p]
        lib.nat_req_sock_id.restype = ctypes.c_uint64
        lib.nat_req_free.argtypes = [ctypes.c_void_p]
        lib.nat_req_free.restype = None
        lib.nat_req_kind.argtypes = [ctypes.c_void_p]
        lib.nat_req_kind.restype = ctypes.c_int32
        lib.nat_rpc_server_enable_raw_fallback.argtypes = [ctypes.c_int]
        lib.nat_rpc_server_enable_raw_fallback.restype = ctypes.c_int
        lib.nat_rpc_set_dispatchers.argtypes = [ctypes.c_int]
        lib.nat_rpc_set_dispatchers.restype = ctypes.c_int
        lib.nat_sock_write.argtypes = [
            ctypes.c_uint64, ctypes.c_char_p, ctypes.c_size_t]
        lib.nat_sock_write.restype = ctypes.c_int
        lib.nat_sock_set_failed.argtypes = [ctypes.c_uint64]
        lib.nat_sock_set_failed.restype = ctypes.c_int
        lib.nat_respond.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
            ctypes.c_size_t]
        lib.nat_respond.restype = ctypes.c_int
        lib.nat_channel_open.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int]
        lib.nat_channel_open.restype = ctypes.c_void_p
        lib.nat_channel_close.argtypes = [ctypes.c_void_p]
        lib.nat_channel_close.restype = None
        lib.nat_channel_call.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int,
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_size_t),
            ctypes.POINTER(ctypes.c_char_p)]
        lib.nat_channel_call.restype = ctypes.c_int
        lib.nat_channel_call_full.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int, ctypes.c_int,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_size_t),
            ctypes.POINTER(ctypes.c_char_p)]
        lib.nat_channel_call_full.restype = ctypes.c_int
        lib.nat_buf_free.argtypes = [ctypes.c_char_p]
        lib.nat_buf_free.restype = None
        lib.nat_rpc_client_bench.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_double, ctypes.c_int, ctypes.POINTER(ctypes.c_uint64)]
        lib.nat_rpc_client_bench.restype = ctypes.c_double
        lib.nat_channel_acall.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int, ctypes.c_void_p,
            ctypes.c_void_p]
        lib.nat_channel_acall.restype = ctypes.c_int
        lib.nat_rpc_client_bench_async.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_double, ctypes.c_int, ctypes.POINTER(ctypes.c_uint64)]
        lib.nat_rpc_client_bench_async.restype = ctypes.c_double
        lib.nat_rpc_use_io_uring.argtypes = [ctypes.c_int]
        lib.nat_rpc_use_io_uring.restype = ctypes.c_int
        lib.nat_ring_counters.argtypes = [
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64)]
        lib.nat_ring_counters.restype = None
        lib.nat_disp_count.argtypes = []
        lib.nat_disp_count.restype = ctypes.c_int
        lib.nat_disp_stat.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_int)]
        lib.nat_disp_stat.restype = ctypes.c_int
        # -- native HTTP/1.1 lane --
        lib.nat_rpc_server_native_http.argtypes = [ctypes.c_int]
        lib.nat_rpc_server_native_http.restype = ctypes.c_int
        lib.nat_http_respond.argtypes = [
            ctypes.c_uint64, ctypes.c_int64, ctypes.c_char_p,
            ctypes.c_size_t, ctypes.c_int]
        lib.nat_http_respond.restype = ctypes.c_int
        lib.nat_sock_graceful_close.argtypes = [ctypes.c_uint64]
        lib.nat_sock_graceful_close.restype = ctypes.c_int
        lib.nat_grpc_respond.argtypes = [
            ctypes.c_uint64, ctypes.c_int64, ctypes.c_char_p,
            ctypes.c_size_t, ctypes.c_int, ctypes.c_char_p]
        lib.nat_grpc_respond.restype = ctypes.c_int
        lib.nat_rpc_server_ssl.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.nat_rpc_server_ssl.restype = ctypes.c_int
        # -- overload protection (nat_overload.cpp) --
        lib.nat_rpc_server_limiter.argtypes = [ctypes.c_char_p]
        lib.nat_rpc_server_limiter.restype = ctypes.c_int
        lib.nat_rpc_server_queue_deadline_ms.argtypes = [ctypes.c_int]
        lib.nat_rpc_server_queue_deadline_ms.restype = ctypes.c_int
        lib.nat_rpc_server_inflight.restype = ctypes.c_int
        lib.nat_rpc_server_limit.restype = ctypes.c_int
        # -- graceful quiesce/drain lifecycle (nat_quiesce.cpp) --
        lib.nat_server_quiesce.argtypes = [ctypes.c_int]
        lib.nat_server_quiesce.restype = ctypes.c_int
        lib.nat_server_draining.restype = ctypes.c_int
        # -- deterministic fault injection (nat_fault.cpp) --
        lib.nat_fault_configure.argtypes = [ctypes.c_char_p]
        lib.nat_fault_configure.restype = ctypes.c_int
        lib.nat_fault_enabled.restype = ctypes.c_int
        lib.nat_fault_injected.restype = ctypes.c_uint64
        # -- refcount-contract runtime twin (nat_refguard.cpp) --
        lib.nat_refguard_enabled.restype = ctypes.c_int
        lib.nat_refguard_ops.restype = ctypes.c_uint64
        lib.nat_refguard_selftest.argtypes = [ctypes.c_int]
        lib.nat_refguard_selftest.restype = ctypes.c_int
        # -- client circuit breaker + retry budget (nat_channel.cpp) --
        lib.nat_channel_set_breaker.argtypes = [ctypes.c_void_p,
                                                ctypes.c_int]
        lib.nat_channel_set_breaker.restype = ctypes.c_int
        lib.nat_channel_breaker_state.argtypes = [ctypes.c_void_p]
        lib.nat_channel_breaker_state.restype = ctypes.c_int
        lib.nat_channel_retry_budget.argtypes = [ctypes.c_void_p]
        lib.nat_channel_retry_budget.restype = ctypes.c_int
        lib.nat_take_request_batch.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_int, ctypes.c_int]
        lib.nat_take_request_batch.restype = ctypes.c_int
        lib.nat_http_client_bench.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_double, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_size_t, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64)]
        lib.nat_http_client_bench.restype = ctypes.c_double
        lib.nat_grpc_client_bench.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_double, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_size_t, ctypes.POINTER(ctypes.c_uint64)]
        lib.nat_grpc_client_bench.restype = ctypes.c_double
        # -- native client lanes (HTTP/h2 through the framework client) --
        lib.nat_channel_open_proto.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_char_p]
        lib.nat_channel_open_proto.restype = ctypes.c_void_p
        lib.nat_http_call.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_int, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_size_t)]
        lib.nat_http_call.restype = ctypes.c_int
        lib.nat_grpc_call.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_size_t, ctypes.c_int, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_size_t),
            ctypes.POINTER(ctypes.c_char_p)]
        lib.nat_grpc_call.restype = ctypes.c_int
        lib.nat_grpc_channel_bench.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_double, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_size_t, ctypes.POINTER(ctypes.c_uint64)]
        lib.nat_grpc_channel_bench.restype = ctypes.c_double
        lib.nat_http_channel_bench.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_double, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_size_t, ctypes.POINTER(ctypes.c_uint64)]
        lib.nat_http_channel_bench.restype = ctypes.c_double
        # -- native Redis lane --
        lib.nat_rpc_server_redis.argtypes = [ctypes.c_int]
        lib.nat_rpc_server_redis.restype = ctypes.c_int
        lib.nat_redis_respond.argtypes = [
            ctypes.c_uint64, ctypes.c_int64, ctypes.c_char_p,
            ctypes.c_size_t]
        lib.nat_redis_respond.restype = ctypes.c_int
        lib.nat_redis_client_bench.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_double, ctypes.POINTER(ctypes.c_uint64)]
        lib.nat_redis_client_bench.restype = ctypes.c_double
        # -- shm usercode worker lane --
        lib.nat_shm_lane_create.argtypes = [ctypes.c_size_t]
        lib.nat_shm_lane_create.restype = ctypes.c_int
        lib.nat_shm_lane_name.restype = ctypes.c_char_p
        lib.nat_shm_lane_enable.argtypes = [ctypes.c_int]
        lib.nat_shm_lane_enable.restype = ctypes.c_int
        lib.nat_shm_seg_validate.argtypes = [ctypes.c_void_p,
                                             ctypes.c_size_t]
        lib.nat_shm_seg_validate.restype = ctypes.c_int
        lib.nat_shm_worker_attach.argtypes = [ctypes.c_char_p]
        lib.nat_shm_worker_attach.restype = ctypes.c_int
        lib.nat_shm_take_request.argtypes = [ctypes.c_int]
        lib.nat_shm_take_request.restype = ctypes.c_void_p
        lib.nat_shm_respond.argtypes = [
            ctypes.c_int, ctypes.c_uint64, ctypes.c_int64, ctypes.c_char_p,
            ctypes.c_size_t, ctypes.c_int32, ctypes.c_char_p, ctypes.c_int]
        lib.nat_shm_respond.restype = ctypes.c_int
        lib.nat_shm_lane_set_timeout_ms.argtypes = [ctypes.c_int]
        lib.nat_shm_lane_set_timeout_ms.restype = ctypes.c_int
        lib.nat_shm_lane_workers.restype = ctypes.c_int
        lib.nat_shm_lane_max_workers.restype = ctypes.c_int
        lib.nat_shm_lane_recover_probe.restype = ctypes.c_int
        lib.nat_shm_push_tensor.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint64]
        lib.nat_shm_push_tensor.restype = ctypes.c_int
        # -- tensor fabric (producer slots + receiver leases, ISSUE 15) --
        lib.nat_shm_producer_attach.argtypes = [ctypes.c_char_p]
        lib.nat_shm_producer_attach.restype = ctypes.c_int
        lib.nat_shm_fabric_push.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint64]
        lib.nat_shm_fabric_push.restype = ctypes.c_int
        lib.nat_shm_fabric_take.argtypes = [ctypes.c_int]
        lib.nat_shm_fabric_take.restype = ctypes.c_void_p
        lib.nat_shm_push_bench.argtypes = [
            ctypes.c_size_t, ctypes.c_double,
            ctypes.POINTER(ctypes.c_uint64)]
        lib.nat_shm_push_bench.restype = ctypes.c_double
        lib.nat_shm_worker_drain_bench.argtypes = [ctypes.c_int]
        lib.nat_shm_worker_drain_bench.restype = ctypes.c_uint64
        # -- native observability (nat_stats.cpp: per-thread stat cells,
        #    log2 latency histograms, rpcz span ring) --
        lib.nat_stats_counter_count.restype = ctypes.c_int
        lib.nat_stats_counter_name.argtypes = [ctypes.c_int]
        lib.nat_stats_counter_name.restype = ctypes.c_char_p
        lib.nat_stats_counters.argtypes = [
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int]
        lib.nat_stats_counters.restype = ctypes.c_int
        lib.nat_stats_counter_bump.argtypes = [ctypes.c_char_p,
                                               ctypes.c_uint64]
        lib.nat_stats_counter_bump.restype = ctypes.c_int
        lib.nat_stats_lane_count.restype = ctypes.c_int
        lib.nat_stats_lane_name.argtypes = [ctypes.c_int]
        lib.nat_stats_lane_name.restype = ctypes.c_char_p
        lib.nat_stats_hist_nbuckets.restype = ctypes.c_int
        lib.nat_stats_hist.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int]
        lib.nat_stats_hist.restype = ctypes.c_int
        lib.nat_stats_hist_quantile.argtypes = [ctypes.c_int,
                                                ctypes.c_double]
        lib.nat_stats_hist_quantile.restype = ctypes.c_double
        lib.nat_stats_enable_spans.argtypes = [ctypes.c_int]
        lib.nat_stats_enable_spans.restype = None
        lib.nat_stats_drain_spans.argtypes = [ctypes.POINTER(NatSpanRec),
                                              ctypes.c_int]
        lib.nat_stats_drain_spans.restype = ctypes.c_int
        lib.nat_stats_reset.restype = None
        lib.nat_stats_now_ns.restype = ctypes.c_uint64
        # -- native observatory: per-method stats, /connections rows,
        #    lock-contention profiler (ISSUE 9) --
        lib.nat_method_stats.argtypes = [ctypes.POINTER(NatMethodStatRow),
                                         ctypes.c_int]
        lib.nat_method_stats.restype = ctypes.c_int
        lib.nat_method_quantile.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                            ctypes.c_double]
        lib.nat_method_quantile.restype = ctypes.c_double
        # -- fleet observatory: raw mergeable buckets + the wire snapshot
        #    behind builtin.stats (ISSUE 16) --
        lib.nat_method_hist.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                        ctypes.POINTER(ctypes.c_uint64),
                                        ctypes.c_int]
        lib.nat_method_hist.restype = ctypes.c_int
        lib.nat_stats_snapshot.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_size_t)]
        lib.nat_stats_snapshot.restype = ctypes.c_int
        lib.nat_conn_snapshot.argtypes = [ctypes.POINTER(NatConnRow),
                                          ctypes.c_int]
        lib.nat_conn_snapshot.restype = ctypes.c_int
        # -- native memory observatory (nat_res.cpp, ISSUE 14) --
        lib.nat_res_count.restype = ctypes.c_int
        lib.nat_res_name.argtypes = [ctypes.c_int]
        lib.nat_res_name.restype = ctypes.c_char_p  # static string
        lib.nat_res_stats.argtypes = [ctypes.POINTER(NatResRow),
                                      ctypes.c_int]
        lib.nat_res_stats.restype = ctypes.c_int
        lib.nat_res_accounted_bytes.restype = ctypes.c_uint64
        lib.nat_res_prof_start.argtypes = [ctypes.c_int, ctypes.c_uint64]
        lib.nat_res_prof_start.restype = ctypes.c_int
        lib.nat_res_prof_stop.restype = ctypes.c_int
        lib.nat_res_prof_running.restype = ctypes.c_int
        lib.nat_res_prof_samples.restype = ctypes.c_uint64
        lib.nat_res_prof_reset.restype = None
        lib.nat_res_heap_report.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_size_t)]
        lib.nat_res_heap_report.restype = ctypes.c_int
        lib.nat_res_growth_baseline.restype = ctypes.c_int
        lib.nat_res_growth_report.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_size_t)]
        lib.nat_res_growth_report.restype = ctypes.c_int
        lib.nat_res_selftest.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.nat_res_selftest.restype = ctypes.c_int
        lib.nat_mu_prof_start.argtypes = [ctypes.c_int, ctypes.c_int,
                                          ctypes.c_uint64]
        lib.nat_mu_prof_start.restype = ctypes.c_int
        lib.nat_mu_prof_stop.restype = ctypes.c_int
        lib.nat_mu_prof_running.restype = ctypes.c_int
        lib.nat_mu_prof_samples.restype = ctypes.c_uint64
        lib.nat_mu_prof_reset.restype = None
        lib.nat_mu_prof_reset_samples.restype = None
        lib.nat_mu_prof_report.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_size_t)]
        lib.nat_mu_prof_report.restype = ctypes.c_int
        lib.nat_mu_rank_stats.argtypes = [ctypes.POINTER(NatLockRankRow),
                                          ctypes.c_int]
        lib.nat_mu_rank_stats.restype = ctypes.c_int
        lib.nat_mu_rank_name.argtypes = [ctypes.c_int]
        lib.nat_mu_rank_name.restype = ctypes.c_char_p  # static string
        lib.nat_mu_contend_selftest.argtypes = [ctypes.c_int, ctypes.c_int,
                                                ctypes.c_int]
        lib.nat_mu_contend_selftest.restype = ctypes.c_uint64
        # -- traffic flight recorder (nat_dump.cpp / nat_replay.cpp) --
        lib.nat_dump_start.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.c_int, ctypes.c_uint64]
        lib.nat_dump_start.restype = ctypes.c_int
        lib.nat_dump_stop.restype = ctypes.c_int
        lib.nat_dump_running.restype = ctypes.c_int
        lib.nat_dump_status.argtypes = [ctypes.POINTER(NatDumpStatusRec)]
        lib.nat_dump_status.restype = ctypes.c_int
        lib.nat_replay_run.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_double, ctypes.c_double, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(NatReplayResult)]
        lib.nat_replay_run.restype = ctypes.c_int
        # -- native fan-out cluster (nat_cluster.cpp / nat_lb.cpp) --
        lib.nat_rpc_server_add_port.argtypes = [ctypes.c_char_p,
                                                ctypes.c_int]
        lib.nat_rpc_server_add_port.restype = ctypes.c_int
        lib.nat_rpc_server_remove_port.argtypes = [ctypes.c_int]
        lib.nat_rpc_server_remove_port.restype = ctypes.c_int
        lib.nat_cluster_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int]
        lib.nat_cluster_create.restype = ctypes.c_void_p
        lib.nat_cluster_close.argtypes = [ctypes.c_void_p]
        lib.nat_cluster_close.restype = None
        lib.nat_cluster_update.argtypes = [ctypes.c_void_p,
                                           ctypes.c_char_p]
        lib.nat_cluster_update.restype = ctypes.c_int
        lib.nat_cluster_backend_count.argtypes = [ctypes.c_void_p]
        lib.nat_cluster_backend_count.restype = ctypes.c_int
        lib.nat_cluster_select_debug.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_size_t]
        lib.nat_cluster_select_debug.restype = ctypes.c_int
        lib.nat_cluster_call.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int, ctypes.c_int,
            ctypes.c_uint64, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_size_t),
            ctypes.POINTER(ctypes.c_char_p)]
        lib.nat_cluster_call.restype = ctypes.c_int
        lib.nat_cluster_parallel_call.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_size_t),
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int)]
        lib.nat_cluster_parallel_call.restype = ctypes.c_int
        lib.nat_cluster_partition_call.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_size_t),
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int)]
        lib.nat_cluster_partition_call.restype = ctypes.c_int
        lib.nat_cluster_dynpart_call.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_size_t),
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int)]
        lib.nat_cluster_dynpart_call.restype = ctypes.c_int
        lib.nat_cluster_dynpart_debug.argtypes = [
            ctypes.c_void_p, ctypes.c_double,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
            ctypes.c_int, ctypes.POINTER(ctypes.c_int)]
        lib.nat_cluster_dynpart_debug.restype = ctypes.c_int
        lib.nat_cluster_stats.argtypes = [ctypes.c_void_p,
                                          ctypes.POINTER(NatClusterRow),
                                          ctypes.c_int]
        lib.nat_cluster_stats.restype = ctypes.c_int
        lib.nat_cluster_bench.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_int, ctypes.c_int, ctypes.c_double, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_double)]
        lib.nat_cluster_bench.restype = ctypes.c_double
        # -- trace context + in-process sampling profiler (nat_prof.cpp) --
        lib.nat_trace_set.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
        lib.nat_trace_set.restype = None
        lib.nat_prof_start.argtypes = [ctypes.c_int]
        lib.nat_prof_start.restype = ctypes.c_int
        lib.nat_prof_stop.restype = ctypes.c_int
        lib.nat_prof_running.restype = ctypes.c_int
        lib.nat_prof_samples.restype = ctypes.c_uint64
        lib.nat_prof_reset.restype = None
        lib.nat_prof_report.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_size_t)]
        lib.nat_prof_report.restype = ctypes.c_int
        # -- parser fuzz seams (nat_fuzz_entry.cpp / nat_replay.cpp) --
        lib.nat_fuzz_rpc_meta.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.nat_fuzz_rpc_meta.restype = ctypes.c_int
        lib.nat_fuzz_http.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.nat_fuzz_http.restype = ctypes.c_int
        lib.nat_fuzz_h2.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.nat_fuzz_h2.restype = ctypes.c_int
        lib.nat_fuzz_redis.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.nat_fuzz_redis.restype = ctypes.c_int
        lib.nat_fuzz_hpack.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.nat_fuzz_hpack.restype = ctypes.c_int
        lib.nat_fuzz_recordio.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.nat_fuzz_recordio.restype = ctypes.c_int
        lib.nat_fuzz_shm_seg.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.nat_fuzz_shm_seg.restype = ctypes.c_int
        _lib = lib
        return lib


def available() -> bool:
    try:
        load()
        return True
    except NativeUnavailable:
        return False


# -- convenience wrappers --------------------------------------------------

def sched_start(nworkers: int = 4) -> int:
    return load().nat_sched_start(nworkers)


def sched_stop():
    load().nat_sched_stop()


def bench_spawn_join(nfibers: int, rounds: int) -> int:
    return load().nat_bench_spawn_join(nfibers, rounds)


def bench_ping_pong(rounds: int = 10000) -> float:
    """Returns ns per fiber ping-pong round trip."""
    return load().nat_bench_ping_pong(rounds)


def echo_server_start(ip: str = "127.0.0.1", port: int = 0) -> int:
    """Starts the native echo server; returns the bound port."""
    rc = load().nat_echo_server_start(ip.encode(), port)
    if rc <= 0:
        raise RuntimeError("native echo server failed to start")
    return rc


def echo_server_stop():
    load().nat_echo_server_stop()


def echo_server_requests() -> int:
    return load().nat_echo_server_requests()


def echo_client_bench(ip: str, port: int, nconn: int = 2,
                      seconds: float = 2.0, payload: int = 16,
                      pipeline: int = 32) -> dict:
    out_requests = ctypes.c_uint64(0)
    qps = load().nat_echo_client_bench(ip.encode(), port, nconn, seconds,
                                       payload, pipeline,
                                       ctypes.byref(out_requests))
    return {"qps": qps, "requests": out_requests.value}


# -- native RPC runtime (framework path: Socket/dispatcher/messenger on
#    fibers + IOBuf; see native/src/nat_rpc.cpp) -----------------------------

def rpc_server_start(ip: str = "127.0.0.1", port: int = 0,
                     nworkers: int = 0, native_echo: bool = False) -> int:
    """Start the native RPC server; returns the bound port."""
    rc = load().nat_rpc_server_start(ip.encode(), port, nworkers,
                                     1 if native_echo else 0)
    if rc <= 0:
        raise RuntimeError("native rpc server failed to start")
    return rc


def use_io_uring(enable: bool = True) -> int:
    """Toggle the RingListener datapath (the fork's -use_io_uring). Returns
    1 = ring live, 0 = kernel refused (epoll stays), -1 = runtime error."""
    return load().nat_rpc_use_io_uring(1 if enable else 0)


def ring_counters():
    """(recv_completions, send_completions) of the io_uring datapath."""
    recv = ctypes.c_uint64()
    send = ctypes.c_uint64()
    load().nat_ring_counters(ctypes.byref(recv), ctypes.byref(send))
    return recv.value, send.value


def dispatcher_count() -> int:
    """Number of epoll/io_uring dispatcher loops in the pool (the
    event_dispatcher_num analog; default min(cores, 4))."""
    return load().nat_disp_count()


def dispatcher_stats() -> list:
    """Per-dispatcher rows: [{'sockets': owned-now, 'wakeups': rounds
    that delivered events, 'sqpoll': -1 no ring / 0 / 1}, ...]."""
    lib = load()
    rows = []
    for i in range(lib.nat_disp_count()):
        sockets = ctypes.c_uint64()
        wakeups = ctypes.c_uint64()
        sqpoll = ctypes.c_int()
        if lib.nat_disp_stat(i, ctypes.byref(sockets), ctypes.byref(wakeups),
                             ctypes.byref(sqpoll)) != 0:
            break
        rows.append({"sockets": sockets.value, "wakeups": wakeups.value,
                     "sqpoll": sqpoll.value})
    return rows


def rpc_server_stop():
    load().nat_rpc_server_stop()


def rpc_server_requests() -> int:
    return load().nat_rpc_server_requests()


def take_request(timeout_ms: int = 100):
    """Python lane: pull one item handed off by the native runtime.
    Returns (handle, kind, meta_bytes, payload, attachment, sock_id, seq,
    f0, f1, aux) or None. kind 0 = parsed tpu_std request; 1 = raw
    protocol bytes (seq orders chunks per socket); 2 = connection closed;
    3 = native-parsed HTTP request (f0 = verb, f1 = uri, meta_bytes =
    lowercased "key: value\\n" header lines, payload = body, seq = the
    connection-ordered response token for http_respond); 4 =
    native-parsed gRPC request (f1 = :path, payload = gRPC-framed body,
    seq = h2 stream id); 5 = streaming frame (aux = dest stream id,
    payload = frame body, seq orders frames per socket). aux is 0 except
    for kind 5."""
    lib = load()
    h = lib.nat_take_request(timeout_ms)
    if not h:
        return None
    kind = lib.nat_req_kind(h)
    def field(which):
        n = ctypes.c_size_t(0)
        p = lib.nat_req_field(h, which, ctypes.byref(n))
        return ctypes.string_at(p, n.value) if p and n.value else b""
    if kind in (3, 4):  # native-parsed HTTP / gRPC-over-h2
        return (h, kind, field(4), field(2), b"",
                lib.nat_req_sock_id(h), lib.nat_req_cid(h),
                field(0), field(1), 0)
    if kind == 5:  # native-cut streaming frame: aux = dest stream id,
        # f0 = frame type (same contract as take_requests), cid = order
        return (h, kind, b"", field(2), b"", lib.nat_req_sock_id(h),
                lib.nat_req_cid(h), lib.nat_req_compress(h), b"",
                lib.nat_req_aux(h))
    return (h, kind, field(4), field(2), field(3),
            lib.nat_req_sock_id(h), lib.nat_req_cid(h), b"", b"", 0)


def take_requests(max_items: int = 16, timeout_ms: int = 100):
    """Batch take: one condvar round + one FFI crossing per burst. Returns
    a list of the same tuples take_request yields (possibly empty)."""
    lib = load()
    arr = (ctypes.c_void_p * max_items)()
    n = lib.nat_take_request_batch(arr, max_items, timeout_ms)
    out = []
    for i in range(n):
        h = arr[i]
        kind = lib.nat_req_kind(h)

        def field(which, h=h):
            ln = ctypes.c_size_t(0)
            p = lib.nat_req_field(h, which, ctypes.byref(ln))
            return ctypes.string_at(p, ln.value) if p and ln.value else b""

        if kind in (3, 4):
            out.append((h, kind, field(4), field(2), b"",
                        lib.nat_req_sock_id(h), lib.nat_req_cid(h),
                        field(0), field(1), 0))
        elif kind == 5:
            # frame type rides in the f0 slot (the zero-copy path below
            # hands the handle to a finalizer, so it can't be queried
            # at dispatch time)
            ftype = lib.nat_req_compress(h)
            ln = ctypes.c_size_t(0)
            p = lib.nat_req_field(h, 2, ctypes.byref(ln))
            if p and ln.value >= 65536:
                # big stream payload: wrap the native buffer read-only
                # with ZERO copy; the request handle is freed when the
                # last view of the buffer is garbage-collected, so a
                # handler retaining the message stays safe. The handle
                # slot in the tuple is None: ownership moved here.
                cbuf = (ctypes.c_char * ln.value).from_address(p)
                weakref.finalize(cbuf, lib.nat_req_free, h)
                payload = memoryview(cbuf).toreadonly()
                out.append((None, kind, b"", payload, b"",
                            lib.nat_req_sock_id(h), lib.nat_req_cid(h),
                            ftype, b"", lib.nat_req_aux(h)))
                continue
            out.append((h, kind, b"", field(2), b"",
                        lib.nat_req_sock_id(h), lib.nat_req_cid(h),
                        ftype, b"", lib.nat_req_aux(h)))
        else:
            out.append((h, kind, field(4), field(2), field(3),
                        lib.nat_req_sock_id(h), lib.nat_req_cid(h),
                        b"", b"", 0))
    return out


def rpc_server_enable_raw_fallback(enable: bool = True) -> int:
    """Multi-protocol native port: unknown framing goes to the Python
    protocol stack as ordered raw chunks instead of failing the socket."""
    return load().nat_rpc_server_enable_raw_fallback(1 if enable else 0)


def rpc_set_dispatchers(n: int) -> int:
    """-event_dispatcher_num analog; call before the runtime starts."""
    return load().nat_rpc_set_dispatchers(n)


def req_free(handle):
    load().nat_req_free(handle)


def sock_write(sock_id: int, data: bytes) -> int:
    return load().nat_sock_write(sock_id, data, len(data))


def sock_set_failed(sock_id: int) -> int:
    return load().nat_sock_set_failed(sock_id)


def sock_graceful_close(sock_id: int) -> int:
    """Fail the socket once queued writes drain (FIN after the last
    response byte) — Connection: close semantics."""
    return load().nat_sock_graceful_close(sock_id)


def rpc_server_ssl(certfile: str, keyfile: str) -> int:
    """TLS on the native port (Socket-level SSLState role): connections
    whose first record is a TLS handshake get a native SSL session; the
    same port keeps answering plaintext. 0 = ok, -2 = libssl missing."""
    return load().nat_rpc_server_ssl(certfile.encode(), keyfile.encode())


def rpc_server_native_http(enable: bool = True) -> int:
    """Native HTTP/1.1 lane: HTTP-shaped connections parse in the native
    cut loop and surface as kind-3 py-lane requests."""
    return load().nat_rpc_server_native_http(1 if enable else 0)


def grpc_respond(sock_id: int, stream_id: int, payload: bytes = b"",
                 grpc_status: int = 0, grpc_message: str = "") -> int:
    """Answer a kind-4 request: unary gRPC response framed natively
    (HEADERS + DATA + grpc-status trailers) onto the h2 session."""
    return load().nat_grpc_respond(sock_id, stream_id, payload,
                                   len(payload), grpc_status,
                                   grpc_message.encode() or None)


def http_respond(sock_id: int, seq: int, data: bytes,
                 close_after: bool = False) -> int:
    """Answer a kind-3 request: data is the complete serialized HTTP
    response; ordering across pipelined requests is enforced natively."""
    return load().nat_http_respond(sock_id, seq, data, len(data),
                                   1 if close_after else 0)


def fault_configure(spec: str = "") -> int:
    """Install (or clear, with "") the deterministic fault table — see
    native/src/nat_fault.h for the grammar. 0 = ok, -1 = parse error.
    Same seed + same per-site op sequence = same fault schedule. The
    NAT_FAULT env var arms the table at library load (workers inherit
    it); restore the env spec with fault_configure(os.environ.get(
    "NAT_FAULT", ""))."""
    return load().nat_fault_configure(spec.encode() or None)


def fault_enabled() -> bool:
    return bool(load().nat_fault_enabled())


def fault_injected() -> int:
    """Total faults injected in THIS process since load (also exported
    as the nat_faults_injected counter)."""
    return load().nat_fault_injected()


def refguard_enabled() -> bool:
    """True when the loaded .so was built with -DNAT_REFGUARD (the
    NAT_REF_* ownership ledger of native/src/nat_refown.h is live —
    `make -C native refguard` + the BRPC_TPU_NATIVE_SO override)."""
    return bool(load().nat_refguard_enabled())


def refguard_ops() -> int:
    """Total refguard ledger operations recorded (0 in normal builds)."""
    return load().nat_refguard_ops()


def refguard_selftest(scenario: int = 0) -> int:
    """Scenario 0: balanced acquire/transfer/borrow/release/dead round
    (returns 0 in every build). Scenario 1: deliberate double release —
    ABORTS the process under refguard, returns -1 otherwise."""
    return load().nat_refguard_selftest(scenario)


def rpc_server_limiter(spec: str = "") -> int:
    """Native server admission control: "" / "none" = off, "auto" =
    gradient limiter (concurrency_limiter.py's AutoLimiter ported to the
    C++ lane), "constant:N" / "N" = fixed limit. Rejections answer
    ELIMIT(2004) / HTTP 503 / gRPC RESOURCE_EXHAUSTED on the wire."""
    return load().nat_rpc_server_limiter(spec.encode() or None)


def rpc_server_queue_deadline_ms(ms: int) -> int:
    """Queue-deadline drop: py-lane requests older than `ms` when a
    worker would take them are rejected with ELIMIT before dispatch
    (bounded accepted-request tail latency). <= 0 disables."""
    return load().nat_rpc_server_queue_deadline_ms(ms)


def rpc_server_inflight() -> int:
    """Currently admitted in-flight work requests (observability)."""
    return load().nat_rpc_server_inflight()


def rpc_server_limit() -> int:
    """Effective concurrency limit (auto: the computed one); 0 = off."""
    return load().nat_rpc_server_limit()


def server_quiesce(timeout_ms: int = 5000) -> int:
    """Graceful quiesce of the running native server (the Server::Stop
    (timeout)/Join lifecycle): stop accepting, lame-duck every live
    connection per protocol (h2 GOAWAY, HTTP Connection: close, tpu_std
    SHUTDOWN meta bit, RESP close-after-reply), drain admitted work
    (incl. shm-worker in-flight) under the deadline while rejecting new
    arrivals with ELIMIT/503/RESOURCE_EXHAUSTED, then close sockets only
    once their write stacks are idle. Returns 0 (drained clean), 1
    (deadline expired — stragglers were 503'd), -1 (no running server).
    Call rpc_server_stop() afterwards."""
    return load().nat_server_quiesce(timeout_ms)


def server_draining() -> bool:
    """True from quiesce start until the server stops/restarts."""
    return bool(load().nat_server_draining())


def channel_set_breaker(handle, enable: bool = True) -> int:
    """Per-channel circuit breaker (two-EMA-window isolation mirroring
    rpc/circuit_breaker.py): errored completions trip it, the socket is
    failed, calls fail fast through the isolation window, and the
    health-check chain revives + resets it once the peer answers."""
    return load().nat_channel_set_breaker(handle, 1 if enable else 0)


def channel_breaker_state(handle) -> int:
    """0 = closed (healthy), 1 = broken (isolated/awaiting revival)."""
    return load().nat_channel_breaker_state(handle)


def channel_retry_budget(handle) -> int:
    """Remaining channel retry budget in deci-tokens (a retry costs 10;
    every success replenishes 1, capped)."""
    return load().nat_channel_retry_budget(handle)


def rpc_server_redis(mode: int = 1) -> int:
    """Native Redis lane: 1 = RESP parsed natively, commands to the
    Python RedisService (kind-6); 2 = + native in-memory store for the
    GET/SET family."""
    return load().nat_rpc_server_redis(mode)


def redis_respond(sock_id: int, seq: int, data: bytes) -> int:
    """Answer a kind-6 request: data is the complete RESP reply;
    ordering across pipelined commands is enforced natively."""
    return load().nat_redis_respond(sock_id, seq, data, len(data))


def redis_client_bench(ip: str, port: int, nconn: int = 2,
                       pipeline: int = 64, seconds: float = 2.0) -> dict:
    """Raw RESP pipelined GET load against the native redis lane."""
    out_requests = ctypes.c_uint64(0)
    qps = load().nat_redis_client_bench(ip.encode(), port, nconn, pipeline,
                                        seconds,
                                        ctypes.byref(out_requests))
    return {"qps": qps, "requests": out_requests.value}


def grpc_client_bench(ip: str, port: int, nconn: int = 4,
                      window: int = 64, seconds: float = 2.0,
                      path: str = "/EchoService/Echo",
                      payload: bytes = b"x" * 16) -> dict:
    """gRPC-over-h2 bench client (minimal native h2 client, `window`
    concurrent unary streams per connection)."""
    out_requests = ctypes.c_uint64(0)
    qps = load().nat_grpc_client_bench(ip.encode(), port, nconn, window,
                                       seconds, path.encode(), payload,
                                       len(payload),
                                       ctypes.byref(out_requests))
    return {"qps": qps, "requests": out_requests.value}


def http_client_bench(ip: str, port: int, nconn: int = 4,
                      pipeline: int = 32, seconds: float = 2.0,
                      path: str = "/echo", post_body: bytes = b"",
                      content_type: str = "application/octet-stream"
                      ) -> dict:
    """HTTP bench client (blocking sockets, pipelined keep-alive).
    Empty post_body = GET, else POST with that body."""
    if isinstance(post_body, int):  # tolerate the byte-count shorthand
        post_body = b"x" * post_body
    out_requests = ctypes.c_uint64(0)
    qps = load().nat_http_client_bench(ip.encode(), port, nconn, pipeline,
                                       seconds, path.encode(), post_body,
                                       len(post_body),
                                       content_type.encode(),
                                       ctypes.byref(out_requests))
    return {"qps": qps, "requests": out_requests.value}


def respond(handle, error_code: int = 0, error_text: str = "",
            payload: bytes = b"", attachment: bytes = b"") -> int:
    """Python lane: answer a request taken with take_request."""
    return load().nat_respond(handle, error_code,
                              error_text.encode() or None,
                              payload, len(payload),
                              attachment, len(attachment))


def channel_open(ip: str, port: int, batch_writes: bool = False,
                 connect_timeout_ms: int = 0, health_check_ms: int = 0):
    """Open a native client channel. connect_timeout_ms bounds the dial
    (0 = 10s guard); health_check_ms > 0 revives a failed connection in
    the background, and any call after failure re-dials on demand."""
    h = load().nat_channel_open(ip.encode(), port, 0,
                                1 if batch_writes else 0,
                                connect_timeout_ms, health_check_ms)
    if not h:
        raise RuntimeError("native channel connect failed")
    return h


def channel_close(handle):
    load().nat_channel_close(handle)


def channel_open_http(ip: str, port: int, authority: str = "",
                      connect_timeout_ms: int = 0,
                      health_check_ms: int = 0):
    """Open a native HTTP/1.1 client channel (the client half of the
    native HTTP lane: request framing, pipelined response correlation,
    chunked decode — all in C++)."""
    h = load().nat_channel_open_proto(
        ip.encode(), port, 0, 0, connect_timeout_ms, health_check_ms, 1,
        authority.encode() or None)
    if not h:
        raise RuntimeError("native http channel connect failed")
    return h


def channel_open_grpc(ip: str, port: int, authority: str = "",
                      connect_timeout_ms: int = 0,
                      health_check_ms: int = 0):
    """Open a native h2/gRPC client channel (preface + SETTINGS + HPACK
    + flow-controlled unary streams in C++)."""
    h = load().nat_channel_open_proto(
        ip.encode(), port, 0, 0, connect_timeout_ms, health_check_ms, 2,
        authority.encode() or None)
    if not h:
        raise RuntimeError("native grpc channel connect failed")
    return h


def http_call(handle, verb: str, path: str, body: bytes = b"",
              headers: str = "", timeout_ms: int = 0):
    """Synchronous HTTP call through the native client lane. Returns
    (status, body_bytes); raises on transport errors. `headers` is raw
    "Name: value\\r\\n" lines appended to the request head."""
    lib = load()
    status = ctypes.c_int(0)
    resp = ctypes.c_char_p()
    rlen = ctypes.c_size_t(0)
    rc = lib.nat_http_call(handle, verb.encode(), path.encode(),
                           headers.encode() or None, body, len(body),
                           timeout_ms, ctypes.byref(status),
                           ctypes.byref(resp), ctypes.byref(rlen))
    if rc != 0:
        raise ConnectionError(f"native http call failed: rc={rc}")
    # pointer truthiness only: .value would strlen an un-terminated
    # malloc'd buffer (out-of-bounds read)
    out = b""
    if resp:
        out = ctypes.string_at(resp, rlen.value)
        lib.nat_buf_free(resp)
    return status.value, out


def grpc_call(handle, path: str, payload: bytes = b"",
              timeout_ms: int = 0):
    """Synchronous gRPC unary call through the native h2 client lane.
    Returns (grpc_status, response_bytes, message); raises on transport
    errors."""
    lib = load()
    st = ctypes.c_int(-1)
    resp = ctypes.c_char_p()
    rlen = ctypes.c_size_t(0)
    err = ctypes.c_char_p()
    rc = lib.nat_grpc_call(handle, path.encode(), payload, len(payload),
                           timeout_ms, ctypes.byref(st), ctypes.byref(resp),
                           ctypes.byref(rlen), ctypes.byref(err))
    # err IS NUL-terminated (malloc'd c_str copy); resp is NOT — only
    # pointer truthiness + string_at(len) may touch it
    message = ""
    if err:
        message = ctypes.string_at(err).decode(errors="replace")
        lib.nat_buf_free(err)
    if rc != 0:
        raise ConnectionError(
            f"native grpc call failed: {message or f'rc={rc}'}")
    out = b""
    if resp:
        out = ctypes.string_at(resp, rlen.value)
        lib.nat_buf_free(resp)
    return st.value, out, message


def grpc_channel_bench(ip: str, port: int, nconn: int = 4,
                       window: int = 64, seconds: float = 2.0,
                       path: str = "/EchoService/Echo",
                       payload: bytes = b"x" * 16) -> dict:
    """gRPC through the REAL native client lane (NatChannel + h2 session),
    `window` async unary calls in flight per connection."""
    out_requests = ctypes.c_uint64(0)
    qps = load().nat_grpc_channel_bench(ip.encode(), port, nconn, window,
                                        seconds, path.encode(), payload,
                                        len(payload),
                                        ctypes.byref(out_requests))
    return {"qps": qps, "requests": out_requests.value}


def http_channel_bench(ip: str, port: int, nconn: int = 4,
                       window: int = 64, seconds: float = 2.0,
                       path: str = "/echo", body: bytes = b"x" * 16) -> dict:
    """HTTP through the REAL native client lane (NatChannel + pipelined
    FIFO correlation), `window` async calls in flight per connection."""
    out_requests = ctypes.c_uint64(0)
    qps = load().nat_http_channel_bench(ip.encode(), port, nconn, window,
                                        seconds, path.encode(), body,
                                        len(body),
                                        ctypes.byref(out_requests))
    return {"qps": qps, "requests": out_requests.value}


ACALL_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_int32,
                            ctypes.POINTER(ctypes.c_char), ctypes.c_size_t)

_acall_live = {}  # id -> CFUNCTYPE thunk, alive until its done fires
_acall_live_lock = threading.Lock()


def channel_acall(handle, service: str, method: str, payload: bytes,
                  done, timeout_ms: int = 0) -> int:
    """Asynchronous call: done(error_code, response_bytes) runs on a
    framework FIBER (256KB stack) when the response arrives — keep it
    lightweight and non-blocking, exactly like a brpc done closure with
    usercode_in_pthread off; heavy work belongs on your own thread (hand
    off via a queue). Returns 0 when done WILL fire exactly once
    (possibly already, with an error code) — including failures detected
    before queueing, which also surface through done. The wrapper owns
    the callback thunk's lifetime."""
    holder = []

    def trampoline(_arg, code, resp, n):
        try:
            done(code, ctypes.string_at(resp, n) if n else b"")
        finally:
            with _acall_live_lock:
                _acall_live.pop(holder[0], None)

    cb = ACALL_CB(trampoline)
    holder.append(id(cb))
    with _acall_live_lock:
        _acall_live[id(cb)] = cb  # native side holds no GC-visible ref
    rc = load().nat_channel_acall(handle, service.encode(), method.encode(),
                                  payload, len(payload), timeout_ms, cb,
                                  None)
    if rc != 0:  # never queued: done will not fire
        with _acall_live_lock:
            _acall_live.pop(id(cb), None)
    return rc


def channel_call(handle, service: str, method: str,
                 payload: bytes = b"", timeout_ms: int = 0,
                 max_retry: int = 0, backup_ms: int = 0):
    """Synchronous call through the native client. timeout_ms > 0 arms a
    native deadline covering ALL attempts (ERPCTIMEDOUT on expiry);
    max_retry re-attempts failed-socket calls with on-demand re-dial;
    backup_ms > 0 re-sends the request if no response arrived in time
    (same correlation id — first response wins). Returns
    (error_code, response_bytes, error_text)."""
    lib = load()
    resp = ctypes.c_char_p()
    rlen = ctypes.c_size_t(0)
    err = ctypes.c_char_p()
    rc = lib.nat_channel_call_full(handle, service.encode(),
                                   method.encode(),
                                   payload, len(payload), timeout_ms,
                                   max_retry, backup_ms,
                                   ctypes.byref(resp),
                                   ctypes.byref(rlen), ctypes.byref(err))
    body = b""
    if resp:
        body = ctypes.string_at(resp, rlen.value)
        lib.nat_buf_free(resp)
    text = ""
    if err:
        text = ctypes.string_at(err).decode(errors="replace")
        lib.nat_buf_free(err)
    return rc, body, text


def rpc_client_bench(ip: str, port: int, nconn: int = 2,
                     fibers_per_conn: int = 32, seconds: float = 2.0,
                     payload: int = 16) -> dict:
    """Framework-path echo benchmark: sync calls from fibers through the
    full native client+server stack."""
    out_requests = ctypes.c_uint64(0)
    qps = load().nat_rpc_client_bench(ip.encode(), port, nconn,
                                      fibers_per_conn, seconds, payload,
                                      ctypes.byref(out_requests))
    return {"qps": qps, "requests": out_requests.value}


# -- shm descriptor-ring lane (nat_shm_lane.cpp) ----------------------------

def shm_push_bench(record_bytes: int, seconds: float = 1.0) -> dict:
    """Parent-side descriptor-ring throughput probe: push fixed-size
    records into the blob arena against live worker drains. Returns
    {"GBps": float, "records": int}. Requires a created lane with at
    least one attached worker (see nat_shm_worker_attach /
    shm_worker_drain_bench)."""
    out = ctypes.c_uint64(0)
    gbps = load().nat_shm_push_bench(record_bytes, seconds,
                                     ctypes.byref(out))
    return {"GBps": gbps, "records": out.value}


def shm_worker_drain_bench(idle_exit_ms: int = 1000) -> int:
    """Worker-side native drain loop: pops descriptors and releases their
    arena spans in place until the lane shuts down or `idle_exit_ms`
    passes with no data. Returns the number of records drained."""
    return load().nat_shm_worker_drain_bench(idle_exit_ms)


# -- tensor fabric: producer slots + receiver leases (ISSUE 15) -------------

class FabricLease:
    """One kind-8 tensor record leased from the descriptor-ring fabric.

    ``view()`` is a ZERO-COPY memoryview straight into the producer's
    shared blob arena: the span stays pinned (and accounted in the
    ``shm.span`` nat_res ledger row) until ``release()``, which may run
    OUT OF ORDER relative to other leases — the arena's released-bit +
    lazy reclaim is built for exactly that. Views must not be read after
    release (the producer reclaims the bytes). Dropping the last
    reference releases the lease too."""

    __slots__ = ("_h", "tag", "trace_id", "parent_span_id", "nbytes",
                 "_ptr", "__weakref__")

    def __init__(self, h: int):
        lib = load()
        self._h = h
        self.tag = lib.nat_req_aux(h)
        self.trace_id = lib.nat_req_sock_id(h)
        self.parent_span_id = lib.nat_req_cid(h) & ((1 << 63) - 1)
        n = ctypes.c_size_t(0)
        self._ptr = lib.nat_req_field(h, 2, ctypes.byref(n))
        self.nbytes = n.value

    def view(self) -> memoryview:
        if self._h is None:
            raise ValueError("fabric lease already released")
        if self.nbytes == 0 or not self._ptr:
            return memoryview(b"")
        return memoryview(
            (ctypes.c_char * self.nbytes).from_address(self._ptr))

    def tobytes(self) -> bytes:
        return bytes(self.view()) if self.nbytes else b""

    def release(self):
        h, self._h = self._h, None
        if h:
            load().nat_req_free(h)

    def __del__(self):
        try:
            self.release()
        except Exception:
            pass


def fabric_producer_attach(name) -> int:
    """Claim a PRODUCER slot on a peer's fabric segment (by shm name).
    This process becomes the sole producer of that slot's ring; a crash
    here surfaces as EOWNERDEAD on the receiver's recovery probe.
    Returns the slot index (>= 0) or -1."""
    if isinstance(name, str):
        name = name.encode()
    return load().nat_shm_producer_attach(name)


def fabric_push(data, tag: int) -> int:
    """Stage `data` ONCE into the attached fabric's shared blob arena and
    publish one kind-8 descriptor (the producer write of the zero-copy
    path). numpy arrays push straight from their buffer (no bytes()
    staging copy). Returns 0, or -1 on backpressure (ring/arena full)."""
    lib = load()
    try:
        import numpy as np

        if isinstance(data, np.ndarray):
            a = np.ascontiguousarray(data)
            ptr = ctypes.cast(ctypes.c_void_p(a.ctypes.data),
                              ctypes.c_char_p)
            return lib.nat_shm_fabric_push(ptr, a.nbytes, tag)
    except ImportError:
        pass
    if isinstance(data, memoryview):
        data = bytes(data)
    elif not isinstance(data, (bytes, bytearray)):
        data = bytes(data)
    return lib.nat_shm_fabric_push(bytes(data) if isinstance(
        data, bytearray) else data, len(data), tag)


def fabric_take(timeout_ms: int = 200):
    """Receiver side: take one pushed tensor record from any producer
    slot as a FabricLease (zero-copy arena view, out-of-order release),
    or None on timeout/shutdown."""
    h = load().nat_shm_fabric_take(timeout_ms)
    return FabricLease(h) if h else None


# -- native observability (nat_stats.cpp) -----------------------------------

def stats_counter_names() -> list:
    """Names of the native monotonic counters, index-aligned with the
    snapshot stats_counters() returns."""
    lib = load()
    n = lib.nat_stats_counter_count()
    return [lib.nat_stats_counter_name(i).decode() for i in range(n)]


def stats_counters() -> dict:
    """Combined snapshot {name: value} of every native counter (per-thread
    cells summed; gauges computed in place)."""
    lib = load()
    n = lib.nat_stats_counter_count()
    arr = (ctypes.c_uint64 * n)()
    got = lib.nat_stats_counters(arr, n)
    return {lib.nat_stats_counter_name(i).decode(): arr[i]
            for i in range(got)}


def stats_counter_bump(name: str, delta: int = 1) -> int:
    """Bump a native counter by NAME from Python-side controllers (the
    fleet autoscaler charges nat_autoscale_* here so its decisions land
    in the same /vars + /brpc_metrics surface as native events).
    Returns the counter id, or -1 for an unknown name."""
    return load().nat_stats_counter_bump(name.encode(), delta)


def stats_lane_names() -> list:
    """Latency-histogram lane names (echo/http/redis/grpc/client)."""
    lib = load()
    return [lib.nat_stats_lane_name(i).decode()
            for i in range(lib.nat_stats_lane_count())]


def stats_hist(lane: int) -> list:
    """Combined log2-bucket latency histogram of one lane (counts; bucket
    b covers [2^(b-1), 2^b) ns)."""
    lib = load()
    nb = lib.nat_stats_hist_nbuckets()
    arr = (ctypes.c_uint64 * nb)()
    got = lib.nat_stats_hist(lane, arr, nb)
    return list(arr[:got])


def stats_quantile(lane: int, q: float) -> float:
    """Latency quantile (ns) over a lane's combined histogram,
    interpolated inside the winning log2 bucket; 0.0 when empty."""
    return load().nat_stats_hist_quantile(lane, q)


def stats_enable_spans(every: int = 1):
    """0 = spans off; N = record one of every N native-handled calls into
    the bounded span ring (the bvar::Collector budget analog)."""
    load().nat_stats_enable_spans(every)


def stats_now_ns() -> int:
    """The span clock (CLOCK_MONOTONIC ns) — subtract from time.time() to
    map drained span timestamps onto wall time."""
    return load().nat_stats_now_ns()


def stats_drain_spans(max_spans: int = 4096) -> list:
    """Drain up to max_spans native span records as dicts (consuming
    them); timestamps are monotonic ns (see stats_now_ns)."""
    lib = load()
    arr = (NatSpanRec * max_spans)()
    n = lib.nat_stats_drain_spans(arr, max_spans)
    lanes = stats_lane_names()
    out = []
    for i in range(n):
        r = arr[i]
        lane_i = r.protocol
        out.append({
            "trace_id": r.trace_id,
            "span_id": r.span_id,
            "parent_span_id": r.parent_span_id,
            "sock_id": r.sock_id,
            "recv_ns": r.recv_ns,
            "parse_ns": r.parse_ns,
            "dispatch_ns": r.dispatch_ns,
            "write_ns": r.write_ns,
            "lane": lanes[lane_i] if 0 <= lane_i < len(lanes)
                    else str(lane_i),
            "error_code": r.error_code,
            "req_bytes": r.req_bytes,
            "resp_bytes": r.resp_bytes,
            "method": r.method.decode(errors="replace"),
        })
    return out


def stats_reset():
    """Zero every stat cell and forget undrained spans (test/bench
    hygiene only)."""
    load().nat_stats_reset()


# -- native observatory (ISSUE 9) -------------------------------------------

def method_stats() -> list:
    """Per-method stats rows of the native MethodStatus table: one dict
    per (lane, method) recorded at the native-handler call sites and the
    shm worker emit path — {'lane', 'method', 'count', 'errors',
    'concurrency', 'max_concurrency'}."""
    lib = load()
    lanes = stats_lane_names()
    arr = (NatMethodStatRow * 128)()
    n = lib.nat_method_stats(arr, 128)
    out = []
    for i in range(n):
        r = arr[i]
        out.append({
            "lane": lanes[r.lane] if 0 <= r.lane < len(lanes)
                    else str(r.lane),
            "method": r.method.decode(errors="replace"),
            "count": r.count,
            "errors": r.errors,
            # an in-flight end racing a stats_reset can briefly read -1
            "concurrency": max(0, r.concurrency),
            "max_concurrency": max(0, r.max_concurrency),
        })
    return out


def method_quantile(lane: int, method: str, q: float) -> float:
    """Latency quantile (ns) of one method's own log2 histogram."""
    return load().nat_method_quantile(lane, method.encode(), q)


def method_hist(lane: int, method: str) -> list:
    """Raw log2 buckets of one method's latency histogram (the mergeable
    form: fleet quantiles are computed from bucket-wise sums across
    processes, never from averaged percentiles). Empty list when the
    method has no slot."""
    lib = load()
    nb = lib.nat_stats_hist_nbuckets()
    arr = (ctypes.c_uint64 * nb)()
    n = lib.nat_method_hist(lane, method.encode(), arr, nb)
    if n < 0:
        return []
    return list(arr[:n])


def stats_snapshot() -> bytes:
    """The builtin.stats snapshot JSON, built in-process (the same bytes
    the wire endpoint serves): counters, per-lane and per-method raw
    log2 buckets, overload/quiesce state, open client channels, and the
    nat_res subsystem ledger."""
    lib = load()
    out = ctypes.c_char_p()
    n = ctypes.c_size_t(0)
    rc = lib.nat_stats_snapshot(ctypes.byref(out), ctypes.byref(n))
    if rc != 0 or not out:
        return b""
    try:
        return ctypes.string_at(out, n.value)
    finally:
        lib.nat_buf_free(out)


def conn_snapshot() -> list:
    """Native /connections rows: one dict per live native socket with
    byte/message/syscall counters, unwritten (queued-not-yet-accepted)
    bytes, sniffed protocol, peer address and owning dispatcher."""
    lib = load()
    # n == cap means the table may be truncated (the C export clamps to
    # the caller's buffer): regrow so a thousand-backend fan-out shows
    # every socket instead of a silently partial table
    cap = 1024
    while True:
        arr = (NatConnRow * cap)()
        n = lib.nat_conn_snapshot(arr, cap)
        if n < cap:
            break
        cap *= 2
    out = []
    for i in range(n):
        r = arr[i]
        out.append({
            "sock_id": r.sock_id,
            "in_bytes": r.in_bytes,
            "out_bytes": r.out_bytes,
            "in_msgs": r.in_msgs,
            "out_msgs": r.out_msgs,
            "read_calls": r.read_calls,
            "write_calls": r.write_calls,
            "unwritten_bytes": r.unwritten_bytes,
            "mem_bytes": r.mem_bytes,
            "fd": r.fd,
            "disp_idx": r.disp_idx,
            "server_side": bool(r.server_side),
            "protocol": r.protocol.decode(errors="replace"),
            "remote": r.remote.decode(errors="replace"),
        })
    return out


def res_stats() -> list:
    """The native memory observatory's per-subsystem ledger: one dict
    per allocator subsystem (iobuf blocks, socket slabs, WriteReq pools,
    fiber stacks, shm segments, ...) with live bytes/objects, cumulative
    allocs/frees and the high-water mark."""
    lib = load()
    n = lib.nat_res_count()
    arr = (NatResRow * n)()
    got = lib.nat_res_stats(arr, n)
    out = []
    for i in range(got):
        r = arr[i]
        out.append({
            "subsystem": r.name.decode(errors="replace"),
            "live_bytes": r.live_bytes,
            "live_objects": r.live_objects,
            "cum_allocs": r.cum_allocs,
            "cum_frees": r.cum_frees,
            "cum_alloc_bytes": r.cum_alloc_bytes,
            "hwm_bytes": r.hwm_bytes,
        })
    return out


def res_names() -> list:
    """Subsystem names in enum order (the nat_mem_* label values)."""
    lib = load()
    return [lib.nat_res_name(i).decode()
            for i in range(lib.nat_res_count())]


def res_accounted_bytes() -> int:
    """Total live bytes across every accounted native subsystem — the
    /status RSS reconciliation's accounted side."""
    return load().nat_res_accounted_bytes()


def res_prof_start(every: int = 1, seed: int = 42) -> int:
    """Arm allocation-site stack sampling (1-in-`every`, seeded
    deterministic). 0 = ok, -1 = already running (an embedder owns it —
    report without stealing, the nat_prof discipline)."""
    return load().nat_res_prof_start(every, seed)


def res_prof_stop() -> int:
    return load().nat_res_prof_stop()


def res_prof_running() -> bool:
    return bool(load().nat_res_prof_running())


def res_prof_samples() -> int:
    return load().nat_res_prof_samples()


def res_prof_reset():
    """Forget sampled sites/baseline (the always-on ledger is separate
    and untouched)."""
    load().nat_res_prof_reset()


def res_heap_report(collapsed: bool = True) -> str:
    """/heap/native body: live bytes by allocation site — collapsed
    stacks (default, leaf = "res:<subsystem>") or a flat table."""
    lib = load()
    out = ctypes.c_char_p()
    n = ctypes.c_size_t(0)
    rc = lib.nat_res_heap_report(1 if collapsed else 0, ctypes.byref(out),
                                 ctypes.byref(n))
    if rc != 0 or not out:
        return ""
    try:
        return ctypes.string_at(out, n.value).decode(errors="replace")
    finally:
        lib.nat_buf_free(out)


def res_growth_baseline() -> int:
    """Re-take the /growth/native zero point."""
    return load().nat_res_growth_baseline()


def res_growth_report() -> str:
    """/growth/native body: collapsed stacks weighted by live-bytes
    growth since the baseline."""
    lib = load()
    out = ctypes.c_char_p()
    n = ctypes.c_size_t(0)
    rc = lib.nat_res_growth_report(ctypes.byref(out), ctypes.byref(n))
    if rc != 0 or not out:
        return ""
    try:
        return ctypes.string_at(out, n.value).decode(errors="replace")
    finally:
        lib.nat_buf_free(out)


def res_selftest(nthreads: int = 4, iters: int = 200) -> int:
    """Deterministic alloc/free churn with concurrent snapshot/report
    readers; 0 = the ledger balanced exactly."""
    return load().nat_res_selftest(nthreads, iters)


def mu_prof_start(threshold_us: int = 0, every: int = 1,
                  seed: int = 42) -> int:
    """Arm contended-NatMutex stack sampling: waits >= threshold_us are
    rate-decimated to one in `every` (seeded, deterministic) and sampled
    with a frame-pointer stack naming the contended lock site. 0 = ok,
    -1 = already running (a bench/embedder owns the window)."""
    return load().nat_mu_prof_start(threshold_us, every, seed)


def mu_prof_stop() -> int:
    """Stop sampling; accumulated contention samples stay reportable."""
    return load().nat_mu_prof_stop()


def mu_prof_running() -> bool:
    return bool(load().nat_mu_prof_running())


def mu_prof_samples() -> int:
    return load().nat_mu_prof_samples()


def mu_prof_reset():
    """Forget sampled stacks AND the always-on per-rank wait totals."""
    load().nat_mu_prof_reset()


def mu_prof_reset_samples():
    """Forget sampled stacks only; the per-rank wait totals stay
    monotonic (they are exported as Prometheus counters)."""
    load().nat_mu_prof_reset_samples()


def mu_prof_report(collapsed: bool = True) -> str:
    """Contention profile: collapsed stacks weighted by wait-us
    (default; leaf frame = "lock:<rank name>") or a flat wait-us table
    per contended lock site."""
    lib = load()
    out = ctypes.c_char_p()
    n = ctypes.c_size_t(0)
    rc = lib.nat_mu_prof_report(1 if collapsed else 0, ctypes.byref(out),
                                ctypes.byref(n))
    if rc != 0 or not out:
        return ""
    try:
        return ctypes.string_at(out, n.value).decode(errors="replace")
    finally:
        lib.nat_buf_free(out)


def mu_rank_stats() -> list:
    """Always-on per-rank contended-wait totals (independent of
    sampling): [{'rank', 'name', 'waits', 'wait_us'}, ...]."""
    lib = load()
    arr = (NatLockRankRow * 128)()
    n = lib.nat_mu_rank_stats(arr, 128)
    return [{"rank": arr[i].rank,
             "name": arr[i].name.decode(errors="replace"),
             "waits": arr[i].waits,
             "wait_us": arr[i].wait_us} for i in range(n)]


def mu_rank_name(rank: int):
    """Human name of a NatMutex lock rank, or None when unnamed (the
    drift test asserts every nat_lockrank.h constant resolves)."""
    nm = load().nat_mu_rank_name(rank)
    return nm.decode() if nm is not None else None


def mu_contend_selftest(nthreads: int = 4, iters: int = 100,
                        hold_us: int = 20) -> int:
    """Deterministic contention generator (tests): N threads fight over
    one declared-rank NatMutex; returns that rank's contended-wait
    count so far."""
    return load().nat_mu_contend_selftest(nthreads, iters, hold_us)


# Python-side shadow of the C-side thread-local trace context (the
# Python wrappers are the only setters from this interpreter), so
# trace_scope can RESTORE the enclosing context on exit instead of
# clobbering it to (0,0) — nested scopes / scopes inside an already
# traced request keep propagating after they close.
_trace_tls = threading.local()


def trace_set(trace_id: int = 0, span_id: int = 0):
    """Arm (or clear, with 0,0) this thread's ambient trace context:
    native client calls issued on this thread propagate (trace_id,
    span_id) on the wire — tpu_std meta trace fields, HTTP x-bd-trace-*
    headers, gRPC metadata, kind-8 shm descriptors — so the receiving
    side's spans chain under span_id in /rpcz find_trace."""
    load().nat_trace_set(trace_id, span_id)
    _trace_tls.ctx = (trace_id, span_id)


class trace_scope:
    """with native.trace_scope(trace_id, span_id): ... — arm the ambient
    trace context for the calls inside, restoring the PREVIOUS context
    (not bare zero) on exit."""

    def __init__(self, trace_id: int, span_id: int):
        self._ctx = (trace_id, span_id)
        self._prev = (0, 0)

    def __enter__(self):
        self._prev = getattr(_trace_tls, "ctx", (0, 0))
        trace_set(*self._ctx)
        return self

    def __exit__(self, *exc):
        trace_set(*self._prev)


# -- traffic flight recorder (nat_dump.cpp / nat_replay.cpp) ----------------

def dump_start(directory: str, every: int = 1, seed: int = 42,
               max_file_bytes: int = 64 << 20, generations: int = 4,
               max_payload: int = 1 << 20) -> int:
    """Arm the native traffic flight recorder: sample 1-in-`every`
    requests at the native protocol seams (tpu_std, native HTTP,
    gRPC/h2, redis store, kind-8 shm descriptors) into recordio files
    under `directory` — the format butil/recordio.py reads — rotated
    past max_file_bytes keeping `generations` files. Payloads past
    max_payload are skipped whole (a truncated request is not
    replayable). 0 = ok, -1 = already running, -2 = dir/file error."""
    return load().nat_dump_start(directory.encode(), every, seed,
                                 max_file_bytes, generations, max_payload)


def dump_stop() -> int:
    """Disarm the recorder: drain the capture rings, flush + close the
    current file. Safe when not running."""
    return load().nat_dump_stop()


def dump_running() -> bool:
    return bool(load().nat_dump_running())


def dump_status() -> dict:
    """Flight-recorder status snapshot (counts since the current start;
    config reflects the armed window, or the last one when stopped)."""
    st = NatDumpStatusRec()
    load().nat_dump_status(ctypes.byref(st))
    return {
        "running": bool(st.running),
        "dir": st.dir.decode(errors="replace"),
        "every": st.every,
        "seed": st.seed,
        "samples": st.samples,
        "written": st.written,
        "bytes": st.bytes,
        "drops": st.drops,
        "oversize": st.oversize,
        "rotations": st.rotations,
        "max_file_bytes": st.max_file_bytes,
        "max_payload": st.max_payload,
        "generations": st.generations,
    }


def replay_run(ip: str, port: int, files, times: int = 1,
               qps: float = 0.0, qps_to: float = 0.0,
               concurrency: int = 4, timeout_ms: int = 2000) -> dict:
    """Replay captured recordio traffic against ip:port through the
    native client lanes (tpu_std / HTTP / gRPC). `files` is a path, a
    directory, or a list of either. qps > 0 throttles the fire schedule
    (qps_to > 0 ramps linearly to it across the run); qps <= 0 is press
    mode: no throttle, `concurrency` callers back to back. Raises on
    empty captures / connect failures."""
    if qps_to > 0 and qps <= 0:
        # fire_time ignores the ramp without a starting rate: running
        # UNTHROTTLED when the caller asked for a 500-qps ceiling is
        # the opposite of what they meant — refuse loudly
        raise ValueError("qps_to requires a starting qps > 0 "
                         "(use qps=<low>, qps_to=<high> for a ramp)")
    if isinstance(files, (list, tuple)):
        spec = ";".join(str(f) for f in files)
    else:
        spec = str(files)
    res = NatReplayResult()
    rc = load().nat_replay_run(ip.encode(), port, spec.encode(), times,
                               qps, qps_to, concurrency, timeout_ms,
                               ctypes.byref(res))
    if rc == -1:
        raise ValueError(f"no replayable records under {spec!r}")
    if rc != 0:
        raise ConnectionError(f"native replay failed: rc={rc}")
    return {
        "loaded": res.loaded,
        "sent": res.sent,
        "ok": res.ok,
        "failed": res.failed,
        "skipped": res.skipped,
        "seconds": res.seconds,
        "qps": res.qps,
        "p50_us": res.p50_us,
        "p99_us": res.p99_us,
    }


# -- native fan-out cluster (nat_cluster.cpp / nat_lb.cpp) ------------------

def rpc_server_add_port(ip: str = "127.0.0.1", port: int = 0) -> int:
    """Listen on another port with the RUNNING native server (the
    swarm-backend seam: one process, N ports, each port a distinct LB
    backend). Returns the bound port; raises if no server is running."""
    rc = load().nat_rpc_server_add_port(ip.encode(), port)
    if rc <= 0:
        raise RuntimeError("nat_rpc_server_add_port failed")
    return rc


def rpc_server_remove_port(port: int) -> int:
    """Unregister a port added with rpc_server_add_port (accepted
    connections keep serving; new connects are refused)."""
    return load().nat_rpc_server_remove_port(port)


def cluster_create(lb: str = "rr", connect_timeout_ms: int = 500,
                   health_check_ms: int = 100, breaker: bool = True):
    """Open a native cluster: DoublyBufferedData server list, native LB
    (rr/wrr/random/wr/la/c_hash), per-backend lazily-dialed channels
    with circuit breakers + lame-duck failover. Feed it with
    cluster_update; call through cluster_call / cluster_parallel_call /
    cluster_partition_call. The higher-level wrapper (NativeCluster in
    brpc_tpu.rpc.native_cluster) adds the naming-observer thread."""
    h = load().nat_cluster_create(lb.encode(), connect_timeout_ms,
                                  health_check_ms, 1 if breaker else 0)
    if not h:
        raise RuntimeError(f"nat_cluster_create failed (lb={lb!r})")
    return h


def cluster_close(handle):
    load().nat_cluster_close(handle)


def cluster_node_entry(node):
    """(endpoint[, weight[, tag]]) tuple or bare endpoint ->
    (endpoint, weight, tag) with per-missing-field defaults (naive list
    padding would hand a 2-tuple the weight default as its TAG)."""
    if isinstance(node, (tuple, list)):
        ep = node[0]
        weight = node[1] if len(node) > 1 else 1
        tag = node[2] if len(node) > 2 else ""
        return str(ep), int(weight), str(tag)
    return str(node), 1, ""


def cluster_update(handle, servers) -> int:
    """Full-list naming feed. `servers` is a spec string of
    "ip:port[ weight[ tag]]" entries (';'/','/newline separated) or an
    iterable of such entries / (endpoint, weight, tag) tuples. Returns
    the backend count."""
    if not isinstance(servers, (str, bytes)):
        parts = []
        for s in servers:
            ep, weight, tag = cluster_node_entry(s)
            parts.append(f"{ep} {weight} {tag}".strip())
        servers = ";".join(parts)
    if isinstance(servers, str):
        servers = servers.encode()
    rc = load().nat_cluster_update(handle, servers)
    if rc < 0:
        raise ValueError("malformed server spec (or closed cluster)")
    return rc


def cluster_backend_count(handle) -> int:
    return load().nat_cluster_backend_count(handle)


def cluster_select_debug(handle, request_code: int = 0):
    """Which endpoint would the LB pick for request_code right now?
    Lookup-only (no dial, no counters); None when nothing is usable."""
    buf = ctypes.create_string_buffer(32)
    rc = load().nat_cluster_select_debug(handle, request_code, buf, 32)
    return buf.value.decode() if rc == 0 else None


def cluster_call(handle, service: str, method: str, payload: bytes = b"",
                 timeout_ms: int = 0, max_retry: int = 2,
                 request_code: int = 0):
    """SelectiveChannel verb: LB-pick one backend, fail over to another
    on failure (timeout covers all attempts). Returns
    (error_code, response_bytes, error_text)."""
    lib = load()
    resp = ctypes.c_char_p()
    rlen = ctypes.c_size_t(0)
    err = ctypes.c_char_p()
    rc = lib.nat_cluster_call(handle, service.encode(), method.encode(),
                              payload, len(payload), timeout_ms, max_retry,
                              request_code, ctypes.byref(resp),
                              ctypes.byref(rlen), ctypes.byref(err))
    body = b""
    if resp:
        body = ctypes.string_at(resp, rlen.value)
        lib.nat_buf_free(resp)
    text = ""
    if err:
        text = ctypes.string_at(err).decode(errors="replace")
        lib.nat_buf_free(err)
    return rc, body, text


def _cluster_fan(fn, handle, service, method, payload, timeout_ms, args):
    lib = load()
    resp = ctypes.c_char_p()
    rlen = ctypes.c_size_t(0)
    err = ctypes.c_char_p()
    failed = ctypes.c_int(0)
    rc = fn(handle, service.encode(), method.encode(), payload,
            len(payload), timeout_ms, *args, ctypes.byref(resp),
            ctypes.byref(rlen), ctypes.byref(err), ctypes.byref(failed))
    body = b""
    if resp:
        body = ctypes.string_at(resp, rlen.value)
        lib.nat_buf_free(resp)
    text = ""
    if err:
        text = ctypes.string_at(err).decode(errors="replace")
        lib.nat_buf_free(err)
    return rc, body, text, failed.value


def cluster_parallel_call(handle, service: str, method: str,
                          payload: bytes = b"", timeout_ms: int = 0,
                          fail_limit: int = 0):
    """ParallelChannel verb: fan the request to EVERY backend, merge the
    successful responses natively (concatenation in backend order ==
    protobuf MergeFrom). Returns (error_code, merged_bytes, error_text,
    failed_subcalls); fails once failed sub-calls reach fail_limit
    (<= 0 = all must fail)."""
    return _cluster_fan(load().nat_cluster_parallel_call, handle, service,
                        method, payload, timeout_ms, (fail_limit,))


def cluster_partition_call(handle, service: str, method: str,
                           payload: bytes = b"", timeout_ms: int = 0,
                           partitions: int = 0, fail_limit: int = 0):
    """PartitionChannel verb: one sub-call per "i/n" partition group
    (partitions = n; 0 infers the largest scheme present), merged in
    partition order. Returns (error_code, merged_bytes, error_text,
    failed_subcalls)."""
    return _cluster_fan(load().nat_cluster_partition_call, handle, service,
                        method, payload, timeout_ms,
                        (partitions, fail_limit))


def cluster_dynpart_call(handle, service: str, method: str,
                         payload: bytes = b"", timeout_ms: int = 0,
                         fail_limit: int = 0):
    """DynamicPartitionChannel verb: the partition count is picked PER
    CALL from the live "i/n" schemes, weighted by usable capacity
    (_dynpart LB), then fanned one sub-call per group. A resize is never
    caller-visible — in-flight fans complete against their pinned server
    list version. Returns (error_code, merged_bytes, error_text,
    failed_subcalls, chosen_scheme)."""
    lib = load()
    resp = ctypes.c_char_p()
    rlen = ctypes.c_size_t(0)
    err = ctypes.c_char_p()
    failed = ctypes.c_int(0)
    scheme = ctypes.c_int(0)
    rc = lib.nat_cluster_dynpart_call(
        handle, service.encode(), method.encode(), payload, len(payload),
        timeout_ms, fail_limit, ctypes.byref(resp), ctypes.byref(rlen),
        ctypes.byref(err), ctypes.byref(failed), ctypes.byref(scheme))
    body = b""
    if resp:
        body = ctypes.string_at(resp, rlen.value)
        lib.nat_buf_free(resp)
    text = ""
    if err:
        text = ctypes.string_at(err).decode(errors="replace")
        lib.nat_buf_free(err)
    return rc, body, text, failed.value, scheme.value


def cluster_dynpart_debug(handle, x01: float = 0.0,
                          max_schemes: int = 64) -> dict:
    """Equivalence probe for the dynpart pick: the live scheme table
    (ascending part_total with usable capacities) plus the scheme the
    weighted walk chooses for the caller-supplied point x01 in [0,1) —
    so a Python DynPartLB walk can be replayed against identical inputs.
    Returns {'schemes': [(part_total, capacity), ...], 'chosen': int}."""
    totals = (ctypes.c_int * max_schemes)()
    caps = (ctypes.c_int * max_schemes)()
    chosen = ctypes.c_int(0)
    n = load().nat_cluster_dynpart_debug(handle, x01, totals, caps,
                                         max_schemes,
                                         ctypes.byref(chosen))
    n = min(n, max_schemes)
    return {"schemes": [(totals[i], caps[i]) for i in range(n)],
            "chosen": chosen.value}


def cluster_stats(handle, max_rows: int = 4096) -> list:
    """Per-backend rows: [{'endpoint', 'tag', 'weight', 'selects',
    'errors', 'inflight', 'ema_latency_us', 'breaker_open', 'lame_duck',
    'part_index', 'part_total'}, ...]."""
    arr = (NatClusterRow * max_rows)()
    n = load().nat_cluster_stats(handle, arr, max_rows)
    out = []
    for i in range(n):
        r = arr[i]
        out.append({
            "endpoint": r.endpoint.decode(errors="replace"),
            "tag": r.tag.decode(errors="replace"),
            "weight": r.weight,
            "selects": r.selects,
            "errors": r.errors,
            "inflight": r.inflight,
            "ema_latency_us": r.ema_latency_us,
            "breaker_open": bool(r.breaker_open),
            "lame_duck": bool(r.lame_duck),
            "part_index": r.part_index,
            "part_total": r.part_total,
        })
    return out


def cluster_bench(handle, mode: int = 0, service: str = "EchoService",
                  method: str = "Echo", payload: bytes = b"x" * 16,
                  timeout_ms: int = 2000, param: int = 2,
                  seconds: float = 2.0, concurrency: int = 4) -> dict:
    """Drive the cluster from C threads: mode 0 = selective (param =
    max_retry), 1 = parallel (param = fail_limit), 2 = dynpart (param =
    fail_limit; the autoscale drill's flood). ctypes releases the
    GIL for the whole run, so churn orchestration (SIGTERMs, naming
    updates) can ride a Python thread beside it. Returns {'qps',
    'calls', 'failed', 'p99_us'}."""
    calls = ctypes.c_uint64(0)
    failed = ctypes.c_uint64(0)
    p99 = ctypes.c_double(0.0)
    qps = load().nat_cluster_bench(
        handle, mode, service.encode(), method.encode(), payload,
        len(payload), timeout_ms, param, seconds, concurrency,
        ctypes.byref(calls), ctypes.byref(failed), ctypes.byref(p99))
    return {"qps": qps, "calls": calls.value, "failed": failed.value,
            "p99_us": p99.value}


# -- in-process sampling profiler (nat_prof.cpp) ----------------------------

def prof_start(hz: int = 99) -> int:
    """Start SIGPROF/CPU-time stack sampling at `hz` (frame-pointer
    unwind into lock-free per-thread rings). 0 = ok, -1 = already
    running, -2 = handler/timer install failed."""
    return load().nat_prof_start(hz)


def prof_stop() -> int:
    """Stop sampling; accumulated samples stay reportable."""
    return load().nat_prof_stop()


def prof_running() -> bool:
    return bool(load().nat_prof_running())


def prof_samples() -> int:
    """Samples captured since start/reset."""
    return load().nat_prof_samples()


def prof_reset():
    """Forget everything sampled so far."""
    load().nat_prof_reset()


def prof_report(collapsed: bool = False) -> str:
    """Render the accumulated profile: flat self-sample symbol table
    (default, the PROFILE_r*.md shape) or collapsed stacks
    (flamegraph.pl / speedscope compatible)."""
    lib = load()
    out = ctypes.c_char_p()
    n = ctypes.c_size_t(0)
    rc = lib.nat_prof_report(1 if collapsed else 0, ctypes.byref(out),
                             ctypes.byref(n))
    if rc != 0 or not out:
        return ""
    try:
        return ctypes.string_at(out, n.value).decode(errors="replace")
    finally:
        lib.nat_buf_free(out)
