"""Heap / growth / contention / TPU profilers behind /hotspots.

Counterpart of the reference's profiler suite
(/root/reference/src/brpc/builtin/hotspots_service.h:38-68: CPU, HEAP,
GROWTH, CONTENTION via gperftools/tcmalloc hooks) translated to this
runtime:

- heap      -> tracemalloc snapshot, allocations by stack (collapsed)
- growth    -> tracemalloc diff against the first snapshot taken since
               profiling started (tcmalloc's cumulative-growth view)
- contention-> statistical sampler keeping only stacks blocked in lock /
               condition waits (the reference hooks its own mutexes;
               sampling the wait frames gives the same "who waits where"
               answer without patching every lock)
- tpu       -> jax.profiler trace (XProf) zipped for TensorBoard — the
               SURVEY §5 TPU translation of the pprof endpoints
"""
from __future__ import annotations

import io
import os
import sys
import threading
import time
import tracemalloc
import zipfile
from collections import Counter

_growth_baseline = None
_baseline_lock = threading.Lock()


def _ensure_tracemalloc(frames: int = 16) -> bool:
    """Start tracemalloc on first profile request. Returns False if it
    JUST started (no data yet)."""
    global _growth_baseline
    if tracemalloc.is_tracing():
        # Tracing was begun externally (PYTHONTRACEMALLOC / user code):
        # adopt the current state as the growth baseline.
        with _baseline_lock:
            if _growth_baseline is None:
                _growth_baseline = tracemalloc.take_snapshot()
        return True
    tracemalloc.start(frames)
    with _baseline_lock:
        _growth_baseline = tracemalloc.take_snapshot()
    return False


def _collapse(stat) -> str:
    parts = []
    for frame in reversed(stat.traceback):
        parts.append(f"{os.path.basename(frame.filename)}:{frame.lineno}")
    return ";".join(parts) if parts else "<unknown>"


def heap_profile(top: int = 64) -> str:
    """Live allocations by stack, collapsed format, byte counts."""
    fresh = not _ensure_tracemalloc()
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("traceback")
    total = sum(s.size for s in stats)
    lines = [
        f"# heap profile: {len(stats)} allocation sites, "
        f"{total} bytes live (tracemalloc)",
        "# format: collapsed stacks, value = live bytes",
    ]
    if fresh:
        lines.append("# note: tracing just started; rerun for steady state")
    for s in stats[:top]:
        lines.append(f"{_collapse(s)} {s.size}")
    return "\n".join(lines) + "\n"


def growth_profile(top: int = 64) -> str:
    """Allocation growth since profiling began (tcmalloc HEAP_GROWTH)."""
    fresh = not _ensure_tracemalloc()
    snap = tracemalloc.take_snapshot()
    with _baseline_lock:
        baseline = _growth_baseline
    lines = ["# growth profile: bytes allocated since profiling start",
             "# format: collapsed stacks, value = grown bytes"]
    if fresh or baseline is None:
        lines.append("# note: baseline just taken; rerun to see growth")
        return "\n".join(lines) + "\n"
    diffs = snap.compare_to(baseline, "traceback")
    grown = [d for d in diffs if d.size_diff > 0]
    grown.sort(key=lambda d: d.size_diff, reverse=True)
    lines.insert(1, f"# {len(grown)} growing sites, "
                    f"{sum(d.size_diff for d in grown)} bytes total")
    for d in grown[:top]:
        lines.append(f"{_collapse(d)} {d.size_diff}")
    return "\n".join(lines) + "\n"


_WAIT_LEAVES = ("wait", "acquire", "_wait_for_tstate_lock", "wait_for",
                "futex_wait", "join")
_WAIT_FILES = ("threading.py", "butex.py", "parking_lot.py",
               "execution_queue.py", "id.py")


def contention_profile(seconds: float = 1.0, hz: int = 99) -> str:
    """Stacks observed blocked in lock/condition waits
    (contention_profiler.md's question answered by sampling)."""
    seconds = max(0.1, min(10.0, seconds))
    interval = 1.0 / max(1, hz)
    stacks: Counter = Counter()
    own = threading.get_ident()
    deadline = time.monotonic() + seconds
    nsamples = 0
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == own or frame is None:
                continue
            leaf = frame.f_code
            fname = os.path.basename(leaf.co_filename)
            if not (leaf.co_name.startswith(_WAIT_LEAVES)
                    or leaf.co_name in _WAIT_LEAVES) or \
                    fname not in _WAIT_FILES:
                continue
            parts = []
            f = frame
            depth = 0
            while f is not None and depth < 64:
                code = f.f_code
                parts.append(
                    f"{code.co_name} "
                    f"({os.path.basename(code.co_filename)}:{f.f_lineno})")
                f = f.f_back
                depth += 1
            stacks[";".join(reversed(parts))] += 1
        nsamples += 1
        time.sleep(interval)
    lines = [
        f"# contention profile: {nsamples} samples at {hz}Hz over "
        f"{seconds}s; stacks blocked in lock/cond waits",
        "# format: collapsed stacks, value = samples observed waiting",
    ]
    for stack, count in stacks.most_common():
        lines.append(f"{stack} {count}")
    if len(lines) == 2:
        lines.append("# no contention observed")
    return "\n".join(lines) + "\n"


def tpu_trace(seconds: float = 1.0):
    """XProf/libtpu trace via jax.profiler; returns (content_type, body).
    Loading the zip into TensorBoard's profile plugin gives the device
    timeline — the TPU-idiomatic /hotspots backend (SURVEY §5)."""
    seconds = max(0.1, min(30.0, seconds))
    import tempfile

    if (os.cpu_count() or 1) < 2 and not os.environ.get(
            "BRPC_TPU_FORCE_TPU_TRACE"):
        # Trace collection is not bounded by `seconds`: profiler start/stop
        # does several seconds of native work that monopolises the only
        # core, starving every other handler on the server (observed as
        # cascading 60s timeouts on 1-cpu CI). Explain instead of hanging;
        # BRPC_TPU_FORCE_TPU_TRACE=1 overrides when the stall is acceptable.
        return ("text/plain",
                "profiler trace skipped: single-cpu host (trace collection "
                "would starve the server; set BRPC_TPU_FORCE_TPU_TRACE=1 "
                "to force)\n")
    try:
        import jax
    except Exception as e:  # pragma: no cover - jax is baked in
        return "text/plain", f"jax unavailable: {e}\n"
    with tempfile.TemporaryDirectory(prefix="xprof_") as tmp:
        try:
            with jax.profiler.trace(tmp):
                # idle-wait: RPC traffic and device work during the window
                # get captured by the profiler's own hooks
                time.sleep(seconds)
        except Exception as e:
            return "text/plain", f"profiler trace failed: {e}\n"
        buf = io.BytesIO()
        nfiles = 0
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
            for root, _dirs, files in os.walk(tmp):
                for name in files:
                    full = os.path.join(root, name)
                    zf.write(full, os.path.relpath(full, tmp))
                    nfiles += 1
        if nfiles == 0:
            return "text/plain", "profiler produced no trace files\n"
        return "application/zip", buf.getvalue()
