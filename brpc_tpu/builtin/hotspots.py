"""Hotspots — on-demand CPU profiling behind the console.

Counterpart of /hotspots/cpu + /pprof (builtin/hotspots_service.h:38-68,
builtin/pprof_service.h:26-48): GET /hotspots/cpu?seconds=N runs a
statistical sampler over sys._current_frames() (all threads, the
whole-process view gperftools gives the reference) and returns collapsed
stacks ("frame;frame;frame count" lines — flamegraph.pl / speedscope
ingestible). The TPU-side profiler hook (XProf) plugs in the same handler
table (SURVEY.md section 5).
"""
from __future__ import annotations

import sys
import threading
import time
from collections import Counter
from typing import Dict


def sample_cpu(seconds: float = 1.0, hz: int = 99) -> str:
    """Collapsed-stack sample of every live thread."""
    seconds = max(0.1, min(10.0, seconds))
    interval = 1.0 / max(1, hz)
    stacks: Counter = Counter()
    deadline = time.monotonic() + seconds
    own = threading.get_ident()
    nsamples = 0
    while time.monotonic() < deadline:
        frames: Dict[int, object] = sys._current_frames()
        for tid, frame in frames.items():
            if tid == own:
                continue
            parts = []
            f = frame
            depth = 0
            while f is not None and depth < 64:
                code = f.f_code
                parts.append(f"{code.co_name} ({code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno})")
                f = f.f_back
                depth += 1
            if parts:
                stacks[";".join(reversed(parts))] += 1
        nsamples += 1
        time.sleep(interval)
    lines = [f"# cpu profile: {nsamples} samples at {hz}Hz over {seconds}s",
             "# format: collapsed stacks (flamegraph.pl compatible)"]
    for stack, count in stacks.most_common():
        lines.append(f"{stack} {count}")
    return "\n".join(lines) + "\n"


def thread_dump() -> str:
    """Instantaneous stacks of all threads (/threads page role)."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in frames.items():
        out.append(f"--- thread {names.get(tid, '?')} ({tid}) ---")
        f = frame
        depth = 0
        while f is not None and depth < 64:
            code = f.f_code
            out.append(f"  {code.co_filename}:{f.f_lineno} {code.co_name}")
            f = f.f_back
            depth += 1
    return "\n".join(out) + "\n"


# one /hotspots/native window at a time: a concurrent request's
# stop/reset must not wipe another window's samples mid-flight (the
# second request waits and then gets its own full window)
_native_prof_lock = threading.Lock()


def sample_native(seconds: float = 1.0, hz: int = 99,
                  collapsed: bool = True) -> str:
    """Native-runtime CPU profile via nat_prof (the in-process SIGPROF
    sampler, native/src/nat_prof.cpp): samples every thread actually
    burning CPU — fiber workers, dispatcher loops, py-lane pthreads —
    with frame-pointer unwind through the C++ core, where the Python
    sampler above only sees interpreter frames."""
    try:
        from brpc_tpu import native

        if not native.available():
            return "native runtime unavailable\n"
    except Exception as e:
        return f"native runtime unavailable: {e}\n"
    seconds = max(0.1, min(30.0, seconds))
    with _native_prof_lock:
        rc = native.prof_start(hz)
        owns = rc == 0
        if rc == -2:
            return "nat_prof: could not install SIGPROF handler/timer\n"
        # rc == -1: a bench/embedder already runs the profiler — report
        # the window without stealing ownership of start/stop/reset
        time.sleep(seconds)
        if owns:
            native.prof_stop()
        report = native.prof_report(collapsed=collapsed)
        if owns:
            native.prof_reset()
    return report or "nat_prof: no samples (no native CPU burned?)\n"


def hotspots_handler(server, req):
    """/hotspots/{cpu,native,heap,growth,contention,tpu} — the full
    profiler surface of hotspots_service.h:38-68 (+ the XProf TPU
    translation and the nat_prof native sampler)."""
    from brpc_tpu.builtin import profilers

    parts = [p for p in req.path.split("/") if p]
    kind = parts[1] if len(parts) > 1 else "cpu"
    seconds = float(req.query.get("seconds", "1") or 1)
    if kind == "cpu":
        return 200, "text/plain", sample_cpu(seconds)
    if kind == "native":
        collapsed = req.query.get("flat", "") in ("", "0")
        return 200, "text/plain", sample_native(seconds,
                                                collapsed=collapsed)
    if kind == "heap":
        return 200, "text/plain", profilers.heap_profile()
    if kind == "growth":
        return 200, "text/plain", profilers.growth_profile()
    if kind == "contention":
        return 200, "text/plain", profilers.contention_profile(seconds)
    if kind == "tpu":
        ctype, body = profilers.tpu_trace(seconds)
        return 200, ctype, body
    return 404, "text/plain", f"unknown hotspots kind {kind}\n"


def pprof_handler(server, req):
    """/pprof/{profile,heap,growth,symbol} — pprof_service.h:26-48 slots."""
    from brpc_tpu.builtin import profilers

    parts = [p for p in req.path.split("/") if p]
    kind = parts[1] if len(parts) > 1 else "profile"
    if kind == "profile":
        seconds = float(req.query.get("seconds", "1") or 1)
        return 200, "text/plain", sample_cpu(seconds)
    if kind == "heap":
        return 200, "text/plain", profilers.heap_profile()
    if kind == "growth":
        return 200, "text/plain", profilers.growth_profile()
    if kind == "contention":
        seconds = float(req.query.get("seconds", "1") or 1)
        return 200, "text/plain", profilers.contention_profile(seconds)
    if kind == "symbol":
        return 200, "text/plain", "python frames are pre-symbolized\n"
    return 404, "text/plain", f"unknown pprof endpoint {kind}\n"


def threads_handler(server, req):
    return 200, "text/plain", thread_dump()
