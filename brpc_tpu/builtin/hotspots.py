"""Hotspots — on-demand CPU profiling behind the console.

Counterpart of /hotspots/cpu + /pprof (builtin/hotspots_service.h:38-68,
builtin/pprof_service.h:26-48): GET /hotspots/cpu?seconds=N runs a
statistical sampler over sys._current_frames() (all threads, the
whole-process view gperftools gives the reference) and returns collapsed
stacks ("frame;frame;frame count" lines — flamegraph.pl / speedscope
ingestible). The TPU-side profiler hook (XProf) plugs in the same handler
table (SURVEY.md section 5).
"""
from __future__ import annotations

import sys
import threading
import time
from collections import Counter
from typing import Dict


def sample_cpu(seconds: float = 1.0, hz: int = 99) -> str:
    """Collapsed-stack sample of every live thread."""
    seconds = max(0.1, min(10.0, seconds))
    interval = 1.0 / max(1, hz)
    stacks: Counter = Counter()
    deadline = time.monotonic() + seconds
    own = threading.get_ident()
    nsamples = 0
    while time.monotonic() < deadline:
        frames: Dict[int, object] = sys._current_frames()
        for tid, frame in frames.items():
            if tid == own:
                continue
            parts = []
            f = frame
            depth = 0
            while f is not None and depth < 64:
                code = f.f_code
                parts.append(f"{code.co_name} ({code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno})")
                f = f.f_back
                depth += 1
            if parts:
                stacks[";".join(reversed(parts))] += 1
        nsamples += 1
        time.sleep(interval)
    lines = [f"# cpu profile: {nsamples} samples at {hz}Hz over {seconds}s",
             "# format: collapsed stacks (flamegraph.pl compatible)"]
    for stack, count in stacks.most_common():
        lines.append(f"{stack} {count}")
    return "\n".join(lines) + "\n"


def thread_dump() -> str:
    """Instantaneous stacks of all threads (/threads page role)."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in frames.items():
        out.append(f"--- thread {names.get(tid, '?')} ({tid}) ---")
        f = frame
        depth = 0
        while f is not None and depth < 64:
            code = f.f_code
            out.append(f"  {code.co_filename}:{f.f_lineno} {code.co_name}")
            f = f.f_back
            depth += 1
    return "\n".join(out) + "\n"


class _ProfWindow:
    """One profiler window at a time: the sample window (nat_prof's
    SIGPROF aggregate, nat_mu_prof's contention aggregate) is a single
    shared resource — a concurrent request's stop/reset would wipe
    another window's samples mid-flight, so the SECOND request gets
    503 + Retry-After instead of a corrupted/blocking collision.
    Retry-After derives from the RUNNING window's remaining time (its
    monotonic deadline), not the rejected request's own seconds."""

    def __init__(self, clamp_max_s: float, busy_text: str):
        self._lock = threading.Lock()
        self._deadline = 0.0
        self._clamp_max_s = clamp_max_s
        self._busy_text = busy_text

    def run(self, seconds: float, sample_fn):
        if not self._lock.acquire(blocking=False):
            remaining = self._deadline - time.monotonic()
            retry_s = max(1, int(remaining) + 1)
            return (503, "text/plain", self._busy_text,
                    {"Retry-After": str(retry_s)})
        try:
            # mirror the sampler's own window clamp: the advertised
            # Retry-After must reflect the window that actually runs,
            # not a caller-supplied ?seconds=3600
            seconds = max(0.1, min(self._clamp_max_s, seconds))
            self._deadline = time.monotonic() + seconds
            return 200, "text/plain", sample_fn(seconds)
        finally:
            self._lock.release()


_native_prof_window = _ProfWindow(
    30.0, "nat_prof busy: another /hotspots/native window is running\n")
_contention_prof_window = _ProfWindow(
    10.0, "nat_mu_prof busy: another /hotspots/contention window is "
          "running\n")
# /heap/native and /growth/native share ONE window: both drain the same
# allocation-event rings and the growth baseline is shared state — a
# concurrent pair would race baseline/report (the /hotspots/* 503 +
# Retry-After discipline)
_res_prof_window = _ProfWindow(
    30.0, "nat_res busy: another /heap/native or /growth/native window "
          "is running\n")


def _res_ensure_armed():
    """Arm the native allocation-site tracker on first use (the
    tracemalloc ensure-on-first-profile discipline). Returns (native
    module or None, fresh: True when tracking JUST started)."""
    try:
        from brpc_tpu import native

        if not native.available():
            return None, False
    except Exception:
        return None, False
    if native.res_prof_running():
        return native, False
    # rc == -1: an embedder owns the profiler — report without stealing
    return native, native.res_prof_start(1, 42) == 0


def heap_native(seconds: float = 0.0, flat: bool = False) -> str:
    """/heap/native body: live bytes by native allocation site from the
    nat_res ledger's sampled profiler (the tcmalloc /heap role for the
    runtime's OWN allocators, which tracemalloc cannot see). ?seconds=N
    lets the armed tracker observe N seconds of churn before reporting.
    Caller must hold _res_prof_window."""
    native, fresh = _res_ensure_armed()
    if native is None:
        return "native runtime unavailable\n"
    if seconds > 0:
        time.sleep(min(seconds, 30.0))
    report = native.res_heap_report(collapsed=not flat)
    if fresh:
        report = ("# note: allocation-site tracking just started; pool "
                  "memory allocated earlier is in the nat_mem_* ledger "
                  "but not attributed to a site — rerun for steady "
                  "state\n") + report
    return report


def growth_native(seconds: float = 0.0) -> str:
    """/growth/native body: live-bytes-by-site growth since the
    baseline (taken at arming). ?seconds=N re-takes the baseline NOW
    and reports the growth of exactly that window — the leak-trend
    question ("what grew while I watched") answered directly. Caller
    must hold _res_prof_window."""
    native, fresh = _res_ensure_armed()
    if native is None:
        return "native runtime unavailable\n"
    if seconds > 0:
        native.res_growth_baseline()
        time.sleep(min(seconds, 30.0))
    report = native.res_growth_report()
    if fresh:
        report = ("# note: tracking just started; baseline taken now — "
                  "rerun (or pass ?seconds=N) to see growth\n") + report
    return report


def sample_native(seconds: float = 1.0, hz: int = 99,
                  collapsed: bool = True) -> str:
    """Native-runtime CPU profile via nat_prof (the in-process SIGPROF
    sampler, native/src/nat_prof.cpp): samples every thread actually
    burning CPU — fiber workers, dispatcher loops, py-lane pthreads —
    with frame-pointer unwind through the C++ core, where the Python
    sampler above only sees interpreter frames. Caller must hold
    _native_prof_window (hotspots_handler serializes windows there)."""
    try:
        from brpc_tpu import native

        if not native.available():
            return "native runtime unavailable\n"
    except Exception as e:
        return f"native runtime unavailable: {e}\n"
    seconds = max(0.1, min(30.0, seconds))
    rc = native.prof_start(hz)
    owns = rc == 0
    if rc == -2:
        return "nat_prof: could not install SIGPROF handler/timer\n"
    # rc == -1: a bench/embedder already runs the profiler — report
    # the window without stealing ownership of start/stop/reset
    time.sleep(seconds)
    if owns:
        native.prof_stop()
    report = native.prof_report(collapsed=collapsed)
    if owns:
        native.prof_reset()
    return report or "nat_prof: no samples (no native CPU burned?)\n"


def sample_contention(seconds: float = 1.0, hz: int = 99) -> str:
    """/hotspots/contention: the native NatMutex wait profile (nat_mu_prof
    — collapsed stacks weighted by wait-us, leaf = "lock:<rank name>")
    merged with the Python wait-frame sampler. The native sampler is
    armed for exactly the window the Python sampler spends sleeping, so
    both halves describe the same interval."""
    from brpc_tpu.builtin import profilers

    seconds = max(0.1, min(10.0, seconds))
    native_mod = None
    owns = False
    try:
        from brpc_tpu import native as native_mod  # type: ignore

        if native_mod.available():
            # sample every contended wait in the window (threshold 0);
            # a bench/embedder already holding the window (rc == -1)
            # keeps ownership — we still report it
            owns = native_mod.mu_prof_start(0, 1, 42) == 0
        else:
            native_mod = None
    except Exception:
        native_mod = None
    try:
        py_report = profilers.contention_profile(seconds, hz)
    except BaseException:
        # disarm the native sampler we armed: leaving g_mu_on set would
        # make every later window (and BRPC_TPU_BENCH_PROF bench) see
        # rc == -1 and silently lose extra.contention until restart
        if native_mod is not None and owns:
            try:
                native_mod.mu_prof_stop()
                native_mod.mu_prof_reset_samples()
            except Exception:
                pass
        raise
    parts = []
    if native_mod is not None:
        try:
            if owns:
                native_mod.mu_prof_stop()
            ranks = native_mod.mu_rank_stats()
            parts.append("# native lock contention (nat_mu_prof: "
                         "contended NatMutex waits, wait-us weighted)")
            parts.append(native_mod.mu_prof_report(collapsed=True).rstrip())
            if ranks:
                parts.append("# per-rank wait totals since start/reset:")
                for r in sorted(ranks, key=lambda r: -r["wait_us"]):
                    parts.append(
                        f"#   rank {r['rank']:>3d} {r['name']:<14s} "
                        f"waits={r['waits']} wait_us={r['wait_us']}")
            if owns:
                # samples only: the per-rank totals ride /brpc_metrics
                # as counters and must survive debug-page requests
                native_mod.mu_prof_reset_samples()
        except Exception as e:
            parts.append(f"# native contention profiler failed: {e}")
    parts.append("# python wait-frame profile")
    parts.append(py_report.rstrip())
    return "\n".join(parts) + "\n"


def hotspots_handler(server, req):
    """/hotspots/{cpu,native,heap,growth,contention,tpu} — the full
    profiler surface of hotspots_service.h:38-68 (+ the XProf TPU
    translation and the nat_prof native sampler)."""
    from brpc_tpu.builtin import profilers

    parts = [p for p in req.path.split("/") if p]
    kind = parts[1] if len(parts) > 1 else "cpu"
    seconds = float(req.query.get("seconds", "1") or 1)
    if kind == "cpu":
        return 200, "text/plain", sample_cpu(seconds)
    if kind == "native":
        collapsed = req.query.get("flat", "") in ("", "0")
        # 503 + Retry-After on collision (regression: ISSUE 9 satellite)
        return _native_prof_window.run(
            seconds, lambda s: sample_native(s, collapsed=collapsed))
    if kind == "heap":
        return 200, "text/plain", profilers.heap_profile()
    if kind == "growth":
        return 200, "text/plain", profilers.growth_profile()
    if kind == "contention":
        return _contention_prof_window.run(seconds, sample_contention)
    if kind == "tpu":
        ctype, body = profilers.tpu_trace(seconds)
        return 200, ctype, body
    return 404, "text/plain", f"unknown hotspots kind {kind}\n"


def pprof_handler(server, req):
    """/pprof/{profile,heap,growth,symbol} — pprof_service.h:26-48 slots."""
    from brpc_tpu.builtin import profilers

    parts = [p for p in req.path.split("/") if p]
    kind = parts[1] if len(parts) > 1 else "profile"
    if kind == "profile":
        seconds = float(req.query.get("seconds", "1") or 1)
        return 200, "text/plain", sample_cpu(seconds)
    if kind == "heap":
        return 200, "text/plain", profilers.heap_profile()
    if kind == "growth":
        return 200, "text/plain", profilers.growth_profile()
    if kind == "contention":
        seconds = float(req.query.get("seconds", "1") or 1)
        return 200, "text/plain", profilers.contention_profile(seconds)
    if kind == "symbol":
        return 200, "text/plain", "python frames are pre-symbolized\n"
    return 404, "text/plain", f"unknown pprof endpoint {kind}\n"


def threads_handler(server, req):
    return 200, "text/plain", thread_dump()
